"""Fleet collector: the gang-level aggregation layer over per-rank
exporters.

Every rank already serves its own observability surface — the param
server and :class:`~sparktorch_tpu.native.gang.GangMetricsExporter`
both expose ``/metrics`` (Prometheus text), ``/telemetry`` (the full
snapshot as JSON, including named SECTIONS like the last published
xprof analysis), and ``/heartbeats`` — but a multi-host run is N of
those, one per host, and nothing assembled a whole-gang view (the
ROADMAP's "multi-host half of the Dapper gap"). The
:class:`FleetCollector` closes it:

- **scrape**: periodically pull every rank's ``/telemetry`` and
  ``/heartbeats``; a failing rank degrades to a warning + counter
  (``collector.scrape_errors_total{rank}``), never a dead poll loop —
  its last good snapshot keeps serving, aging visibly.
- **tag**: every scraped metric series is re-keyed with ``rank`` and
  ``host`` labels (existing labels win on conflict — a heartbeat
  gauge's own ``rank`` label already names the right rank), so the
  merged view never aliases two ranks' series.
- **merge**: per-rank ``xprof`` snapshot sections fold into one gang
  budget via :func:`sparktorch_tpu.obs.xprof.merge_analyses`
  (families summed, step walls max'd, cross-rank skew) and publish
  onto the collector's own bus under ``xprof.gang_*``; heartbeat
  tables union into one gang table.
- **re-serve**: ``GET /gang`` (the joined gang document: rank scrape
  status, merged heartbeats, merged xprof budget, per-rank run_ids),
  ``GET /metrics`` (Prometheus text of the merged view), and
  ``GET /telemetry`` (the merged snapshot as JSON) — plus an optional
  JSONL sink appending one merged snapshot per poll, which
  ``python -m sparktorch_tpu.obs.timeline --gang`` renders.

Run-ID correlation: a gang-unique ``run_id`` (:func:`mint_run_id`) is
minted at bring-up, announced by the gang coordinator's OK reply,
stamped on every span/event/heartbeat, and carried as a 16-bit tag
(:func:`run_tag`) in the binary wire header's reserved bytes — the
collector joins per-rank streams on it.

This module also owns the ONLY sanctioned exporter-scraping helpers
(:func:`scrape_json` / :func:`scrape_text`): ``make lint-obs`` bans
ad-hoc ``urllib`` scraping of exporter routes outside ``obs/`` so
every reader shares the same timeout/error/telemetry discipline.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
import zlib
from typing import Any, Dict, List, Mapping, Optional, Tuple
from urllib.parse import urlsplit

from sparktorch_tpu.obs.log import get_logger
from sparktorch_tpu.obs.prom import _parse_flat_key  # shared key grammar
from sparktorch_tpu.obs.telemetry import Telemetry, format_key

_LOG = get_logger("sparktorch_tpu.obs.collector")

_SCRAPE_TIMEOUT = 2.0


# ---------------------------------------------------------------------------
# Run-ID minting + wire tag
# ---------------------------------------------------------------------------


def mint_run_id(prefix: str = "gang") -> str:
    """A gang-unique run id: sortable timestamp + random suffix, no
    protocol-reserved characters (spaces, commas, '=' — it travels on
    the gang REG line and as a metric-adjacent token)."""
    return f"{prefix}-{time.strftime('%Y%m%dT%H%M%S')}-{os.urandom(3).hex()}"


def run_tag(run_id: Optional[str]) -> int:
    """16-bit correlation tag for the binary wire header's reserved
    bytes (frames predate string payloads there; two bytes is room for
    a join key, not a name). 0 is reserved for "untagged" — the value
    every pre-tag encoder wrote — so a real run id always maps to a
    nonzero tag."""
    if not run_id:
        return 0
    tag = zlib.crc32(str(run_id).encode()) & 0xFFFF
    return tag or 1


# ---------------------------------------------------------------------------
# Sanctioned scrape helpers
# ---------------------------------------------------------------------------


class ScrapeError(OSError):
    """The exporter was unreachable or answered garbage."""


def scrape_text(url: str, timeout: float = _SCRAPE_TIMEOUT) -> str:
    """GET a text route (e.g. ``/metrics``). Raises ScrapeError on any
    network failure or non-200 status."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                raise ScrapeError(f"{url}: HTTP {resp.status}")
            return resp.read().decode("utf-8", errors="replace")
    except ScrapeError:
        raise
    except (OSError, ValueError) as e:
        raise ScrapeError(f"{url}: {type(e).__name__}: {e}") from e


def scrape_json(url: str, timeout: float = _SCRAPE_TIMEOUT) -> Any:
    """GET + parse a JSON route (``/telemetry``, ``/heartbeats``,
    ``/gang``). Raises ScrapeError on network failure, non-200, or a
    body that is not valid JSON (the torn-response case readers must
    survive)."""
    body = scrape_text(url, timeout=timeout)
    try:
        return json.loads(body)
    except ValueError as e:
        raise ScrapeError(f"{url}: torn/invalid JSON: {e}") from e


def post_json(url: str, payload: Mapping[str, Any],
              timeout: float = _SCRAPE_TIMEOUT,
              headers: Optional[Mapping[str, str]] = None) -> Any:
    """POST a JSON document to a control route (``/ctl``) and parse
    the JSON reply — the write-side twin of :func:`scrape_json`, kept
    in obs/ so control traffic shares the same timeout/error taxonomy
    the lint-obs scrape discipline enforces on readers. Raises
    :class:`ScrapeError` on network failure or a non-JSON reply;
    non-2xx statuses raise with the server's body in the message (a
    403 bad-token or 400 unknown-verb reply is the diagnostic)."""
    req = urllib.request.Request(
        url, data=json.dumps(dict(payload)).encode(), method="POST",
        headers={"Content-Type": "application/json",
                 **(dict(headers) if headers else {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read().decode("utf-8", errors="replace")
            if resp.status < 200 or resp.status >= 300:
                raise ScrapeError(f"{url}: HTTP {resp.status}: {body}")
    except ScrapeError:
        raise
    except urllib.error.HTTPError as e:
        detail = e.read().decode("utf-8", errors="replace")
        raise ScrapeError(f"{url}: HTTP {e.code}: {detail}") from e
    except (OSError, ValueError) as e:
        raise ScrapeError(f"{url}: {type(e).__name__}: {e}") from e
    try:
        return json.loads(body)
    except ValueError as e:
        raise ScrapeError(f"{url}: torn/invalid JSON reply: {e}") from e


def snapshot_histogram(snapshot: Mapping[str, Any], name: str,
                       labels: Optional[Mapping[str, Any]] = None
                       ) -> Optional[Dict[str, Any]]:
    """The roll-up of histogram ``name`` in a telemetry snapshot dict
    (a ``/telemetry`` scrape, a collector's merged snapshot, or a
    JSONL dump record), matched by name + a label SUBSET: every given
    label must match, EXTRA labels on the series are ignored — a
    collector re-keys scraped series with rank/host labels, and a
    consumer asking for ``serve.request_latency_s{replica=2}`` must
    find it regardless of which target it was scraped from. When
    several series match (the same replica scraped under two targets)
    the one with the largest sample count wins. None when nothing
    matches — readers must treat that as "no signal", never as zero.

    This is the sanctioned read path for routing/consuming decisions
    off scraped snapshots (the lint-obs scrape discipline's read-side
    twin): the ``name{k=v}`` key grammar stays parsed in obs/."""
    hists = snapshot.get("histograms")
    if not isinstance(hists, Mapping):
        return None
    want = {str(k): str(v) for k, v in (labels or {}).items()}
    best: Optional[Dict[str, Any]] = None
    for flat, rollup in hists.items():
        series_name, series_labels = _parse_flat_key(str(flat))
        if series_name != name or not isinstance(rollup, Mapping):
            continue
        have = dict(series_labels)
        if any(have.get(k) != v for k, v in want.items()):
            continue
        if best is None or (rollup.get("count") or 0) > \
                (best.get("count") or 0):
            best = dict(rollup)
    return best


# ---------------------------------------------------------------------------
# The collector
# ---------------------------------------------------------------------------


class _RankState:
    __slots__ = ("url", "host", "snapshot", "heartbeats", "last_ok_ts",
                 "last_error", "scrapes", "errors", "committed_seq")

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.host = urlsplit(self.url).hostname or "?"
        self.snapshot: Optional[Dict[str, Any]] = None
        self.heartbeats: Optional[Dict[str, Any]] = None
        self.last_ok_ts: Optional[float] = None
        self.last_error: Optional[str] = None
        self.scrapes = 0
        self.errors = 0
        # Sweep generation of the last committed scrape: a straggler
        # from an OLDER sweep must never overwrite a newer snapshot
        # (and re-stamp it fresh) after a later sweep already landed.
        self.committed_seq = -1


def _tag_series(flat: str, rank: str, host: str) -> str:
    """Re-key ``name{labels}`` with rank/host labels. Labels the
    series already carries WIN (a heartbeat gauge's own ``rank`` names
    the heartbeat's rank, not the scrape target's)."""
    name, labels = _parse_flat_key(flat)
    merged = {"rank": rank, "host": host}
    merged.update(labels)
    return format_key((name, tuple(sorted(merged.items()))))


class FleetCollector:
    """Scrape N rank exporters, merge, re-serve the unified view.

    ``targets`` maps rank -> exporter base URL (the
    ``GangMetricsExporter`` / ``ParamServerHttp`` address). ``poll()``
    is one synchronous sweep — callable directly (tests, one-shot CLI
    use) or driven by the background loop ``start()`` launches when
    ``poll_interval_s`` > 0. ``jsonl_path`` appends one merged
    snapshot per poll (the ``timeline --gang`` input).
    """

    def __init__(self, targets: Mapping[Any, str],
                 telemetry: Optional[Telemetry] = None,
                 run_id: Optional[str] = None,
                 poll_interval_s: float = 2.0,
                 jsonl_path: Optional[str] = None,
                 fallback_jsonl: Optional[str] = None,
                 scrape_timeout_s: float = _SCRAPE_TIMEOUT,
                 poll_parallelism: int = 8,
                 poll_deadline_s: Optional[float] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ctl=None, ctl_token: Optional[str] = None,
                 history=True,
                 history_retention: Optional[int] = None,
                 history_spill_jsonl: Optional[str] = None,
                 alert_rules=None):
        if not targets:
            raise ValueError("FleetCollector needs at least one target")
        self.run_id = run_id or mint_run_id("collector")
        self.telemetry = telemetry or Telemetry(run_id=self.run_id)
        # Retained history: every poll sweep appends the merged series
        # into bounded per-series rings (obs.history.MetricsHistory),
        # served back as derived queries on ``GET /history`` and as
        # the substrate the alert rules judge. ``history=False`` turns
        # the tier off (the bench's overhead control leg); a
        # MetricsHistory instance is adopted as-is.
        from sparktorch_tpu.obs.history import DEFAULT_RETENTION, MetricsHistory

        if history is True:
            self.history: Optional[MetricsHistory] = MetricsHistory(
                retention=history_retention or DEFAULT_RETENTION,
                spill_jsonl=history_spill_jsonl)
        elif history:
            self.history = history
        else:
            self.history = None
        # Declarative SLO/threshold alerting over the history
        # (obs.alerts): rules evaluate once per sweep; latched,
        # episode-counted transitions land on the bus, in the JSONL
        # sink, and in /gang's ``alerts`` section.
        self.alerts = None
        if alert_rules:
            if self.history is None:
                raise ValueError("alert_rules need history enabled")
            from sparktorch_tpu.obs.alerts import AlertManager

            self.alerts = (alert_rules
                           if isinstance(alert_rules, AlertManager)
                           else AlertManager(self.history, alert_rules,
                                             telemetry=self.telemetry))
        # One atomic (sig, history) pair like _fallback_cache — two
        # separately-assigned attributes can tear under the threading
        # HTTP server and re-serve a reconstruction staler than the file.
        self._fallback_history_cache: Optional[
            Tuple[Tuple[int, int], MetricsHistory]] = None
        self._ranks: Dict[str, _RankState] = {
            str(r): _RankState(url) for r, url in targets.items()
        }
        self.poll_interval_s = poll_interval_s
        self.jsonl_path = jsonl_path
        # HA tail mode: a PEER collector's JSONL sink. When this
        # collector has never scraped a single rank successfully (and
        # none of its last-good snapshots exist), ``/gang`` falls back
        # to the newest merged snapshot in the peer's file — a
        # secondary collector keeps answering operators from the
        # primary's sink while the primary (or the whole scrape plane)
        # is down. Served with ``source: fallback_jsonl`` so a reader
        # can tell live data from tailed data.
        self.fallback_jsonl = fallback_jsonl
        self.scrape_timeout_s = scrape_timeout_s
        # Fan-in at scale: scrape targets in PARALLEL (a param-server
        # fleet multiplies targets — N shards + gateway per host; a
        # serial sweep would take N x timeout when several die at
        # once) under one sweep-wide deadline budget. poll_parallelism
        # <= 1 restores the serial sweep.
        self.poll_parallelism = max(1, int(poll_parallelism))
        self.poll_deadline_s = (
            poll_deadline_s if poll_deadline_s is not None
            else scrape_timeout_s * 2 + 1.0
        )
        self._scrape_pool = None
        self._poll_seq = -1  # sweep generation (stale-commit guard)
        # Control plane: ``POST /ctl`` with a ``rank`` is FORWARDED to
        # that rank's exporter (same route, token header passed
        # through) — the controller talks to one address and the
        # collector fans out, exactly like the read side. Without a
        # rank, the verb dispatches to this collector's own registry
        # (``ctl`` — e.g. the elastic controller's resize verb);
        # ``ctl_token`` guards BOTH paths (None = unguarded, for
        # loopback dev rigs).
        self.ctl = ctl
        self.ctl_token = ctl_token
        self.host = host
        self.port = port
        self._lock = threading.Lock()
        self._gang_xprof: Optional[Dict[str, Any]] = None
        self._xprof_fingerprint: Optional[Tuple] = None
        self._rpc_doc: Optional[Dict[str, Any]] = None
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

    @classmethod
    def for_fleet(cls, fleet, per_shard: bool = False,
                  **kwargs) -> "FleetCollector":
        """Collector over a param-server FLEET's scrape surface.
        Default is the fleet's single deduplicated target (the
        in-process fleet shares ONE bus across shards — scraping
        every frontend would multiply every series by the target
        count; per-shard attribution rides the ``shard`` labels).
        ``per_shard=True`` targets every shard frontend + gateway —
        for fleets whose shards own separate buses. ``fleet`` is a
        :class:`~sparktorch_tpu.serve.fleet.ParamServerFleet` (or
        anything with ``collector_targets()``)."""
        kwargs.setdefault("run_id", getattr(
            getattr(fleet, "telemetry", None), "run_id", None))
        return cls(fleet.collector_targets(per_shard=per_shard), **kwargs)

    # -- scraping ----------------------------------------------------------

    def _scrape_rank(self, rank: str, st: _RankState,
                     seq: int = -1) -> None:
        """One target's scrape (telemetry + heartbeats), with the
        degrade-to-last-good contract. Thread-safe: state lands under
        the collector lock, so parallel sweeps never tear a rank —
        and ``seq`` (the sweep generation) gates the commit, so a
        STRAGGLING scrape from an older sweep that finally answers
        after a newer sweep landed is dropped, never allowed to roll
        the rank's snapshot (and its freshness stamp) backwards."""
        tele = self.telemetry
        labels = {"rank": rank}
        try:
            snap = scrape_json(st.url + "/telemetry",
                               timeout=self.scrape_timeout_s)
            if not isinstance(snap, dict):
                raise ScrapeError(f"{st.url}/telemetry: not an object")
            hb: Optional[Dict[str, Any]] = None
            try:
                got = scrape_json(st.url + "/heartbeats",
                                  timeout=self.scrape_timeout_s)
                hb = got if isinstance(got, dict) else None
            except ScrapeError:
                hb = None  # optional route; /telemetry carries gauges
            with self._lock:
                if seq < st.committed_seq:
                    tele.counter("collector.stale_scrapes_dropped_total",
                                 labels=labels)
                    return
                st.committed_seq = seq
                st.snapshot = snap
                if hb is not None:
                    # Same degrade-to-last-good contract as the
                    # snapshot: a transient /heartbeats failure
                    # must not make this target's ranks VANISH
                    # from /gang — the stale table keeps serving
                    # (its ages grow, which is the visible signal).
                    st.heartbeats = hb
                st.last_ok_ts = time.time()
                st.last_error = None
                st.scrapes += 1
            tele.counter("collector.scrapes_total", labels=labels)
        except ScrapeError as e:
            with self._lock:
                st.errors += 1
                st.last_error = str(e)
            tele.counter("collector.scrape_errors_total", labels=labels)
            _LOG.warning(
                f"[sparktorch_tpu:collector] rank {rank} scrape "
                f"failed (serving last good snapshot): {e}"
            )

    def poll(self) -> Dict[str, Any]:
        """One sweep over every rank: scrape (in parallel), tag,
        merge, sink. Returns the merged snapshot. Per-rank failures
        degrade to warnings + counters; the sweep itself never raises.

        Parallel fan-in: targets scrape concurrently (bounded by
        ``poll_parallelism``) under the ``poll_deadline_s`` sweep
        budget, so sweep wall is ~one timeout even when several
        targets hang — a serial sweep over a fleet's N shard
        frontends would take N timeouts exactly when things are on
        fire. A target that misses the sweep deadline is counted
        (``collector.scrape_deadline_misses_total{rank}``) and its
        last good snapshot keeps serving; its straggling scrape still
        lands when it finishes — unless a NEWER sweep already
        committed for that rank, in which case the stale result is
        dropped (``collector.stale_scrapes_dropped_total{rank}``)
        instead of rolling the snapshot backwards."""
        tele = self.telemetry
        items = list(self._ranks.items())
        self._poll_seq += 1
        seq = self._poll_seq
        if self.poll_parallelism <= 1 or len(items) == 1:
            for rank, st in items:
                self._scrape_rank(rank, st, seq)
        else:
            from concurrent.futures import ThreadPoolExecutor, wait

            if self._scrape_pool is None:
                self._scrape_pool = ThreadPoolExecutor(
                    max_workers=min(len(items), self.poll_parallelism),
                    thread_name_prefix="collector-scrape",
                )
            futures = {
                self._scrape_pool.submit(self._scrape_rank, rank, st,
                                         seq): rank
                for rank, st in items
            }
            _done, not_done = wait(futures, timeout=self.poll_deadline_s)
            for future in not_done:
                rank = futures[future]
                tele.counter("collector.scrape_deadline_misses_total",
                             labels={"rank": rank})
                _LOG.warning(
                    f"[sparktorch_tpu:collector] rank {rank} scrape "
                    f"missed the {self.poll_deadline_s}s sweep deadline "
                    f"(serving last good snapshot)"
                )
        self._merge_xprof()
        self._stitch_rpc()
        self._merge_goodput()
        self._merge_profile()
        self._merge_health()
        merged = self.merged_snapshot()
        alert_events: List[Dict[str, Any]] = []
        if self.history is not None:
            self.history.append(merged)
            if self.alerts is not None:
                alert_events = self.alerts.evaluate(ts=merged.get("ts"))
        if self.jsonl_path:
            from sparktorch_tpu.obs.sinks import write_jsonl

            try:
                # The sink record also carries the unioned heartbeat
                # table (merged_snapshot alone does not — heartbeats
                # are a /gang-level join): a secondary collector
                # tailing this file must be able to serve the
                # straggler/step-skew view, which is exactly what an
                # operator wants DURING the outage HA mode covers.
                # Alert transitions land in the sink as their own
                # records BEFORE the snapshot: a `timeline --follow`
                # tail renders the firing the moment it happens, and
                # the HA fallback secondary replays the same episodes.
                # The run-level goodput accounting rides the same way
                # — one condensed `goodput.run` record per sweep (the
                # shape `--follow` renders as a one-liner), with the
                # full document still on the snapshot's sections.
                goodput_records: List[Dict[str, Any]] = []
                run_doc = (merged.get("sections") or {}).get("goodput_run")
                if isinstance(run_doc, Mapping):
                    goodput_records.append({
                        "kind": "goodput.run", "ts": merged.get("ts"),
                        "goodput": run_doc.get("goodput"),
                        "wall_s": run_doc.get("wall_s"),
                        "n_ranks": run_doc.get("n_ranks"),
                        "comm_source": run_doc.get("comm_source"),
                        "biggest_thief": run_doc.get("biggest_thief"),
                    })
                # Same shape for the merged stack profile: one
                # condensed `profile.run` line per sweep, full tries
                # on the snapshot's sections (timeline --profile
                # reads those back out of this very file).
                profile_records: List[Dict[str, Any]] = []
                prof_doc = (merged.get("sections") or {}).get("profile_run")
                if isinstance(prof_doc, Mapping):
                    profile_records.append({
                        "kind": "profile.run", "ts": merged.get("ts"),
                        "samples_total": prof_doc.get("samples_total"),
                        "n_ranks": prof_doc.get("n_ranks"),
                        "bursts": prof_doc.get("bursts"),
                    })
                # And the model-health merge: one condensed
                # `health.run` line per sweep (anomaly counts stay
                # rank-tagged — a single poisoned rank must surface
                # by name, never averaged into the fleet).
                health_records: List[Dict[str, Any]] = []
                health_doc = (merged.get("sections") or {}).get("health_run")
                if isinstance(health_doc, Mapping):
                    health_records.append({
                        "kind": "health.run", "ts": merged.get("ts"),
                        "n_ranks": health_doc.get("n_ranks"),
                        "last_step": health_doc.get("last_step"),
                        "anomalies_total": health_doc.get(
                            "anomalies_total"),
                        "counts": health_doc.get("counts"),
                        "worst": health_doc.get("worst"),
                    })
                # The cross-rank straggler verdict: one condensed
                # `skew.run` line per sweep (the wire/straggler split
                # plus the named laggard — the `--follow` one-liner),
                # full doc on the snapshot's sections.
                skew_records: List[Dict[str, Any]] = []
                skew_doc = (merged.get("sections") or {}).get("skew_run")
                if isinstance(skew_doc, Mapping):
                    skew_records.append({
                        "kind": "skew.run", "ts": merged.get("ts"),
                        "n_ranks": skew_doc.get("n_ranks"),
                        "steps_aligned": skew_doc.get("steps_aligned"),
                        "wire_s": skew_doc.get("wire_s"),
                        "straggler_wait_s": skew_doc.get(
                            "straggler_wait_s"),
                        "straggler_fraction": skew_doc.get(
                            "straggler_fraction"),
                        "laggard": skew_doc.get("laggard"),
                    })
                write_jsonl(self.jsonl_path,
                            [{"kind": f"alert.{e['event']}", **e}
                             for e in alert_events]
                            + goodput_records + profile_records
                            + health_records + skew_records
                            + [{"kind": "gang_snapshot", **merged,
                                "heartbeats": self._merged_heartbeats()}],
                            append=True)
            except OSError as e:
                _LOG.warning(
                    f"[sparktorch_tpu:collector] JSONL sink "
                    f"{self.jsonl_path!r} failed: {e}"
                )
        return merged

    def _merge_xprof(self) -> None:
        """Fold every rank's ``xprof`` snapshot section into one gang
        budget. Re-published only when some rank's analysis actually
        changed — republishing identical analyses each poll would
        duplicate histogram samples and inflate the merge counters."""
        with self._lock:
            found: List[Tuple[str, Dict[str, Any]]] = []
            for rank, st in self._ranks.items():
                section = ((st.snapshot or {}).get("sections") or {}).get(
                    "xprof")
                if isinstance(section, dict) and section.get("steps"):
                    found.append((rank, section))
        if not found:
            return
        fingerprint = tuple(
            (rank, d.get("source"), d.get("n_events"), d.get("wall_s"))
            for rank, d in found
        )
        if fingerprint == self._xprof_fingerprint:
            return
        from sparktorch_tpu.obs.xprof import merge_analyses

        try:
            gang = merge_analyses([d for _, d in found],
                                  ranks=[r for r, _ in found],
                                  run_id=self.run_id)
        except (KeyError, TypeError, ValueError) as e:
            _LOG.warning(
                f"[sparktorch_tpu:collector] xprof merge failed: {e}"
            )
            return
        self._xprof_fingerprint = fingerprint
        gang.publish(self.telemetry)
        with self._lock:
            self._gang_xprof = gang.to_dict()

    def _stitch_rpc(self) -> None:
        """Join every scraped rank's ``rpc_spans`` ring (plus this
        collector's own, if it records any) into whole-request trees
        by trace_id — the cross-process half of per-request tracing:
        a worker's root span and the serving rank's queue-wait/apply
        spans live on DIFFERENT buses until this stitch. The stitched
        document (each tree with its computed critical path) is
        published as this bus's ``rpc_traces`` section, so the JSONL
        sink, ``/telemetry``, ``/gang``, and ``timeline --rpc`` all
        see one truth."""
        from sparktorch_tpu.obs import rpctrace

        spans: List[Dict[str, Any]] = []
        with self._lock:
            for st in self._ranks.values():
                spans.extend(rpctrace.spans_from_snapshot(
                    st.snapshot or {}))
        own = self.telemetry.get_section(rpctrace.SECTION)
        if isinstance(own, dict):
            spans.extend(own.get("spans") or [])
        if not spans:
            return
        traces = rpctrace.stitch_spans(spans, max_traces=32)
        doc = {
            "n_spans": len(spans),
            "n_traces": len(traces),
            "traces": traces,
        }
        with self._lock:
            self._rpc_doc = doc
        self.telemetry.set_section(rpctrace.TRACES_SECTION, doc)

    def rpc_traces(self) -> List[Dict[str, Any]]:
        """The last stitched whole-request trees (newest first)."""
        with self._lock:
            return list((self._rpc_doc or {}).get("traces") or [])

    def _merge_goodput(self) -> None:
        """Fold every scraped rank's ``goodput`` ledger section (plus
        this collector's own bus's, when a driver-side ledger shares
        it) into ONE run-level report, published as the
        ``goodput_run`` section — so the JSONL sink, ``/telemetry``,
        ``/gang``, postmortem bundles, and ``timeline --goodput`` all
        carry the same run accounting. The last-good contract applies:
        a dead rank's final ledger keeps contributing."""
        from sparktorch_tpu.obs import goodput as _goodput

        from sparktorch_tpu.obs import skew as _skew

        # The skew merge runs FIRST: it decomposes exposed_comm from
        # the same per-rank sections, and the fresh skew_run verdict
        # refines this merge's biggest_thief (straggler_wait vs wire).
        self._merge_skew()
        with self._lock:
            snaps = {r: st.snapshot for r, st in self._ranks.items()}
        docs = _goodput.sections_from_snapshots(snaps)
        own = self.telemetry.get_section(_goodput.SECTION)
        if isinstance(own, Mapping):
            docs.setdefault("collector", own)
        if not docs:
            return
        skew_run = self.telemetry.get_section(_skew.RUN_SECTION)
        run = _goodput.merge_sections(
            docs, skew=skew_run if isinstance(skew_run, Mapping) else None)
        run["run_id"] = self.run_id
        self.telemetry.set_section(_goodput.RUN_SECTION, run)

    def goodput_view(self) -> Optional[Dict[str, Any]]:
        """The run-level goodput report ``GET /goodput`` serves —
        recomputed from the freshest last-good snapshots at read time
        (a rank's ledger advances between poll sweeps only via
        scrapes, so this is one merge over already-held state, never
        a network hop). None when no rank has published a ledger."""
        self._merge_goodput()
        from sparktorch_tpu.obs import goodput as _goodput

        doc = self.telemetry.get_section(_goodput.RUN_SECTION)
        return dict(doc) if isinstance(doc, Mapping) else None

    def _merge_skew(self) -> None:
        """Align every scraped rank's ``skew`` step-stamp ring (plus
        this collector's own bus's, when a driver-side ledger shares
        it) into the run-level straggler verdict, published as the
        ``skew_run`` section and exported as ``skew.*`` gauges (the
        series the sustained straggler-fraction alert rule judges).
        The per-rank goodput/health sections from the SAME snapshots
        supply the exposed_comm budget and the laggard's cause
        evidence. Last-good contract: a dead rank's final stamps keep
        contributing."""
        from sparktorch_tpu.obs import goodput as _goodput
        from sparktorch_tpu.obs import health as _health
        from sparktorch_tpu.obs import skew as _skew

        with self._lock:
            snaps = {r: st.snapshot for r, st in self._ranks.items()}
        docs = _skew.sections_from_snapshots(snaps)
        own = self.telemetry.get_section(_skew.SECTION)
        if isinstance(own, Mapping):
            docs.setdefault("collector", own)
        if not docs:
            return
        gdocs = _goodput.sections_from_snapshots(snaps)
        gown = self.telemetry.get_section(_goodput.SECTION)
        if isinstance(gown, Mapping):
            gdocs.setdefault("collector", gown)
        hdocs = _health.sections_from_snapshots(snaps)
        run = _skew.merge_sections(docs, goodput_docs=gdocs,
                                   health_docs=hdocs)
        run["run_id"] = self.run_id
        self.telemetry.set_section(_skew.RUN_SECTION, run)
        _skew.publish_run_gauges(self.telemetry, run)

    def skew_view(self) -> Optional[Dict[str, Any]]:
        """The run-level straggler verdict ``GET /skew`` serves —
        recomputed from the freshest last-good snapshots at read
        time, like :meth:`goodput_view`. None when no rank has
        published step stamps."""
        self._merge_skew()
        from sparktorch_tpu.obs import skew as _skew

        doc = self.telemetry.get_section(_skew.RUN_SECTION)
        return dict(doc) if isinstance(doc, Mapping) else None

    def _merge_profile(self) -> None:
        """Fold every scraped rank's ``profile`` section (plus this
        collector's own bus's, when a driver-side sampler shares it)
        into one run-level stack profile, published as the
        ``profile_run`` section — the same path the goodput merge
        takes, with the same last-good contract: a SIGKILLed rank's
        final throttled publish keeps contributing its tries."""
        from sparktorch_tpu.obs import profile as _profile

        with self._lock:
            snaps = {r: st.snapshot for r, st in self._ranks.items()}
        docs = _profile.sections_from_snapshots(snaps)
        own = self.telemetry.get_section(_profile.SECTION)
        if isinstance(own, Mapping):
            docs.setdefault("collector", own)
        if not docs:
            return
        run = _profile.merge_sections(docs)
        run["run_id"] = self.run_id
        self.telemetry.set_section(_profile.RUN_SECTION, run)

    def profile_view(self) -> Optional[Dict[str, Any]]:
        """The merged stack profile ``GET /profile`` serves —
        recomputed from the freshest last-good snapshots at read
        time, like :meth:`goodput_view`. None when no rank has
        published a profile section."""
        self._merge_profile()
        from sparktorch_tpu.obs import profile as _profile

        doc = self.telemetry.get_section(_profile.RUN_SECTION)
        return dict(doc) if isinstance(doc, Mapping) else None

    def _merge_health(self) -> None:
        """Fold every scraped rank's ``health`` ledger section (plus
        this collector's own bus's, when a driver-side ledger shares
        it) into one run-level model-health report, published as the
        ``health_run`` section. The merge is strictly rank-tagged —
        anomalies carry their source rank and are never averaged, so
        a single poisoned rank surfaces by name. Last-good contract:
        a dead rank's final ledger keeps contributing its anomalies."""
        from sparktorch_tpu.obs import health as _health

        with self._lock:
            snaps = {r: st.snapshot for r, st in self._ranks.items()}
        docs = _health.sections_from_snapshots(snaps)
        own = self.telemetry.get_section(_health.SECTION)
        if isinstance(own, Mapping):
            docs.setdefault("collector", own)
        if not docs:
            return
        run = _health.merge_sections(docs)
        run["run_id"] = self.run_id
        self.telemetry.set_section(_health.RUN_SECTION, run)

    def health_view(self) -> Optional[Dict[str, Any]]:
        """The run-level model-health report ``GET /health`` serves —
        recomputed from the freshest last-good snapshots at read
        time, like :meth:`goodput_view`. None when no rank has
        published a health section."""
        self._merge_health()
        from sparktorch_tpu.obs import health as _health

        doc = self.telemetry.get_section(_health.RUN_SECTION)
        return dict(doc) if isinstance(doc, Mapping) else None

    # -- merged views ------------------------------------------------------

    def _rank_status_locked(self, now: float) -> Dict[str, Any]:
        """Per-rank scrape status; caller holds ``self._lock``."""
        return {
            r: {
                "url": st.url,
                "host": st.host,
                "ok": st.last_error is None and st.snapshot is not None,
                "scrapes": st.scrapes,
                "errors": st.errors,
                "last_error": st.last_error,
                "last_scrape_age_s": (
                    now - st.last_ok_ts
                    if st.last_ok_ts is not None else None
                ),
                "run_id": (st.snapshot or {}).get("run_id"),
            }
            for r, st in self._ranks.items()
        }

    def merged_snapshot(self) -> Dict[str, Any]:
        """The unified metric view: every rank's series re-keyed with
        rank/host labels, the collector's own metrics (scrape counters,
        gang xprof budget) alongside, plus per-rank scrape status."""
        own = self.telemetry.snapshot()
        now = time.time()
        with self._lock:
            rank_snaps = {r: (st.snapshot, st.host)
                          for r, st in self._ranks.items()}
            status = self._rank_status_locked(now)
        merged: Dict[str, Any] = {
            "run_id": self.run_id,
            "ts": now,
            "counters": dict(own.get("counters", {})),
            "gauges": dict(own.get("gauges", {})),
            "histograms": dict(own.get("histograms", {})),
            "spans": dict(own.get("spans", {})),
            "info": dict(own.get("info", {})),
            "ranks": status,
        }
        if "sections" in own:
            merged["sections"] = own["sections"]
        for r, (snap, host) in rank_snaps.items():
            if not snap:
                continue
            for section in ("counters", "gauges", "histograms", "spans",
                            "info"):
                for flat, value in (snap.get(section) or {}).items():
                    merged[section][_tag_series(flat, r, host)] = value
        merged["gauges"]["collector.ranks"] = float(len(self._ranks))
        merged["gauges"]["collector.ranks_ok"] = float(
            sum(1 for s in status.values() if s["ok"])
        )
        return merged

    def _merged_heartbeats(self) -> Dict[str, Any]:
        """The unioned gang heartbeat table (freshest record per rank
        across targets sharing a directory) + derived step skew —
        shared by ``gang_view`` and the JSONL sink record, so a
        fallback secondary tails the same table ``/gang`` serves."""
        hb_ranks: Dict[str, Any] = {}
        steps: List[int] = []
        with self._lock:
            for r, st in self._ranks.items():
                for hb_rank, rec in ((st.heartbeats or {}).get("ranks")
                                     or {}).items():
                    prev = hb_ranks.get(str(hb_rank))
                    # Two targets may report the same heartbeat rank
                    # (shared directory): freshest record wins.
                    if prev is not None and (
                            prev.get("last_seen_age_s", 1e18)
                            <= rec.get("last_seen_age_s", 1e18)):
                        continue
                    hb_ranks[str(hb_rank)] = dict(rec)
        for rec in hb_ranks.values():
            if rec.get("step") is not None:
                steps.append(int(rec["step"]))
        heartbeats: Dict[str, Any] = {
            "n_ranks": len(hb_ranks),
            "ranks": hb_ranks,
            "alive": sorted((r for r, v in hb_ranks.items()
                             if v.get("alive")), key=str),
        }
        if steps:
            heartbeats["step_min"] = min(steps)
            heartbeats["step_max"] = max(steps)
            heartbeats["step_skew"] = max(steps) - min(steps)
        return heartbeats

    def gang_view(self) -> Dict[str, Any]:
        """The joined gang document ``GET /gang`` serves: scrape
        status per rank, the unioned heartbeat table (re-aged at read
        time), the merged xprof budget, and every run_id seen — the
        cross-rank correlation surface. Reads only the per-rank status
        and heartbeat/xprof state — it does NOT pay the full series
        tag-and-merge that ``merged_snapshot`` does (O(ranks), not
        O(total series), per ``/gang`` poll)."""
        now = time.time()
        with self._lock:
            status = self._rank_status_locked(now)
            gang_xprof = self._gang_xprof
            rpc_doc = self._rpc_doc
        if self.fallback_jsonl and not any(
                s["ok"] or s["scrapes"] for s in status.values()):
            # HA tail mode: this collector has NEVER landed a scrape
            # (secondary spun up while the scrape plane is dark) — keep
            # answering from the peer collector's sink rather than
            # serving an empty gang.
            fallback = self._fallback_gang_view(now)
            if fallback is not None:
                return fallback
        heartbeats = self._merged_heartbeats()
        doc = {
            "run_id": self.run_id,
            "ts": now,
            "source": "live",
            "ranks": status,
            "run_ids": {r: s.get("run_id") for r, s in status.items()},
            "heartbeats": heartbeats,
            "xprof": gang_xprof,
        }
        # Elastic control-plane state: when an ElasticController shares
        # this collector's bus (bringup wires them together), its
        # generation-tagged world document — current world size,
        # members, and the shrink/grow/restart event history — rides
        # /gang beside liveness, so one scrape answers both "who is
        # alive" and "what did the controller do about it".
        elastic = self.telemetry.get_section("elastic")
        if isinstance(elastic, dict):
            doc["elastic"] = elastic
        # The judgment layer rides the same scrape: what the collector
        # is worried about (alerts) and how much it remembers
        # (history shape) — one /gang answers liveness, control-plane
        # state, AND the SLO verdicts.
        if self.alerts is not None:
            doc["alerts"] = self.alerts.doc()
        if self.history is not None:
            doc["history"] = self.history.describe()
        if rpc_doc:
            # Condensed per-request view: what an operator wants from
            # /gang is "which requests, how slow, bounded by what" —
            # the full trees ride the telemetry section.
            doc["rpc"] = {
                "n_traces": rpc_doc.get("n_traces", 0),
                "n_spans": rpc_doc.get("n_spans", 0),
                "traces": [
                    {
                        "trace_id": t.get("trace_id"),
                        "name": (t.get("root") or {}).get("name"),
                        "wall_s": t.get("wall_s"),
                        "n_spans": t.get("n_spans"),
                        "status": (t.get("root") or {}).get("status"),
                        "critical": {
                            k: (t.get("critical") or {}).get(k)
                            for k in ("name", "shard", "self_s",
                                      "fraction")
                        },
                    }
                    for t in (rpc_doc.get("traces") or [])[:8]
                ],
            }
        return doc

    def _fallback_gang_view(self, now: float) -> Optional[Dict[str, Any]]:
        """Reconstruct a ``/gang`` document from the newest merged
        snapshot in the peer collector's JSONL sink (``gang_snapshot``
        records carry rank status + the xprof_gang / rpc_traces
        sections). None when the file is unreadable or empty — the
        caller then serves its own (empty) live view. The parsed
        record is CACHED on the file's (size, mtime) signature: the
        primary appends one snapshot per poll for hours, and
        re-parsing a tens-of-MB sink per operator ``/gang`` request
        would make fallback latency grow with primary uptime."""
        import os as _os

        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            st = _os.stat(self.fallback_jsonl)
            sig = (st.st_size, st.st_mtime_ns)
            cached = getattr(self, "_fallback_cache", None)
            if cached is not None and cached[0] == sig:
                rec = cached[1]
            else:
                records = read_jsonl(self.fallback_jsonl)
                rec = next((r for r in reversed(records)
                            if r.get("kind") == "gang_snapshot"), None)
                self._fallback_cache = (sig, rec)
        except OSError as e:
            _LOG.warning(
                f"[sparktorch_tpu:collector] fallback sink "
                f"{self.fallback_jsonl!r} unreadable: {e}"
            )
            return None
        if rec is None:
            return None
        self.telemetry.counter("collector.fallback_serves_total")
        sections = rec.get("sections") or {}
        return {
            "run_id": rec.get("run_id"),
            "ts": rec.get("ts"),
            "source": "fallback_jsonl",
            "fallback_path": self.fallback_jsonl,
            "fallback_age_s": (now - float(rec["ts"])
                               if rec.get("ts") is not None else None),
            "serving_run_id": self.run_id,
            "ranks": rec.get("ranks") or {},
            "run_ids": {r: s.get("run_id")
                        for r, s in (rec.get("ranks") or {}).items()},
            "heartbeats": rec.get("heartbeats") or {},
            "xprof": sections.get("xprof_gang"),
            "rpc": {
                "n_traces": (sections.get("rpc_traces")
                             or {}).get("n_traces", 0),
                "traces": [
                    {
                        "trace_id": t.get("trace_id"),
                        "name": (t.get("root") or {}).get("name"),
                        "wall_s": t.get("wall_s"),
                        "critical": t.get("critical"),
                    }
                    for t in ((sections.get("rpc_traces")
                               or {}).get("traces") or [])[:8]
                ],
            },
        }

    # -- history serving ---------------------------------------------------

    def _history_for_serving(self):
        """The history ``GET /history`` answers from: this collector's
        own rings normally; in HA tail mode (never scraped, peer sink
        configured) a history RECONSTRUCTED from the peer's JSONL —
        the fallback secondary answers windowed queries, not just the
        newest snapshot. The reconstruction is cached on the file's
        (size, mtime) signature like the fallback gang view."""
        live = self.history
        if live is not None and live.sweeps > 0:
            return live
        if not self.fallback_jsonl:
            return live
        with self._lock:
            never_scraped = not any(st.scrapes for st in
                                    self._ranks.values())
        if not never_scraped:
            return live
        import os as _os

        from sparktorch_tpu.obs.history import (DEFAULT_RETENTION,
                                                MetricsHistory)

        try:
            st = _os.stat(self.fallback_jsonl)
            sig = (st.st_size, st.st_mtime_ns)
        except OSError:
            return live
        cached = self._fallback_history_cache
        if cached is None or cached[0] != sig:
            try:
                rebuilt = MetricsHistory.from_jsonl(
                    self.fallback_jsonl,
                    retention=(live.retention if live is not None
                               else DEFAULT_RETENTION))
            except OSError as e:
                _LOG.warning(
                    f"[sparktorch_tpu:collector] fallback history "
                    f"{self.fallback_jsonl!r} unreadable: {e}")
                return live
            cached = (sig, rebuilt)
            self._fallback_history_cache = cached
            self.telemetry.counter("collector.fallback_history_builds_total")
        return cached[1]

    def _handle_history(self, params: Mapping[str, Any]
                        ) -> Tuple[int, Dict[str, Any]]:
        """One ``GET /history`` request (params = parsed query string,
        one value per key). No ``name`` -> the describe block + series
        list; with one -> the named derived query."""
        from sparktorch_tpu.obs.history import parse_labels

        history = self._history_for_serving()
        if history is None:
            return 404, {"ok": False, "error": "history tier disabled"}
        cached = self._fallback_history_cache
        source = ("fallback_jsonl"
                  if cached is not None and history is cached[1]
                  else "live")
        name = params.get("name")
        if not name:
            doc = history.describe()
            doc["series"] = history.series_names()
            doc["source"] = source
            return 200, doc
        try:
            doc = history.query(
                params.get("query") or "series",
                str(name),
                labels=parse_labels(params.get("labels")),
                window_s=(float(params["window_s"])
                          if params.get("window_s") else None),
                q=float(params["q"]) if params.get("q") else None,
                field=params.get("field") or None,
                since_ts=(float(params["since_ts"])
                          if params.get("since_ts") else None),
            )
        except ValueError as e:
            return 400, {"ok": False, "error": str(e)}
        doc["source"] = source
        return 200, doc

    # -- control plane -----------------------------------------------------

    def _check_ctl_token(self, token: Optional[str]) -> bool:
        if self.ctl is not None:
            return bool(self.ctl.check_token(token))
        if self.ctl_token:
            return token == self.ctl_token
        return True  # unguarded (loopback dev rigs)

    def _handle_ctl(self, body: Mapping[str, Any],
                    token: Optional[str]) -> Tuple[int, Dict[str, Any]]:
        """One ``POST /ctl`` request: with a ``rank``, forward the
        verb to that rank's exporter (the collector is the control
        fan-out exactly as it is the scrape fan-in — the controller
        needs one address); without one, dispatch to this collector's
        own registry (e.g. an elastic controller's ``resize``)."""
        if not self._check_ctl_token(token):
            return 403, {"ok": False, "error": "bad ctl token"}
        verb = body.get("verb")
        rank = body.get("rank")
        args = body.get("args") or {}
        labels = {"verb": str(verb)}
        if rank is not None:
            st = self._ranks.get(str(rank))
            if st is None:
                return 404, {"ok": False,
                             "error": f"unknown rank {rank!r}"}
            headers = {"X-Ctl-Token": token} if token else None
            try:
                reply = post_json(st.url + "/ctl",
                                  {"verb": verb, "args": args},
                                  timeout=self.scrape_timeout_s,
                                  headers=headers)
            except ScrapeError as e:
                self.telemetry.counter("collector.ctl_forward_errors_total",
                                       labels=labels)
                return 502, {"ok": False, "rank": str(rank),
                             "error": str(e)}
            self.telemetry.counter("collector.ctl_forwards_total",
                                   labels=labels)
            return 200, {"ok": True, "rank": str(rank), "reply": reply}
        if self.ctl is None:
            return 404, {"ok": False,
                         "error": "no collector-side ctl registry"}
        try:
            result = self.ctl.handle(verb, args)
        except KeyError:
            return 400, {"ok": False, "error": f"unknown verb {verb!r}"}
        except Exception as e:  # verb handlers are user code
            return 500, {"ok": False,
                         "error": f"{type(e).__name__}: {e}"}
        self.telemetry.counter("collector.ctl_requests_total",
                               labels=labels)
        return 200, {"ok": True, "verb": verb, "result": result}

    # -- HTTP surface ------------------------------------------------------

    def start(self, serve: bool = True,
              poll_loop: bool = True) -> "FleetCollector":
        """Start the HTTP surface (``/gang``, ``/metrics``,
        ``/telemetry``, ``/history``, ``/goodput``, ``/profile``,
        ``/health``, ``/skew``, ``POST /ctl``) and — when
        ``poll_interval_s`` > 0 and ``poll_loop`` — the background
        scrape loop."""
        if serve and self._httpd is None:
            from http.server import (
                BaseHTTPRequestHandler,
                ThreadingHTTPServer,
            )

            from sparktorch_tpu.obs.prom import (
                CONTENT_TYPE as PROM_CONTENT_TYPE,
                render_prometheus,
            )

            collector = self

            class Handler(BaseHTTPRequestHandler):
                def log_message(self, *a):
                    pass

                def _send(self, code: int, body: bytes = b"",
                          content_type: Optional[str] = None):
                    self.send_response(code)
                    if content_type:
                        self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    if body:
                        self.wfile.write(body)

                def do_GET(self):
                    route = self.path.split("?", 1)[0]
                    if route == "/":
                        self._send(200, b"sparktorch-tpu fleet collector")
                    elif route == "/history":
                        from urllib.parse import parse_qs

                        qs = parse_qs(self.path.partition("?")[2])
                        params = {k: v[0] for k, v in qs.items() if v}
                        code, doc = collector._handle_history(params)
                        self._send(code, json.dumps(doc).encode(),
                                   content_type="application/json")
                    elif route == "/goodput":
                        doc = collector.goodput_view()
                        if doc is None:
                            self._send(404, json.dumps(
                                {"ok": False,
                                 "error": "no goodput ledger published "
                                          "by any scraped rank"}).encode(),
                                content_type="application/json")
                        else:
                            self._send(200, json.dumps(doc).encode(),
                                       content_type="application/json")
                    elif route == "/profile":
                        doc = collector.profile_view()
                        if doc is None:
                            self._send(404, json.dumps(
                                {"ok": False,
                                 "error": "no stack profile published "
                                          "by any scraped rank"}).encode(),
                                content_type="application/json")
                        else:
                            self._send(200, json.dumps(doc).encode(),
                                       content_type="application/json")
                    elif route == "/health":
                        doc = collector.health_view()
                        if doc is None:
                            self._send(404, json.dumps(
                                {"ok": False,
                                 "error": "no health ledger published "
                                          "by any scraped rank"}).encode(),
                                content_type="application/json")
                        else:
                            self._send(200, json.dumps(doc).encode(),
                                       content_type="application/json")
                    elif route == "/skew":
                        doc = collector.skew_view()
                        if doc is None:
                            self._send(404, json.dumps(
                                {"ok": False,
                                 "error": "no skew stamps published "
                                          "by any scraped rank"}).encode(),
                                content_type="application/json")
                        else:
                            self._send(200, json.dumps(doc).encode(),
                                       content_type="application/json")
                    elif route == "/gang":
                        self._send(200,
                                   json.dumps(collector.gang_view()).encode(),
                                   content_type="application/json")
                    elif route == "/metrics":
                        text = render_prometheus(collector.merged_snapshot())
                        self._send(200, text.encode(),
                                   content_type=PROM_CONTENT_TYPE)
                    elif route == "/telemetry":
                        self._send(
                            200,
                            json.dumps(collector.merged_snapshot()).encode(),
                            content_type="application/json")
                    else:
                        self._send(404)

                def do_POST(self):
                    route = self.path.split("?", 1)[0]
                    if route != "/ctl":
                        self._send(404)
                        return
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("ctl body must be an object")
                    except (ValueError, TypeError) as e:
                        self._send(400, str(e).encode())
                        return
                    token = self.headers.get("X-Ctl-Token")
                    code, reply = collector._handle_ctl(body, token)
                    self._send(code, json.dumps(reply).encode(),
                               content_type="application/json")

            self._httpd = ThreadingHTTPServer((self.host, self.port),
                                              Handler)
            self.port = self._httpd.server_address[1]
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True
            )
            self._http_thread.start()
        if poll_loop and self.poll_interval_s > 0 \
                and self._poll_thread is None:
            self._poll_stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="fleet-collector-poll",
            )
            self._poll_thread.start()
        return self

    def _poll_loop(self) -> None:
        while not self._poll_stop.is_set():
            try:
                self.poll()
            except Exception as e:  # the loop must outlive any sweep
                _LOG.warning(
                    f"[sparktorch_tpu:collector] poll sweep failed: "
                    f"{type(e).__name__}: {e}"
                )
            self._poll_stop.wait(self.poll_interval_s)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None
        if self._scrape_pool is not None:
            # wait=False: a target hung past its socket timeout must
            # not hold collector shutdown hostage.
            self._scrape_pool.shutdown(wait=False)
            self._scrape_pool = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
