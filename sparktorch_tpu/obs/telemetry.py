"""Run-scoped telemetry event bus: spans, counters, histograms, gauges.

The reference's observability story is a ``verbose`` int gating raw
``print`` of per-partition losses (SURVEY §5 "Metrics: minimal",
"Tracing: none"). This bus is the structured replacement every layer
shares: trainers and the param server record into one
:class:`Telemetry`, sinks stream JSONL events, and
:mod:`sparktorch_tpu.obs.prom` renders the same state as
Prometheus text for the param server's ``/metrics`` route.

Design constraints:

- **Hot-path cheap.** A counter bump is a dict add under one lock; a
  span is two ``perf_counter`` calls. Nothing here touches the device
  unless the caller explicitly asks (``Span.sync``).
- **Bounded memory.** Histograms keep streaming count/sum/min/max plus
  a fixed-size ring of recent samples for the percentile roll-ups — a
  million-step run holds O(ring), not O(steps).
- **Thread-safe.** Hogwild workers, the param-server writer thread,
  and HTTP handler threads all record into the same instance.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import contextlib

import numpy as np

# (name, (("k","v"), ...)) — one metric series per name+labels pair.
MetricKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def wall_ts() -> float:
    """The sanctioned wall-clock TIMESTAMP read (``time.time()``):
    cross-process joinable stamps for events, heartbeats, history
    points, and snapshot ``ts`` fields. This is the named helper the
    ``make lint-obs`` wall-clock rule exempts — DURATION math must use
    ``time.perf_counter()`` (wall clock steps under NTP slew, and a
    negative or doubled "duration" has burned this codebase before);
    anything that genuinely needs the epoch reads it through here so
    the grep can tell timestamps from arithmetic."""
    return time.time()


def _key(name: str, labels: Optional[Dict[str, Any]]) -> MetricKey:
    if not labels:
        return (name, ())
    return (name, tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def format_key(key: MetricKey) -> str:
    """``name{k=v,...}`` — the flat-dict spelling used by snapshots.
    ',' and '=' are reserved delimiters: label values must be simple
    tokens (ranks, hosts, routes), never free-form strings like
    filesystem paths — those belong on events, not labels."""
    name, labels = key
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class _Hist:
    """Streaming histogram: exact count/sum/min/max, percentiles from a
    bounded ring of the most recent samples."""

    __slots__ = ("count", "total", "vmin", "vmax", "ring")

    def __init__(self, ring_size: int):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.ring: "collections.deque[float]" = collections.deque(
            maxlen=ring_size
        )

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        self.ring.append(v)

    def state(self) -> Tuple[int, float, float, float, Tuple[float, ...]]:
        """A consistent COPY of the streaming aggregates + ring — the
        cheap part a reader takes under the bus lock, so the expensive
        percentile math can run OUTSIDE it (see
        :func:`rollup_from_state`)."""
        return (self.count, self.total, self.vmin, self.vmax,
                tuple(self.ring))

    def rollup(self) -> Dict[str, Any]:
        """p50/p95/p99 + streaming aggregates; safe on empty and
        single-sample histograms (percentiles of one sample are that
        sample; an empty histogram rolls up to count=0 with null
        quantiles rather than raising)."""
        return rollup_from_state(self.state())


def rollup_from_state(state: Tuple[int, float, float, float,
                                   Tuple[float, ...]]) -> Dict[str, Any]:
    """Percentile roll-up from a :meth:`_Hist.state` copy. Kept OUT of
    the bus lock on purpose: the ``np.percentile`` over a 4096-sample
    ring is the expensive half of a histogram read, and computing it
    under the lock serialized every bus writer against every reader —
    the router's per-request p50 reads measurably throttled the very
    replicas it was routing to (3x throughput at 400 threads). Readers
    snapshot the ring under the lock, then compute here."""
    count, total, vmin, vmax, ring = state
    if count == 0:
        return {"count": 0, "sum": 0.0, "mean": None, "min": None,
                "max": None, "p50": None, "p95": None, "p99": None}
    samples = np.asarray(ring, dtype=np.float64)
    p50, p95, p99 = np.percentile(samples, [50.0, 95.0, 99.0])
    return {
        "count": count,
        "sum": total,
        "mean": total / count,
        "min": vmin,
        "max": vmax,
        "p50": float(p50),
        "p95": float(p95),
        "p99": float(p99),
    }


class Span:
    """One timed region, yielded by :meth:`Telemetry.span`.

    ``duration_s`` is wall clock by default. Call :meth:`sync` with the
    region's output arrays to fold device completion into the timing —
    JAX dispatch is async, so without a sync a span around a compiled
    call measures enqueue time, not compute (the ROUND4 "honest
    timing" lesson).
    """

    __slots__ = ("name", "path", "labels", "depth", "t0", "duration_s",
                 "synced")

    def __init__(self, name: str, path: str, labels: Dict[str, Any],
                 depth: int):
        self.name = name
        self.path = path
        self.labels = labels
        self.depth = depth
        self.t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.synced = False

    def sync(self, *arrays: Any) -> None:
        """Block until the given device values are materialized, so the
        span's duration covers their compute. No-op on host values."""
        import jax

        jax.block_until_ready(arrays)
        self.synced = True


class Telemetry:
    """The event bus. One instance per run scope (a trainer invocation,
    a parameter server, the bench CLI); a process-global default exists
    for code that doesn't thread one through (:func:`get_telemetry`)."""

    def __init__(self, run_id: Optional[str] = None,
                 ring_size: int = 4096):
        self.run_id = run_id or time.strftime("%Y%m%dT%H%M%S")
        self._ring_size = ring_size
        self._lock = threading.Lock()
        self._counters: Dict[MetricKey, float] = {}
        self._gauges: Dict[MetricKey, float] = {}
        self._hists: Dict[MetricKey, _Hist] = {}
        self._spans: Dict[MetricKey, _Hist] = {}
        self._info: Dict[MetricKey, str] = {}
        self._sections: Dict[str, Any] = {}
        self._sinks: List[Callable[[Dict[str, Any]], None]] = []
        self._tls = threading.local()

    def set_run_id(self, run_id: str) -> None:
        """Adopt a (typically gang-minted) run id mid-scope: every
        event emitted from here on — spans included — carries it, so
        per-rank streams sharing one gang run_id can be joined by a
        collector. Metric state is unaffected."""
        self.run_id = str(run_id)

    # -- recording ---------------------------------------------------------

    def counter(self, name: str, inc: float = 1.0,
                labels: Optional[Dict[str, Any]] = None) -> float:
        """Monotonic counter bump; returns the new value."""
        if inc < 0:
            raise ValueError(f"counter {name!r}: negative increment {inc}")
        k = _key(name, labels)
        with self._lock:
            value = self._counters.get(k, 0.0) + inc
            self._counters[k] = value
        return value

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, Any]] = None) -> None:
        """Last-write-wins instantaneous value (queue depth, version,
        last-seen timestamp)."""
        with self._lock:
            self._gauges[_key(name, labels)] = float(value)

    def info(self, name: str, value: str,
             labels: Optional[Dict[str, Any]] = None) -> None:
        """Last-write-wins STRING annotation (a trace-viewer URL, a
        build id) — the non-numeric sibling of a gauge. Snapshots carry
        these under ``info``, so they ride the ``/telemetry`` JSON;
        the Prometheus renderer emits them build_info-style (value 1
        with the string as a label)."""
        with self._lock:
            self._info[_key(name, labels)] = str(value)

    def info_value(self, name: str,
                   labels: Optional[Dict[str, Any]] = None) -> Optional[str]:
        with self._lock:
            return self._info.get(_key(name, labels))

    def set_section(self, name: str, payload: Any) -> None:
        """Attach a named JSON-serializable SECTION to snapshots (the
        last published xprof analysis, a gang budget). Sections ride
        ``snapshot()["sections"]`` — so ``/telemetry`` scrapes and
        JSONL dumps carry structured documents the flat metric dicts
        cannot (a fleet collector merges them cross-rank) — and are
        ignored by the Prometheus renderer. Last write wins; ``None``
        removes the section."""
        with self._lock:
            if payload is None:
                self._sections.pop(name, None)
            else:
                self._sections[name] = payload

    def get_section(self, name: str) -> Optional[Any]:
        with self._lock:
            return self._sections.get(name)

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, Any]] = None) -> None:
        """Histogram sample (step time, latency, batch fill)."""
        k = _key(name, labels)
        with self._lock:
            hist = self._hists.get(k)
            if hist is None:
                hist = self._hists[k] = _Hist(self._ring_size)
            hist.observe(value)

    @contextlib.contextmanager
    def span(self, name: str,
             labels: Optional[Dict[str, Any]] = None) -> Iterator[Span]:
        """Nestable timed region. The span records under its full
        slash-joined path (``train/step`` inside ``train``), so nested
        timings stay attributable; completion emits one event to the
        sinks and one histogram sample."""
        stack: List[Span] = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        parent = stack[-1] if stack else None
        path = f"{parent.path}/{name}" if parent is not None else name
        span = Span(name, path, dict(labels or {}), depth=len(stack))
        stack.append(span)
        try:
            yield span
        finally:
            span.duration_s = time.perf_counter() - span.t0
            stack.pop()
            k = _key(path, labels)
            with self._lock:
                hist = self._spans.get(k)
                if hist is None:
                    hist = self._spans[k] = _Hist(self._ring_size)
                hist.observe(span.duration_s)
            self.event("span", name=path, dur_s=span.duration_s,
                       depth=span.depth, synced=span.synced,
                       **span.labels)

    def event(self, kind: str, **fields: Any) -> None:
        """Emit one structured event to every attached sink."""
        if not self._sinks:
            return
        record = {"ts": time.time(), "kind": kind, "run_id": self.run_id,
                  **fields}
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            sink(record)

    # -- sinks -------------------------------------------------------------

    def add_sink(self, sink: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def add_jsonl_sink(self, path: str, append: bool = True):
        """Stream events to a JSONL file (directories created, append
        by default so multi-phase runs accumulate). Returns the sink;
        ``sink.close()`` detaches and closes it."""
        from sparktorch_tpu.obs.sinks import JsonlSink

        sink = JsonlSink(path, append=append, telemetry=self)
        self.add_sink(sink)
        return sink

    # -- read side ---------------------------------------------------------

    def counter_value(self, name: str,
                      labels: Optional[Dict[str, Any]] = None) -> float:
        with self._lock:
            return self._counters.get(_key(name, labels), 0.0)

    def gauge_value(self, name: str,
                    labels: Optional[Dict[str, Any]] = None
                    ) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_key(name, labels))

    def histogram(self, name: str,
                  labels: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        # Ring snapshotted under the lock, percentiles computed OUTSIDE
        # it: per-request readers (the router's p50 weight) must not
        # serialize against the writers they observe.
        with self._lock:
            hist = self._hists.get(_key(name, labels))
            state = hist.state() if hist is not None else None
        return (rollup_from_state(state) if state is not None
                else rollup_from_state((0, 0.0, 0.0, 0.0, ())))

    def span_rollup(self, path: str,
                    labels: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        with self._lock:
            hist = self._spans.get(_key(path, labels))
            state = hist.state() if hist is not None else None
        return (rollup_from_state(state) if state is not None
                else rollup_from_state((0, 0.0, 0.0, 0.0, ())))

    def snapshot(self) -> Dict[str, Any]:
        """One coherent view of every metric: counters and gauges as
        flat ``name{labels}`` -> value dicts, histograms and spans as
        roll-ups. This is what the JSONL dump writes and what the
        Prometheus renderer consumes — one source of truth, so the
        ``/metrics`` route can never disagree with the JSONL sink.

        The lock covers only the cheap copies (dicts + ring
        snapshots); the percentile math over every histogram runs
        outside it, so a collector scrape or snapshot-hungry reader
        cannot stall the recording hot path."""
        with self._lock:
            snap = {
                "run_id": self.run_id,
                "ts": time.time(),
                "counters": {format_key(k): v
                             for k, v in sorted(self._counters.items())},
                "gauges": {format_key(k): v
                           for k, v in sorted(self._gauges.items())},
                "info": {format_key(k): v
                         for k, v in sorted(self._info.items())},
            }
            hist_states = {format_key(k): h.state()
                           for k, h in sorted(self._hists.items())}
            span_states = {format_key(k): h.state()
                           for k, h in sorted(self._spans.items())}
            sections = dict(self._sections) if self._sections else None
        snap["histograms"] = {k: rollup_from_state(s)
                              for k, s in hist_states.items()}
        snap["spans"] = {k: rollup_from_state(s)
                         for k, s in span_states.items()}
        if sections:
            snap["sections"] = sections
        return snap

    def dump(self, path: str, append: bool = True) -> Dict[str, Any]:
        """Write the snapshot as one JSONL line (the CLI dump format);
        returns the snapshot."""
        from sparktorch_tpu.obs.sinks import write_jsonl

        snap = self.snapshot()
        write_jsonl(path, [{"kind": "snapshot", **snap}], append=append)
        return snap

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._spans.clear()
            self._info.clear()
            self._sections.clear()

    # -- pickling ----------------------------------------------------------
    # A bus rides inside objects that get dill-dumped (a fitted model
    # holding a BatchPredictor; a worker closure shipped to an
    # executor). Locks, thread-locals, and open-file sinks cannot
    # cross a pickle boundary — and must not: the deserialized copy is
    # a NEW scope on the far side. Metric state (plain dicts + rings)
    # does travel, so a restored object keeps its numbers.

    def __getstate__(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "run_id": self.run_id,
                "_ring_size": self._ring_size,
                "_counters": dict(self._counters),
                "_gauges": dict(self._gauges),
                "_hists": dict(self._hists),
                "_spans": dict(self._spans),
                "_info": dict(self._info),
                "_sections": dict(self._sections),
            }

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self.__dict__.setdefault("_info", {})  # pre-info pickles
        self.__dict__.setdefault("_sections", {})  # pre-section pickles
        self._lock = threading.Lock()
        self._sinks = []
        self._tls = threading.local()


# ---------------------------------------------------------------------------
# Process-global default
# ---------------------------------------------------------------------------

_GLOBAL: Optional[Telemetry] = None
_GLOBAL_LOCK = threading.Lock()


def get_telemetry() -> Telemetry:
    """The process-global bus — the default for call sites that don't
    thread a run-scoped instance through."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Telemetry(run_id="global")
        return _GLOBAL


def set_telemetry(telemetry: Optional[Telemetry]) -> None:
    """Swap the process-global bus (tests; run-scoped CLI entries)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = telemetry
