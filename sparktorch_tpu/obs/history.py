"""Retained metrics history: the collector's memory.

Everything the obs stack serves today is point-in-time — the
collector's ``/telemetry`` is the LATEST merged snapshot, the router
reads an instantaneous p50, and the drift/elasticity consumers the
ROADMAP wants (burn rates, sustained breaches, trends) have nothing to
read them from. :class:`MetricsHistory` is the bounded time-series
tier that closes that:

- **append**: every :meth:`~sparktorch_tpu.obs.collector.
  FleetCollector.poll` sweep appends one POINT per metric series —
  ``(ts, value)`` for counters and gauges, ``(ts, rollup)`` for
  histogram/span digests — into a per-series ring with configurable
  retention. Cost is O(series) dict/deque appends per sweep; memory is
  O(series x retention), never O(run length).
- **derived queries**: :meth:`rate` (reset-aware per-second counter
  increase over a window), :meth:`percentile_over` (windowed
  percentile-of-percentiles across the retained per-sweep digests),
  :meth:`delta_since` (reset-aware increase since a timestamp), and
  raw :meth:`series` — exposed both as this Python API and as the
  collector's ``GET /history`` route.
- **timestamps come from the snapshot** (``snapshot["ts"]``), never
  from the wall clock at append time — a scripted metric sequence
  replays deterministically, which is what makes the golden tests
  exact and the JSONL reconstruction honest.
- **spill / reconstruct**: an optional JSONL spill appends one compact
  record per sweep; :meth:`from_jsonl` rebuilds a history from a spill
  file OR a collector sink (``gang_snapshot`` records) — the HA
  fallback-tail mode (PR 8) can therefore serve ``/history``, not just
  the newest snapshot, while the primary is dark.

Series are matched like :func:`~sparktorch_tpu.obs.collector.
snapshot_histogram`: by name + a label SUBSET (the collector re-keys
scraped series with rank/host labels; a consumer asking for
``wire_latency_s{shard=2}`` must find it whatever target it was
scraped from). When several series match, the one with the most
retained points wins.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

import numpy as np

from sparktorch_tpu.obs.prom import _parse_flat_key  # shared key grammar

DEFAULT_RETENTION = 512

# Sweep-record kinds from_jsonl understands: this module's own spill
# records, the collector sink's merged snapshots, and plain telemetry
# dumps — all carry ts + counters/gauges/histograms.
_RECORD_KINDS = ("history_sweep", "gang_snapshot", "snapshot")

# Snapshot sections retained per sweep, with the point shape each one
# appends (scalar vs digest).
_SCALAR_SECTIONS = ("counters", "gauges")
_DIGEST_SECTIONS = ("histograms", "spans")

_DIGEST_FIELDS = ("count", "sum", "mean", "min", "max", "p50", "p95",
                  "p99")


class _Series:
    __slots__ = ("kind", "name", "labels", "points")

    def __init__(self, flat: str, kind: str, retention: int):
        self.kind = kind  # counter | gauge | histogram | span
        # Parsed once at creation: the flat key is immutable, and the
        # per-sweep rule evaluations would otherwise re-parse every
        # series' key grammar on every query (measured in the
        # collector-sweep overhead budget).
        self.name, self.labels = _parse_flat_key(flat)
        self.points: "deque[Tuple[float, Any]]" = deque(maxlen=retention)


def _increase(points: List[Tuple[float, float]]) -> float:
    """Reset-aware monotonic increase over consecutive points: a value
    DROP is a counter reset (process restart), and the post-reset value
    is itself increase — never a negative delta."""
    total = 0.0
    for (_, v0), (_, v1) in zip(points, points[1:]):
        total += (v1 - v0) if v1 >= v0 else v1
    return total


class MetricsHistory:
    """Bounded per-series time-series rings with derived queries.

    Thread-safe: the collector's poll loop appends while ``/history``
    handler threads query. All query windows are measured back from
    the NEWEST retained point's timestamp (not the wall clock), so a
    replayed scripted sequence answers identically every time.
    """

    def __init__(self, retention: int = DEFAULT_RETENTION,
                 spill_jsonl: Optional[str] = None):
        if retention < 2:
            raise ValueError(f"retention must be >= 2, got {retention}")
        self.retention = int(retention)
        self.spill_jsonl = spill_jsonl
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        self._last_ts: Optional[float] = None
        self.sweeps = 0

    # -- append --------------------------------------------------------------

    def append(self, snapshot: Mapping[str, Any],
               ts: Optional[float] = None) -> None:
        """Retain one sweep. ``snapshot`` is a telemetry/merged
        snapshot dict; its own ``ts`` stamps every point unless an
        explicit ``ts`` overrides it (scripted sequences)."""
        when = float(ts if ts is not None
                     else snapshot.get("ts") or 0.0)
        spill: Dict[str, Any] = {}
        with self._lock:
            for section, kind in (("counters", "counter"),
                                  ("gauges", "gauge"),
                                  ("histograms", "histogram"),
                                  ("spans", "span")):
                table = snapshot.get(section)
                if not isinstance(table, Mapping):
                    continue
                digest = section in _DIGEST_SECTIONS
                for flat, value in table.items():
                    series = self._series.get(flat)
                    if series is None:
                        series = self._series[flat] = _Series(
                            flat, kind, self.retention)
                    if digest:
                        if not isinstance(value, Mapping):
                            continue
                        point = {k: value.get(k) for k in _DIGEST_FIELDS}
                    else:
                        point = float(value)
                    series.points.append((when, point))
                    if self.spill_jsonl:
                        spill.setdefault(section, {})[flat] = point
            self._last_ts = when
            self.sweeps += 1
        if self.spill_jsonl and spill:
            from sparktorch_tpu.obs.sinks import write_jsonl

            write_jsonl(self.spill_jsonl,
                        [{"kind": "history_sweep", "ts": when, **spill}],
                        append=True)

    @classmethod
    def from_jsonl(cls, path: str,
                   retention: int = DEFAULT_RETENTION) -> "MetricsHistory":
        """Rebuild a history from a spill file or a collector sink —
        the HA fallback's read path: a secondary that never scraped
        can still answer windowed queries from the primary's records."""
        from sparktorch_tpu.obs.sinks import read_jsonl

        history = cls(retention=retention)
        for rec in read_jsonl(path):
            if rec.get("kind") in _RECORD_KINDS and rec.get("ts") is not None:
                history.append(rec)
        return history

    # -- series lookup -------------------------------------------------------

    def _match_locked(self, name: str,
                      labels: Optional[Mapping[str, Any]]) -> Optional[str]:
        """Best-matching retained series key: name + label subset,
        most points wins (caller holds the lock)."""
        want = {str(k): str(v) for k, v in (labels or {}).items()}
        best_key, best_n = None, -1
        for flat, series in self._series.items():
            if series.name != name:
                continue
            have = series.labels
            if any(have.get(k) != v for k, v in want.items()):
                continue
            if len(series.points) > best_n:
                best_key, best_n = flat, len(series.points)
        return best_key

    def _points(self, name: str, labels: Optional[Mapping[str, Any]],
                window_s: Optional[float]) -> Tuple[Optional[str],
                                                    List[Tuple[float, Any]]]:
        with self._lock:
            key = self._match_locked(name, labels)
            if key is None:
                return None, []
            pts = list(self._series[key].points)
        if window_s is not None and pts:
            cutoff = pts[-1][0] - float(window_s)
            pts = [p for p in pts if p[0] >= cutoff]
        return key, pts

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def n_series(self) -> int:
        with self._lock:
            return len(self._series)

    # -- derived queries -----------------------------------------------------

    def series(self, name: str,
               labels: Optional[Mapping[str, Any]] = None,
               window_s: Optional[float] = None,
               field: Optional[str] = None) -> List[Tuple[float, Any]]:
        """Raw retained points ``[(ts, value), ...]`` (oldest first).
        ``field`` projects one digest field (``p99``, ``count``, …) out
        of histogram/span points; None points are dropped under a
        projection (an empty sweep's digest has null quantiles)."""
        _, pts = self._points(name, labels, window_s)
        if field is None:
            return pts
        return [(ts, v.get(field)) for ts, v in pts
                if isinstance(v, Mapping) and v.get(field) is not None]

    def latest(self, name: str,
               labels: Optional[Mapping[str, Any]] = None,
               field: Optional[str] = None) -> Optional[Any]:
        """Newest retained value (field-projected for digests). Peeks
        the ring tail directly — the per-sweep rule evaluations must
        not copy a full retention window to read one point."""
        with self._lock:
            key = self._match_locked(name, labels)
            if key is None:
                return None
            points = self._series[key].points
            if not points:
                return None
            value = points[-1][1]
        if field is None:
            return value
        if isinstance(value, Mapping):
            return value.get(field)
        return None

    def rate(self, name: str,
             labels: Optional[Mapping[str, Any]] = None,
             window_s: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a counter over the window (the whole
        retention when None): reset-aware total increase divided by the
        covered time span. None with fewer than two points or a zero
        span — "no signal", which callers must not read as zero."""
        _, pts = self._points(name, labels, window_s)
        pts = [(ts, float(v)) for ts, v in pts
               if not isinstance(v, Mapping)]
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        return _increase(pts) / span

    def delta_since(self, name: str, since_ts: float,
                    labels: Optional[Mapping[str, Any]] = None
                    ) -> Optional[float]:
        """Reset-aware increase from the newest point at-or-before
        ``since_ts`` (or the oldest retained point when the window
        predates retention) to the newest point. None when nothing is
        retained."""
        _, pts = self._points(name, labels, None)
        pts = [(ts, float(v)) for ts, v in pts
               if not isinstance(v, Mapping)]
        if not pts:
            return None
        start = 0
        for i, (ts, _) in enumerate(pts):
            if ts <= float(since_ts):
                start = i
        return _increase(pts[start:]) if len(pts) > start + 1 else 0.0

    def percentile_over(self, name: str, q: float,
                        labels: Optional[Mapping[str, Any]] = None,
                        window_s: Optional[float] = None,
                        field: str = "p99") -> Optional[float]:
        """Windowed percentile-of-percentiles: the ``q``-th percentile
        (0-100) of the per-sweep ``field`` digests retained in the
        window — e.g. ``percentile_over("wire_latency_s", 90,
        field="p99", window_s=30)`` is "the p99 level the worst decile
        of recent sweeps saw". None when no digest in the window
        carries the field."""
        values = [v for _, v in self.series(name, labels,
                                            window_s=window_s,
                                            field=field)]
        if not values:
            return None
        return float(np.percentile(np.asarray(values, dtype=np.float64),
                                   float(q)))

    # -- sweep-level deltas (postmortem input) -------------------------------

    def deltas_since(self, since_ts: float,
                     max_series: int = 64) -> Dict[str, float]:
        """Nonzero counter increases since ``since_ts`` across every
        retained counter series, largest first, capped — the
        "last-good metrics delta" block a postmortem bundle carries."""
        # Each ring is read directly by its exact flat key — routing
        # through delta_since would re-run the subset MATCH per counter
        # (O(counters x series) on the supervisor's death path, and a
        # bare key could resolve to a superset-labeled sibling).
        with self._lock:
            rings = [(flat, list(s.points))
                     for flat, s in self._series.items()
                     if s.kind == "counter"]
        out: Dict[str, float] = {}
        for flat, raw in rings:
            pts = [(ts, float(v)) for ts, v in raw
                   if not isinstance(v, Mapping)]
            if not pts:
                continue
            start = 0
            for i, (ts, _) in enumerate(pts):
                if ts <= float(since_ts):
                    start = i
            delta = (_increase(pts[start:])
                     if len(pts) > start + 1 else 0.0)
            if delta:
                out[flat] = round(delta, 6)
        ranked = sorted(out.items(), key=lambda kv: -abs(kv[1]))
        return dict(ranked[:max_series])

    # -- the /history dispatch ----------------------------------------------

    def query(self, query: str, name: str,
              labels: Optional[Mapping[str, Any]] = None,
              window_s: Optional[float] = None,
              q: Optional[float] = None,
              field: Optional[str] = None,
              since_ts: Optional[float] = None) -> Dict[str, Any]:
        """One ``GET /history`` answer: ``query`` in ``series`` /
        ``rate`` / ``pctile`` / ``delta`` / ``latest``. Raises
        ``ValueError`` on an unknown query or missing required
        argument (the route's 400)."""
        doc: Dict[str, Any] = {"query": query, "name": name,
                               "labels": dict(labels or {}),
                               "window_s": window_s}
        if query == "series":
            doc["points"] = [[ts, v] for ts, v in
                             self.series(name, labels, window_s=window_s,
                                         field=field)]
            doc["field"] = field
        elif query == "rate":
            doc["value"] = self.rate(name, labels, window_s=window_s)
        elif query == "pctile":
            if q is None:
                raise ValueError("pctile query needs q= (0-100)")
            doc["q"] = float(q)
            doc["field"] = field or "p99"
            doc["value"] = self.percentile_over(
                name, float(q), labels, window_s=window_s,
                field=field or "p99")
        elif query == "delta":
            if since_ts is None:
                raise ValueError("delta query needs since_ts=")
            doc["since_ts"] = float(since_ts)
            doc["value"] = self.delta_since(name, float(since_ts), labels)
        elif query == "latest":
            doc["field"] = field
            doc["value"] = self.latest(name, labels, field=field)
        else:
            raise ValueError(f"unknown history query {query!r} (want "
                             f"series/rate/pctile/delta/latest)")
        return doc

    def describe(self) -> Dict[str, Any]:
        """The summary block ``/gang`` and ``/history`` (no args)
        serve: retention shape, sweep count, newest timestamp."""
        with self._lock:
            return {
                "retention": self.retention,
                "sweeps": self.sweeps,
                "n_series": len(self._series),
                "last_ts": self._last_ts,
            }


def parse_labels(spec: Optional[str]) -> Dict[str, str]:
    """``k:v,k2:v2`` (the /history query-string spelling — '=' is
    taken by the query string itself) -> a labels dict."""
    out: Dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"bad label {part!r} (want k:v)")
        k, v = part.split(":", 1)
        out[k.strip()] = v.strip()
    return out
