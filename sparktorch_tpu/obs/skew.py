"""Cross-rank step-skew ledger: wire time vs straggler wait, named.

The goodput ledger (:mod:`sparktorch_tpu.obs.goodput`) attributes
every second of a run per rank, but its biggest recurring thief —
``exposed_comm`` — is ambiguous: a rank blocked in an all-reduce may
be paying real wire time or just waiting for the slowest peer to
arrive, and those two diagnoses demand opposite fixes (overlap /
compress the collective vs fix or evict the straggler). MegaScale
(arXiv:2402.15627) and Google's ML-goodput work both name cross-rank
straggler attribution as the signal that makes large-run triage
tractable; ROADMAP items 3 (goodput-driven elasticity) and 5 (drive
exposed_comm toward zero) are blocked on a referee that can name the
slow rank and the cause.

The split this module computes:

- Each rank's :class:`~sparktorch_tpu.obs.goodput.GoodputLedger`
  stamps a bounded :class:`StepSkewRing` of per-step boundary
  timestamps (step index, enter/exit of the step's collective fence)
  from inside the existing ``step_span()`` close path — ZERO new
  clock sites: the ring receives the span's own perf_counter pair,
  converted to wall time through the ledger's ctor anchor
  (``started_ts + (t - _t0)``), so stamps from different processes
  share the wall clock's epoch and stay comparable. This module
  itself never reads a clock (the sparklint SPK201 stamp-scope pins
  that): every number here is arithmetic over ledger-provided stamps.
- The ring publishes as the ``skew`` telemetry section beside
  ``goodput``; the FleetCollector aligns step indices across scraped
  ranks and calls :func:`merge_sections`, which computes per-step
  arrival skew (last-arrival minus median), charges each step's
  victims' fence waits to that step's laggard, and decomposes the
  run's merged ``exposed_comm`` rank-seconds into ``wire_s`` (real
  collective time every rank pays together) vs ``straggler_wait_s``
  (seconds the fleet spent waiting for the slowest peer).
- A PERSISTENT laggard is named by rank with a cause hypothesis
  cross-referenced from that rank's own goodput/health sections
  (data_wait spike, compile, GC/unattributed idle, preempt) — the
  merged doc is served at ``GET /skew``, rendered by
  ``timeline --skew``, folded into ``/goodput``'s ``biggest_thief``
  when straggler wait dominates wire, and exported as ``skew.*``
  gauges so :func:`skew_alert_rules`'s sustained straggler-fraction
  rule feeds latched firings into the ElasticController's
  ``ctl.scale_signal`` path.

Physics of the decomposition: with a per-step collective fence, every
rank EXITS the fence together (when the last arrival lands), so a
victim's exposed wait at step ``i`` is ``last_enter - enter_victim``
— observable from enter stamps alone — clipped to the victim's own
measured span (a rank cannot have waited longer than it was inside
the step). The per-step waits sum to the fleet's total straggler
seconds; whatever remains of merged ``exposed_comm`` is wire. The sum
is clipped to merged ``exposed_comm`` (skew can also show up as idle
on ranks that fence outside a comm span — claiming more straggler
wait than the ledger saw as comm would break the MECE story the
goodput report tells).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, Mapping, Optional, Tuple

from sparktorch_tpu.obs.alerts import AlertRule
from sparktorch_tpu.obs.telemetry import Telemetry

SECTION = "skew"
RUN_SECTION = "skew_run"

# Per-step detail entries retained in the merged doc (the timeline's
# arrival-bar table); the full decomposition always covers EVERY
# aligned step regardless of this window.
DEFAULT_WINDOW = 32

# A laggard must have topped this many aligned steps AND own this
# share of the fleet's total straggler wait before the verdict calls
# it persistent — one noisy step must not name a rank.
MIN_LAGGARD_STEPS = 3
LAGGARD_DOMINANCE = 0.5


class StepSkewRing:
    """Bounded ring of per-step boundary stamps for ONE rank.

    Each entry is ``(step, count, enter_ts, exit_ts)``: the step index
    the stamp starts at, how many fused steps the span trained, and
    the wall-clock enter/exit of the step span (the collective fence's
    boundary — arrival at the fence is the enter stamp). Stamps are
    recorded by the goodput ledger's ``step_span()`` close path; this
    class never reads a clock. Thread-safe; overflow evicts oldest and
    counts ``dropped`` so the merge can say how much history it lost.
    """

    __slots__ = ("capacity", "_ring", "_dropped", "_lock")

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._ring: Deque[Tuple[int, int, float, float]] = deque(
            maxlen=self.capacity)
        self._dropped = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def record(self, step: int, count: int,
               enter_ts: float, exit_ts: float) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append((int(step), max(1, int(count)),
                               float(enter_ts), float(exit_ts)))

    def snapshot(self) -> Dict[str, Any]:
        """The publishable ``skew`` section body: newest-last stamp
        list plus ring accounting. Stamps serialize as 4-lists so the
        section survives a JSON round-trip unchanged."""
        with self._lock:
            stamps = [[s, c, round(t0, 6), round(t1, 6)]
                      for (s, c, t0, t1) in self._ring]
            dropped = self._dropped
        return {"n_stamps": len(stamps), "capacity": self.capacity,
                "dropped": dropped, "stamps": stamps}


# ---------------------------------------------------------------------------
# Run-level merge (the collector's /skew)
# ---------------------------------------------------------------------------


def _median(values: List[float]) -> float:
    vals = sorted(values)
    n = len(vals)
    if n == 0:
        return 0.0
    mid = n // 2
    if n % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2.0


def _stamps_by_step(doc: Mapping[str, Any]
                    ) -> Dict[int, Tuple[float, float]]:
    """{step: (enter, exit)} from one rank's section, tolerant of
    malformed entries (a torn scrape must not kill the merge)."""
    out: Dict[int, Tuple[float, float]] = {}
    for entry in (doc.get("stamps") or []):
        try:
            step, _count, enter, exit_ = entry[0], entry[1], entry[2], entry[3]
            out[int(step)] = (float(enter), float(exit_))
        except (TypeError, ValueError, IndexError):
            continue
    return out


def _hypothesize_cause(lag: str,
                       goodput_docs: Mapping[str, Mapping[str, Any]],
                       health_docs: Mapping[str, Mapping[str, Any]]
                       ) -> Tuple[str, List[str]]:
    """Name WHY the laggard is slow from its own ledger, judged
    against its peers' medians: a data_wait spike, compile storms,
    preemption downtime, or unattributed time (the GC / host-stall
    shape — seconds the laggard's own ledger could not explain are
    exactly where a straggling host hides). Health anomalies ride as
    corroborating evidence whatever the bucket verdict."""
    evidence: List[str] = []
    gdoc = goodput_docs.get(lag)
    cause = "unknown"
    if isinstance(gdoc, Mapping) and isinstance(gdoc.get("fractions"),
                                                Mapping):
        fr = gdoc["fractions"]
        peers = [d for r, d in goodput_docs.items()
                 if r != lag and isinstance(d, Mapping)
                 and isinstance(d.get("fractions"), Mapping)]

        def peer_med(key: str) -> float:
            return _median([float(p["fractions"].get(key) or 0.0)
                            for p in peers]) if peers else 0.0

        data_wait = float(fr.get("data_wait") or 0.0)
        compile_f = float(fr.get("compile") or 0.0)
        idle = float(fr.get("idle") or 0.0)
        downtime = (float(fr.get("restart_downtime") or 0.0)
                    + float(fr.get("resize_downtime") or 0.0))
        compiles = int(gdoc.get("compiles") or 0)
        peer_compiles = _median([float(p.get("compiles") or 0)
                                 for p in peers]) if peers else 0.0
        if data_wait > max(2.0 * peer_med("data_wait"), 0.02):
            cause = "data_wait"
            evidence.append(
                f"data_wait {data_wait:.1%} vs peer median "
                f"{peer_med('data_wait'):.1%}")
        elif (compile_f > max(2.0 * peer_med("compile"), 0.02)
              or compiles > peer_compiles + 1):
            cause = "compile"
            evidence.append(
                f"{compiles} compiles ({compile_f:.1%} of wall) vs "
                f"peer median {peer_compiles:.0f}")
        elif downtime > max(2.0 * (peer_med("restart_downtime")
                                   + peer_med("resize_downtime")), 0.02):
            cause = "preempt"
            evidence.append(
                f"restart/resize downtime {downtime:.1%} of wall")
        elif idle > 2.0 * peer_med("idle") + 0.05:
            # Time the laggard's OWN ledger could not attribute: the
            # GC-pause / host-stall / noisy-neighbor shape.
            cause = "gc_or_unattributed"
            evidence.append(
                f"unattributed (idle) {idle:.1%} vs peer median "
                f"{peer_med('idle'):.1%}")
    hdoc = health_docs.get(lag)
    if isinstance(hdoc, Mapping):
        anoms = hdoc.get("anomalies") or []
        kinds = sorted({str((a or {}).get("kind"))
                        for a in anoms if isinstance(a, Mapping)})
        if kinds:
            evidence.append("health anomalies: " + ", ".join(kinds))
    return cause, evidence


def merge_sections(rank_docs: Mapping[Any, Mapping[str, Any]],
                   goodput_docs: Optional[Mapping[Any, Mapping]] = None,
                   health_docs: Optional[Mapping[Any, Mapping]] = None,
                   window: int = DEFAULT_WINDOW) -> Dict[str, Any]:
    """Fold per-rank ``skew`` sections into ONE run-level verdict —
    what ``GET /skew`` serves. Steps present on >= 2 ranks align; per
    step, arrival skew is last-enter minus the median enter, each
    victim's wait is charged to that step's laggard, and the summed
    waits decompose the merged goodput ``exposed_comm`` into
    ``wire_s`` + ``straggler_wait_s``. ``goodput_docs`` /
    ``health_docs`` (the same per-rank sections the collector already
    scraped, keyed by the same ranks) supply the exposed_comm budget
    and the laggard's cause evidence; without them the doc still
    reports raw arrival waits but leaves the decomposition null.

    Stamps are wall-clock, so cross-PROCESS comparability is bounded
    by host clock sync (NTP-class skew is µs–ms, far under the
    step-level stalls this referee exists to name)."""
    per_rank_stamps: Dict[str, Dict[int, Tuple[float, float]]] = {}
    docs: Dict[str, Mapping[str, Any]] = {}
    for rank in sorted(rank_docs, key=str):
        doc = rank_docs[rank]
        if not isinstance(doc, Mapping):
            continue
        r = str(rank)
        docs[r] = doc
        per_rank_stamps[r] = _stamps_by_step(doc)
    gdocs = {str(r): d for r, d in (goodput_docs or {}).items()
             if isinstance(d, Mapping)}
    hdocs = {str(r): d for r, d in (health_docs or {}).items()
             if isinstance(d, Mapping)}

    # Align: step -> {rank: (enter, exit)} on every step >=2 ranks saw.
    by_step: Dict[int, Dict[str, Tuple[float, float]]] = {}
    for r, stamps in per_rank_stamps.items():
        for step, pair in stamps.items():
            by_step.setdefault(step, {})[r] = pair
    aligned = sorted(s for s, ranks in by_step.items() if len(ranks) >= 2)

    wait_by_laggard: Dict[str, float] = {}
    wait_by_victim: Dict[str, float] = {}
    laggard_steps: Dict[str, int] = {}
    lag_samples: Dict[str, List[float]] = {r: [] for r in docs}
    per_step: List[Dict[str, Any]] = []
    worst: Optional[Dict[str, Any]] = None
    newest_ts = 0.0
    for step in aligned:
        arrivals = by_step[step]
        enters = {r: pair[0] for r, pair in arrivals.items()}
        lag_r = max(enters, key=lambda r: enters[r])
        last = enters[lag_r]
        med = _median(list(enters.values()))
        first = min(enters.values())
        skew_s = max(last - med, 0.0)
        step_wait = 0.0
        for r, (enter, exit_) in arrivals.items():
            newest_ts = max(newest_ts, exit_)
            lag_samples.setdefault(r, []).append(max(enter - med, 0.0))
            if r == lag_r:
                continue
            # The victim exits the fence with the last arrival; it
            # cannot have waited longer than it was inside the span.
            wait = max(min(last - enter, max(exit_ - enter, 0.0)), 0.0)
            wait_by_victim[r] = wait_by_victim.get(r, 0.0) + wait
            step_wait += wait
        wait_by_laggard[lag_r] = wait_by_laggard.get(lag_r, 0.0) + step_wait
        laggard_steps[lag_r] = laggard_steps.get(lag_r, 0) + 1
        entry = {"step": step, "skew_s": round(skew_s, 6),
                 "laggard": lag_r, "wait_s": round(step_wait, 6),
                 "arrivals": {r: round(e - first, 6)
                              for r, e in enters.items()}}
        per_step.append(entry)
        if worst is None or skew_s > worst["skew_s"]:
            worst = {"step": step, "skew_s": round(skew_s, 6),
                     "laggard": lag_r}

    total_wait = sum(wait_by_victim.values())
    exposed: Optional[float] = None
    if gdocs:
        exposed = sum(float(((d.get("buckets") or {})
                             .get("exposed_comm")) or 0.0)
                      for d in gdocs.values())
    if exposed is not None:
        straggler_wait = min(total_wait, exposed)
        wire = max(exposed - straggler_wait, 0.0)
        fraction = (straggler_wait / exposed) if exposed > 0 else 0.0
    else:
        # No goodput budget scraped: report raw waits, decomposition
        # null, fraction 0 (never a false alert on missing data).
        straggler_wait, wire, fraction = total_wait, None, 0.0

    run: Dict[str, Any] = {
        "kind": "skew_run",
        "ts": round(newest_ts, 6),
        "n_ranks": len(docs),
        "steps_aligned": len(aligned),
        "arrival_wait_s": round(total_wait, 6),
        "exposed_comm_s": (round(exposed, 6)
                           if exposed is not None else None),
        "straggler_wait_s": round(straggler_wait, 6),
        "wire_s": (round(wire, 6) if wire is not None else None),
        "straggler_fraction": round(fraction, 6),
        "wait_by_laggard": {r: round(s, 6)
                            for r, s in sorted(wait_by_laggard.items())},
        "wait_by_victim": {r: round(s, 6)
                           for r, s in sorted(wait_by_victim.items())},
        "per_rank": {
            r: {"steps": len(per_rank_stamps.get(r) or {}),
                "laggard_steps": laggard_steps.get(r, 0),
                "wait_caused_s": round(wait_by_laggard.get(r, 0.0), 6),
                "wait_suffered_s": round(wait_by_victim.get(r, 0.0), 6),
                "arrival_lag_p50_s": round(
                    _median(lag_samples.get(r) or []), 6),
                "arrival_lag_max_s": round(
                    max(lag_samples.get(r) or [0.0]), 6),
                "dropped": int(docs[r].get("dropped") or 0)}
            for r in sorted(docs)},
        "worst_step": worst,
        "per_step": per_step[-max(1, int(window)):],
        "laggard": None,
    }
    if total_wait > 0 and wait_by_laggard:
        lag = max(wait_by_laggard, key=lambda r: wait_by_laggard[r])
        share = wait_by_laggard[lag] / total_wait
        persistent = (laggard_steps.get(lag, 0) >= MIN_LAGGARD_STEPS
                      and share >= LAGGARD_DOMINANCE)
        verdict: Dict[str, Any] = {
            "rank": lag,
            "steps": laggard_steps.get(lag, 0),
            "share": round(share, 6),
            "persistent": persistent,
        }
        if persistent:
            cause, evidence = _hypothesize_cause(lag, gdocs, hdocs)
            verdict["cause"] = cause
            verdict["evidence"] = evidence
        run["laggard"] = verdict
    return run


def sections_from_snapshots(snapshots: Mapping[Any, Optional[Mapping]]
                            ) -> Dict[Any, Mapping[str, Any]]:
    """Pull each rank's ``skew`` section out of its (last-good)
    telemetry snapshot; ranks without one are skipped."""
    out: Dict[Any, Mapping[str, Any]] = {}
    for rank, snap in snapshots.items():
        section = ((snap or {}).get("sections") or {}).get(SECTION)
        if isinstance(section, Mapping):
            out[rank] = section
    return out


def publish_run_gauges(telemetry: Telemetry,
                       run: Mapping[str, Any]) -> None:
    """Export the merged verdict as ``skew.*`` gauges on the
    collector's bus — the series :class:`MetricsHistory` retains and
    :func:`skew_alert_rules` judges."""
    for key in ("straggler_fraction", "straggler_wait_s", "wire_s",
                "arrival_wait_s", "steps_aligned", "n_ranks"):
        val = run.get(key)
        if val is not None:
            telemetry.gauge(f"skew.{key}", float(val))
    worst = run.get("worst_step") or {}
    if worst:
        telemetry.gauge("skew.worst_step_skew_s",
                        float(worst.get("skew_s") or 0.0))
    for r, caused in (run.get("wait_by_laggard") or {}).items():
        telemetry.gauge("skew.wait_caused_s", float(caused),
                        labels={"rank": str(r)})


def skew_alert_rules(threshold: float = 0.5, for_sweeps: int = 3,
                     severity: str = "warning") -> List[AlertRule]:
    """The sustained straggler rule: fire (latched, episode-counted)
    when straggler wait has dominated the run's exposed_comm for
    ``for_sweeps`` consecutive collector sweeps — the signal the
    ElasticController consumes as a ``ctl.scale_signal`` (evict or
    replace the named rank beats compressing the collective). One
    noisy sweep never flaps the signal; that is what ``sustained``
    means in :mod:`sparktorch_tpu.obs.alerts`."""
    return [AlertRule(
        name="skew_straggler_sustained",
        metric="skew.straggler_fraction",
        kind="sustained",
        op=">",
        threshold=float(threshold),
        for_sweeps=int(for_sweeps),
        severity=severity,
    )]
