"""Human-readable step timelines and comm/compute budgets.

The CLI twin of :mod:`sparktorch_tpu.obs.xprof`: render a captured
XLA trace (or a telemetry JSONL dump that already carries published
``xprof.*`` metrics) as a per-step timeline and budget report a human
can read in a terminal, no TensorBoard required.

    python -m sparktorch_tpu.obs.timeline /tmp/trace_dir
    python -m sparktorch_tpu.obs.timeline run_telemetry.jsonl
    python -m sparktorch_tpu.obs.timeline trace.json.gz --json

``--gang`` renders the WHOLE-GANG view (per-rank lanes + cross-rank
skew annotations) from N per-host traces merged on the spot, or from
a fleet collector's JSONL sink / ``/gang`` document that already
carries the merged budget:

    python -m sparktorch_tpu.obs.timeline --gang host0_trace host1_trace
    python -m sparktorch_tpu.obs.timeline --gang collector_sink.jsonl

``--rpc`` renders PER-REQUEST waterfalls from distributed RPC traces
(:mod:`sparktorch_tpu.obs.rpctrace`): a telemetry JSONL dump whose
snapshots carry the ``rpc_spans`` ring, or a fleet collector sink
whose records carry the already-stitched ``rpc_traces`` section. One
tree per sampled request — each hop offset on the root's clock, the
computed critical path starred, the bounding hop (straggler shard
included) named in the header:

    python -m sparktorch_tpu.obs.timeline --rpc run_telemetry.jsonl
    python -m sparktorch_tpu.obs.timeline --rpc collector_sink.jsonl

Rendering is pure string-building (testable offline); only the CLI
entry prints.
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List, Optional, Tuple

from sparktorch_tpu.obs.xprof import (
    GangAnalysis,
    TraceAnalysis,
    TraceParseError,
    analyze_trace,
    merge_analyses,
)

_BAR_W = 40


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:.2f}ms"


def _budget_bar(window_s: float, compute_s: float, comm_s: float,
                overlap_s: float, width: int = _BAR_W) -> str:
    """Budget bar (not a temporal strip): ``#`` compute-only, ``=``
    comm overlapped with compute, ``~`` comm-only (exposed), ``.``
    idle/unattributed — each sized by its share of the step window."""
    if window_s <= 0:
        return "." * width
    comp_only = max(compute_s - overlap_s, 0.0)
    comm_only = max(comm_s - overlap_s, 0.0)
    cells = []
    for sym, val in (("#", comp_only), ("=", overlap_s), ("~", comm_only)):
        cells.append((sym, int(round(width * min(val / window_s, 1.0)))))
    used = sum(n for _, n in cells)
    if used > width:  # rounding spill: trim the largest segment
        sym, n = max(cells, key=lambda c: c[1])
        cells[cells.index((sym, n))] = (sym, n - (used - width))
        used = width
    return "".join(sym * n for sym, n in cells) + "." * (width - used)


def render_report(analysis: TraceAnalysis, top: int = 10) -> str:
    """Per-step timeline + whole-run budget for one analyzed trace."""
    d = analysis.to_dict()
    lines = [
        f"trace: {d['source']}",
        f"steps: {d['n_steps']}   device events: {d['n_device_events']}"
        f"   collective events: {d['n_collective_events']}"
        f"   unattributed: {d['n_unattributed']}",
        "",
        f"{'step':>6} {'wall':>10} {'window':>10} {'compute':>10}"
        f" {'comm':>10} {'comm%':>7} {'ovl%':>6}  budget"
        f" [#=compute ==hidden-comm ~=exposed-comm]",
    ]
    for s in d["steps"]:
        step = "-" if s["step"] is None else str(s["step"])
        lines.append(
            f"{step:>6} {_fmt_ms(s['wall_s']):>10}"
            f" {_fmt_ms(s['window_s']):>10}"
            f" {_fmt_ms(s['compute_s']):>10} {_fmt_ms(s['comm_s']):>10}"
            f" {100 * s['comm_fraction']:>6.1f} {100 * s['overlap_fraction']:>5.1f}"
            f"  {_budget_bar(s['window_s'], s['compute_s'], s['comm_s'], s['overlap_s'])}"
        )
        for fam, sec in sorted(s["families"].items()):
            lines.append(
                f"{'':>6}   {fam:<16} {_fmt_ms(sec):>10}"
                f"  x{s['counts'].get(fam, 0)}"
            )
    lines += [
        "",
        f"budget: wall {_fmt_ms(d['wall_s'])} | compute "
        f"{_fmt_ms(d['compute_s'])} | comm {_fmt_ms(d['comm_s'])} "
        f"({100 * d['comm_fraction']:.1f}% of windows, "
        f"{100 * d['overlap_fraction']:.1f}% hidden under compute)",
    ]
    if d["collective_s"]:
        lines.append("collectives:")
        for fam, sec in sorted(d["collective_s"].items(),
                               key=lambda kv: -kv[1]):
            lines.append(f"  {fam:<16} {_fmt_ms(sec):>10}"
                         f"  x{d['collective_counts'].get(fam, 0)}")
    else:
        lines.append("collectives: none found in this capture")
    if d["top_ops"]:
        # Device-seconds (summed across lanes), so concurrent lanes
        # add up here — unlike the union walls above.
        lines.append(f"top {min(top, len(d['top_ops']))} ops by total "
                     f"device time:")
        for i, op in enumerate(d["top_ops"][:top]):
            lines.append(
                f"  {i + 1:>2}. {op['name']:<32} {op['family']:<12}"
                f" {_fmt_ms(op['total_s']):>10}  x{op['count']}"
            )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Gang rendering (per-rank lanes, skew annotations)
# ---------------------------------------------------------------------------


def render_gang_report(gang: Any) -> str:
    """Whole-gang timeline from a :class:`GangAnalysis` (or its
    ``to_dict()`` form — what a collector's ``/gang`` route or JSONL
    sink carries): one line per step with the gang wall (the slowest
    rank's pace) and the cross-rank skew annotation, then one LANE per
    rank showing where that rank's copy of the step went."""
    d = gang.to_dict() if isinstance(gang, GangAnalysis) else dict(gang)
    lines = [
        f"gang: {d.get('n_ranks', '?')} ranks"
        + (f"   run: {d['run_id']}" if d.get("run_id") else ""),
        f"steps: {d.get('n_steps', len(d.get('steps', [])))}"
        f"   worst step skew: {_fmt_ms(d.get('step_skew_s', 0.0))}",
        "",
        f"{'step':>6} {'gang wall':>10} {'skew':>10} {'comm':>10}"
        f" {'comm%':>7} {'ovl%':>6}"
        f"  [walls max'd across ranks; seconds summed]",
    ]
    for s in d.get("steps", []):
        step = "-" if s.get("step") is None else str(s["step"])
        lines.append(
            f"{step:>6} {_fmt_ms(s['wall_s']):>10}"
            f" {_fmt_ms(s.get('skew_s', 0.0)):>10}"
            f" {_fmt_ms(s['comm_s']):>10}"
            f" {100 * s.get('comm_fraction', 0.0):>6.1f}"
            f" {100 * s.get('overlap_fraction', 0.0):>5.1f}"
        )
        ranks = s.get("ranks") or {}
        walls = [lane.get("wall_s", 0.0) for lane in ranks.values()]
        slowest = max(walls) if walls else 0.0

        def _rank_key(item):
            try:
                return (0, int(item[0]))
            except ValueError:
                return (1, item[0])

        for rank, lane in sorted(ranks.items(), key=_rank_key):
            bar = _budget_bar(lane.get("window_s", 0.0),
                              lane.get("compute_s", 0.0),
                              lane.get("comm_s", 0.0),
                              lane.get("overlap_s", 0.0))
            straggler = (" <- straggler"
                         if walls and lane.get("wall_s", 0.0) == slowest
                         and s.get("skew_s", 0.0) > 0 else "")
            lines.append(
                f"{'':>6}   rank {rank:<4} {_fmt_ms(lane.get('wall_s', 0.0)):>10}"
                f"  {bar}{straggler}"
            )
    lines += [
        "",
        f"gang budget: wall {_fmt_ms(d.get('wall_s', 0.0))} | compute "
        f"{_fmt_ms(d.get('compute_s', 0.0))} | comm "
        f"{_fmt_ms(d.get('comm_s', 0.0))} "
        f"({100 * d.get('comm_fraction', 0.0):.1f}% of gang device-time, "
        f"{100 * d.get('overlap_fraction', 0.0):.1f}% hidden under compute)",
    ]
    fams = d.get("collective_s") or {}
    if fams:
        lines.append("collectives (summed across ranks):")
        counts = d.get("collective_counts") or {}
        for fam, sec in sorted(fams.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {fam:<16} {_fmt_ms(sec):>10}"
                         f"  x{counts.get(fam, 0)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# RPC request-trace rendering (per-request waterfalls)
# ---------------------------------------------------------------------------


def render_rpc_report(traces: List[Dict[str, Any]], top: int = 10,
                      width: int = 44) -> str:
    """Per-request waterfalls from stitched RPC trace trees (the
    :func:`sparktorch_tpu.obs.rpctrace.stitch_spans` output). Each
    span renders at its offset on the ROOT's clock with a bar scaled
    to the root wall; spans on the computed critical path are starred,
    errored spans flagged, and the header names the hop (and shard)
    that actually bounded the request."""
    if not traces:
        return "no rpc traces found\n"
    lines = [f"rpc traces: {len(traces)}"
             f" (showing {min(top, len(traces))}, newest first)", ""]
    for t in traces[:top]:
        root = t.get("root") or {}
        wall = float(t.get("wall_s") or root.get("dur_s") or 0.0)
        crit = t.get("critical") or {}
        # Condensed /gang docs strip the path; those render unstarred.
        crit_ids = {e.get("span_id") for e in (crit.get("path") or [])
                    if e.get("span_id")}
        head = (f"trace {str(t.get('trace_id'))[:16]}"
                f"  {root.get('name')}  {_fmt_ms(wall)}"
                f"  {t.get('n_spans')} spans")
        if root.get("status") == "error":
            head += "  [ERROR]"
        if root.get("forced"):
            head += "  [slo-forced]"
        if crit.get("name"):
            shard = (f", shard {crit['shard']}"
                     if crit.get("shard") is not None else "")
            head += (f"   bound by: {crit['name']}{shard}"
                     f" ({100 * float(crit.get('fraction') or 0):.0f}%"
                     f" of wall)")
        lines.append(head)
        t0 = float(root.get("ts", 0.0))

        def _bar(off_s: float, dur_s: float) -> str:
            if wall <= 0:
                return "." * width
            pos = min(int(round(width * max(off_s, 0.0) / wall)),
                      width - 1)
            n = max(1, int(round(width * dur_s / wall)))
            n = min(n, width - pos)
            return "." * pos + "#" * n + "." * (width - pos - n)

        def _walk(node: Dict[str, Any], depth: int) -> None:
            off = float(node.get("ts", 0.0)) - t0
            name = str(node.get("name"))
            ann = node.get("ann") or {}
            if ann.get("shard") is not None:
                name += f" shard={ann['shard']}"
            mark = "*" if node.get("span_id") in crit_ids else " "
            err = "!" if node.get("status") == "error" else " "
            dur = float(node.get("dur_s") or 0.0)
            pad = max(30 - 2 * depth, 1)
            lines.append(
                f" {mark}{err}{'  ' * depth}{name:<{pad}}"
                f" {_fmt_ms(off):>9} +{_fmt_ms(dur):>9}"
                f" |{_bar(off, dur)}|"
            )
            for child in node.get("children") or []:
                _walk(child, depth + 1)

        _walk(root, 0)
        for extra in t.get("extra_roots") or []:
            _walk(extra, 0)
        for orphan in t.get("orphans") or []:
            lines.append(f"  ~ orphan hop: {orphan.get('name')}"
                         f" +{_fmt_ms(float(orphan.get('dur_s') or 0))}"
                         f" (parent span not scraped)")
        lines.append("")
    return "\n".join(lines)


def _rpc_from_jsonl(records: List[Dict[str, Any]]
                    ) -> Optional[List[Dict[str, Any]]]:
    """Stitched request trees out of a JSONL file: the newest record
    carrying an already-stitched ``rpc_traces`` section (a collector
    sink) wins; otherwise every record's raw ``rpc_spans`` rings are
    pooled and stitched here (a per-process telemetry dump — possibly
    several processes' snapshots appended to one file)."""
    from sparktorch_tpu.obs import rpctrace

    for rec in reversed(records):
        section = (rec.get("sections") or {}).get(rpctrace.TRACES_SECTION)
        if isinstance(section, dict) and section.get("traces"):
            return list(section["traces"])
    spans: List[Dict[str, Any]] = []
    for rec in records:
        spans.extend(rpctrace.spans_from_snapshot(rec))
    if not spans:
        return None
    return rpctrace.stitch_spans(spans)


# ---------------------------------------------------------------------------
# Goodput ledger rendering (stacked run-attribution bars)
# ---------------------------------------------------------------------------

# One glyph per bucket, in render order (compute first so the
# productive share reads left-to-right as "the good part").
_GOODPUT_GLYPHS = (
    ("compute", "#"),
    ("exposed_comm", "~"),
    ("compile", "C"),
    ("checkpoint", "K"),
    ("data_wait", "D"),
    ("restart_downtime", "R"),
    ("resize_downtime", "Z"),
    ("idle", "."),
)


def _goodput_bar(buckets: Dict[str, Any], wall_s: float,
                 width: int = _BAR_W) -> str:
    """Stacked attribution strip: each bucket sized by its share of
    the wall. Rounding spill trims the largest segment (the same
    discipline as the xprof budget bar)."""
    if wall_s <= 0:
        return "." * width
    cells = [(glyph, int(round(width * min(
        float(buckets.get(b, 0.0)) / wall_s, 1.0))))
        for b, glyph in _GOODPUT_GLYPHS]
    used = sum(n for _, n in cells)
    while used > width:
        glyph, n = max(cells, key=lambda c: c[1])
        cells[cells.index((glyph, n))] = (glyph, n - 1)
        used -= 1
    return "".join(glyph * n for glyph, n in cells) + "." * (width - used)


def render_goodput_report(doc: Dict[str, Any]) -> str:
    """One terminal page from a run-level goodput report (the
    collector's ``GET /goodput`` document, or a single rank's
    ``goodput`` section): the run summary with the goodput fraction
    and the biggest thief named, one stacked attribution bar per
    rank, then the bucket table. The ``comm_source`` label says
    whether exposed comm was measured (xprof) or modeled."""
    per_rank = doc.get("per_rank")
    if not isinstance(per_rank, dict) or not per_rank:
        # A bare rank section renders as a one-rank run.
        per_rank = {str(doc.get("rank", "?")): doc}
    wall = float(doc.get("wall_s") or 0.0)
    goodput = float(doc.get("goodput") or 0.0)
    lines = [
        f"goodput: {100 * goodput:.1f}% of {wall:.2f}s rank-seconds "
        f"productive ({doc.get('n_ranks', len(per_rank))} ranks, "
        f"{doc.get('n_steps', 0)} steps, {doc.get('compiles', 0)} "
        f"compiles)"
        + (f"   run: {doc['run_id']}" if doc.get("run_id") else ""),
        f"exposed comm: {doc.get('comm_source', 'none')}"
        + (f"   mfu: {100 * float(doc['mfu']):.2f}%"
           if doc.get("mfu") is not None else ""),
    ]
    thief = doc.get("biggest_thief")
    if not thief:
        from sparktorch_tpu.obs.goodput import biggest_thief as _bt

        ranked = _bt(doc)
        if ranked:
            thief = {"bucket": ranked[0], "seconds": ranked[1],
                     "fraction": ranked[1] / max(wall, 1e-9)}
    if thief:
        lines.append(
            f"biggest thief: {thief['bucket']} "
            f"{float(thief['seconds']):.2f}s "
            f"({100 * float(thief.get('fraction') or 0):.1f}% of wall)")
    over = float(doc.get("overattributed_s") or 0.0)
    if over > 0:
        lines.append(f"WARNING: {over:.3f}s over-attributed "
                     f"(double-counted regions)")
    legend = " ".join(f"{g}={b}" for b, g in _GOODPUT_GLYPHS)
    lines += ["", f"{'rank':>10} {'wall':>9} {'goodput':>8}  [{legend}]"]

    def _rank_key(item):
        try:
            return (0, int(item[0]))
        except (TypeError, ValueError):
            return (1, str(item[0]))

    for rank, rdoc in sorted(per_rank.items(), key=_rank_key):
        rwall = float(rdoc.get("wall_s") or 0.0)
        bar = _goodput_bar(rdoc.get("buckets") or {}, rwall)
        lines.append(
            f"{str(rank):>10} {rwall:>8.2f}s"
            f" {100 * float(rdoc.get('goodput') or 0.0):>7.1f}%"
            f"  {bar}"
            + (f"  [{rdoc.get('comm_source')}]"
               if rdoc.get("comm_source") not in (None, "none",
                                                  doc.get("comm_source"))
               else ""))
    buckets = doc.get("buckets") or {}
    fractions = doc.get("fractions") or {}
    lines += ["", "buckets (rank-seconds summed):"]
    for b, _ in _GOODPUT_GLYPHS:
        sec = float(buckets.get(b, 0.0))
        if sec <= 0:
            continue
        lines.append(f"  {b:<18} {sec:>9.3f}s"
                     f"  {100 * float(fractions.get(b, 0.0)):>5.1f}%"
                     + (f"  x{doc.get('counts', {}).get(b)}"
                        if (doc.get("counts") or {}).get(b) else ""))
    return "\n".join(lines) + "\n"


def _goodput_from_jsonl(records: List[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """The newest goodput accounting in a JSONL file: a collector
    sink/dump record carrying the merged ``goodput_run`` section wins;
    a bare rank dump's ``goodput`` section renders as one lane."""
    for rec in reversed(records):
        sections = rec.get("sections") or {}
        doc = sections.get("goodput_run")
        if isinstance(doc, dict) and doc.get("buckets"):
            return doc
    for rec in reversed(records):
        sections = rec.get("sections") or {}
        doc = sections.get("goodput")
        if isinstance(doc, dict) and doc.get("buckets"):
            return doc
    return None


# ---------------------------------------------------------------------------
# Model-health rendering (per-rank sparklines + anomaly log)
# ---------------------------------------------------------------------------


_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[Any], width: int = _BAR_W) -> str:
    """A unicode sparkline over the last ``width`` values; non-finite
    points render as ``!`` — the whole point of the health view is
    that a NaN must be VISIBLE, not interpolated away."""
    import math

    vals = []
    for v in values[-width:]:
        try:
            vals.append(float(v))
        except (TypeError, ValueError):
            vals.append(float("nan"))
    if not vals:
        return ""
    finite = [v for v in vals if math.isfinite(v)]
    lo = min(finite) if finite else 0.0
    hi = max(finite) if finite else 0.0
    span = (hi - lo) or 1.0
    out = []
    for v in vals:
        if not math.isfinite(v):
            out.append("!")
        else:
            idx = int((v - lo) / span * (len(_SPARK_GLYPHS) - 1))
            out.append(_SPARK_GLYPHS[max(0, min(idx,
                                                len(_SPARK_GLYPHS) - 1))])
    return "".join(out)


def render_health_report(doc: Dict[str, Any], top: int = 10) -> str:
    """One terminal page from a run-level model-health report (the
    collector's ``GET /health`` document, or a single rank's
    ``health`` section merged to the same shape): the run summary
    with per-kind anomaly counts, one loss + grad-norm sparkline pair
    per rank (NaNs render as ``!``), then the recent anomaly log —
    every line rank-tagged, never a fleet average."""
    per_rank = doc.get("per_rank")
    if not isinstance(per_rank, dict) or not per_rank:
        per_rank = {str(doc.get("rank", "?")): doc}
    counts = doc.get("counts") or {}
    total = int(doc.get("anomalies_total") or 0)
    lines = [
        f"model health: {doc.get('n_ranks', len(per_rank))} ranks, "
        f"{doc.get('steps_total', 0)} steps ingested, "
        f"last step {doc.get('last_step', -1)}"
        + (f"   run: {doc['run_id']}" if doc.get("run_id") else ""),
        "anomalies: "
        + (", ".join(f"{k}={counts[k]}" for k in sorted(counts)
                     if counts[k]) or "none")
        + (f"  (total {total})" if total else ""),
    ]
    worst = doc.get("worst")
    if isinstance(worst, dict):
        lines.append(
            f"worst: {worst.get('akind')} @ step {worst.get('step')} "
            f"rank {worst.get('rank')} value={worst.get('value')}")
    lines += ["", f"{'rank':>10} {'step':>7} {'last loss':>12}  "
                  f"loss / grad-norm (! = non-finite)"]

    def _rank_key(item):
        try:
            return (0, int(item[0]))
        except (TypeError, ValueError):
            return (1, str(item[0]))

    for rank, rdoc in sorted(per_rank.items(), key=_rank_key):
        series = rdoc.get("series") or {}
        last = rdoc.get("last") or {}
        loss = last.get("loss")
        loss_s = (f"{float(loss):.5g}"
                  if isinstance(loss, (int, float)) else "?")
        lines.append(
            f"{str(rank):>10} {rdoc.get('last_step', -1):>7}"
            f" {loss_s:>12}  {_sparkline(series.get('loss') or [])}")
        gn = series.get("grad_norm") or []
        if gn:
            lines.append(f"{'':>10} {'':>7} {'':>12}  {_sparkline(gn)}")
        leaves = rdoc.get("top_grad_leaves") or []
        if leaves:
            lines.append(
                f"{'':>10} {'':>7} {'':>12}  top grad leaves: "
                + ", ".join(f"{k}={float(v):.3g}"
                            for k, v in leaves[:3]))
    anomalies = doc.get("anomalies") or []
    if anomalies:
        lines += ["", f"recent anomalies (last {min(len(anomalies), top)}):"]
        for a in anomalies[-top:]:
            lines.append(
                f"  step {a.get('step'):>6}  rank {a.get('rank')!s:<6}"
                f" {a.get('akind'):<14} value={a.get('value')}"
                f" threshold={a.get('threshold')}"
                f" lag={a.get('detect_lag')}")
    return "\n".join(lines) + "\n"


def _health_from_jsonl(records: List[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """The newest model-health doc in a JSONL file: a collector
    sink/dump record carrying the merged ``health_run`` section wins;
    a bare rank dump's composite ``health`` section is merged to the
    same shape so one renderer serves both."""
    for rec in reversed(records):
        sections = rec.get("sections") or {}
        doc = sections.get("health_run")
        if isinstance(doc, dict) and doc.get("per_rank"):
            return doc
    from sparktorch_tpu.obs import health as _health

    for rec in reversed(records):
        sections = rec.get("sections") or {}
        sec = sections.get("health")
        if isinstance(sec, dict) and (sec.get("ranks") or sec.get("rank")):
            return _health.merge_sections({"dump": sec})
    return None


# ---------------------------------------------------------------------------
# Cross-rank step-skew rendering (arrival bars + straggler verdict)
# ---------------------------------------------------------------------------


def render_skew_report(doc: Dict[str, Any], top: int = 10) -> str:
    """One terminal page from a run-level skew verdict (the
    collector's ``GET /skew`` document): the wire vs straggler-wait
    decomposition of exposed_comm, per-rank arrival bars (median
    arrival lag, wait caused/suffered), the named persistent laggard
    with its cause hypothesis, and a per-step arrival detail table."""
    n_ranks = int(doc.get("n_ranks") or 0)
    aligned = int(doc.get("steps_aligned") or 0)
    lines = [
        f"step skew: {n_ranks} ranks, {aligned} aligned steps"
        + (f"   run: {doc['run_id']}" if doc.get("run_id") else ""),
    ]
    exposed = doc.get("exposed_comm_s")
    wait = float(doc.get("straggler_wait_s") or 0.0)
    if exposed is not None:
        frac = float(doc.get("straggler_fraction") or 0.0)
        lines.append(
            f"exposed comm {float(exposed):.3f}s = "
            f"wire {float(doc.get('wire_s') or 0.0):.3f}s + "
            f"straggler wait {wait:.3f}s ({100 * frac:.1f}% straggler)")
    else:
        lines.append(
            f"arrival wait {wait:.3f}s (no goodput budget scraped — "
            f"wire split unavailable)")
    lag = doc.get("laggard")
    if isinstance(lag, dict) and lag.get("persistent"):
        cause = lag.get("cause") or "unknown"
        ev = "; ".join(lag.get("evidence") or [])
        lines.append(
            f"verdict: rank {lag.get('rank')} is a persistent "
            f"straggler — caused {100 * float(lag.get('share') or 0):.1f}%"
            f" of the wait over {lag.get('steps')} steps; "
            f"cause hypothesis: {cause}" + (f" ({ev})" if ev else ""))
    elif isinstance(lag, dict):
        lines.append(
            f"verdict: no persistent straggler (top laggard rank "
            f"{lag.get('rank')} at {100 * float(lag.get('share') or 0):.1f}%"
            f" of wait over {lag.get('steps')} step(s))")
    elif aligned:
        lines.append("verdict: no straggler wait observed")
    else:
        lines.append("verdict: no cross-rank alignment "
                     "(need the same step stamped on >= 2 ranks)")
    per_rank = doc.get("per_rank") or {}
    if per_rank:
        total_caused = sum(float((r or {}).get("wait_caused_s") or 0.0)
                           for r in per_rank.values()) or 1.0
        lines += ["", f"{'rank':>10} {'steps':>6} {'lag p50':>9} "
                      f"{'lag max':>9} {'caused':>9} {'suffered':>9}"
                      f"  wait share"]

        def _rank_key(item):
            try:
                return (0, int(item[0]))
            except (TypeError, ValueError):
                return (1, str(item[0]))

        for rank, rdoc in sorted(per_rank.items(), key=_rank_key):
            caused = float(rdoc.get("wait_caused_s") or 0.0)
            bar = "#" * int(round(_BAR_W / 2 * caused / total_caused))
            lines.append(
                f"{str(rank):>10} {rdoc.get('steps', 0):>6}"
                f" {_fmt_ms(float(rdoc.get('arrival_lag_p50_s') or 0)):>9}"
                f" {_fmt_ms(float(rdoc.get('arrival_lag_max_s') or 0)):>9}"
                f" {caused:>8.3f}s"
                f" {float(rdoc.get('wait_suffered_s') or 0.0):>8.3f}s"
                f"  {bar}")
    per_step = doc.get("per_step") or []
    if per_step:
        shown = per_step[-top:]
        lines += ["", f"per-step arrivals (last {len(shown)}; "
                      f"offset from first arrival):"]
        for entry in shown:
            arrivals = entry.get("arrivals") or {}
            arr = "  ".join(
                f"{r}+{_fmt_ms(float(arrivals[r]))}"
                for r in sorted(arrivals, key=str))
            lines.append(
                f"  step {entry.get('step'):>6}"
                f"  skew {_fmt_ms(float(entry.get('skew_s') or 0)):>9}"
                f"  laggard {str(entry.get('laggard')):<6} {arr}")
    return "\n".join(lines) + "\n"


def _skew_from_jsonl(records: List[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """The newest skew verdict in a JSONL file: a collector sink/dump
    record carrying the merged ``skew_run`` section wins; a bare rank
    dump's ``skew`` section is merged to the same shape (no alignment
    from one rank, but the stamp accounting renders)."""
    for rec in reversed(records):
        sections = rec.get("sections") or {}
        doc = sections.get("skew_run")
        if isinstance(doc, dict) and doc.get("per_rank"):
            return doc
    from sparktorch_tpu.obs import skew as _skew

    for rec in reversed(records):
        sections = rec.get("sections") or {}
        sec = sections.get("skew")
        if isinstance(sec, dict) and sec.get("stamps"):
            return _skew.merge_sections({"dump": sec})
    return None


# ---------------------------------------------------------------------------
# Stack-profile rendering (per-bucket top-down trees)
# ---------------------------------------------------------------------------


def render_profile_report(doc: Dict[str, Any], top: int = 10) -> str:
    """One terminal page from a stack-profile doc (the collector's
    ``GET /profile`` document, or a single rank's ``profile``
    section): per ledger bucket, the hottest self-time frame and a
    flamegraph-style top-down tree. ``top`` caps the tree lines per
    bucket; children below 2%% of their bucket are pruned (they are
    noise at sampling resolution)."""
    from sparktorch_tpu.obs.profile import top_frames

    total = int(doc.get("samples_total") or 0)
    lines = [
        f"profile: {total} samples over "
        f"{float(doc.get('wall_s') or 0.0):.2f}s"
        + (f" ({doc.get('n_ranks')} ranks)"
           if doc.get("n_ranks") is not None else
           (f" (rank {doc['rank']})"
            if doc.get("rank") is not None else ""))
        + (f" @ {float(doc['hz']):g}Hz" if doc.get("hz") else "")
        + (f"   run: {doc['run_id']}" if doc.get("run_id") else ""),
    ]
    if doc.get("bursts"):
        lines.append(f"burst windows: {doc['bursts']} "
                     f"(alert-triggered high-rate captures)")
    if doc.get("truncated"):
        lines.append(f"note: {doc['truncated']} stacks truncated at "
                     f"max depth (leaf side kept)")
    buckets = doc.get("buckets") or {}
    ranked = sorted(buckets.items(),
                    key=lambda kv: -int((kv[1] or {}).get("samples", 0)))
    for bucket, root in ranked:
        n = int((root or {}).get("samples", 0))
        if n <= 0:
            continue
        share = n / max(total, 1)
        lines.append("")
        lines.append(f"[{bucket}] {n} samples "
                     f"({100 * share:.1f}% of run)")
        hot = top_frames(doc, bucket, 1)
        if hot:
            lines.append(f"  hot: {hot[0][0]}  self={hot[0][1]} "
                         f"({100 * hot[0][1] / max(n, 1):.1f}% of bucket)")
        budget = [max(int(top), 1)]
        floor = max(n * 0.02, 0.5)

        def walk(node, depth):
            kids = sorted((node.get("children") or {}).items(),
                          key=lambda kv: (-kv[1].get("samples", 0),
                                          kv[0]))
            for name, child in kids:
                cn = int(child.get("samples", 0))
                if cn < floor:
                    continue
                if budget[0] <= 0:
                    lines.append("    " + "  " * depth + "...")
                    return
                budget[0] -= 1
                own = int(child.get("self", 0))
                lines.append(
                    "    " + "  " * depth
                    + f"{name}  {cn} ({100 * cn / max(n, 1):.1f}%)"
                    + (f" [self {own}]" if own else ""))
                walk(child, depth + 1)

        walk(root, 0)
    return "\n".join(lines) + "\n"


def render_profile_diff(diff: Dict[str, Any], top: int = 10) -> str:
    """Render a :func:`~sparktorch_tpu.obs.profile.diff_docs` output:
    per bucket, the frames whose self-time SHARE of the bucket moved
    most (positive delta = the frame grew since the prior profile)."""
    lines = [
        f"profile diff: {diff.get('current_samples', 0)} samples now "
        f"vs {diff.get('prior_samples', 0)} prior",
    ]
    buckets = diff.get("buckets") or {}
    ranked = sorted(buckets.items(),
                    key=lambda kv: -int((kv[1] or {}).get(
                        "current_samples", 0)))
    moved = False
    for bucket, bdoc in ranked:
        frames = (bdoc or {}).get("frames") or []
        if not frames:
            continue
        moved = True
        lines.append("")
        lines.append(
            f"[{bucket}] {bdoc.get('current_samples', 0)} samples now "
            f"vs {bdoc.get('prior_samples', 0)} prior")
        for f in frames[:max(int(top), 1)]:
            delta = float(f.get("delta") or 0.0)
            lines.append(
                f"  {delta:>+7.1%}  {f.get('frame')}"
                f"  ({float(f.get('current_share') or 0):.1%}"
                f" <- {float(f.get('prior_share') or 0):.1%})")
    if not moved:
        lines.append("no frame moved (identical shares)")
    return "\n".join(lines) + "\n"


def _profile_from_jsonl(records: List[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """The newest stack profile in a JSONL file: a collector sink/dump
    record carrying the merged ``profile_run`` section wins; a bare
    rank dump's ``profile`` section renders as one rank."""
    for rec in reversed(records):
        sections = rec.get("sections") or {}
        doc = sections.get("profile_run")
        if isinstance(doc, dict) and doc.get("buckets"):
            return doc
    for rec in reversed(records):
        sections = rec.get("sections") or {}
        doc = sections.get("profile")
        if isinstance(doc, dict) and doc.get("buckets"):
            return doc
    return None


# ---------------------------------------------------------------------------
# Postmortem rendering (flight-recorder bundles)
# ---------------------------------------------------------------------------


def render_postmortem_report(doc: Dict[str, Any], top: int = 40) -> str:
    """One terminal page from a postmortem bundle (``obs.blackbox.
    collect_postmortem`` output): the header (reason, rank, world),
    the causal event window offset on the trigger's clock (negative =
    before the death), the biggest metric deltas of the last good
    interval, and the stitched request traces' verdicts."""
    trigger = float(doc.get("ts") or 0.0)
    lines = [
        f"postmortem: {doc.get('reason', '?')}"
        + (f"   rank {doc['rank']}" if doc.get("rank") is not None else ""),
        f"trigger ts: {trigger:.3f}   window: {doc.get('window_s')}s"
        f"   events: {doc.get('n_events', 0)}"
        + (f"   run: {doc['run_id']}" if doc.get("run_id") else ""),
    ]
    world = doc.get("world")
    if isinstance(world, dict):
        members = world.get("members") or {}
        states = ",".join(f"{r}:{m.get('state')}"
                          for r, m in sorted(members.items()))
        lines.append(
            f"world: generation {world.get('generation')}, "
            f"size {world.get('world_size')}"
            + (f"   members [{states}]" if states else ""))
    hb = doc.get("heartbeats")
    if isinstance(hb, dict) and hb.get("ranks"):
        lines.append(
            f"heartbeats: {len(hb.get('alive') or [])}/"
            f"{hb.get('n_ranks')} alive"
            + (f", step skew {hb['step_skew']}"
               if hb.get("step_skew") is not None else ""))
    events = list(doc.get("events") or [])
    lines.append("")
    lines.append(f"event window (offsets on the trigger's clock, "
                 f"showing last {min(top, len(events))}):")
    for e in events[-top:]:
        off = float(e.get("ts", trigger)) - trigger
        kind = str(e.get("kind", "?"))
        who = ""
        if e.get("rank") is not None:
            who = f" rank={e['rank']}"
        elif e.get("worker") is not None:
            who = f" worker={e['worker']}"
        detail = ""
        if kind == "span":
            detail = (f" {e.get('name')}"
                      f" +{_fmt_ms(float(e.get('dur_s') or 0.0))}")
        elif kind.startswith("alert."):
            detail = (f" {e.get('alert')} value={e.get('value')}"
                      f" episode={e.get('episode')}")
        else:
            extras = {k: v for k, v in e.items()
                      if k not in ("ts", "kind", "rank", "worker",
                                   "run_id", "generation", "world_size")
                      and not isinstance(v, (dict, list))}
            if e.get("generation") is not None:
                detail = f" gen={e['generation']}"
            detail += "".join(f" {k}={v}" for k, v in
                              sorted(extras.items())[:4])
        lines.append(f"  {off:>+9.3f}s  {kind:<24}{who}{detail}")
    deltas = doc.get("metric_deltas") or {}
    if deltas:
        lines.append("")
        lines.append("metric deltas over the last good window:")
        for name, delta in list(deltas.items())[:12]:
            lines.append(f"  {name:<56} +{delta:g}")
    gp = doc.get("goodput")
    if isinstance(gp, dict) and gp.get("buckets"):
        from sparktorch_tpu.obs.goodput import biggest_thief as _bt

        thief = _bt(gp)
        lines.append("")
        lines.append(
            f"goodput at death: {100 * float(gp.get('goodput') or 0):.1f}%"
            f" of {float(gp.get('wall_s') or 0):.2f}s rank-seconds"
            + (f", biggest thief {thief[0]} {thief[1]:.2f}s"
               if thief else "")
            + f" (comm: {gp.get('comm_source', 'none')})")
    prof = doc.get("profile")
    if isinstance(prof, dict) and prof.get("buckets"):
        from sparktorch_tpu.obs.profile import top_frames

        lines.append("")
        lines.append(
            f"stack profile at death: "
            f"{prof.get('samples_total', 0)} samples")
        pbuckets = sorted(
            (prof.get("buckets") or {}).items(),
            key=lambda kv: -int((kv[1] or {}).get("samples", 0)))
        for bucket, root in pbuckets[:4]:
            n = int((root or {}).get("samples", 0))
            hot = top_frames(prof, bucket, 1)
            if n <= 0 or not hot:
                continue
            lines.append(f"  {bucket:<18} {n:>6} samples"
                         f"  hot: {hot[0][0]} [self {hot[0][1]}]")
    hdoc = doc.get("health")
    if isinstance(hdoc, dict) and (hdoc.get("per_rank")
                                   or hdoc.get("anomalies")):
        counts = hdoc.get("counts") or {}
        lines.append("")
        lines.append(
            f"model health at death: "
            + (", ".join(f"{k}={counts[k]}" for k in sorted(counts)
                         if counts[k]) or "no anomalies")
            + f" over {hdoc.get('n_ranks', '?')} rank(s), last step "
            f"{hdoc.get('last_step', -1)}")
        worst = hdoc.get("worst")
        if isinstance(worst, dict):
            lines.append(
                f"  worst: {worst.get('akind')} @ step "
                f"{worst.get('step')} rank {worst.get('rank')} "
                f"value={worst.get('value')}")
        for a in (hdoc.get("anomalies") or [])[-4:]:
            lines.append(
                f"  step {a.get('step'):>6}  rank {a.get('rank')!s:<6}"
                f" {a.get('akind')} value={a.get('value')}")
    sdoc = doc.get("skew")
    if isinstance(sdoc, dict) and (sdoc.get("steps_aligned")
                                   or sdoc.get("per_rank")):
        wait = float(sdoc.get("straggler_wait_s") or 0.0)
        exposed = sdoc.get("exposed_comm_s")
        lines.append("")
        lines.append(
            f"step skew at death: "
            f"{sdoc.get('steps_aligned', 0)} aligned steps, "
            f"straggler wait {wait:.3f}s"
            + (f" of {float(exposed):.3f}s exposed comm "
               f"(wire {float(sdoc.get('wire_s') or 0.0):.3f}s)"
               if exposed is not None else ""))
        lag = sdoc.get("laggard")
        if isinstance(lag, dict):
            lines.append(
                f"  laggard: rank {lag.get('rank')} "
                f"({100 * float(lag.get('share') or 0):.1f}% of wait, "
                f"{lag.get('steps')} steps"
                + (f", cause: {lag.get('cause')}"
                   if lag.get("persistent") else ", not persistent")
                + ")")
    traces = doc.get("rpc_traces") or []
    if traces:
        lines.append("")
        lines.append(f"stitched request traces ({len(traces)}):")
        for t in traces[:5]:
            crit = t.get("critical") or {}
            root = t.get("root") or {}
            shard = (f", shard {crit['shard']}"
                     if crit.get("shard") is not None else "")
            lines.append(
                f"  {str(t.get('trace_id'))[:16]}  {root.get('name')}"
                f"  {_fmt_ms(float(t.get('wall_s') or 0.0))}"
                f"  bound by {crit.get('name')}{shard}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Follow mode (live JSONL tail)
# ---------------------------------------------------------------------------


class FollowReader:
    """Incremental JSONL reader for ``--follow``: each :meth:`poll`
    returns the records appended since the last one. Survives a file
    that does not exist yet, keeps a torn (still-being-written) final
    line buffered until its newline lands, and resets cleanly when the
    file is truncated/rotated under it."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._tail = b""

    def poll(self) -> List[Dict[str, Any]]:
        import os

        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._pos:  # truncated/rotated: start over
            self._pos = 0
            self._tail = b""
        if size == self._pos:
            return []
        # Binary read: getsize/seek offsets are BYTES, and a writer's
        # flush boundary can land mid-UTF-8-character — torn bytes stay
        # buffered with the torn line until the rest lands, instead of
        # a UnicodeDecodeError killing the live tail.
        with open(self.path, "rb") as f:
            f.seek(self._pos)
            chunk = f.read()
            self._pos = f.tell()
        data = self._tail + chunk
        lines = data.split(b"\n")
        self._tail = lines.pop()  # torn final line: wait for newline
        out: List[Dict[str, Any]] = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                continue
        return out


# Record kinds --follow renders (everything else is metric volume the
# tail mode exists to cut through). "span" is deliberately absent.
_FOLLOW_PREFIXES = ("alert.", "ctl.", "ft_", "chaos", "gang_snapshot",
                    "goodput", "profile", "health", "skew")


def render_follow_line(rec: Dict[str, Any]) -> Optional[str]:
    """One tail line for a sink record — alerts and control-plane
    transitions as they land, collector snapshots condensed to a
    liveness one-liner. None = not a record the tail shows."""
    kind = str(rec.get("kind") or "")
    if not kind.startswith(_FOLLOW_PREFIXES):
        return None
    ts = float(rec.get("ts") or 0.0)
    stamp = f"{ts:.3f}"
    if kind == "gang_snapshot":
        ranks = rec.get("ranks") or {}
        ok = sum(1 for s in ranks.values() if s.get("ok"))
        hb = rec.get("heartbeats") or {}
        skew = hb.get("step_skew")
        return (f"{stamp}  gang_snapshot       ranks {ok}/{len(ranks)} ok"
                + (f", step skew {skew}" if skew is not None else ""))
    if kind.startswith("alert."):
        return (f"{stamp}  {kind:<18}  {rec.get('alert')}"
                f"  value={rec.get('value')}"
                f"  threshold={rec.get('threshold')}"
                f"  episode={rec.get('episode')}")
    if kind.startswith("goodput"):
        # The ledger's condensed record (goodput.ledger events, or a
        # sink-dumped run doc): one line says how productive the run
        # is NOW and who is stealing the rest.
        who = (f" rank={rec['rank']}"
               if rec.get("rank") is not None else "")
        frac = rec.get("goodput")
        thief = rec.get("thief")
        if thief is None:
            bt = rec.get("biggest_thief") or {}
            thief, thief_s = bt.get("bucket"), bt.get("seconds")
        else:
            thief_s = rec.get("thief_s")
        return (f"{stamp}  {kind:<18} {who}"
                + (f" goodput={100 * float(frac):.1f}%"
                   if frac is not None else "")
                + f" wall={float(rec.get('wall_s') or 0.0):.2f}s"
                + (f" thief={thief}:{float(thief_s or 0.0):.2f}s"
                   if thief else "")
                + (f" comm={rec['comm_source']}"
                   if rec.get("comm_source") else ""))
    if kind == "health.run":
        # The collector's condensed model-health record: one line says
        # whether the numerics are clean NOW and, if not, names the
        # worst anomaly with its source rank.
        worst = rec.get("worst") or {}
        n_anom = int(rec.get("anomalies_total") or 0)
        return (f"{stamp}  {kind:<18} "
                f" ranks={rec.get('n_ranks')}"
                f" step={rec.get('last_step')}"
                f" anomalies={n_anom}"
                + (f" worst={worst.get('akind')}"
                   f"@step{worst.get('step')}"
                   f" rank={worst.get('rank')}"
                   if worst else ""))
    if kind == "skew.run":
        # The collector's condensed straggler record: one line says
        # whether exposed comm is wire or waiting, and for whom.
        lag = rec.get("laggard") or {}
        frac = rec.get("straggler_fraction")
        return (f"{stamp}  {kind:<18} "
                f" ranks={rec.get('n_ranks')}"
                f" steps={rec.get('steps_aligned')}"
                + (f" wire={float(rec.get('wire_s') or 0.0):.2f}s"
                   if rec.get("wire_s") is not None else "")
                + f" straggler={float(rec.get('straggler_wait_s') or 0.0):.2f}s"
                + (f" ({100 * float(frac):.0f}%)"
                   if frac is not None else "")
                + (f" laggard=rank {lag.get('rank')}"
                   + (f" cause={lag.get('cause')}"
                      if lag.get("cause") else "")
                   if lag else ""))
    who = ""
    if rec.get("rank") is not None:
        who = f" rank={rec['rank']}"
    elif rec.get("worker") is not None:
        who = f" worker={rec['worker']}"
    gen = (f" gen={rec['generation']}"
           if rec.get("generation") is not None else "")
    extras = {k: v for k, v in rec.items()
              if k not in ("ts", "kind", "rank", "worker", "run_id",
                           "generation", "world_size")
              and not isinstance(v, (dict, list))}
    detail = "".join(f" {k}={v}" for k, v in sorted(extras.items())[:4])
    return f"{stamp}  {kind:<18} {who}{gen}{detail}"


def follow(path: str, poll_s: float = 0.2, stop=None,
           max_records: Optional[int] = None):
    """Generator of renderable tail lines from a growing JSONL sink —
    the engine under ``timeline --follow`` (the CLI prints; tests
    consume with ``max_records``/``stop``). Existing records render
    first, then new ones as they land."""
    import time as _time

    reader = FollowReader(path)
    emitted = 0
    while True:
        for rec in reader.poll():
            line = render_follow_line(rec)
            if line is None:
                continue
            yield line
            emitted += 1
            if max_records is not None and emitted >= max_records:
                return
        if stop is not None and stop.is_set():
            return
        _time.sleep(poll_s)


# ---------------------------------------------------------------------------
# Auto-tune rendering (the search's ranking + prune decisions)
# ---------------------------------------------------------------------------


def render_tune_report(doc: Dict[str, Any]) -> str:
    """Render a tune artifact (``tune_result.json`` or the
    ``xprof_tune`` snapshot section): the measured ranking as wall
    bars, then the prune/skip decisions — so "why did the tuner pick
    this mesh, and what did it refuse to run" is one terminal page."""
    cands = [dict(c) for c in doc.get("candidates", [])]
    measured = [c for c in cands if c.get("status") == "measured"
                and c.get("measured")]
    measured.sort(key=lambda c: c.get("score") or 0.0)
    best = doc.get("best_label", "?")
    lines = [
        f"mesh auto-tune: {doc.get('n_devices', '?')} devices, "
        f"global batch {doc.get('global_batch', '?')}"
        + (f"   run: {doc['run_id']}" if doc.get("run_id") else ""),
        f"chosen: {best}   candidates: {len(cands)}"
        f" ({len(measured)} measured,"
        f" {sum(c.get('status') == 'pruned' for c in cands)} pruned,"
        f" {sum(c.get('status') == 'failed' for c in cands)} failed)"
        + ("   [early stop]" if doc.get("early_stopped") else ""),
        f"noise floor: {_fmt_ms(doc.get('noise_floor_s', 0.0))}"
        f"   search wall: {doc.get('wall_s', 0.0):.1f}s",
        "",
    ]
    if measured:
        worst = max(float(c["measured"].get("step_wall_s", 0.0))
                    for c in measured) or 1.0
        lines.append(
            f"{'mesh':>18} {'step wall':>10} {'exposed%':>9}"
            f" {'ovl%':>6} {'score':>10}  wall (vs slowest measured)"
        )
        for c in measured:
            m = c["measured"]
            wall = float(m.get("step_wall_s", 0.0))
            bar = "#" * max(int(round(_BAR_W * wall / worst)), 1)
            mark = " <- chosen" if c.get("label") == best else ""
            lines.append(
                f"{c.get('label', '?'):>18} {_fmt_ms(wall):>10}"
                f" {100 * float(m.get('exposed_comm_fraction', 0.0)):>8.1f}"
                f" {100 * float(m.get('overlap_fraction', 0.0)):>5.1f}"
                f" {_fmt_ms(float(c.get('score') or 0.0)):>10}"
                f"  {bar}{mark}"
            )
    not_run = [c for c in cands
               if c.get("status") not in ("measured", None)]
    if not_run:
        lines.append("")
        lines.append("not measured:")
        for c in not_run:
            pred = (c.get("predicted") or {})
            cost = pred.get("total_cost", pred.get("total_bytes", 0.0))
            lines.append(
                f"  {c.get('label', '?'):<18} {c.get('status'):<8}"
                f" pred {float(cost) / 1e6:>8.2f}MB-eq  {c.get('reason', '')}"
            )
    return "\n".join(lines) + "\n"


def _tune_from_jsonl(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The last tune search in a telemetry dump (the ``xprof_tune``
    snapshot section a ``TuneResult.publish`` leaves behind)."""
    for rec in reversed(records):
        section = (rec.get("sections") or {}).get("xprof_tune")
        if isinstance(section, dict) and section.get("candidates"):
            return section
    return None


def _gang_from_jsonl(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The last merged gang budget in a collector sink (or a dumped
    collector snapshot): ``sections.xprof_gang`` on snapshot-shaped
    records, ``xprof`` on ``/gang``-document records."""
    for rec in reversed(records):
        section = (rec.get("sections") or {}).get("xprof_gang")
        if isinstance(section, dict) and section.get("steps"):
            return section
        xprof = rec.get("xprof")
        if isinstance(xprof, dict) and xprof.get("kind") == "gang" \
                and xprof.get("steps"):
            return xprof
    return None


# ---------------------------------------------------------------------------
# Snapshot (JSONL dump) rendering
# ---------------------------------------------------------------------------


def _xprof_snapshot(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The last snapshot record that carries published xprof metrics."""
    for rec in reversed(records):
        hists = rec.get("histograms") or {}
        if any(k.startswith("xprof.") for k in hists):
            return rec
    return None


def render_snapshot_report(snap: Dict[str, Any]) -> str:
    """Budget report from a telemetry snapshot (``--telemetry-dump``
    JSONL or a ``/telemetry`` read) — the roll-up view of the same
    numbers :meth:`TraceAnalysis.publish` put on the bus, so a dump
    and a trace render the same budget."""
    hists = snap.get("histograms", {})
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def roll(name: str) -> Dict[str, Any]:
        return hists.get(name) or {"count": 0, "sum": 0.0, "p50": None,
                                   "p99": None}

    wall, comm = roll("xprof.step_wall_s"), roll("xprof.comm_s")
    compute = roll("xprof.compute_s")
    lines = [
        f"run: {snap.get('run_id', '?')} (telemetry snapshot)",
        f"steps analyzed: {wall['count']}",
        f"step wall: sum {_fmt_ms(wall['sum'])}"
        + (f", p50 {_fmt_ms(wall['p50'])}, p99 {_fmt_ms(wall['p99'])}"
           if wall["p50"] is not None else ""),
        f"compute:   sum {_fmt_ms(compute['sum'])}",
        f"comm:      sum {_fmt_ms(comm['sum'])}",
    ]
    cf = gauges.get("xprof.comm_fraction_run")
    of = gauges.get("xprof.overlap_fraction_run")
    if cf is not None:
        lines.append(f"comm fraction: {100 * cf:.1f}%"
                     + (f" ({100 * of:.1f}% hidden under compute)"
                        if of is not None else ""))
    fams = [(k, v) for k, v in hists.items()
            if k.startswith("xprof.collective_time_s{")]
    if fams:
        lines.append("collectives (per-step seconds, rolled up):")
        for key, r in sorted(fams, key=lambda kv: -kv[1].get("sum", 0.0)):
            fam = key.split("op=", 1)[-1].rstrip("}")
            n = counters.get(f"xprof.collectives_total{{op={fam}}}", 0)
            lines.append(
                f"  {fam:<16} sum {_fmt_ms(r.get('sum', 0.0)):>10}"
                + (f"  p50 {_fmt_ms(r['p50'])}" if r.get("p50") is not None
                   else "")
                + f"  events {int(n)}"
            )
    else:
        lines.append("collectives: none published")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _looks_like_jsonl(path: str) -> bool:
    return path.endswith((".jsonl", ".ndjson"))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparktorch_tpu.obs.timeline",
        description="Per-step timeline and comm/compute budget from an "
                    "XLA trace capture or a telemetry JSONL dump; "
                    "--gang merges N per-host traces (or reads a fleet "
                    "collector sink) into one whole-gang view.",
    )
    parser.add_argument("paths", nargs="+", metavar="path",
                        help="trace.json(.gz), a profile log dir, or a "
                             "telemetry/collector .jsonl; --gang "
                             "accepts several traces (one per host)")
    parser.add_argument("--gang", action="store_true",
                        help="render the whole-gang view: per-rank "
                             "lanes, cross-rank skew annotations")
    parser.add_argument("--tune", action="store_true",
                        help="render a mesh auto-tune artifact "
                             "(tune_result.json, or a telemetry JSONL "
                             "carrying the xprof_tune section): "
                             "measured ranking + prune decisions")
    parser.add_argument("--rpc", action="store_true",
                        help="render per-request RPC trace waterfalls "
                             "from a telemetry JSONL dump (rpc_spans) "
                             "or a collector sink (stitched "
                             "rpc_traces): one tree per sampled "
                             "request, critical path starred")
    parser.add_argument("--postmortem", action="store_true",
                        help="render a flight-recorder postmortem "
                             "bundle (postmortem_<ts>.json): causal "
                             "event window, metric deltas, world doc, "
                             "stitched traces")
    parser.add_argument("--follow", action="store_true",
                        help="tail a growing JSONL sink live: render "
                             "alert firings, control-plane transitions "
                             "and goodput ledger records as they land "
                             "(Ctrl-C stops)")
    parser.add_argument("--goodput", action="store_true",
                        help="render a run-level goodput ledger "
                             "(a saved GET /goodput document, or a "
                             "collector/telemetry .jsonl carrying the "
                             "goodput_run/goodput section): stacked "
                             "attribution bar per rank, biggest thief "
                             "named")
    parser.add_argument("--profile", action="store_true",
                        help="render a ledger-keyed stack profile "
                             "(a saved GET /profile document, or a "
                             "collector/telemetry .jsonl carrying the "
                             "profile_run/profile section): per-bucket "
                             "top-down trees, hottest frame named")
    parser.add_argument("--health", action="store_true",
                        help="render a run-level model-health report "
                             "(a saved GET /health document, or a "
                             "collector/telemetry .jsonl carrying the "
                             "health_run/health section): per-rank "
                             "loss/grad-norm sparklines, rank-tagged "
                             "anomaly log, worst anomaly named")
    parser.add_argument("--skew", action="store_true",
                        help="render the cross-rank step-skew verdict "
                             "(a saved GET /skew document, or a "
                             "collector/telemetry .jsonl carrying the "
                             "skew_run/skew section): wire vs "
                             "straggler-wait split, per-rank arrival "
                             "bars, persistent laggard named with a "
                             "cause hypothesis")
    parser.add_argument("--diff", metavar="PRIOR", default=None,
                        help="with --profile: compare against a prior "
                             "profile document/JSONL and render the "
                             "frames whose bucket share moved")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw analysis dict as JSON")
    parser.add_argument("--top", type=int, default=None,
                        help="top-K entries to list (default 10; "
                             "postmortem event window defaults to 40)")
    parser.add_argument("--step-name", default="train_step",
                        help="step annotation event name")
    args = parser.parse_args(argv)
    args.path = args.paths[0]
    # Per-mode defaults: an EXPLICIT --top always wins (a postmortem's
    # wider 40-event window is a default, not a floor).
    if args.top is None:
        args.top = 40 if args.postmortem else 10

    if sum((args.gang, args.tune, args.rpc, args.postmortem,
            args.follow, args.goodput, args.profile, args.health,
            args.skew)) > 1:
        print("error: --gang, --tune, --rpc, --postmortem, --follow, "
              "--goodput, --profile, --health and --skew are different "
              "reports; pick one")
        return 2
    if args.diff is not None and not args.profile:
        print("error: --diff goes with --profile")
        return 2
    if args.profile:
        return _main_profile(args)
    if args.health:
        return _main_health(args)
    if args.skew:
        return _main_skew(args)
    if args.goodput:
        return _main_goodput(args)
    if args.tune:
        return _main_tune(args)
    if args.rpc:
        return _main_rpc(args)
    if args.postmortem:
        return _main_postmortem(args)
    if args.follow:
        return _main_follow(args)
    if args.gang:
        return _main_gang(args)
    if len(args.paths) > 1:
        print("error: multiple paths need --gang (per-host traces "
              "merge into one gang view)")
        return 2

    if _looks_like_jsonl(args.path):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(args.path)
        except OSError as e:
            print(f"error: {e}")
            return 1
        snap = _xprof_snapshot(records)
        if snap is None:
            print(f"no snapshot with xprof.* metrics in {args.path}")
            return 1
        print(json.dumps(snap) if args.json else render_snapshot_report(snap),
              end="" if not args.json else "\n")
        return 0

    try:
        analysis = analyze_trace(args.path, step_name=args.step_name,
                                 top_k=max(args.top, 15))
    except TraceParseError as e:
        print(f"error: {e}")
        return 1
    if args.json:
        print(json.dumps(analysis.to_dict()))
    else:
        print(render_report(analysis, top=args.top), end="")
    return 0


def _main_tune(args) -> int:
    """--tune: a tune_result.json artifact, or a telemetry JSONL dump
    whose last snapshot carries the xprof_tune section."""
    if len(args.paths) > 1:
        print("error: --tune renders one artifact at a time")
        return 2
    path = args.paths[0]
    if _looks_like_jsonl(path):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(path)
        except OSError as e:
            print(f"error: {e}")
            return 1
        doc = _tune_from_jsonl(records)
        if doc is None:
            print(f"no tune search (sections.xprof_tune) in {path}")
            return 1
    else:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: {e}")
            return 1
        if not isinstance(doc, dict) or doc.get("kind") != "tune":
            print(f"error: {path} is not a tune artifact "
                  f"(kind != 'tune')")
            return 1
    print(json.dumps(doc) if args.json else render_tune_report(doc),
          end="" if not args.json else "\n")
    return 0


def _main_goodput(args) -> int:
    """--goodput: a saved /goodput JSON document, or a JSONL whose
    newest record carries the goodput_run (collector) / goodput
    (single rank) section."""
    if len(args.paths) > 1:
        print("error: --goodput renders one file at a time")
        return 2
    path = args.paths[0]
    if _looks_like_jsonl(path):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(path)
        except OSError as e:
            print(f"error: {e}")
            return 1
        doc = _goodput_from_jsonl(records)
        if doc is None:
            print(f"no goodput ledger (sections.goodput_run / "
                  f"sections.goodput) in {path}")
            return 1
    else:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: {e}")
            return 1
        if not isinstance(doc, dict) or not doc.get("buckets"):
            print(f"error: {path} is not a goodput document "
                  f"(no buckets)")
            return 1
    print(json.dumps(doc) if args.json else render_goodput_report(doc),
          end="" if not args.json else "\n")
    return 0


def _main_health(args) -> int:
    """--health: a saved /health JSON document, or a JSONL whose
    newest record carries the health_run (collector) / health
    (single rank) section."""
    if len(args.paths) > 1:
        print("error: --health renders one file at a time")
        return 2
    path = args.paths[0]
    if _looks_like_jsonl(path):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(path)
        except OSError as e:
            print(f"error: {e}")
            return 1
        doc = _health_from_jsonl(records)
        if doc is None:
            print(f"no model-health ledger (sections.health_run / "
                  f"sections.health) in {path}")
            return 1
    else:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: {e}")
            return 1
        if not isinstance(doc, dict) or doc.get("kind") != "health_run":
            print(f"error: {path} is not a health document "
                  f"(kind != 'health_run')")
            return 1
    print(json.dumps(doc) if args.json
          else render_health_report(doc, top=args.top),
          end="" if not args.json else "\n")
    return 0


def _main_skew(args) -> int:
    """--skew: a saved /skew JSON document, or a JSONL whose newest
    record carries the skew_run (collector) / skew (single rank)
    section."""
    if len(args.paths) > 1:
        print("error: --skew renders one file at a time")
        return 2
    path = args.paths[0]
    if _looks_like_jsonl(path):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(path)
        except OSError as e:
            print(f"error: {e}")
            return 1
        doc = _skew_from_jsonl(records)
        if doc is None:
            print(f"no step-skew verdict (sections.skew_run / "
                  f"sections.skew) in {path}")
            return 1
    else:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: {e}")
            return 1
        if not isinstance(doc, dict) or doc.get("kind") != "skew_run":
            print(f"error: {path} is not a skew document "
                  f"(kind != 'skew_run')")
            return 1
    print(json.dumps(doc) if args.json
          else render_skew_report(doc, top=args.top),
          end="" if not args.json else "\n")
    return 0


def _load_profile_doc(path: str) -> Tuple[Optional[Dict[str, Any]], int]:
    """A stack-profile doc from a saved /profile JSON document or a
    JSONL carrying the profile_run/profile section; (None, rc) on
    failure, with the error already printed."""
    if _looks_like_jsonl(path):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(path)
        except OSError as e:
            print(f"error: {e}")
            return None, 1
        doc = _profile_from_jsonl(records)
        if doc is None:
            print(f"no stack profile (sections.profile_run / "
                  f"sections.profile) in {path}")
            return None, 1
        return doc, 0
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: {e}")
        return None, 1
    buckets = doc.get("buckets") if isinstance(doc, dict) else None
    if not (isinstance(buckets, dict)
            and all(isinstance(v, dict) and "children" in v
                    for v in buckets.values())):
        print(f"error: {path} is not a stack-profile document "
              f"(no per-bucket tries)")
        return None, 1
    return doc, 0


def _main_profile(args) -> int:
    """--profile: render a profile doc; with --diff, the movement
    against a prior one."""
    if len(args.paths) > 1:
        print("error: --profile renders one file at a time")
        return 2
    doc, rc = _load_profile_doc(args.paths[0])
    if doc is None:
        return rc
    if args.diff is not None:
        prior, rc = _load_profile_doc(args.diff)
        if prior is None:
            return rc
        from sparktorch_tpu.obs.profile import diff_docs

        diff = diff_docs(doc, prior)
        print(json.dumps(diff) if args.json
              else render_profile_diff(diff, top=args.top),
              end="" if not args.json else "\n")
        return 0
    print(json.dumps(doc) if args.json
          else render_profile_report(doc, top=args.top),
          end="" if not args.json else "\n")
    return 0


def _main_rpc(args) -> int:
    """--rpc: request waterfalls from a telemetry dump or a collector
    sink."""
    if len(args.paths) > 1:
        print("error: --rpc renders one JSONL file at a time")
        return 2
    path = args.paths[0]
    if not _looks_like_jsonl(path):
        print("error: --rpc reads a telemetry/collector .jsonl "
              "(rpc_spans or rpc_traces)")
        return 2
    from sparktorch_tpu.obs.sinks import read_jsonl

    try:
        records = read_jsonl(path)
    except OSError as e:
        print(f"error: {e}")
        return 1
    traces = _rpc_from_jsonl(records)
    if not traces:
        print(f"no rpc spans (sections.rpc_spans / rpc_traces) in {path}")
        return 1
    print(json.dumps(traces) if args.json
          else render_rpc_report(traces, top=args.top), end="")
    if args.json:
        print()
    return 0


def _main_postmortem(args) -> int:
    """--postmortem: render one flight-recorder bundle."""
    if len(args.paths) > 1:
        print("error: --postmortem renders one bundle at a time")
        return 2
    from sparktorch_tpu.obs.blackbox import read_postmortem

    try:
        doc = read_postmortem(args.paths[0])
    except (OSError, ValueError) as e:
        print(f"error: {e}")
        return 1
    print(json.dumps(doc) if args.json
          else render_postmortem_report(doc, top=args.top),
          end="" if not args.json else "\n")
    return 0


def _main_follow(args) -> int:
    """--follow: live-tail a JSONL sink until interrupted."""
    if len(args.paths) > 1:
        print("error: --follow tails one JSONL file at a time")
        return 2
    if not _looks_like_jsonl(args.paths[0]):
        print("error: --follow tails a telemetry/collector .jsonl")
        return 2
    try:
        for line in follow(args.paths[0]):
            print(line, flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def _main_gang(args) -> int:
    """--gang: one collector JSONL (already-merged budget) or N
    per-host traces merged here."""
    if len(args.paths) == 1 and _looks_like_jsonl(args.paths[0]):
        from sparktorch_tpu.obs.sinks import read_jsonl

        try:
            records = read_jsonl(args.paths[0])
        except OSError as e:
            print(f"error: {e}")
            return 1
        gang = _gang_from_jsonl(records)
        if gang is None:
            print(f"no merged gang budget (sections.xprof_gang) in "
                  f"{args.paths[0]}")
            return 1
    else:
        analyses = []
        for p in args.paths:
            try:
                analyses.append(analyze_trace(p, step_name=args.step_name))
            except TraceParseError as e:
                print(f"error: {e}")
                return 1
        gang = merge_analyses(analyses).to_dict()
    print(json.dumps(gang) if args.json
          else render_gang_report(gang), end="" if not args.json else "\n")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # e.g. piped into `head`
        raise SystemExit(0)
