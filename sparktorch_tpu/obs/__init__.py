"""sparktorch_tpu.obs — the unified telemetry subsystem.

One bus (:class:`Telemetry`) shared by every trainer, the parameter
server, inference, and the bench CLI: nestable timed spans, monotonic
counters, histogram metrics with p50/p95/p99 roll-ups, gauges. Sinks
stream JSONL events; :func:`render_prometheus` serves the same state
from the param server's ``/metrics`` route; gang heartbeats give
multi-process runs per-rank liveness and step skew.
"""

from sparktorch_tpu.obs.telemetry import (
    Span,
    Telemetry,
    format_key,
    get_telemetry,
    set_telemetry,
    wall_ts,
)
from sparktorch_tpu.obs.history import MetricsHistory
from sparktorch_tpu.obs.alerts import AlertManager, AlertRule
from sparktorch_tpu.obs.blackbox import (
    FlightRecorder,
    attach_recorder,
    collect_postmortem,
    read_postmortem,
)
from sparktorch_tpu.obs.goodput import (
    GoodputLedger,
    LedgerSpan,
    mfu_honest,
)
from sparktorch_tpu.obs.health import (
    HealthConfig,
    TrainHealthLedger,
    health_alert_rules,
    tree_checksum,
)
from sparktorch_tpu.obs.replay import load_bundle, replay_bundle
from sparktorch_tpu.obs.sinks import JsonlSink, read_jsonl, write_jsonl
from sparktorch_tpu.obs.prom import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    parse_prometheus,
    render_prometheus,
)
from sparktorch_tpu.obs.heartbeat import (
    HEARTBEAT_DIR_ENV,
    HeartbeatEmitter,
    gang_report,
    read_heartbeats,
)
from sparktorch_tpu.obs.log import get_logger
from sparktorch_tpu.obs.xprof import (
    GangAnalysis,
    TraceAnalysis,
    TraceParseError,
    analyze_and_publish,
    analyze_trace,
    merge_analyses,
)
from sparktorch_tpu.obs.collector import (
    FleetCollector,
    ScrapeError,
    mint_run_id,
    run_tag,
    scrape_json,
    scrape_text,
    snapshot_histogram,
)
from sparktorch_tpu.obs.rpctrace import (
    RpcTracer,
    SpanContext,
    critical_path,
    critical_summary,
    stitch_spans,
    tracer_for,
    write_chrome_trace,
)

__all__ = [
    "Span",
    "Telemetry",
    "format_key",
    "get_telemetry",
    "set_telemetry",
    "wall_ts",
    "MetricsHistory",
    "AlertManager",
    "AlertRule",
    "FlightRecorder",
    "attach_recorder",
    "collect_postmortem",
    "read_postmortem",
    "GoodputLedger",
    "LedgerSpan",
    "mfu_honest",
    "HealthConfig",
    "TrainHealthLedger",
    "health_alert_rules",
    "tree_checksum",
    "load_bundle",
    "replay_bundle",
    "JsonlSink",
    "read_jsonl",
    "write_jsonl",
    "PROMETHEUS_CONTENT_TYPE",
    "parse_prometheus",
    "render_prometheus",
    "HEARTBEAT_DIR_ENV",
    "HeartbeatEmitter",
    "gang_report",
    "read_heartbeats",
    "get_logger",
    "GangAnalysis",
    "TraceAnalysis",
    "TraceParseError",
    "analyze_and_publish",
    "analyze_trace",
    "merge_analyses",
    "FleetCollector",
    "ScrapeError",
    "mint_run_id",
    "run_tag",
    "scrape_json",
    "scrape_text",
    "snapshot_histogram",
    "RpcTracer",
    "SpanContext",
    "critical_path",
    "critical_summary",
    "stitch_spans",
    "tracer_for",
    "write_chrome_trace",
]
