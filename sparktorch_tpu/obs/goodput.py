"""Run-level goodput ledger: every second of a run, attributed.

The obs stack can trace one request (rpctrace), judge the system over
time (history/alerts), and autopsy a dead rank (blackbox) — but none
of it answers the question that decides where engineering effort goes
on a large run: *of this 40-minute training run, how many seconds were
productive?* Compile walls, restart gaps, resize stalls, checkpoint
writes, dataloader waits, and exposed collectives are each measured
SOMEWHERE (``runner.compile_s`` in the tuner, ``ctl.*`` events,
``xprof.exposed_comm_s``, orbax save walls) but never reconciled
against total wall-clock — the gap MegaScale (arXiv:2402.15627) and
Google's ML-Goodput work name as the first prerequisite for fixing
large-run efficiency. The reference had nothing here at all: its only
training signal was a per-partition loss callback to the driver.

:class:`GoodputLedger` is that reconciliation: a per-rank time ledger
that attributes the full wall-clock of a run into mutually-exclusive,
collectively-exhaustive (MECE) buckets —

- ``compute``     — train-step device time net of exposed comm, plus
                    directly-attributed compute regions (eval, drains,
                    server-side update apply);
- ``exposed_comm``— collective/wire time NOT hidden under compute:
                    per-step exposed seconds from the xprof
                    attribution when a capture was analyzed
                    (``comm_source: measured``), else the alpha-beta
                    model fraction as a labeled estimate
                    (``comm_source: estimate``), plus direct wire
                    waits (hogwild pull/push — always measured);
- ``compile``     — XLA compile walls, detected at the jit boundary
                    (cache-miss counting via ``jitted._cache_size``:
                    a step call that grew the cache is a compile, and
                    its whole wall lands here — compile dominates the
                    one device step riding in it by orders of
                    magnitude, and splitting would require a second
                    uncompiled timing of the same program);
- ``checkpoint``  — orbax save/restore walls;
- ``data_wait``   — host->device batch placement / next-chunk waits;
- ``restart_downtime`` — death detection -> relaunch gaps (the ctl /
                    ft recovery latency window);
- ``resize_downtime``  — world shrink/grow walls (drain -> generation
                    bump -> relaunch);
- ``idle``        — everything unattributed (derived:
                    ``wall - sum(attributed)``, floored at 0).

MECE is structural, not hoped-for: attribution happens through
:class:`LedgerSpan` context managers on a per-thread nesting stack —
a child span's gross duration is SUBTRACTED from its parent's
attribution, so a checkpoint inside a step chunk counts once, in
``checkpoint``. The one failure mode the invariant cannot derive away
is OVER-attribution (attributed > wall — double-counted regions or
spans on several threads): the ledger computes it explicitly
(``overattributed_s``) and the ``make bench-goodput`` gate holds it
near zero.

The ledger publishes as the ``goodput`` telemetry section (riding
every ``/telemetry`` scrape, the collector's last-good snapshots, and
postmortem bundles) plus ``goodput.*`` gauges (so ``MetricsHistory``
retains the series and burn-rate alert rules can fire on goodput
collapse). The :class:`~sparktorch_tpu.obs.collector.FleetCollector`
merges every rank's section into a run-level report served at
``GET /goodput``; ``python -m sparktorch_tpu.obs.timeline --goodput``
renders the stacked attribution bar per rank and names the biggest
thief.

Instrumentation is ambient, like :mod:`sparktorch_tpu.ft.chaos`:
trainers install their ledger process-globally (``with
ledger.activate():``) and the instrumentation points in train/, ctl/,
ft/, serve/ and utils/checkpoint call the module-level :func:`span` /
:func:`add` helpers — a single global read + None check when no
ledger is active, so un-instrumented runs pay nothing.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

from sparktorch_tpu.obs.skew import SECTION as _SKEW_SECTION
from sparktorch_tpu.obs.skew import StepSkewRing
from sparktorch_tpu.obs.telemetry import Telemetry, wall_ts

SECTION = "goodput"
RUN_SECTION = "goodput_run"

# The MECE bucket set. "idle" is DERIVED (wall - attributed), never
# attributed directly; "exposed_comm" is part-derived (the step-split
# share) and part-direct (wire waits).
BUCKETS = ("compute", "exposed_comm", "compile", "checkpoint",
           "data_wait", "restart_downtime", "resize_downtime", "idle")

# Buckets a LedgerSpan / add() may attribute directly. "step" is the
# pseudo-bucket train-step bodies use: its gross seconds are split
# into compute + exposed_comm at read time by the comm model.
_DIRECT_BUCKETS = ("compute", "exposed_comm", "compile", "checkpoint",
                   "data_wait", "restart_downtime", "resize_downtime",
                   "step")

PRODUCTIVE_BUCKETS = ("compute",)

# v5e peak (bf16). Single source of truth for MFU math — bench.py's
# mfu_honest reporting imports these, and the ledger's /goodput MFU
# uses the identical formula (mfu_honest below).
V5E_BF16_PEAK_TFLOPS = 197.0


def mfu_honest(achieved_tflops_per_chip: float,
               peak_tflops: float = V5E_BF16_PEAK_TFLOPS) -> float:
    """Model-FLOPs utilization from honest achieved TFLOPs/chip — the
    exact division bench.py's headline configs report, shared so the
    ledger's /goodput MFU and the bench can never disagree on the
    formula."""
    return achieved_tflops_per_chip / peak_tflops


def achieved_tflops_per_chip(flops_total: float, wall_s: float,
                             n_chips: int = 1) -> float:
    """Honest achieved TFLOPs per chip over a wall-clock window."""
    if wall_s <= 0 or n_chips <= 0:
        return 0.0
    return flops_total / wall_s / n_chips / 1e12


def jit_cache_size(jitted: Any) -> Optional[int]:
    """The jit dispatch cache's entry count, or None when the API is
    absent on this jax. A call that GREW the cache compiled — the
    first-call / tune-auto double-compile detection the ``compile``
    bucket is built on. (``_cache_size`` is the same probe jax's own
    test suite uses for cache-hit assertions; absence degrades to
    "no compile detection", never a wrong attribution.)"""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 - degrade, never break dispatch
        return None


# Per-thread nesting stack of open LedgerSpans (module-level: ambient
# spans from different layers must see each other's nesting).
_TLS = threading.local()

# Cross-thread view of the SAME stacks, keyed by thread ident — what
# the stack profiler (obs/profile.py) samples: sys._current_frames()
# hands it {ident: frame} and this registry answers "which ledger
# bucket is open on that thread right now". Entries live only while a
# thread has at least one open span (registered on the outermost
# __enter__, dropped on the outermost __exit__), so a dead thread's
# reused ident can never alias a stale stack. Mutated only under the
# GIL by the owning thread; readers tolerate the pop race.
_STACKS_BY_IDENT: Dict[int, List["LedgerSpan"]] = {}


def open_span_buckets() -> Dict[int, str]:
    """Snapshot {thread_ident: bucket of the innermost open span} for
    every thread currently inside a LedgerSpan. The ``step``
    pseudo-bucket reads as ``compute``: a sampler cannot split one
    stack sample by the comm model, and compute is where step samples
    overwhelmingly land. Safe to call from any thread."""
    out: Dict[int, str] = {}
    for ident, stack in list(_STACKS_BY_IDENT.items()):
        try:
            bucket = stack[-1].bucket
        except IndexError:  # lost the race with the outermost __exit__
            continue
        out[ident] = "compute" if bucket == "step" else bucket
    return out


class LedgerSpan:
    """One timed attribution region. ALWAYS times (two perf_counter
    reads, ``duration_s`` after close) so call sites can use it as
    their step clock; attributes to a ledger bucket only when a ledger
    is bound. Nesting-aware: a child's gross duration is subtracted
    from the parent's attribution (the MECE mechanism).

    ``count`` (default 1, settable before close — e.g. the number of
    fused steps a chunk dispatched) feeds the ledger's step counter
    for ``step`` spans and the per-bucket event counts otherwise.
    ``rebucket()`` may re-aim an open span (a step call discovered to
    be a compile once the jit cache-miss probe lands).

    ``step`` (optional, step spans only) is the explicit step index
    the span trains — the skew ring's alignment key across ranks;
    when None the ledger's own step counter supplies it."""

    __slots__ = ("ledger", "bucket", "labels", "count", "step", "t0",
                 "duration_s", "_child_s", "_closed")

    def __init__(self, ledger: Optional["GoodputLedger"], bucket: str,
                 labels: Optional[Dict[str, Any]] = None,
                 step: Optional[int] = None):
        if bucket not in _DIRECT_BUCKETS:
            raise ValueError(
                f"bucket {bucket!r} not attributable (want one of "
                f"{_DIRECT_BUCKETS}; 'idle' is derived)")
        self.ledger = ledger
        self.bucket = bucket
        self.labels = dict(labels or {})
        self.count = 1
        self.step = step if step is None else int(step)
        self.t0 = 0.0
        self.duration_s: Optional[float] = None
        self._child_s = 0.0
        self._closed = False

    def rebucket(self, bucket: str) -> None:
        if bucket not in _DIRECT_BUCKETS:
            raise ValueError(f"bucket {bucket!r} not attributable")
        if bucket != self.bucket:
            # count semantics change with the bucket (steps for a step
            # span, events otherwise): a fused chunk re-aimed at
            # ``compile`` is ONE compile, not steps_per_call of them.
            self.count = 1
        self.bucket = bucket

    def __enter__(self) -> "LedgerSpan":
        stack: List[LedgerSpan] = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        if not stack:
            # Outermost span on this thread: expose the stack to the
            # cross-thread sampler registry.
            _STACKS_BY_IDENT[threading.get_ident()] = stack
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        end = time.perf_counter()
        dur = end - self.t0
        self.duration_s = dur
        self._closed = True
        stack: List[LedgerSpan] = getattr(_TLS, "stack", [])
        if stack and stack[-1] is self:
            stack.pop()
        if not stack:
            _STACKS_BY_IDENT.pop(threading.get_ident(), None)
        if stack:
            # Gross duration rolls up to the parent so the parent
            # attributes only its OWN (self) time — one second of
            # wall lands in exactly one bucket.
            stack[-1]._child_s += dur
        if self.ledger is not None:
            if self.bucket == "step":
                # Step-boundary stamp for the cross-rank skew ring:
                # the span's OWN clock pair (no new clock sites),
                # recorded before _attribute so an implicit step
                # index reads the pre-increment counter.
                self.ledger._stamp_step(self.step, self.count,
                                        self.t0, end)
            self.ledger._attribute(self.bucket,
                                   max(dur - self._child_s, 0.0),
                                   self.count)


class GoodputLedger:
    """The per-rank run ledger. Construct at run start (the clock
    starts in the ctor), attribute through :class:`LedgerSpan` /
    :meth:`add`, read via :meth:`snapshot`, publish onto the bus via
    :meth:`publish` (throttled automatically from span closes when a
    bus is bound). Thread-safe."""

    def __init__(self, telemetry: Optional[Telemetry] = None,
                 rank: Optional[Any] = None,
                 publish_interval_s: float = 0.25,
                 flops_per_step: Optional[float] = None,
                 n_chips: int = 1,
                 peak_tflops: float = V5E_BF16_PEAK_TFLOPS,
                 skew_capacity: int = 512):
        self.telemetry = telemetry
        self.rank = rank
        self.publish_interval_s = float(publish_interval_s)
        self.flops_per_step = flops_per_step
        self.n_chips = int(n_chips)
        self.peak_tflops = float(peak_tflops)
        # Concurrent execution LANES attributing into this ledger
        # (e.g. train_async's N local worker threads — each thread is
        # a lane of real work, so the MECE budget is lanes x clock
        # wall, the same rank-seconds unit the run-level merge uses).
        # A single-threaded trainer leaves this at 1. Without it, N
        # threads would attribute ~N x wall and read as massive
        # over-attribution with goodput > 1.
        self.lanes = 1
        # Per-step boundary stamps for the cross-rank straggler
        # referee (obs/skew.py): step spans stamp their enter/exit
        # here, converted to wall time through the ctor anchor pair
        # below so stamps from different processes are comparable.
        self.skew = StepSkewRing(skew_capacity)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self.started_ts = wall_ts()
        self._buckets: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._step_s = 0.0
        self._n_steps = 0
        self._compiles = 0
        # Step-seconds comm split: fraction of step gross that is
        # exposed collective time. "measured" (an analyzed xprof
        # capture), "estimate" (the alpha-beta model), or "none"
        # (no model: all step time counts as compute, labeled so).
        self._comm_fraction = 0.0
        self._comm_source = "none"
        self._last_publish = 0.0
        self._closed_ts: Optional[float] = None
        self._auto_stop: Optional[threading.Event] = None

    # -- attribution ---------------------------------------------------------

    def span(self, bucket: str,
             labels: Optional[Dict[str, Any]] = None) -> LedgerSpan:
        return LedgerSpan(self, bucket, labels)

    def step_span(self, step: Optional[int] = None) -> LedgerSpan:
        """A train-step body: gross seconds split compute vs
        exposed_comm by the comm model at read time; ``count`` is the
        number of (fused) steps the call trained. ``step`` pins the
        skew ring's alignment key (trainers pass their loop index so
        ranks agree on which step is which); None falls back to this
        ledger's own step counter."""
        return LedgerSpan(self, "step", step=step)

    def add(self, bucket: str, seconds: float, count: int = 1) -> None:
        """Direct attribution (no timing) — the downtime buckets'
        entry point: the controller/supervisor already measured the
        detection->relaunch gap."""
        if bucket not in _DIRECT_BUCKETS:
            raise ValueError(f"bucket {bucket!r} not attributable")
        self._attribute(bucket, max(float(seconds), 0.0), count)

    def _stamp_step(self, step: Optional[int], count: int,
                    t0: float, t1: float) -> None:
        """Record one step span's boundary pair into the skew ring.
        ``t0``/``t1`` are the span's perf_counter reads; the ctor
        anchor pair (``started_ts``/``_t0``) converts them to wall
        time — pure arithmetic, zero new clock sites."""
        if step is None:
            with self._lock:
                step = self._n_steps  # pre-increment: _attribute runs after
        base = self.started_ts - self._t0
        self.skew.record(int(step), count, base + t0, base + t1)

    def _attribute(self, bucket: str, seconds: float, count: int) -> None:
        with self._lock:
            if bucket == "step":
                self._step_s += seconds
                self._n_steps += int(count)
            else:
                self._buckets[bucket] = (self._buckets.get(bucket, 0.0)
                                         + seconds)
                self._counts[bucket] = (self._counts.get(bucket, 0)
                                        + int(count))
                if bucket == "compile":
                    self._compiles += int(count)
            due = (self.telemetry is not None
                   and time.perf_counter() - self._last_publish
                   >= self.publish_interval_s)
        if due:
            self.publish()

    def note_compile(self, seconds: float, site: str = "?") -> None:
        """A detected compile wall (cache-miss jit call, AOT lower) —
        sugar over ``add('compile', ...)`` that also counts the site."""
        self.add("compile", seconds)
        if self.telemetry is not None:
            self.telemetry.counter("goodput.compiles_total",
                                   labels={"site": site})

    def set_comm_model(self, fraction: float, source: str) -> None:
        """Install the step-seconds comm split: ``fraction`` of step
        gross is exposed collective time. ``source`` is ``measured``
        (an analyzed capture — always wins) or ``estimate`` (the
        alpha-beta model — never overwrites a measured split)."""
        if source not in ("measured", "estimate"):
            raise ValueError(f"comm source {source!r} "
                             "(want measured|estimate)")
        with self._lock:
            if source == "estimate" and self._comm_source == "measured":
                return
            self._comm_fraction = min(max(float(fraction), 0.0), 1.0)
            self._comm_source = source

    def apply_analysis(self, analysis: Any) -> None:
        """Adopt a :class:`~sparktorch_tpu.obs.xprof.TraceAnalysis`'s
        measured exposed-comm fraction (retroactive: the split is
        applied to ALL step seconds at read time, so the estimate a
        run started under is replaced, not blended)."""
        frac = getattr(analysis, "exposed_comm_fraction", None)
        if frac is None and isinstance(analysis, Mapping):
            frac = analysis.get("exposed_comm_fraction")
        if frac is not None:
            self.set_comm_model(float(frac), "measured")

    # -- reading -------------------------------------------------------------

    def wall_s(self) -> float:
        with self._lock:
            return self._wall_locked()

    def _wall_locked(self) -> float:
        if self._closed_ts is not None:
            return self._closed_ts - self._t0
        return time.perf_counter() - self._t0

    def snapshot(self) -> Dict[str, Any]:
        """The MECE accounting NOW: bucket seconds + fractions, idle
        derived, goodput = productive / wall, comm-source label, and
        MFU when the workload declared FLOPs. ``wall_s`` is the MECE
        budget — clock wall x lanes (lane-seconds, the same
        rank-seconds unit the run merge sums); ``clock_s`` is the raw
        single-clock wall."""
        with self._lock:
            clock = self._wall_locked()
            lanes = max(1, int(self.lanes))
            wall = clock * lanes
            buckets = dict(self._buckets)
            counts = dict(self._counts)
            step_s = self._step_s
            n_steps = self._n_steps
            frac = self._comm_fraction
            source = self._comm_source
        exposed_from_steps = step_s * frac
        buckets["compute"] = (buckets.get("compute", 0.0)
                              + step_s - exposed_from_steps)
        buckets["exposed_comm"] = (buckets.get("exposed_comm", 0.0)
                                   + exposed_from_steps)
        attributed = sum(buckets.values())
        idle = max(wall - attributed, 0.0)
        over = max(attributed - wall, 0.0)
        buckets["idle"] = idle
        full = {b: round(buckets.get(b, 0.0), 6) for b in BUCKETS}
        denom = max(wall, 1e-9)
        productive = sum(full[b] for b in PRODUCTIVE_BUCKETS)
        doc: Dict[str, Any] = {
            "rank": self.rank,
            "started_ts": self.started_ts,
            "wall_s": round(wall, 6),
            "clock_s": round(clock, 6),
            "lanes": lanes,
            "buckets": full,
            "fractions": {b: round(full[b] / denom, 6) for b in BUCKETS},
            "counts": counts,
            "n_steps": n_steps,
            "compiles": self._compiles,
            "goodput": round(productive / denom, 6),
            "comm_source": source,
            "overattributed_s": round(over, 6),
        }
        if self.flops_per_step:
            flops_total = float(self.flops_per_step) * n_steps
            achieved = achieved_tflops_per_chip(flops_total, wall,
                                                self.n_chips)
            doc["flops_per_step"] = float(self.flops_per_step)
            # n_chips/peak ride the doc so the run-level merge divides
            # by this rank's REAL capacity, not an assumed 1 chip at
            # the default peak — /goodput must agree with the per-rank
            # docs it embeds.
            doc["n_chips"] = self.n_chips
            doc["peak_tflops"] = self.peak_tflops
            doc["achieved_tflops_per_chip"] = round(achieved, 4)
            doc["mfu"] = round(mfu_honest(achieved, self.peak_tflops), 6)
        return doc

    # -- publication ---------------------------------------------------------

    def publish(self, event: bool = False) -> Dict[str, Any]:
        """Refresh the bus's ``goodput`` section + ``goodput.*``
        gauges (the series the history tier retains and alert rules
        judge). ``event=True`` additionally emits one ``goodput.ledger``
        event to the sinks — the condensed record ``timeline --follow``
        renders."""
        doc = self.snapshot()
        with self._lock:
            self._last_publish = time.perf_counter()
        tele = self.telemetry
        if tele is None:
            return doc
        tele.set_section(SECTION, doc)
        if len(self.skew):
            # The skew section rides beside goodput only once a step
            # has stamped — a server/ctl ledger with no step spans
            # must not publish an empty ring (the collector's /skew
            # stays 404 until a real stamp exists).
            sdoc = self.skew.snapshot()
            sdoc["rank"] = self.rank
            sdoc["started_ts"] = self.started_ts
            tele.set_section(_SKEW_SECTION, sdoc)
        labels = ({"rank": str(self.rank)}
                  if self.rank is not None else None)
        for b in BUCKETS:
            tele.gauge(f"goodput.{b}_s", doc["buckets"][b], labels=labels)
        tele.gauge("goodput.fraction", doc["goodput"], labels=labels)
        tele.gauge("goodput.wall_s", doc["wall_s"], labels=labels)
        tele.gauge("goodput.overattributed_s", doc["overattributed_s"],
                   labels=labels)
        if "mfu" in doc:
            tele.gauge("goodput.mfu", doc["mfu"], labels=labels)
        if event:
            thief = biggest_thief(doc)
            tele.event("goodput.ledger", rank=self.rank,  # lint-obs: ok (rank IS this record's identity: per-rank ledger event on the local bus, no collector tag to collide with)
                       wall_s=doc["wall_s"], goodput=doc["goodput"],
                       comm_source=doc["comm_source"],
                       thief=(thief[0] if thief else None),
                       thief_s=(round(thief[1], 6) if thief else None))
        return doc

    def start_auto_publish(self, interval_s: float = 0.5
                           ) -> "GoodputLedger":
        """Background refresh of the published section on a cadence —
        for long-lived processes (ctl workers, servers) whose ledger
        would otherwise only publish when something is attributed,
        leaving the scraped ``wall_s`` stale between events. Daemon
        thread; close() stops it."""
        if self._auto_stop is not None or self.telemetry is None:
            return self
        stop = self._auto_stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                self.publish()

        threading.Thread(target=loop, daemon=True,
                         name="goodput-publish").start()
        return self

    def close(self) -> Dict[str, Any]:
        """Freeze the clock and publish the final accounting (with the
        ``goodput.ledger`` sink record): a finished run's last ledger
        survives in the section for whoever scrapes it."""
        with self._lock:
            if self._closed_ts is None:
                self._closed_ts = time.perf_counter()
        if self._auto_stop is not None:
            self._auto_stop.set()
        return self.publish(event=True)

    # -- ambient installation ------------------------------------------------

    @contextlib.contextmanager
    def activate(self):
        """Install this ledger as the process-global ambient ledger
        for a with-block (the chaos-injector shape: instrumentation
        points deep inside worker/writer threads reach it without a
        handle threaded through every layer). Always restores the
        previous ledger; closes this one on exit."""
        prev = install(self)
        try:
            yield self
        finally:
            install(prev)
            self.close()


# ---------------------------------------------------------------------------
# Ambient (process-global) ledger + no-op-cheap helpers
# ---------------------------------------------------------------------------

_ACTIVE: Optional[GoodputLedger] = None
_ACTIVE_LOCK = threading.Lock()


def install(ledger: Optional[GoodputLedger]) -> Optional[GoodputLedger]:
    """Swap the ambient ledger; returns the previous one."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, ledger
    return prev


def active() -> Optional[GoodputLedger]:
    return _ACTIVE


def span(bucket: str, labels: Optional[Dict[str, Any]] = None
         ) -> LedgerSpan:
    """A :class:`LedgerSpan` bound to the ambient ledger (or unbound —
    it still times, so call sites can use ``duration_s`` as their
    step clock whether or not a ledger is active)."""
    return LedgerSpan(_ACTIVE, bucket, labels)


def step_span(step: Optional[int] = None) -> LedgerSpan:
    return LedgerSpan(_ACTIVE, "step", step=step)


def add(bucket: str, seconds: float, count: int = 1) -> None:
    """Direct attribution to the ambient ledger; no-op without one."""
    ledger = _ACTIVE
    if ledger is not None:
        ledger.add(bucket, seconds, count)


def note_compile(seconds: float, site: str = "?") -> None:
    ledger = _ACTIVE
    if ledger is not None:
        ledger.note_compile(seconds, site=site)


def set_comm_model(fraction: float, source: str) -> None:
    ledger = _ACTIVE
    if ledger is not None:
        ledger.set_comm_model(fraction, source)


# ---------------------------------------------------------------------------
# Run-level merge (the collector's /goodput)
# ---------------------------------------------------------------------------


def biggest_thief(doc: Mapping[str, Any],
                  exclude: Tuple[str, ...] = ("compute",)
                  ) -> Optional[Tuple[str, float]]:
    """The largest non-compute bucket of a ledger/run doc — the one
    number an operator acts on. None when nothing is attributed."""
    buckets = doc.get("buckets") or {}
    ranked = sorted(((b, float(s)) for b, s in buckets.items()
                     if b not in exclude and s > 0),
                    key=lambda kv: -kv[1])
    return ranked[0] if ranked else None


def merge_sections(rank_docs: Mapping[Any, Mapping[str, Any]],
                   skew: Optional[Mapping[str, Any]] = None
                   ) -> Dict[str, Any]:
    """Fold per-rank ``goodput`` sections into ONE run-level report —
    what ``GET /goodput`` serves. Bucket seconds SUM across ranks (a
    rank-second is the unit: 2 ranks idle for 1s is 2 rank-seconds of
    idle), wall sums likewise, and the run goodput fraction is
    productive rank-seconds over total rank-seconds. MFU aggregates
    flops-weighted over the ranks that declared FLOPs. The per-rank
    docs ride along so the timeline can render one bar per rank.

    ``skew`` (a merged ``skew_run`` doc from
    :func:`sparktorch_tpu.obs.skew.merge_sections`, when the caller —
    the collector — has one) refines ``biggest_thief``: when the
    thief is ``exposed_comm`` and straggler wait dominates wire, the
    thief is renamed ``straggler_wait`` with the laggard rank, so the
    one number an operator acts on points at the slow rank instead of
    the collective."""
    per_rank: Dict[str, Dict[str, Any]] = {}
    buckets = {b: 0.0 for b in BUCKETS}
    counts: Dict[str, int] = {}
    wall = 0.0
    n_steps = 0
    compiles = 0
    over = 0.0
    sources = set()
    flops_total = 0.0
    chip_seconds = 0.0
    peak_flop_seconds = 0.0  # aggregate capacity of the flops ranks
    for rank, doc in sorted(rank_docs.items(), key=lambda kv: str(kv[0])):
        if not isinstance(doc, Mapping) or "buckets" not in doc:
            continue
        per_rank[str(rank)] = dict(doc)
        for b in BUCKETS:
            buckets[b] += float((doc["buckets"] or {}).get(b, 0.0))
        for b, n in (doc.get("counts") or {}).items():
            counts[b] = counts.get(b, 0) + int(n)
        wall += float(doc.get("wall_s") or 0.0)
        n_steps += int(doc.get("n_steps") or 0)
        compiles += int(doc.get("compiles") or 0)
        over += float(doc.get("overattributed_s") or 0.0)
        sources.add(str(doc.get("comm_source") or "none"))
        if doc.get("flops_per_step"):
            rank_chips = int(doc.get("n_chips") or 1)
            rank_peak = float(doc.get("peak_tflops")
                              or V5E_BF16_PEAK_TFLOPS)
            rank_wall = float(doc.get("wall_s") or 0.0)
            flops_total += (float(doc["flops_per_step"])
                            * int(doc.get("n_steps") or 0))
            chip_seconds += rank_wall * rank_chips
            peak_flop_seconds += rank_wall * rank_chips * rank_peak * 1e12
    denom = max(wall, 1e-9)
    productive = sum(buckets[b] for b in PRODUCTIVE_BUCKETS)
    run: Dict[str, Any] = {
        "kind": "goodput_run",
        "ts": wall_ts(),
        "n_ranks": len(per_rank),
        "wall_s": round(wall, 6),
        "buckets": {b: round(s, 6) for b, s in buckets.items()},
        "fractions": {b: round(s / denom, 6) for b, s in buckets.items()},
        "counts": counts,
        "n_steps": n_steps,
        "compiles": compiles,
        "goodput": round(productive / denom, 6),
        "overattributed_s": round(over, 6),
        # One label for the whole run: measured wins only when EVERY
        # contributing rank measured; a mixed run is labeled mixed so
        # nobody mistakes a half-estimated number for ground truth.
        "comm_source": (sources.pop() if len(sources) == 1 else "mixed"),
        "per_rank": per_rank,
    }
    thief = biggest_thief(run)
    if thief:
        run["biggest_thief"] = {"bucket": thief[0],
                                "seconds": round(thief[1], 6),
                                "fraction": round(thief[1] / denom, 6)}
        if thief[0] == "exposed_comm" and isinstance(skew, Mapping):
            straggler = float(skew.get("straggler_wait_s") or 0.0)
            wire = float(skew.get("wire_s") or 0.0)
            if straggler > wire and straggler > 0:
                bt = run["biggest_thief"]
                bt["bucket"] = "straggler_wait"
                bt["of"] = "exposed_comm"
                bt["seconds"] = round(straggler, 6)
                bt["fraction"] = round(straggler / denom, 6)
                lag = (skew.get("laggard") or {}).get("rank")
                if lag is not None:
                    bt["laggard"] = lag
    if flops_total > 0 and chip_seconds > 0:
        # Per-chip rate over the flops-declaring ranks' chip-seconds;
        # MFU against their AGGREGATE capacity (each rank's own chip
        # count and peak) — so the run report can never disagree with
        # the per-rank docs it embeds.
        achieved = achieved_tflops_per_chip(flops_total, chip_seconds)
        run["achieved_tflops_per_chip"] = round(achieved, 4)
        run["mfu"] = round(flops_total / peak_flop_seconds, 6)
    return run


def sections_from_snapshots(snapshots: Mapping[Any, Optional[Mapping]]
                            ) -> Dict[Any, Mapping[str, Any]]:
    """Pull each rank's ``goodput`` section out of its (last-good)
    telemetry snapshot; ranks without one are skipped."""
    out: Dict[Any, Mapping[str, Any]] = {}
    for rank, snap in snapshots.items():
        section = ((snap or {}).get("sections") or {}).get(SECTION)
        if isinstance(section, Mapping):
            out[rank] = section
    return out
