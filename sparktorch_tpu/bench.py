"""Benchmark suite: the BASELINE.md configs (plus extensions) on real TPU.

The reference publishes no numbers (BASELINE.md), so these are the
project's measured baselines. BASELINE.json configs:

1. mnist_mlp_sync     — MNIST 3-layer MLP, synchronous DP
2. lazy_cnn_sync      — MNIST CNN with LAZY model materialization
3. resnet18_hogwild   — ResNet-18/CIFAR-10 shapes, async param server
4. bert_dp            — BERT-base-shape encoder, sync DP (compute-bound)
5. resnet50_inference — ResNet-50 batch inference (1M-row projection)

Extensions beyond the reference's scope: mnist_cnn_sync (the headline),
long_context_lm (flash kernels at seq 8192), moe_lm (switch MoE vs its
dense twin, with a comm/compute budget from an analyzed XLA capture),
hogwild_wire (dill vs framed-binary parameter-server wire on real
sockets), hogwild_chaos (supervised recovery from one seeded worker
kill), hogwild_chaos_soak (multi-round random kill/freeze/drop
schedule), sharded_trace (capture→analyze→publish trace-attribution
round-trip) — the last three are gates, not just measurements.

Each bench returns a summary dict (examples/sec/chip + p50/p99 step
times where steps exist) and appends raw per-phase records to a JSONL
log (the protocol BASELINE.md prescribes: raw logs under
``benchmarks/``).

Timing: on the tunneled axon platform ``block_until_ready``
under-blocks, so every measured region ends with a forced
materialization (``float(jnp.sum(...))``).

CLI: ``sparktorch-tpu-bench [--config all|headline|<name>] [--log PATH]``.
``headline`` prints the single JSON line the benchmark driver consumes
(same MNIST-CNN metric as round 1, for cross-round comparability).
"""

from __future__ import annotations

import argparse
import gc
import json
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from sparktorch_tpu.obs.telemetry import wall_ts

# Measured reference proxy (examples/sec) for the MNIST-CNN workload:
# torch-CPU forward+backward+Adam, batch 1024, on this machine — the
# substrate the reference's own tests/CI train on (environment.yml
# pins CPU pytorch). Measured 2026-07-29 by benchmarks/reference_proxy.py.
REFERENCE_BASELINE_EXAMPLES_PER_SEC = 1120.8

# v5e single-chip peaks, for MFU/roofline fields (public spec values).
# The bf16 peak lives in obs.goodput (single source: the run-level
# goodput ledger's MFU and the bench headline use the SAME constant
# and the same mfu_honest division, so /goodput and a bench record can
# never disagree on the formula).
from sparktorch_tpu.obs.goodput import (  # noqa: E402
    V5E_BF16_PEAK_TFLOPS,
    mfu_honest as _mfu_honest,
)

V5E_HBM_GB_PER_S = 819.0


def _materialize(*arrays) -> None:
    import jax.numpy as jnp

    for a in arrays:
        float(jnp.sum(a)) if hasattr(a, "dtype") else None


def _steps_summary(times: List[float]) -> Dict[str, float]:
    ts = np.asarray(sorted(times))
    return {
        "step_time_p50_s": float(np.percentile(ts, 50)),
        "step_time_p99_s": float(np.percentile(ts, 99)),
        "step_time_mean_s": float(ts.mean()),
    }


def _xla_cost_per_step(epoch, epoch1, state, batch):
    """XLA's own accounting for ONE train step: ``flops`` (executed
    HLO flops — includes optimizer, layernorms, any remat) and
    ``bytes accessed`` (HBM traffic as modeled by the compiler). Both
    are PER-DEVICE numbers — cost_analysis runs on the SPMD-partitioned
    per-device module (verified against a hand-counted matmul on an
    8-device mesh). The analysis runs on a SINGLE-step program
    (``epoch1``): backends disagree on whether a scanned chunk's while
    body is counted once or trip-count times (TPU counts it once —
    discovered when the 10-step chunk reported exactly 1/10 of the
    analytic FLOPs), and a length-1 program is unambiguous either way.
    This is the methodology-free cross-check for every analytic MFU
    number, costed by the compiler that scheduled it.

    Returns ``(cost_dict_or_None, compiled_or_None)`` — ``compiled``
    is the AOT executable of the MEASURED chunk, which the caller
    reuses so the jit cache doesn't compile it a second time."""
    try:
        compiled = epoch.lower(state, batch).compile()
    except Exception:
        return None, None
    try:
        ca = epoch1.lower(state, batch).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", -1.0))
        byts = float(ca.get("bytes accessed", -1.0))
        if flops <= 0:
            return None, compiled
        return {
            "xla_flops_per_step": flops,
            "xla_bytes_per_step": byts if byts > 0 else None,
        }, compiled
    except Exception:  # cost_analysis availability varies by backend;
        # keep the measured chunk's AOT executable either way.
        return None, compiled


def _sync_epoch_bench(spec, x, y, batch_size: int, iters: int = 30,
                      warmup: int = 3, chunks: int = 8,
                      repeats: int = 5, with_cost_analysis: bool = False,
                      with_trace: bool = False) -> dict:
    """Shared harness for the sync-DP configs: whole chunks of steps
    fused into one compiled call (the framework's fast path).

    Estimator (round 4): PAIRED-SPAN SLOPE. Each repeat times a short
    span (1 fused call of ``iters`` steps) and a long span (``chunks``
    calls dispatched back-to-back), each ended by ONE forced
    materialization; per-step time is the slope
    ``(T_long - T_short) / ((chunks-1)*iters)``, which cancels the
    constant per-span sync cost. On this rig that cost is a 75-115 ms
    tunnel round-trip — the round-3 estimator paid it once per chunk
    (~77% of every measured 30-step chunk) and its run-to-run
    variation WAS the headline's 289k-375k spread
    (benchmarks/headline_probe.jsonl). Reports the median over
    ``repeats`` interleaved slope samples plus best and spread, so a
    regression is distinguishable from residual noise."""
    import jax

    from sparktorch_tpu.obs import get_telemetry
    from sparktorch_tpu.parallel.mesh import MeshConfig, build_mesh, replicated
    from sparktorch_tpu.train.step import create_train_state, make_train_epoch
    from sparktorch_tpu.train.sync import prepare_sharded_batch
    from sparktorch_tpu.utils.data import handle_features

    # Per-phase attribution for the BENCH record: every phase below is
    # a span on the process bus, and the record carries the phase-
    # seconds breakdown — so a regression names its phase (data, init,
    # compile+warmup, measure) instead of being one opaque rate drop.
    tele = get_telemetry()
    devices = jax.devices()
    mesh = build_mesh(MeshConfig(), devices)
    with tele.span("bench/data") as _sp_data:
        batch, _ = handle_features(x, y)
        batch = prepare_sharded_batch(batch, mesh)
        _sp_data.sync(batch.x)
    tx = spec.make_optimizer()
    with tele.span("bench/init") as _sp_init, mesh:
        state = jax.jit(
            lambda: create_train_state(spec, jax.random.key(0),
                                       sample_x=batch.x[:1], tx=tx),
            out_shardings=replicated(mesh),
        )()
        _sp_init.sync(state.step)
    with tele.span("bench/compile_warmup") as _sp_warm:
        epoch = make_train_epoch(spec.make_module().apply, spec.loss_fn(), tx,
                                 mesh, steps_per_call=iters)
        cost = None
        if with_cost_analysis:
            epoch1 = make_train_epoch(spec.make_module().apply,
                                      spec.loss_fn(), tx, mesh,
                                      steps_per_call=1)
            cost, compiled = _xla_cost_per_step(epoch, epoch1, state, batch)
            if compiled is not None:
                epoch = compiled  # one compile serves analysis AND timing
        for _ in range(warmup):
            state, metrics = epoch(state, batch)
        _materialize(metrics.loss)
        _sp_warm.synced = True  # _materialize above fenced the device

    slopes = []  # per-step seconds, one sample per repeat
    n_long = max(chunks, 2)
    with tele.span("bench/measure") as _sp_measure:
        for _ in range(max(2, repeats)):
            t0 = time.perf_counter()
            state, metrics = epoch(state, batch)
            _materialize(metrics.loss)
            t_short = time.perf_counter() - t0
            while True:
                t0 = time.perf_counter()
                for _ in range(n_long):
                    state, metrics = epoch(state, batch)
                _materialize(metrics.loss)
                t_long = time.perf_counter() - t0
                # The difference must dwarf the sync-cost jitter
                # (+-40 ms observed): grow the long span until the
                # extra compute is >= 1.6 s, so jitter stays a <=2.5%
                # effect. The grown span carries over to the
                # remaining repeats.
                if t_long - t_short >= 1.6 or n_long >= 512:
                    break
                n_long *= 2
            # n_long calls vs 1 call: the extra (n_long-1)*iters steps
            # ran with zero extra syncs, so the difference is pure
            # step time.
            slopes.append((t_long - t_short) / max((n_long - 1) * iters, 1))
        _sp_measure.synced = True  # every iteration ended in a fence
    # An RTT drop between the paired spans can push a sample to ~0 or
    # negative; the median over repeats is robust to those, but drop
    # them from the reported spread so it reflects usable samples.
    # Trim SYMMETRICALLY: a near-zero positive slope is the same RTT
    # artifact as a negative one, and leaving it in wildly inflates
    # rate_best/rate_spread_pct (ADVICE r04) — anything below 20% of
    # the positive median is jitter, not a measurement.
    # Optional trace-attribution phase (with_trace): capture an XLA
    # profile of two more fused-epoch calls and machine-read it
    # (obs.xprof) — the per-collective comm/compute budget then rides
    # the record beside the rate, and the same xprof.* metrics land on
    # the bus for --telemetry-dump / /metrics parity.
    trace_rec = None
    _sp_trace = None
    if with_trace:
        import tempfile

        from sparktorch_tpu.utils.tracing import profile_run, step_annotation

        with tele.span("bench/trace") as _sp_trace, \
                tempfile.TemporaryDirectory() as td:
            with profile_run(td, telemetry=tele) as prof_handle:
                for i in range(2):
                    with step_annotation(i, telemetry=tele):
                        state, metrics = epoch(state, batch)
                    _materialize(metrics.loss)
            _sp_trace.synced = True
        analysis = prof_handle["analysis"]
        if analysis is not None:
            trace_rec = {
                "comm_s": round(analysis.comm_s, 6),
                "comm_fraction": round(analysis.comm_fraction, 4),
                "overlap_fraction": round(analysis.overlap_fraction, 4),
                "collective_s": {k: round(v, 6)
                                 for k, v in analysis.family_s().items()},
                "collective_counts": analysis.family_counts(),
                "n_collective_events": analysis.n_collective_events,
            }

    good = [s for s in slopes if s > 0]
    if good:
        floor = 0.2 * float(np.median(good))
        good = [s for s in good if s >= floor]
    if not good:
        # Degenerate link (every sample non-positive): fall back to
        # the whole-span mean INCLUDING its one sync cost — an upper
        # bound on step time, so the reported rate is conservative —
        # rather than crashing the whole benchmark run.
        good = [t_long / max(n_long * iters, 1)]
    med = float(np.median(good))
    best = min(good)
    rates = [batch_size / s / len(devices) for s in good]
    per_chip = batch_size / med / len(devices)
    spread_pct = 100.0 * (max(rates) - min(rates)) / max(np.median(rates), 1e-9)
    out = {
        "examples_per_sec_per_chip": round(per_chip, 1),
        "rate_best": round(batch_size / best / len(devices), 1),
        "rate_samples": [round(r, 1) for r in rates],
        "rate_spread_pct": round(spread_pct, 1),
        "n_chips": len(devices),
        "final_loss": float(np.asarray(metrics.loss)[-1]),
        # Where this config's wall time went — the per-phase breakdown
        # the BENCH logs owe (mirrors the bus's bench/* spans).
        "phase_s": {
            "data": round(_sp_data.duration_s, 3),
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
        },
        **_steps_summary(good),
    }
    if cost is not None:
        out.update(cost)
    if trace_rec is not None:
        # The comm/compute budget section: seconds join the phase
        # breakdown, the attribution detail rides beside it.
        out["comm_budget"] = trace_rec
        out["phase_s"]["trace"] = round(_sp_trace.duration_s, 3)
        out["phase_s"]["comm_s"] = trace_rec["comm_s"]
        out["comm_fraction"] = trace_rec["comm_fraction"]
        out["overlap_fraction"] = trace_rec["overlap_fraction"]
    return out


# ---------------------------------------------------------------------------
# Configs
# ---------------------------------------------------------------------------


def bench_mnist_mlp_sync() -> dict:
    """BASELINE config 1 (examples/simple_dnn.py workload)."""
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.utils.serde import ModelSpec

    rng = np.random.default_rng(0)
    batch = 1024
    x = rng.normal(0, 1, (batch, 784)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int32)
    spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3},
                     input_shape=(784,))
    out = _sync_epoch_bench(spec, x, y, batch)
    return {"config": "mnist_mlp_sync", "unit": "examples/sec/chip", **out}


def bench_mnist_cnn_sync() -> dict:
    """The round-1 headline workload (examples/simple_cnn.py)."""
    from sparktorch_tpu.models import MnistCNN
    from sparktorch_tpu.utils.serde import ModelSpec

    rng = np.random.default_rng(0)
    batch = 1024
    x = rng.normal(0, 1, (batch, 784)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int32)
    spec = ModelSpec(module=MnistCNN(), loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3},
                     input_shape=(784,))
    out = _sync_epoch_bench(spec, x, y, batch)
    return {"config": "mnist_cnn_sync", "unit": "examples/sec/chip", **out}


def bench_lazy_cnn_sync() -> dict:
    """BASELINE config 2: the LAZY serialization path — the model
    class ships unmaterialized and is first instantiated here
    (examples/lazy_load_cnn.py; reference util.py:148-179)."""
    from sparktorch_tpu.models import MnistCNN
    from sparktorch_tpu.utils.serde import deserialize_model, serialize_model_lazy

    payload = serialize_model_lazy(
        MnistCNN, criterion="cross_entropy", optimizer="adam",
        optimizer_params={"lr": 1e-3}, input_shape=(784,),
    )
    t0 = time.perf_counter()
    spec = deserialize_model(payload)
    lazy_materialize_s = time.perf_counter() - t0

    rng = np.random.default_rng(0)
    batch = 1024
    x = rng.normal(0, 1, (batch, 784)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int32)
    out = _sync_epoch_bench(spec, x, y, batch)
    return {"config": "lazy_cnn_sync", "unit": "examples/sec/chip",
            "lazy_materialize_s": round(lazy_materialize_s, 4), **out}


def bench_resnet18_hogwild() -> dict:
    """BASELINE config 3: ResNet-18 on CIFAR-10 shapes through the
    async param server (device-pinned workers, versioned pulls), plus
    a SYNC ResNet-18 leg at the same minibatch so async efficiency
    (hogwild rate / sync rate) is a measured number, not an
    extrapolation. Round 4 hardening: 256 push windows per run (4x
    round 3) and median-of-5 repeats — the spread target is <=20%."""
    import jax

    from sparktorch_tpu.models.resnet import resnet18
    from sparktorch_tpu.obs import get_telemetry
    from sparktorch_tpu.train.hogwild import train_async
    from sparktorch_tpu.utils.serde import ModelSpec

    tele = get_telemetry()
    with tele.span("bench/data") as _sp_data:
        rng = np.random.default_rng(0)
        n, mb = 2048, 256
        x = rng.normal(0, 1, (n, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, (n,)).astype(np.int32)
    with tele.span("bench/init") as _sp_init:
        spec = ModelSpec(module=resnet18(num_classes=10), loss="cross_entropy",
                         optimizer="sgd", optimizer_params={"lr": 1e-2},
                         input_shape=(32, 32, 3))
    # push_every=4: the accumulation knob is part of the async design
    # (k on-device grad means per server apply — wire/apply traffic
    # drops 4x, the same examples train).
    iters = 1024  # 256 push windows per worker: long spans beat jitter
    # Fixed warmup with the SAME shapes and window size: train_async
    # builds fresh jitted closures per call, so this relies on the
    # persistent compilation cache (enabled in main()) to make the
    # measured runs compile-free.
    with tele.span("bench/compile_warmup") as _sp_warm:
        train_async(spec, x, labels=y, iters=8, mini_batch=mb, push_every=4)

    def _one_run(transport: str = "local",
                 run_iters: int = iters) -> tuple[float, dict, dict]:
        t0 = time.perf_counter()
        result = train_async(spec, x, labels=y, iters=run_iters,
                             mini_batch=mb, push_every=4,
                             transport=transport)
        dt = time.perf_counter() - t0
        n_workers = len(jax.devices())
        # One push per window: count distinct (worker, dispatch-ts)
        # pairs, not per-iteration records (push_every=4 emits 4
        # records/push).
        pushes = len({(m["worker"], m["t"]) for m in result.metrics})
        n_rec = len(result.metrics)
        # Steady-state: drop everything up to and INCLUDING the window
        # dispatched at the second timestamp — that window's compute
        # happened before the measured span starts (span begins at
        # uts[1]), so counting it would inflate the rate by ~1 window.
        # The span STARTS at a dispatch timestamp but ENDS at t_done —
        # the device sync each worker records when its final loss
        # materializes — so async dispatch can't overstate throughput.
        uts = sorted({m["t"] for m in result.metrics})
        t_done = [m["t_done"] for m in result.metrics if "t_done" in m]
        if len(uts) > 2 and t_done:
            n_steady = sum(1 for m in result.metrics if m["t"] > uts[1])
            steady = n_steady * mb / (max(t_done) - uts[1]) / n_workers
        else:
            steady = n_rec * mb / dt / n_workers
        budget = (result.summary or {}).get("hogwild_budget", {})
        return steady, {"n_chips": n_workers, "pushes": pushes,
                        "iters_recorded": n_rec, "dt": dt,
                        "final_loss": result.metrics[-1]["loss"]}, budget

    # Five measured repeats: report the median and the spread so a
    # regression is distinguishable from run-to-run variance. The
    # auxiliary stats come from the median run so they can't
    # contradict the headline rate.
    with tele.span("bench/measure") as _sp_measure:
        runs = sorted([_one_run() for _ in range(5)], key=lambda r: r[0])
        rates = [r[0] for r in runs]
        per_chip, info, budget = runs[len(runs) // 2]
        spread_pct = 100.0 * (rates[-1] - rates[0]) / max(
            rates[len(rates) // 2], 1e-9
        )
        times = [info["dt"] / max(1, info["iters_recorded"])] * max(
            1, info["iters_recorded"]
        )

        # Wire ablation: the same workload over the HTTP transport
        # (the deployment wire; binary frames by default since the
        # net/ subsystem landed). local-vs-http separates the DESIGN
        # overhead (server round-trips, pull placement, materialize
        # fences) from the WIRE itself. Fault-isolated: a tunnel
        # trough stalling a 45 MB pull past even the generous deadline
        # must not discard the already-measured local numbers — the
        # failure is recorded instead.
        try:
            http_rate, _, http_budget = _one_run(
                transport="http", run_iters=max(64, iters // 4))
            http_error = None
        except Exception as e:
            http_rate, http_budget = 0.0, {}
            http_error = f"{type(e).__name__}: {e}"
            if e.__cause__ is not None:  # the worker's root failure
                http_error += (f" (from {type(e.__cause__).__name__}: "
                               f"{e.__cause__})")
            http_error = http_error[:300]

    # The decomposition the efficiency ratio owes: where the median
    # run's worker wall time went, as fractions that sum to ~1
    # (pull wire, pulled-params placement, async dispatch, the push's
    # device-draining materialize fence, push wire + server apply,
    # stop-poll, and unattributed loop bookkeeping).
    budget_rec = {}
    if budget and budget.get("loop_s"):
        loop_s = budget["loop_s"]
        phases = ("pull_s", "pull_place_s", "dispatch_s",
                  "push_materialize_s", "push_wire_s", "poll_s",
                  "drain_s", "other_s")
        budget_rec = {
            "budget_loop_s": round(loop_s, 3),
            **{f"budget_{k}": round(budget.get(k, 0.0), 3)
               for k in phases},
            "budget_fractions": {
                k: round(budget.get(k, 0.0) / loop_s, 4) for k in phases
            },
            "pull_mb": round(budget.get("pull_bytes", 0) / 1e6, 2),
            "push_mb": round(budget.get("push_bytes", 0) / 1e6, 2),
            "pulls": int(budget.get("pulls", 0)),
            "pull_fresh": int(budget.get("pull_fresh", 0)),
        }

    # Sync twin at the same PER-CHIP batch: each hogwild worker
    # computes 256-row minibatches, so the sync leg runs 256 rows per
    # chip (global batch mb x n_chips, tiling the dataset when the rig
    # has more chips than 2048 rows cover) — the async/sync ratio then
    # isolates server/transport overhead, not batch-size utilization.
    n_chips_now = len(jax.devices())
    n_sync = mb * n_chips_now
    reps = -(-n_sync // n)
    xs = np.tile(x, (reps, 1, 1, 1))[:n_sync]
    ys = np.tile(y, reps)[:n_sync]
    sync = _sync_epoch_bench(spec, xs, ys, n_sync,
                             iters=16, warmup=2, chunks=4)
    sync_rate = sync["examples_per_sec_per_chip"]
    return {
        "config": "resnet18_hogwild", "unit": "examples/sec/chip",
        "examples_per_sec_per_chip": round(per_chip, 1),
        "repeat_rates": [round(r, 1) for r in rates],
        "repeat_spread_pct": round(spread_pct, 1),
        "n_chips": info["n_chips"], "pushes": info["pushes"],
        "iters_recorded": info["iters_recorded"],
        "final_loss": info["final_loss"],
        "sync_examples_per_sec_per_chip": sync_rate,
        "async_efficiency_vs_sync": round(per_chip / max(sync_rate, 1e-9), 3),
        "http_examples_per_sec_per_chip": round(http_rate, 1),
        "async_efficiency_http_vs_local": round(
            http_rate / max(per_chip, 1e-9), 3
        ),
        "http_push_wire_s_per_push": round(
            http_budget.get("push_wire_s", 0.0)
            / max(1, http_budget.get("pushes", 1)), 4
        ),
        **({"http_ablation_error": http_error} if http_error else {}),
        **budget_rec,
        # Same decomposition contract as _sync_epoch_bench, from this
        # config's own bus spans (the sync twin reports its own
        # phase_s inside `sync_*`; it runs outside the measure span so
        # its nested spans keep their canonical bench/* paths).
        "phase_s": {
            "data": round(_sp_data.duration_s, 3),
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
            "sync_twin": round(sum(sync["phase_s"].values()), 3),
        },
        **_steps_summary(times),
    }


def bench_hogwild_wire() -> dict:
    """Wire ablation: the SAME hogwild workload over the dill wire vs
    the framed binary wire (net/), both on real sockets. The headline
    numbers are per-operation: seconds and bytes per push and per
    fresh pull, which is what the wire change actually buys — the
    end-to-end rate also rides along. ``phase_s`` carries both the
    standard data/init/compile_warmup/measure decomposition and the
    pull/push budget of each wire (the hot-path seconds the ISSUE's
    acceptance names)."""
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.obs import get_telemetry
    from sparktorch_tpu.train.hogwild import train_async
    from sparktorch_tpu.utils.serde import ModelSpec

    tele = get_telemetry()
    with tele.span("bench/data") as _sp_data:
        rng = np.random.default_rng(0)
        n, mb = 2048, 256
        x = rng.normal(0, 1, (n, 784)).astype(np.float32)
        y = rng.integers(0, 10, (n,)).astype(np.int32)
    with tele.span("bench/init") as _sp_init:
        spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                         optimizer="adam", optimizer_params={"lr": 1e-3},
                         input_shape=(784,))
    with tele.span("bench/compile_warmup") as _sp_warm:
        # Same shapes/window as the measured runs: the persistent
        # compile cache (enabled in main()) makes them compile-free.
        train_async(spec, x, labels=y, iters=8, mini_batch=mb,
                    push_every=4)

    iters = 128
    wires: Dict[str, dict] = {}
    with tele.span("bench/measure") as _sp_measure:
        for wire_fmt in ("dill", "binary"):
            t0 = time.perf_counter()
            result = train_async(spec, x, labels=y, iters=iters,
                                 mini_batch=mb, push_every=4,
                                 transport="http", wire=wire_fmt, seed=0)
            wall = time.perf_counter() - t0
            b = (result.summary or {}).get("hogwild_budget", {})
            pushes = max(1, int(b.get("pushes", 0)))
            fresh = max(1, int(b.get("pull_fresh", 0)))
            wires[wire_fmt] = {
                "wall_s": round(wall, 3),
                "pull_s": round(b.get("pull_s", 0.0), 4),
                "push_wire_s": round(b.get("push_wire_s", 0.0), 4),
                "push_materialize_s": round(
                    b.get("push_materialize_s", 0.0), 4),
                "pull_mb": round(b.get("pull_bytes", 0) / 1e6, 3),
                "push_mb": round(b.get("push_bytes", 0) / 1e6, 3),
                "pulls": int(b.get("pulls", 0)),
                "pull_fresh": int(b.get("pull_fresh", 0)),
                "pushes": int(b.get("pushes", 0)),
                "push_wire_s_per_push": round(
                    b.get("push_wire_s", 0.0) / pushes, 5),
                "pull_s_per_fresh_pull": round(
                    b.get("pull_s", 0.0) / fresh, 5),
                # Steps = pushes x push_every (device count varies by
                # rig; the budget's own push count doesn't).
                "push_bytes_per_step": round(
                    b.get("push_bytes", 0)
                    / max(1, int(b.get("pushes", 0)) * 4), 1),
                "final_loss": result.metrics[-1]["loss"],
            }

    d, bn = wires["dill"], wires["binary"]
    return {
        "config": "hogwild_wire", "unit": "s/push",
        "value": bn["push_wire_s_per_push"],
        "binary": bn, "dill": d,
        "push_bytes_ratio_dill_over_binary": round(
            d["push_mb"] / max(bn["push_mb"], 1e-9), 3),
        "pull_bytes_ratio_dill_over_binary": round(
            d["pull_mb"] / max(bn["pull_mb"], 1e-9), 3),
        "push_wire_speedup": round(
            d["push_wire_s_per_push"]
            / max(bn["push_wire_s_per_push"], 1e-9), 3),
        "phase_s": {
            "data": round(_sp_data.duration_s, 3),
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
            # The hot-path budget the wire change targets, per wire.
            "pull": round(bn["pull_s"], 4),
            "push": round(bn["push_wire_s"] + bn["push_materialize_s"], 4),
            "pull_dill": round(d["pull_s"], 4),
            "push_dill": round(d["push_wire_s"] + d["push_materialize_s"], 4),
        },
    }


def bench_hogwild_chaos() -> dict:
    """Fault-tolerance gate: the SAME hogwild workload run clean and
    under a seeded one-worker kill with supervision on. FAILS (raises)
    unless the chaos run completes, the supervisor restarted exactly
    one worker, the recovered model still learned, and the recovery's
    wall-clock overhead stays under budget — so a regression in the
    recovery path breaks `make bench-chaos`, not production.

    Headline value is the measured recovery latency (death ->
    restarted worker running, from the ``ft_recovery_latency_s``
    histogram); ``overhead_pct`` is the chaos run's wall-clock cost
    over the clean twin (the restarted worker reruns its round
    assignment, so the expected overhead is roughly one worker's
    partial rerun plus the backoff delay)."""
    import jax

    from sparktorch_tpu.ft import ChaosConfig, FtPolicy, RestartPolicy, inject
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.obs import Telemetry, get_telemetry
    from sparktorch_tpu.train.hogwild import train_async
    from sparktorch_tpu.utils.serde import ModelSpec

    tele = get_telemetry()
    with tele.span("bench/data") as _sp_data:
        rng = np.random.default_rng(0)
        n, mb = 2048, 128
        x = rng.normal(0, 1, (n, 784)).astype(np.float32)
        y = rng.integers(0, 10, (n,)).astype(np.int32)
    with tele.span("bench/init") as _sp_init:
        spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                         optimizer="adam", optimizer_params={"lr": 1e-3},
                         input_shape=(784,))
    iters, kill_at = 64, 16
    # The victim must be a worker that EXISTS: train_async spawns one
    # per device, and on a single-chip backend that is worker 0.
    n_workers = len(jax.devices())
    victim = 1 if n_workers > 1 else 0
    policy = FtPolicy(restart=RestartPolicy(max_restarts=2,
                                            backoff_base_s=0.05),
                      seed=0)
    with tele.span("bench/compile_warmup") as _sp_warm:
        train_async(spec, x, labels=y, iters=8, mini_batch=mb, seed=0)

    with tele.span("bench/measure") as _sp_measure:
        t0 = time.perf_counter()
        clean = train_async(spec, x, labels=y, iters=iters, mini_batch=mb,
                            seed=0, supervise=True, ft_policy=policy)
        t_clean = time.perf_counter() - t0

        run_tele = Telemetry(run_id="bench_hogwild_chaos")
        t0 = time.perf_counter()
        with inject(ChaosConfig(kill_worker_at={victim: kill_at}, seed=0),
                    telemetry=run_tele):
            result = train_async(spec, x, labels=y, iters=iters,
                                 mini_batch=mb, seed=0, supervise=True,
                                 ft_policy=policy, telemetry=run_tele)
        t_chaos = time.perf_counter() - t0

    restarts = (result.summary or {}).get("ft", {}).get("restarts_total", -1)
    recovery = run_tele.histogram("ft_recovery_latency_s",
                                  labels={"worker": str(victim)})
    overhead_pct = 100.0 * (t_chaos - t_clean) / max(t_clean, 1e-9)

    # The gate. Budgets are generous (CPU rigs jitter) but real: the
    # run must COMPLETE with exactly one restart, the model must have
    # trained, recovery must be sub-second-scale, and the whole-run
    # overhead bounded by a rerun of one worker plus slack.
    if restarts != 1:
        raise AssertionError(f"expected exactly 1 restart, got {restarts}")
    if len(result.metrics) != len(clean.metrics):
        raise AssertionError(
            f"chaos run lost records: {len(result.metrics)} vs "
            f"{len(clean.metrics)} clean"
        )
    if recovery["count"] < 1 or recovery["max"] > 10.0:
        raise AssertionError(f"recovery latency out of budget: {recovery}")
    if overhead_pct > 300.0:
        raise AssertionError(
            f"recovery overhead {overhead_pct:.0f}% exceeds 300% budget"
        )
    return {
        "config": "hogwild_chaos", "unit": "s (recovery latency)",
        "value": round(recovery["max"], 4),
        "recovery_latency_s": round(recovery["max"], 4),
        "restarts": int(restarts),
        "wall_clean_s": round(t_clean, 3),
        "wall_chaos_s": round(t_chaos, 3),
        "overhead_pct": round(overhead_pct, 1),
        "kill_at_step": kill_at,
        "victim_worker": victim,
        "iters": iters,
        "n_chips": n_workers,
        "final_loss_clean": clean.metrics[-1]["loss"],
        "final_loss_chaos": result.metrics[-1]["loss"],
        "phase_s": {
            "data": round(_sp_data.duration_s, 3),
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
        },
    }


def bench_hogwild_ps_fleet() -> dict:
    """Parameter-server FLEET gate (``make bench-ps-fleet``): the
    sharded tier must actually beat the single server where it
    claims to — FAILS (raises) otherwise.

    Workload: a ~28 MB MLP state dict under a SPARSE-update pusher (a
    stable hot quarter of the leaves receives closed-loop gradient
    pushes — the fine-tuning/embedding shape the delta wire exists
    for) while a swarm of stateful workers each completes a fixed
    quota of FRESH pulls at a step cadence. The single server's v1
    wire must re-ship the full tree on every fresh pull (and apply
    dense gradients); the 4-shard fleet ships per-tensor deltas and
    applies the sparse partials shard-parallel. Legs run interleaved
    x3 and gate on MEDIANS (this rig is CPU-share capped and noisy).

    Gates:
    - aggregate pull bandwidth (model-state refreshed per second
      across the swarm: quota x model bytes / leg wall) — fleet must
      beat the single server;
    - p99 fresh-pull latency — fleet must beat the single server;
    - wire bytes per fresh pull — the fleet's deltas must ship
      STRICTLY fewer bytes than the single server's full snapshots
      (and the int8 delta leg strictly fewer than the f32 delta leg);
    - a seeded shard kill (``ft.chaos`` ``fleet.shard`` site) during
      a real ``train_async(shards=4)`` run must complete with exact
      record counts and >= 1 monitored shard restart.
    """
    import threading

    import jax

    from sparktorch_tpu.ft import ChaosConfig, inject
    from sparktorch_tpu.models import MLP
    from sparktorch_tpu.net import wire as _wire
    from sparktorch_tpu.net.sharded import ShardedTransport
    from sparktorch_tpu.net.transport import BinaryTransport
    from sparktorch_tpu.obs import Telemetry, get_telemetry
    from sparktorch_tpu.serve.fleet import ParamServerFleet
    from sparktorch_tpu.serve.param_server import (
        ParameterServer,
        ParamServerHttp,
    )
    from sparktorch_tpu.train.hogwild import train_async
    from sparktorch_tpu.utils.serde import ModelSpec

    tele = get_telemetry()
    n_shards, workers, quota, cadence_s = 4, 6, 10, 0.005
    with tele.span("bench/init") as _sp_init:
        # ~67 MB of parameters: big enough that per-pull BYTES dwarf
        # this rig's scheduler jitter (cpu-share-capped container;
        # ±100-300 ms thread-starvation spikes are routine), so the
        # p99 gate measures the wire design, not the noise floor.
        spec = ModelSpec(module=MLP(features=[1024] * 16 + [10]),
                         loss="cross_entropy", optimizer="sgd",
                         optimizer_params={"lr": 1e-2},
                         input_shape=(784,))

    def _swarm_leg(make_pull, push_fn) -> dict:
        """Closed-loop pusher + W stateful pullers, each completing
        ``quota`` fresh pulls; per-pull latency and wire bytes out.
        Every transport opened here is closed before the leg returns
        (7 legs per bench run — leaked keep-alive sockets and fan-out
        pools would pile up for the life of the process)."""
        stop = threading.Event()
        lat: List[float] = []
        lock = threading.Lock()
        wire_bytes = [0]
        opened: list = []

        def pusher():
            while not stop.is_set():
                push_fn()  # wait=True: version cadence = apply capacity
                time.sleep(cadence_s)

        def puller():
            pull, bytes_fn, transport = make_pull()
            with lock:
                opened.append(transport)
            # Untimed initial sync (both legs ship the full model here
            # — a one-time cost); the measured quota is STEADY-STATE
            # pulls, which is where delta and full genuinely differ.
            have = -1
            snap = pull(have)
            if snap is not None:
                have = snap[0]
            done, mine, b0 = 0, [], bytes_fn()
            # Hard deadline: a server whose writer died stops minting
            # versions, every pull 304s forever, and without this the
            # leg would hang instead of failing the gate.
            deadline = time.monotonic() + 120.0
            while done < quota and time.monotonic() < deadline:
                t0 = time.perf_counter()
                snap = pull(have)
                dt = time.perf_counter() - t0
                if snap is not None:
                    have, done = snap[0], done + 1
                    mine.append(dt)
                time.sleep(cadence_s)
            with lock:
                lat.extend(mine)
                wire_bytes[0] += bytes_fn() - b0

        pt = threading.Thread(target=pusher, daemon=True)
        pt.start()
        t0 = time.perf_counter()
        threads = [threading.Thread(target=puller, daemon=True)
                   for _ in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stop.set()
        pt.join()
        for transport in opened:
            transport.close()
        pulls = workers * quota
        if len(lat) < pulls:
            raise AssertionError(
                f"swarm leg stalled: {len(lat)}/{pulls} fresh pulls "
                f"completed before the 120s deadline — the server "
                f"stopped minting versions (dead writer?)"
            )
        return {
            "wall_s": wall,
            "state_mb_per_s": pulls * model_nbytes / wall / 1e6,
            "wire_mb_per_s": wire_bytes[0] / wall / 1e6,
            "wire_mb_per_pull": wire_bytes[0] / pulls / 1e6,
            "pull_p50_ms": float(np.percentile(lat, 50)) * 1e3,
            "pull_p99_ms": float(np.percentile(lat, 99)) * 1e3,
        }

    def _single_leg() -> dict:
        server = ParameterServer(spec, window_len=workers)
        http = ParamServerHttp(server, port=0).start()
        try:
            _, params = server.slot.read()
            zero_full = jax.tree.map(
                lambda a: np.zeros_like(np.asarray(a)), params)

            def push():
                try:
                    server.push_gradients(zero_full, wait=True)
                except Exception:
                    pass  # a raced stop must not kill the leg

            def make_pull():
                t = BinaryTransport(http.url, quant=None)
                return (lambda have: t.pull(have)), (
                    lambda: t.stats["pull_bytes"]), t

            push()
            server.drain()
            pull, _b, t = make_pull()  # warm render + connection path
            pull(-1)
            t.close()
            return _swarm_leg(make_pull, push)
        finally:
            http.stop()
            server.stop()

    def _fleet_leg(pull_quant=None) -> dict:
        fleet = ParamServerFleet(spec, n_shards=n_shards).start()
        try:
            def push():
                try:
                    fleet.scatter_push(hot_partial, wait=True)
                except Exception:
                    pass

            def make_pull():
                t = ShardedTransport(fleet, pull_quant=pull_quant)
                return (lambda have: t.pull(have)), (
                    lambda: t.stats["pull_bytes"]), t

            push()
            fleet.drain()
            pull, _b, t = make_pull()
            pull(-1)
            t.close()
            return _swarm_leg(make_pull, push)
        finally:
            fleet.stop()

    with tele.span("bench/compile_warmup") as _sp_warm:
        # One throwaway fleet warms the per-shard apply jits and leaf
        # partitioning; the measured legs then start compile-free
        # (same persistent-cache contract as every other config).
        probe = ParamServerFleet(spec, n_shards=n_shards)
        flat = {p: np.asarray(a)
                for p, a in _wire.flatten_tree(probe.assemble())}
        model_nbytes = sum(a.nbytes for a in flat.values())
        paths = sorted(flat)
        hot = paths[:max(1, len(paths) // 4)]
        hot_partial = {p: np.zeros_like(flat[p]) for p in hot}
        probe.scatter_push(hot_partial, wait=True)
        probe.stop()

    with tele.span("bench/measure") as _sp_measure:
        singles, fleets = [], []
        for _ in range(3):  # interleaved: rig noise hits both legs
            singles.append(_single_leg())
            fleets.append(_fleet_leg())
        int8 = _fleet_leg(pull_quant="int8")

    def _median(legs, key):
        return float(np.median([leg[key] for leg in legs]))

    single = {k: round(_median(singles, k), 3) for k in singles[0]}
    fleet = {k: round(_median(fleets, k), 3) for k in fleets[0]}
    bw_ratio = fleet["state_mb_per_s"] / max(single["state_mb_per_s"], 1e-9)
    p99_ratio = fleet["pull_p99_ms"] / max(single["pull_p99_ms"], 1e-9)

    # -- seeded shard kill during a real sharded training run ----------
    with tele.span("bench/shard_kill") as _sp_kill:
        rng = np.random.default_rng(0)
        x = np.concatenate([rng.normal(0, 1, (100, 10)),
                            rng.normal(2, 1, (100, 10))]).astype(np.float32)
        y = np.concatenate([np.zeros(100),
                            np.ones(100)]).astype(np.float32)
        from sparktorch_tpu import serialize_torch_obj
        from sparktorch_tpu.models import ClassificationNet

        clf = serialize_torch_obj(
            ClassificationNet(n_classes=2), criterion="cross_entropy",
            optimizer="adam", optimizer_params={"lr": 5e-3},
            input_shape=(10,),
        )
        kill_tele = Telemetry(run_id="bench_ps_fleet_kill")
        iters, parts = 12, 2
        with inject(ChaosConfig(kill_shard_at={1: 4}, seed=0),
                    telemetry=kill_tele) as inj:
            result = train_async(clf, x, labels=y, iters=iters,
                                 partitions=parts, seed=0,
                                 transport="http", shards=n_shards,
                                 telemetry=kill_tele)
        kill_fired = len([e for e in inj.events
                          if e["site"] == "fleet.shard"])
        kill_records = len(result.metrics)
        kill_restarts = int(result.summary["fleet"]["shard_restarts"])

    # -- the gates ------------------------------------------------------
    if not bw_ratio > 1.0:
        raise AssertionError(
            f"fleet aggregate pull bandwidth did not beat the single "
            f"server: {fleet['state_mb_per_s']:.0f} vs "
            f"{single['state_mb_per_s']:.0f} MB/s (x{bw_ratio:.2f})"
        )
    if not p99_ratio < 1.0:
        raise AssertionError(
            f"fleet p99 pull latency did not beat the single server: "
            f"{fleet['pull_p99_ms']:.0f} vs "
            f"{single['pull_p99_ms']:.0f} ms (x{p99_ratio:.2f})"
        )
    if not fleet["wire_mb_per_pull"] < single["wire_mb_per_pull"]:
        raise AssertionError(
            f"delta pulls did not ship fewer bytes than full pulls: "
            f"{fleet['wire_mb_per_pull']:.2f} vs "
            f"{single['wire_mb_per_pull']:.2f} MB/pull"
        )
    if not int8["wire_mb_per_pull"] < fleet["wire_mb_per_pull"]:
        raise AssertionError(
            f"int8 delta pulls did not ship fewer bytes than f32 "
            f"deltas: {int8['wire_mb_per_pull']:.2f} vs "
            f"{fleet['wire_mb_per_pull']:.2f} MB/pull"
        )
    if kill_fired < 1:
        raise AssertionError("seeded shard kill never fired")
    if kill_records != iters * parts:
        raise AssertionError(
            f"shard-kill run lost records: {kill_records} != "
            f"{iters * parts}"
        )
    if kill_restarts < 1:
        raise AssertionError(
            "shard kill produced no monitored restart "
            "(fleet.shard_restarts_total empty)"
        )

    return {
        "config": "hogwild_ps_fleet", "unit": "x (bandwidth ratio)",
        "value": round(bw_ratio, 3),
        "n_shards": n_shards, "workers": workers, "quota": quota,
        "model_mb": round(model_nbytes / 1e6, 1),
        "hot_leaves": len(hot), "total_leaves": len(paths),
        "bandwidth_ratio": round(bw_ratio, 3),
        "p99_ratio": round(p99_ratio, 3),
        "single": single, "fleet": fleet, "fleet_int8": int8,
        "delta_bytes_saved_pct": round(
            100 * (1 - fleet["wire_mb_per_pull"]
                   / single["wire_mb_per_pull"]), 1),
        "int8_bytes_saved_pct": round(
            100 * (1 - int8["wire_mb_per_pull"]
                   / fleet["wire_mb_per_pull"]), 1),
        "shard_kill": {"fired": kill_fired, "records": kill_records,
                       "restarts": kill_restarts},
        "phase_s": {
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
            "shard_kill": round(_sp_kill.duration_s, 3),
        },
    }


def bench_rpc_trace() -> dict:
    """Per-request RPC tracing gate (``make bench-rpc-trace``): the
    tracing layer must be cheap, honest, and diagnostic — FAILS
    (raises) otherwise.

    Gates:
    - **overhead**: the binary-wire push+pull loop under DEFAULT head
      sampling must cost < 2% wall over the tracer fully OFF
      (medians of interleaved repeats — rig noise hits both legs);
    - **reconcile**: with sampling forced to 1.0, every fresh 4-shard
      pull yields exactly ONE stitched span tree; the per-shard
      ``serve`` span p50 agrees with that shard's ``wire_latency_s``
      histogram p50 (same request population — the span and the
      histogram time the same handler window through different
      pipelines), and every root wall contains its slowest serve hop;
    - **critical path**: a seeded slow shard (``ft.chaos``
      ``slow_shard_s``) is named as the critical path of each traced
      pull in the collector's stitched output AND in
      ``timeline --rpc`` rendered from the collector's JSONL sink.
    """
    import contextlib
    import io
    import os

    import jax

    from sparktorch_tpu.ft import ChaosConfig, inject
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.net.sharded import ShardedTransport
    from sparktorch_tpu.net.transport import BinaryTransport
    from sparktorch_tpu.obs import FleetCollector, Telemetry, get_telemetry
    from sparktorch_tpu.obs import rpctrace
    from sparktorch_tpu.obs import timeline as _timeline
    from sparktorch_tpu.serve.fleet import ParamServerFleet
    from sparktorch_tpu.serve.param_server import (
        ParameterServer,
        ParamServerHttp,
    )
    from sparktorch_tpu.utils.serde import ModelSpec

    tele = get_telemetry()
    with tele.span("bench/init") as _sp_init:
        spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                         optimizer="sgd", optimizer_params={"lr": 1e-2},
                         input_shape=(784,))

    # ---- leg 1: tracing overhead at default sampling ------------------
    # Gate = (measured per-op tracing cost at the default rate) /
    # (measured wire-bench op wall), where the tracing cost is the
    # unsampled fast path PLUS the amortized sampled-commit chain,
    # each timed by a tight microbenchmark (min of batches, ring
    # pre-filled to maxlen so the commit copies are worst-case), and
    # the op wall is the real push + fresh-pull round trip on a live
    # server with the tracer OFF.
    #
    # Why not difference two end-to-end timings? That was tried five
    # ways on this rig (independent legs, paired leg ratios, twin
    # stacks, summed alternating blocks, per-pair block-median
    # ratios on 304 pulls) and falsified: an A/A control (both modes
    # tracer-off) swings +-2%, and off-vs-on swings +-20%
    # UNCORRELATED with the actual sample rate (rate=1e-9 measured
    # "+19.9%", rate=0.01 "-10.2%") — the cpu-share scheduler's
    # multimodal epochs alias against any blocking, drowning a
    # microsecond-scale effect. Timing the mechanism directly and
    # dividing by the measured op wall is the statistic that
    # converges, and it is conservative: the microbench charges every
    # op the full client-root cost plus its amortized share of a
    # 7-commit sampled chain against a worst-case full ring.
    def _per_iter_us(fn, iters: int, batches: int = 7) -> float:
        best = float("inf")
        for _ in range(batches):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            best = min(best, (time.perf_counter() - t0) / iters)
        return best * 1e6

    with tele.span("bench/measure_overhead") as _sp_overhead:
        micro_tele = Telemetry(run_id="rpc_overhead_micro")
        mtr = rpctrace.tracer_for(micro_tele)
        # (a) unsampled fast path: what EVERY untraced wire op pays.
        mtr.sample_rate = 0.0

        def _fast():
            with mtr.root_span("pull", kind="client", host="h", port=1):
                pass

        fast_us = _per_iter_us(_fast, 2000)
        # (b) the sampled commit chain, shaped like a real traced
        # push (root + encode/socket client-side + serve/decode/
        # queue_wait/apply server-side = 7 commits), against a ring
        # already at maxlen (every commit pays the full-copy cost).
        mtr.sample_rate = 1.0
        for _ in range(mtr._ring.maxlen + 8):
            with mtr.root_span("fill"):
                pass

        def _sampled():
            with mtr.root_span("push", kind="client", host="h",
                               port=1) as sp:
                with mtr.child_span("encode", sp.ctx):
                    pass
                with mtr.child_span("socket", sp.ctx):
                    pass
                with mtr.child_span("serve", sp.ctx, kind="server",
                                    route="/update.bin"):
                    pass
                with mtr.child_span("decode", sp.ctx, kind="server"):
                    pass
                mtr.record("queue_wait", sp.ctx, wall_ts(), 0.001,
                           kind="server")
                with mtr.child_span("apply", sp.ctx, kind="server"):
                    pass

        sampled_us = _per_iter_us(_sampled, 300)
        # The timed wire iteration below is push + pull — TWO traced
        # roots — so the per-iteration tracing cost is two roots'
        # worth (each modeled with the push-shaped 7-commit sampled
        # chain, the heavier of the two).
        roots_per_op = 2
        traced_cost_us = roots_per_op * (
            fast_us + rpctrace.DEFAULT_SAMPLE_RATE
            * max(sampled_us - fast_us, 0.0))

        # (c) the real wire-bench op wall, tracer fully off.
        op_tele = Telemetry(run_id="rpc_overhead_op")
        rpctrace.tracer_for(op_tele).sample_rate = -1.0
        server = ParameterServer(spec, telemetry=op_tele)
        http = ParamServerHttp(server, port=0).start()
        try:
            transport = BinaryTransport(http.url, telemetry=op_tele)
            _, params = server.slot.read()
            zeros = jax.tree.map(
                lambda a: np.zeros_like(np.asarray(a)), params)
            transport.push(zeros)  # warm connection + apply jit
            server.drain()
            transport.pull(-1)
            walls = []
            for _ in range(48):
                t0 = time.perf_counter()
                transport.push(zeros)
                transport.pull(-1)
                walls.append(time.perf_counter() - t0)
            transport.close()
        finally:
            http.stop()
            server.stop()
        op_us = float(np.median(walls)) * 1e6
        overhead_pct = 100.0 * traced_cost_us / op_us

    # ---- leg 2: traced sharded pulls reconcile with wire_latency_s ---
    n_shards, n_pulls = 4, 10
    with tele.span("bench/measure_reconcile") as _sp_reconcile:
        rec_tele = Telemetry(run_id="rpc_reconcile")
        tracer = rpctrace.tracer_for(rec_tele)
        tracer.sample_rate = 1.0
        tracer.resize(8192)  # hold every span of the bounded run
        fleet = ParamServerFleet(spec, n_shards=n_shards,
                                 telemetry=rec_tele).start()
        sink_dir = os.environ.get("TMPDIR", "/tmp")
        sink = os.path.join(sink_dir, f"rpc_trace_sink_{os.getpid()}.jsonl")
        collector = None
        try:
            transport = ShardedTransport(fleet, telemetry=rec_tele,
                                         run_id=rec_tele.run_id)
            zeros = jax.tree.map(
                lambda a: np.zeros_like(np.asarray(a)), fleet.assemble())
            have = -1
            pulled = 0
            for _ in range(n_pulls):
                transport.push(zeros)   # advance every leaf's version
                fleet.drain()
                snap = transport.pull(have)
                if snap is not None:
                    have = snap[0]
                    pulled += 1
            spans = tracer.spans
            trees = rpctrace.stitch_spans(spans)
            pull_trees = [t for t in trees
                          if t["root"]["name"] == "pull"
                          and t["root"]["status"] == "ok"]
            if pulled != n_pulls:
                raise AssertionError(
                    f"only {pulled}/{n_pulls} pulls were fresh — the "
                    f"push cadence failed to mint versions"
                )
            # One stitched tree per sampled request: every pull() call
            # is sampled at 1.0 and must stitch to exactly one tree.
            if len(pull_trees) != n_pulls:
                raise AssertionError(
                    f"stitched pull trees != sampled pulls: "
                    f"{len(pull_trees)} vs {n_pulls}"
                )
            # Per-shard: serve-span p50 vs the wire_latency_s p50 the
            # same handlers recorded — two pipelines, one truth.
            serve_by_shard: Dict[str, List[float]] = {}
            for s in spans:
                if s["name"] == "serve" \
                        and s["ann"].get("route") == "/delta.bin":
                    serve_by_shard.setdefault(
                        str(s["ann"].get("shard")), []).append(s["dur_s"])
            if len(serve_by_shard) != n_shards:
                raise AssertionError(
                    f"serve spans seen for shards "
                    f"{sorted(serve_by_shard)} != {n_shards} shards"
                )
            recon = {}
            for sid, durs in serve_by_shard.items():
                span_p50 = float(np.percentile(durs, 50))
                hist = rec_tele.histogram(
                    "param_server.wire_latency_s",
                    labels={"route": "/delta.bin", "shard": sid})
                hist_p50 = hist["p50"]
                if hist_p50 is None:
                    raise AssertionError(
                        f"no wire_latency_s series for shard {sid}")
                tol = max(0.5 * hist_p50, 0.002)
                recon[sid] = {"span_p50_ms": round(span_p50 * 1e3, 3),
                              "hist_p50_ms": round(hist_p50 * 1e3, 3),
                              "spans": len(durs),
                              "hist_count": hist["count"]}
                if abs(span_p50 - hist_p50) > tol:
                    raise AssertionError(
                        f"shard {sid} serve-span p50 "
                        f"{span_p50 * 1e3:.2f}ms does not reconcile "
                        f"with wire_latency_s p50 "
                        f"{hist_p50 * 1e3:.2f}ms (tol "
                        f"{tol * 1e3:.2f}ms)"
                    )
            # Containment: a root wall must cover its slowest serve
            # hop — a tree whose hops outrun the root is mis-stitched.
            def _serves(node, acc):
                if node["name"] == "serve":
                    acc.append(float(node["dur_s"] or 0.0))
                for c in node.get("children") or []:
                    _serves(c, acc)
                return acc

            for t in pull_trees:
                hops = _serves(t["root"], [])
                if hops and t["wall_s"] < max(hops) - 1e-4:
                    raise AssertionError(
                        f"trace {t['trace_id'][:8]}: root wall "
                        f"{t['wall_s'] * 1e3:.2f}ms < slowest serve hop "
                        f"{max(hops) * 1e3:.2f}ms"
                    )

            # ---- leg 3: seeded slow shard named as critical path ----
            slow_shard, delay_s, slow_pulls = "2", 0.12, 3
            with inject(ChaosConfig(slow_shard_s={slow_shard: delay_s},
                                    seed=0)):
                for _ in range(slow_pulls):
                    transport.push(zeros)
                    fleet.drain()
                    snap = transport.pull(have)
                    if snap is not None:
                        have = snap[0]
            collector = FleetCollector.for_fleet(
                fleet, poll_interval_s=0, jsonl_path=sink)
            collector.poll()
            stitched = collector.rpc_traces()
            slow_trees = [t for t in stitched
                          if t["root"]["name"] == "pull"
                          and t["wall_s"] >= delay_s * 0.8][:slow_pulls]
            if len(slow_trees) < slow_pulls:
                raise AssertionError(
                    f"collector stitched only {len(slow_trees)} "
                    f"slow-pull trees of {slow_pulls}"
                )
            named = sum(1 for t in slow_trees
                        if str((t.get("critical") or {}).get("shard"))
                        == slow_shard)
            if named < slow_pulls:
                raise AssertionError(
                    f"slow shard {slow_shard} named as critical path in "
                    f"only {named}/{slow_pulls} traced pulls: "
                    f"{[t.get('critical') for t in slow_trees]}"
                )
            # And the CLI renders the same verdict from the sink.
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = _timeline.main(["--rpc", sink])
            rendered = buf.getvalue()
            if rc != 0 or f"shard {slow_shard}" not in rendered \
                    or "bound by" not in rendered:
                raise AssertionError(
                    f"timeline --rpc did not name shard {slow_shard} "
                    f"(rc={rc})"
                )
            transport.close()
        finally:
            if collector is not None:
                collector.stop()
            fleet.stop()
            try:
                os.remove(sink)
            except OSError:
                pass

    # ---- the overhead gate (checked last so a failure reports with
    # the reconcile evidence already computed) -------------------------
    if overhead_pct >= 2.0:
        raise AssertionError(
            f"tracing overhead {overhead_pct:.3f}% >= 2% at default "
            f"sampling (fast path {fast_us:.2f}us + amortized sampled "
            f"chain {sampled_us:.1f}us x {rpctrace.DEFAULT_SAMPLE_RATE} "
            f"vs wire op p50 {op_us / 1e3:.2f}ms)"
        )

    return {
        "config": "rpc_trace", "unit": "% (tracing overhead)",
        "value": round(overhead_pct, 4),
        "overhead_pct": round(overhead_pct, 4),
        "fast_path_us": round(fast_us, 2),
        "sampled_chain_us": round(sampled_us, 1),
        "traced_cost_per_op_us": round(traced_cost_us, 2),
        "wire_op_p50_ms": round(op_us / 1e3, 3),
        "sample_rate_default": rpctrace.DEFAULT_SAMPLE_RATE,
        "pull_trees": len(pull_trees),
        "reconcile": recon,
        "slow_shard": {"shard": slow_shard, "delay_s": delay_s,
                       "named": named, "pulls": slow_pulls},
        "phase_s": {
            "init": round(_sp_init.duration_s, 3),
            "measure_overhead": round(_sp_overhead.duration_s, 3),
            "measure_reconcile": round(_sp_reconcile.duration_s, 3),
        },
    }


def bench_serve_online() -> dict:
    """Online serving gate (``make bench-serve``): the continuous-
    batching tier must actually beat the fixed-window tool where it
    claims to, and survive the faults it claims to — FAILS (raises)
    otherwise.

    Workload: Poisson open-loop single-row requests (seeded
    exponential interarrivals at ~2x the measured serial capacity, so
    a one-at-a-time server is genuinely overloaded — open loop:
    arrivals never wait for completions, like real users). The load
    threads are all PRE-SPAWNED and sleep to their own arrival times:
    spawning threads on the clock makes the generator the bottleneck
    and voids the comparison (measured: it halves the fast side's
    apparent throughput). The model is sized so single-row COMPUTE
    (~5ms) dominates per-request Python overhead — on a tiny model
    both legs converge on the GIL and the batching win is invisible.
    Legs run interleaved x2 and gate on MEDIANS (cpu-share rig noise
    hits both sides).

    Gates:
    - throughput at equal-or-better p99: the continuous-batching
      replica (admission queue -> coalesced bucket batches) must beat
      a serially-dispatched :class:`BatchPredictor` (the fixed-window
      tool — no admission, no coalescing) on completed rows/sec AND
      p99 request latency under the SAME arrival schedule, with zero
      failed requests on either side;
    - a seeded replica kill (``ft.chaos`` ``serve.replica`` site)
      mid-load drops ZERO requests: the router evicts the victim,
      re-routes its in-flight admissions, the tier monitor restarts
      it, and the router re-admits it — all observed in counters;
    - a mid-load weight push lands on EVERY replica within the
      staleness bound (20 poll intervals + 1s slack), and the served
      parameters equal the server's exactly after the swap;
    - drift: continuous throughput within tolerance of the newest
      prior ``serve_online`` record (``SPARKTORCH_TPU_SERVE_DRIFT_TOL``,
      default 0.5 relative — this rig's scheduler swings are real);
      skips cleanly with no prior record.
    """
    import os
    import threading

    import jax

    from sparktorch_tpu import serialize_torch_obj
    from sparktorch_tpu.ft import ChaosConfig, inject
    from sparktorch_tpu.ft.policy import FtPolicy, RestartPolicy
    from sparktorch_tpu.inference import BatchPredictor
    from sparktorch_tpu.models import ClassificationNet
    from sparktorch_tpu.net.transport import BinaryTransport
    from sparktorch_tpu.obs import Telemetry, get_telemetry
    from sparktorch_tpu.serve.infer import InferenceReplica
    from sparktorch_tpu.serve.param_server import (
        ParameterServer,
        ParamServerHttp,
    )
    from sparktorch_tpu.serve.router import InferenceTier, Router

    from sparktorch_tpu.models import MLP

    tele = get_telemetry()
    n_requests, overload = 300, 2.0
    rng = np.random.default_rng(0)

    with tele.span("bench/init") as _sp_init:
        # Throughput legs: an MLP big enough that one row costs real
        # compute (~5ms serial on this rig; batch-32 runs ~6x the
        # rows/sec of serial dispatch — the amortization continuous
        # batching exists to capture).
        module = MLP(features=[2048, 2048, 1024, 10])
        xpool = rng.normal(0, 1, (512, 512)).astype(np.float32)
        variables = module.init(jax.random.key(0), xpool[:1])
        params = variables["params"]
        # Fault/weight legs: the small classifier the param server
        # trains (recovery and staleness don't need the big model).
        clf_module = ClassificationNet(n_classes=2)
        xsmall = rng.normal(0, 1, (64, 10)).astype(np.float32)

    with tele.span("bench/compile_warmup") as _sp_warm:
        # Calibrate the SERIAL service time (the fixed-window tool's
        # capacity) on warmed compiles, then pick the arrival rate to
        # overload it: the gate must compare the designs under load,
        # not two idle servers.
        bp = BatchPredictor(module, params, chunk=32,
                            telemetry=Telemetry(run_id="serve_base"))
        bp.predict(xpool[:1])
        svc = []
        for _ in range(30):
            t0 = time.perf_counter()
            bp.predict(xpool[:1])
            svc.append(time.perf_counter() - t0)
        svc_s = float(np.median(svc))
        interarrival_s = svc_s / overload
        arrivals = np.cumsum(rng.exponential(interarrival_s, n_requests))

    def _poisson_leg(submit_fn, pool) -> dict:
        """Open-loop load: every request thread is PRE-SPAWNED, waits
        for the start gun, sleeps to its own scheduled arrival, fires,
        and records its own completion latency (arrivals never wait
        for completions). Failures are collected, never swallowed —
        the zero-drop gates read them."""
        lats: List[Optional[float]] = [None] * n_requests
        errors: list = []
        start = threading.Event()
        t_ref = [0.0]

        def _fire(i: int) -> None:
            start.wait()
            delay = arrivals[i] - (time.perf_counter() - t_ref[0])
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            try:
                out = submit_fn(pool[i % len(pool)][None, :])
                assert out.shape[0] == 1
                lats[i] = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 - gate counts these
                errors.append((i, f"{type(e).__name__}: {e}"))

        threads = [threading.Thread(target=_fire, args=(i,), daemon=True)
                   for i in range(n_requests)]
        for th in threads:
            th.start()
        time.sleep(0.05)  # let every thread park on the gun
        t_ref[0] = time.perf_counter()
        start.set()
        for th in threads:
            th.join(timeout=120)
        wall = time.perf_counter() - t_ref[0]
        done = [l for l in lats if l is not None]
        return {
            "wall_s": wall,
            "completed": len(done),
            "errors": len(errors),
            "error_samples": [e for _, e in errors[:3]],
            "rows_per_s": len(done) / max(wall, 1e-9),
            "p50_ms": float(np.percentile(done, 50)) * 1e3 if done else -1,
            "p99_ms": float(np.percentile(done, 99)) * 1e3 if done else -1,
        }

    def _baseline_leg() -> dict:
        # The fixed-window tool behind a serial dispatch: one
        # compiled predict per request, one at a time — exactly what
        # BatchPredictor gives an online caller (no admission queue,
        # no coalescing; concurrent callers serialize on the device
        # dispatch anyway, the lock just keeps the accounting honest).
        lock = threading.Lock()

        def submit(x):
            with lock:
                return bp.predict(x)

        return _poisson_leg(submit, xpool)

    def _continuous_leg() -> dict:
        # SAME hardware, same arrival schedule, ONE replica: the
        # throughput win must come from admission/coalescing, not
        # from extra compute.
        leg_tele = Telemetry(run_id="serve_cont")
        replica = InferenceReplica(module, params, replica_id="0",
                                   telemetry=leg_tele,
                                   buckets=(1, 8, 32),
                                   max_queue_rows=1024,
                                   warm_input=xpool[:1])
        router = Router(telemetry=leg_tele)
        router.register(replica)
        try:
            out = _poisson_leg(
                lambda x: router.submit(x, deadline_s=120.0), xpool)
            out["batches"] = leg_tele.counter_value(
                "serve.batches_total", {"replica": "0"})
            fill = leg_tele.histogram("serve.batch_fill",
                                      {"replica": "0"})
            out["batch_fill_p50"] = fill.get("p50")
            out["queue_depth_p99"] = leg_tele.histogram(
                "serve.queue_depth", {"replica": "0"}).get("p99")
            return out
        finally:
            router.stop()
            replica.stop()

    with tele.span("bench/measure") as _sp_measure:
        bases, conts = [], []
        for _ in range(2):  # interleaved: rig noise hits both legs
            bases.append(_baseline_leg())
            conts.append(_continuous_leg())

    def _median(legs, key):
        vals = [leg[key] for leg in legs if leg.get(key) is not None]
        return float(np.median(vals)) if vals else None

    base = {k: (round(_median(bases, k), 3)
                if isinstance(bases[0][k], (int, float)) else bases[0][k])
            for k in bases[0]}
    cont = {k: (round(_median(conts, k), 3)
                if isinstance(conts[0][k], (int, float)) else conts[0][k])
            for k in conts[0]}
    throughput_ratio = cont["rows_per_s"] / max(base["rows_per_s"], 1e-9)
    p99_ratio = cont["p99_ms"] / max(base["p99_ms"], 1e-9)

    # -- seeded replica kill under load --------------------------------
    with tele.span("bench/replica_kill") as _sp_kill:
        kill_tele = Telemetry(run_id="serve_kill")
        policy = FtPolicy(restart=RestartPolicy(backoff_base_s=0.02,
                                                backoff_max_s=0.1,
                                                max_restarts=3))
        clf_variables = clf_module.init(jax.random.key(0), xsmall[:1])
        tier = InferenceTier(clf_module, clf_variables["params"],
                             n_replicas=2,
                             telemetry=kill_tele, ft_policy=policy,
                             buckets=(1, 8, 32), max_queue_rows=1024,
                             warm_input=xsmall[:1],
                             probe_interval_s=0.05)
        # Deterministic victim: replica 0 carries a fat observed
        # latency so the weighted pick opens on replica 1, whose 8th
        # admission is the seeded kill.
        kill_tele.observe("serve.request_latency_s", 0.5,
                          labels={"replica": "0"})
        try:
            with inject(ChaosConfig(kill_replica_at={1: 8}),
                        telemetry=kill_tele) as inj:
                kill_leg = _poisson_leg(
                    lambda x: tier.submit(x, deadline_s=60.0), xsmall)
            kills = len([e for e in inj.events
                         if e["site"] == "serve.replica"])
            deadline = time.monotonic() + 15.0
            while (kill_tele.counter_value("router.readmissions_total",
                                           {"replica": "1"}) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            evictions = kill_tele.counter_value(
                "router.evictions_total",
                {"replica": "1", "reason": "error"})
            restarts = kill_tele.counter_value(
                "serve.replica_restarts_total", {"replica": "1"})
            readmissions = kill_tele.counter_value(
                "router.readmissions_total", {"replica": "1"})
        finally:
            tier.stop()

    # -- mid-load weight push: bounded staleness + exactness -----------
    with tele.span("bench/weight_push") as _sp_push:
        poll_s = 0.05
        staleness_bound_s = 20 * poll_s + 1.0
        clf = serialize_torch_obj(
            ClassificationNet(n_classes=2), criterion="cross_entropy",
            optimizer="sgd", optimizer_params={"lr": 0.1},
            input_shape=(10,),
        )
        push_tele = Telemetry(run_id="serve_push")
        server = ParameterServer(clf, telemetry=push_tele)
        http = ParamServerHttp(server, port=0).start()
        _v, params0 = server.slot.read()
        tier = InferenceTier(clf_module, params0, n_replicas=2,
                             telemetry=push_tele,
                             buckets=(1, 8), max_queue_rows=1024,
                             warm_input=xsmall[:1],
                             probe_interval_s=0.05)
        tier.start_pullers(
            lambda: BinaryTransport(http.url, quant=None),
            poll_s=poll_s)
        stop_load = threading.Event()

        def _background_load():
            while not stop_load.is_set():
                tier.submit(xsmall[:1], deadline_s=30.0)
                time.sleep(0.005)

        loader = threading.Thread(target=_background_load, daemon=True)
        loader.start()
        try:
            time.sleep(0.3)  # pullers sync the initial version
            grads = jax.tree.map(
                lambda a: np.ones_like(np.asarray(a)), params0)
            server.push_gradients(grads, wait=True)
            pushed_version = server.slot.version
            t_push = time.monotonic()
            staleness: Dict[str, float] = {}
            deadline = t_push + staleness_bound_s + 5.0
            while (len(staleness) < len(tier.replicas)
                   and time.monotonic() < deadline):
                for rid, replica in tier.replicas.items():
                    if rid not in staleness \
                            and replica.params_version >= pushed_version:
                        staleness[rid] = time.monotonic() - t_push
                time.sleep(0.01)
            stop_load.set()
            loader.join(timeout=30)
            # Exactness: the SERVED parameters equal the pushed ones.
            _v2, server_params = server.slot.read()
            ref = np.asarray(clf_module.apply(
                {"params": server_params}, xsmall[:8]))
            push_exact = True
            for replica in tier.replicas.values():
                out = replica.infer(xsmall[:8])
                if not np.allclose(out, ref, rtol=1e-5, atol=1e-6):
                    push_exact = False
        finally:
            stop_load.set()
            tier.stop()
            http.stop()
            server.stop()

    # -- the gates ------------------------------------------------------
    if base["errors"] or cont["errors"]:
        raise AssertionError(
            f"load legs dropped requests: baseline {base['errors']} "
            f"({base['error_samples']}), continuous {cont['errors']} "
            f"({cont['error_samples']})"
        )
    # Completion counted SEPARATELY from errors: a future that is
    # never resolved raises nothing — its load thread just times out
    # — and an errors-only gate would report that orphaned request as
    # success.
    for leg_name, leg in (("baseline", base), ("continuous", cont),
                          ("replica_kill", kill_leg)):
        if leg["completed"] != n_requests:
            raise AssertionError(
                f"{leg_name} leg completed only {leg['completed']}/"
                f"{n_requests} requests with no error raised — "
                f"orphaned futures are silent drops"
            )
    if not throughput_ratio > 1.0:
        raise AssertionError(
            f"continuous batching did not beat the fixed-window "
            f"BatchPredictor on throughput: {cont['rows_per_s']:.0f} "
            f"vs {base['rows_per_s']:.0f} rows/s "
            f"(x{throughput_ratio:.2f})"
        )
    if not p99_ratio <= 1.0:
        raise AssertionError(
            f"continuous batching p99 regressed vs the fixed-window "
            f"baseline: {cont['p99_ms']:.1f} vs {base['p99_ms']:.1f} "
            f"ms (x{p99_ratio:.2f}) — the throughput win must not be "
            f"bought with latency"
        )
    if kill_leg["errors"]:
        raise AssertionError(
            f"replica-kill leg DROPPED {kill_leg['errors']} requests "
            f"({kill_leg['error_samples']}) — the router must re-route "
            f"every admission of the killed replica"
        )
    if kills < 1:
        raise AssertionError("seeded replica kill never fired")
    if evictions < 1 or restarts < 1 or readmissions < 1:
        raise AssertionError(
            f"recovery pipeline incomplete: evictions={evictions} "
            f"restarts={restarts} readmissions={readmissions}"
        )
    if len(staleness) < 2:
        raise AssertionError(
            f"mid-load weight push reached only {len(staleness)}/2 "
            f"replicas within {staleness_bound_s + 5.0:.1f}s"
        )
    max_staleness = max(staleness.values())
    if max_staleness > staleness_bound_s:
        raise AssertionError(
            f"weight-update staleness {max_staleness:.2f}s exceeds "
            f"the {staleness_bound_s:.2f}s bound"
        )
    if not push_exact:
        raise AssertionError(
            "served parameters != pushed parameters after the swap"
        )

    # -- drift gate (arms once a prior record is retained) -------------
    tol = float(os.environ.get("SPARKTORCH_TPU_SERVE_DRIFT_TOL", "0.5"))
    prior = _prior_record("serve_online", "cont_rows_per_s")
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        prior_rate = float(prior["cont_rows_per_s"])
        drift = {
            "status": "checked", "tolerance": tol,
            "prior_ts": prior.get("ts"),
            "prior_cont_rows_per_s": round(prior_rate, 1),
            "rows_per_s_ratio": round(
                cont["rows_per_s"] / max(prior_rate, 1e-9), 3),
        }
        if cont["rows_per_s"] < prior_rate * (1.0 - tol):
            raise AssertionError(
                f"serve_online throughput regressed: "
                f"{cont['rows_per_s']:.0f} vs prior "
                f"{prior_rate:.0f} rows/s (past the {tol} relative "
                f"tolerance); drift: {drift}"
            )

    return {
        "config": "serve_online", "unit": "x (throughput ratio)",
        "value": round(throughput_ratio, 3),
        "n_requests": n_requests,
        "serial_service_ms": round(svc_s * 1e3, 3),
        "offered_rate_rps": round(1.0 / interarrival_s, 1),
        "throughput_ratio": round(throughput_ratio, 3),
        "p99_ratio": round(p99_ratio, 3),
        "cont_rows_per_s": cont["rows_per_s"],
        "baseline": base, "continuous": cont,
        "replica_kill": {**kill_leg, "kills": kills,
                         "evictions": evictions, "restarts": restarts,
                         "readmissions": readmissions},
        "weight_push": {
            "poll_s": poll_s,
            "staleness_s": {k: round(v, 3)
                            for k, v in sorted(staleness.items())},
            "staleness_bound_s": staleness_bound_s,
            "exact": push_exact,
        },
        "serve_drift": drift,
        "phase_s": {
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
            "replica_kill": round(_sp_kill.duration_s, 3),
            "weight_push": round(_sp_push.duration_s, 3),
        },
    }


def _prior_records(config: str, field: str,
                   root: Optional[str] = None,
                   mesh: Optional[str] = None) -> List[dict]:
    """Every PRIOR round's record for ``config`` carrying ``field``,
    oldest first — scanned from the retained round artifacts
    (repo-root ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` and the
    ``benchmarks/*.jsonl`` logs). ``mesh`` restricts the scan to
    records captured under the SAME layout (or predating the mesh
    field): the SPARKTORCH_TPU_TRACE_MESH=auto knob means adjacent
    rounds can capture different layouts with legitimately different
    comm budgets, and the newest same-mesh prior — not the newest
    prior outright — is the valid baseline."""
    import glob
    import os
    import re

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates: List[tuple] = []

    def _round_of(path: str) -> int:
        m = re.search(r"_r(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    # Recency key: the record's own ISO timestamp first (sortable as a
    # string; records without one sort oldest), the artifact's round
    # number as the tiebreak. NEVER the raw filename — lexicographic
    # basenames would rank any lowercase benchmarks/*.jsonl above
    # every BENCH_r*.json and compare the gate against a stale round.
    def _consider(rec, path):
        if isinstance(rec, dict) and rec.get("config") == config \
                and rec.get(field) is not None \
                and (mesh is None or rec.get("mesh") in (None, mesh)):
            candidates.append(((str(rec.get("ts") or ""),
                                _round_of(path)), rec))

    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))
                       + glob.glob(os.path.join(root, "MULTICHIP_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # a torn artifact never blocks the bench
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        for rec in (parsed if isinstance(parsed, list) else [parsed]):
            _consider(rec, path)
    for path in sorted(glob.glob(os.path.join(root, "benchmarks",
                                              "*.jsonl"))):
        try:
            with open(path) as f:
                rows = [json.loads(line) for line in f if line.strip()]
        except (OSError, ValueError):
            continue
        for rec in rows:
            _consider(rec, path)
    return [rec for _, rec in sorted(candidates, key=lambda c: c[0])]


def _prior_record(config: str, field: str,
                  root: Optional[str] = None,
                  mesh: Optional[str] = None) -> Optional[dict]:
    """The most recent prior record (see :func:`_prior_records`).
    None when no (matching) prior exists — first armed round, the
    drift gate skips cleanly."""
    recs = _prior_records(config, field, root=root, mesh=mesh)
    return recs[-1] if recs else None


def _prior_window(config: str, field: str, k: int = 3,
                  root: Optional[str] = None,
                  mesh: Optional[str] = None) -> Optional[dict]:
    """WINDOWED drift baseline: the median of ``field`` over the
    newest ``k`` prior records, not the single newest one — the same
    judgment the collector's history tier applies to live metrics,
    applied to retained bench rounds. A drift gate comparing against
    one record inherits that record's rig luck (this rig's serve
    throughput breathes 2x hour to hour — see the PR 9 notes); the
    windowed median absorbs one outlier round. None when no prior
    exists."""
    recs = _prior_records(config, field, root=root, mesh=mesh)[-max(1, k):]
    if not recs:
        return None
    values = [float(r[field]) for r in recs]
    return {
        "median": float(np.median(values)),
        "n": len(values),
        "values": [round(v, 6) for v in values],
        "newest_ts": recs[-1].get("ts"),
    }


def _prior_comm_budget(config: str,
                       root: Optional[str] = None,
                       mesh: Optional[str] = None) -> Optional[dict]:
    """Most recent prior record of ``config`` with a comm budget —
    restricted to the same mesh layout when one is named."""
    return _prior_record(config, "comm_fraction", root, mesh=mesh)


def _prior_gang_budget(config: str,
                       root: Optional[str] = None) -> Optional[dict]:
    """Most recent prior record of ``config`` carrying a MERGED gang
    budget (``gang_comm_fraction`` — what ``gang_obs`` and multi-host
    rounds report). None until a multi-host round has recorded one."""
    return _prior_record(config, "gang_comm_fraction", root)


def _check_gang_drift(config: str, step_skew_s: float,
                      gang_comm_fraction: float) -> dict:
    """The GANG-level drift gate (PR 5 follow-up, armed): compare this
    run's merged cross-rank step skew and gang comm fraction against
    the newest prior round's gang record and FAIL when a rank started
    straggling (skew grew beyond tolerance) or gang comm grew to
    dominate the budget. Skips cleanly (``no_prior_record``) until a
    multi-host round has recorded a gang budget. Tolerances:
    ``SPARKTORCH_TPU_COMM_DRIFT_TOL`` (absolute, on the fraction —
    shared with the per-rank gate) and ``SPARKTORCH_TPU_GANG_SKEW_TOL``
    (relative growth on the skew, default 0.5 = +50%, with a 50ms
    absolute floor so microsecond-scale synthetic skews don't trip on
    rounding)."""
    import os

    tol = float(os.environ.get("SPARKTORCH_TPU_COMM_DRIFT_TOL", "0.25"))
    skew_tol = float(os.environ.get("SPARKTORCH_TPU_GANG_SKEW_TOL", "0.5"))
    prior = _prior_gang_budget(config)
    if prior is None:
        return {"status": "no_prior_record", "tolerance": tol,
                "skew_tolerance": skew_tol}
    prior_cf = float(prior["gang_comm_fraction"])
    prior_skew = float(prior.get("gang_step_skew_s", 0.0))
    skew_limit = prior_skew * (1.0 + skew_tol) + 0.05
    drift = {
        "status": "checked",
        "tolerance": tol,
        "skew_tolerance": skew_tol,
        "prior_ts": prior.get("ts"),
        "prior_gang_comm_fraction": round(prior_cf, 4),
        "prior_gang_step_skew_s": round(prior_skew, 6),
        "gang_comm_fraction_delta": round(gang_comm_fraction - prior_cf, 4),
        "gang_step_skew_delta_s": round(step_skew_s - prior_skew, 6),
    }
    if step_skew_s > skew_limit:
        raise AssertionError(
            f"{config}: gang step skew regressed "
            f"{prior_skew:.4f}s -> {step_skew_s:.4f}s (past the "
            f"{skew_limit:.4f}s limit) — a rank is straggling; "
            f"drift: {drift}"
        )
    if gang_comm_fraction - prior_cf > tol:
        raise AssertionError(
            f"{config}: gang comm_fraction regressed "
            f"{prior_cf:.3f} -> {gang_comm_fraction:.3f} "
            f"(comm grew beyond the {tol} tolerance); drift: {drift}"
        )
    return drift


def _check_comm_drift(config: str, comm_fraction: float,
                      overlap_fraction: float,
                      mesh: Optional[str] = None) -> dict:
    """The comm-fraction drift gate (ROADMAP follow-up, armed): now
    that ``sharded_trace`` and ``moe_lm`` record ``comm_budget`` every
    round, compare this run's fractions against the previous round's
    record and FAIL (AssertionError -> ``make bench-trace`` fails)
    when an overlap was lost (overlap_fraction collapsed — e.g. a
    remat change serializing the dp all-reduce) or comm grew to
    dominate the step. Skips cleanly when no prior record exists.
    Tolerance is absolute on the fractions (default 0.25 — generous
    for CPU-rig jitter; tighten via SPARKTORCH_TPU_COMM_DRIFT_TOL on
    stable hardware). ``mesh`` (when the config records one — the
    SPARKTORCH_TPU_TRACE_MESH=auto knob means different rounds can
    capture different LAYOUTS) guards the baseline: the prior scan is
    restricted to the newest record captured under the SAME mesh
    (records predating the mesh field compare as before), so an
    auto-mode round can neither raise a fake regression against a
    tp2 baseline nor mask a real one — and interleaved tp2/auto
    rounds still each find their own valid baseline instead of
    skipping forever. Returns the drift record the bench attaches."""
    import os

    tol = float(os.environ.get("SPARKTORCH_TPU_COMM_DRIFT_TOL", "0.25"))
    prior = _prior_comm_budget(config, mesh=mesh)
    if prior is None:
        return {"status": "no_prior_record", "tolerance": tol,
                "mesh": mesh}
    prior_cf = float(prior["comm_fraction"])
    prior_of = float(prior.get("overlap_fraction", 0.0))
    drift = {
        "status": "checked",
        "tolerance": tol,
        "prior_ts": prior.get("ts"),
        "prior_comm_fraction": round(prior_cf, 4),
        "prior_overlap_fraction": round(prior_of, 4),
        "comm_fraction_delta": round(comm_fraction - prior_cf, 4),
        "overlap_fraction_delta": round(overlap_fraction - prior_of, 4),
    }
    if prior_of - overlap_fraction > tol:
        raise AssertionError(
            f"{config}: overlap_fraction regressed "
            f"{prior_of:.3f} -> {overlap_fraction:.3f} "
            f"(lost overlap beyond the {tol} tolerance) — a comm that "
            f"was hidden under compute is now exposed; drift: {drift}"
        )
    if comm_fraction - prior_cf > tol:
        raise AssertionError(
            f"{config}: comm_fraction regressed "
            f"{prior_cf:.3f} -> {comm_fraction:.3f} "
            f"(comm grew beyond the {tol} tolerance); drift: {drift}"
        )
    return drift


def bench_sharded_trace() -> dict:
    """Trace-attribution gate (``make bench-trace``): capture an XLA
    profile of the GSPMD sharded trainer, machine-read it offline
    (:mod:`sparktorch_tpu.obs.xprof`), and FAIL unless

    - the analysis finds >=1 collective event (on any multi-device
      backend — GSPMD must have inserted tp/dp collectives),
    - the per-step slice wall reconciles with the bus's
      ``train_sharded/step`` span wall within tolerance (the step
      annotations live INSIDE those spans), and
    - a real ``/metrics`` scrape equals the JSONL telemetry dump for
      every published ``xprof.*`` metric (capture -> analyze ->
      publish round-trip, one source of truth).

    The record reports the comm/compute budget the capture exposed:
    ``comm_s`` / ``comm_fraction`` / ``overlap_fraction`` plus the
    per-family breakdown and top ops."""
    import tempfile

    import jax

    from sparktorch_tpu.models import SequenceClassifier, tiny_transformer
    from sparktorch_tpu.native.gang import GangMetricsExporter
    from sparktorch_tpu.obs import (
        Telemetry,
        parse_prometheus,
        read_jsonl,
        scrape_text,
    )
    from sparktorch_tpu.obs.prom import sanitize_name
    from sparktorch_tpu.parallel.compat import set_mesh as _set_mesh
    from sparktorch_tpu.parallel.mesh import MeshConfig, build_mesh
    from sparktorch_tpu.train.sharded import (
        create_sharded_state,
        make_sharded_train_step,
        shard_batch,
    )
    from sparktorch_tpu.utils.data import DataBatch
    from sparktorch_tpu.utils.serde import ModelSpec

    # This config executes a collective-bearing GSPMD program; the
    # persistent compile cache is disarmed for it on CPU (executing a
    # deserialized collective executable segfaults jax 0.4.37 CPU —
    # see tests/conftest.py / ROADMAP).
    old_cache = jax.config.jax_compilation_cache_dir
    if jax.default_backend() == "cpu":
        jax.config.update("jax_compilation_cache_dir", None)
    try:
        tele = Telemetry(run_id="bench_sharded_trace")
        devices = jax.devices()
        n_dev = len(devices)
        steps = 6
        with tele.span("bench/data") as _sp_data:
            rng = np.random.default_rng(0)
            bsz = 4 * n_dev
            batch = DataBatch(
                x=np.asarray(rng.integers(0, 256, (bsz, 16)).astype(np.int32)),
                y=np.asarray(rng.integers(0, 2, (bsz,)).astype(np.int32)),
                w=np.ones((bsz,), np.float32),
            )
        with tele.span("bench/init") as _sp_init:
            import os

            module = SequenceClassifier(tiny_transformer())
            spec = ModelSpec(module=module, loss="cross_entropy",
                             optimizer="adam", optimizer_params={"lr": 1e-3})
            tx = spec.make_optimizer()
            # Mesh knob: tp2 (default — tensor-parallel all-reduces
            # INSIDE the step, beside the dp gradient reduction), or
            # "auto" to let the trace-guided tuner pick the layout
            # (SPARKTORCH_TPU_TRACE_MESH=auto make bench-trace).
            mesh_knob = os.environ.get("SPARKTORCH_TPU_TRACE_MESH", "tp2")
            if mesh_knob not in ("tp2", "auto"):
                raise AssertionError(
                    f"SPARKTORCH_TPU_TRACE_MESH={mesh_knob!r}: "
                    f"use 'tp2' or 'auto'"
                )
            if mesh_knob == "auto":
                from sparktorch_tpu.parallel.tune import GSPMD_AXES, autotune

                # GSPMD_AXES: this leg builds a GSPMD step below — a
                # pp>1 schedule winner would not fit it (the pp space
                # has its own gate, bench-pp-tune).
                tuned = autotune(spec, batch, devices, steps=3,
                                 measure_top_k=3, telemetry=tele,
                                 axes=GSPMD_AXES)
                mesh = build_mesh(tuned.best_config(), devices)
            else:
                mesh = build_mesh(MeshConfig(tp=2) if n_dev % 2 == 0
                                  else MeshConfig(), devices)
            # Recorded from the mesh actually built — never the knob
            # (the tp2 fallback on an odd rig is pure dp, and the
            # retained record must say so).
            from sparktorch_tpu.parallel.tune import mesh_label

            mesh_ran = mesh_label(dict(mesh.shape))
            state, shardings = create_sharded_state(
                spec, mesh, jax.random.key(0), sample_x=batch.x[:1], tx=tx,
            )
        with tempfile.TemporaryDirectory() as profile_dir:
            step = make_sharded_train_step(
                module.apply, spec.loss_fn(), tx, mesh, shardings,
                profile_dir=profile_dir, telemetry=tele,
            )
            sharded = shard_batch(batch, mesh)
            with tele.span("bench/compile_warmup") as _sp_warm:
                # Compile OUTSIDE the capture (run.jitted directly, no
                # annotation/span), so the trace holds steady steps.
                with _set_mesh(mesh):
                    state, m = step.jitted(state, sharded)
                _sp_warm.sync(m.loss)
            with tele.span("bench/measure") as _sp_measure:
                for _ in range(steps):
                    state, metrics = step(state, sharded)
                    # Block per step so each step's device work drains
                    # inside its attribution slice.
                    jax.block_until_ready(metrics.loss)
                _sp_measure.synced = True
            analysis = step.finish()

        # ---- gates -------------------------------------------------------
        if analysis is None or analysis.n_device_events == 0:
            raise AssertionError(
                "trace analysis found no device events — the runtime "
                "emitted no usable capture"
            )
        if n_dev > 1 and analysis.n_collective_events < 1:
            raise AssertionError(
                f"no collectives found in a {n_dev}-device sharded step "
                f"(families seen: {analysis.family_counts()})"
            )
        # Span paths are slash-joined by nesting: the step spans ran
        # inside this config's bench/measure span.
        span = tele.span_rollup("bench/measure/train_sharded/step")
        step_wall = analysis.wall_s
        span_wall = span["sum"]
        # The annotations sit INSIDE the spans: their wall can never
        # exceed the span wall (beyond clock jitter), and must account
        # for most of it (the span adds only set_mesh + bookkeeping).
        tol = max(0.5 * span_wall, 0.02)
        if not (0 < step_wall <= span_wall + 0.005) or \
                abs(span_wall - step_wall) > tol:
            raise AssertionError(
                f"step-slice wall {step_wall:.4f}s does not reconcile "
                f"with bus span wall {span_wall:.4f}s (tol {tol:.4f}s)"
            )
        if len(analysis.steps) != steps or span["count"] != steps:
            raise AssertionError(
                f"expected {steps} steps: trace has "
                f"{len(analysis.steps)}, bus has {span['count']}"
            )

        # ---- /metrics scrape == JSONL dump parity ------------------------
        with GangMetricsExporter(telemetry=tele) as exporter:
            scraped = parse_prometheus(scrape_text(exporter.url + "/metrics"))
        with tempfile.TemporaryDirectory() as d:
            import os

            dump_path = os.path.join(d, "telemetry.jsonl")
            snap = tele.dump(dump_path)
            (snap_read,) = read_jsonl(dump_path)
        mismatches = []
        for flat, val in snap["counters"].items():
            if not flat.startswith("xprof."):
                continue
            name, _, labels = flat.partition("{")
            key = "sparktorch_" + sanitize_name(name)
            if labels:
                k, _, v = labels[:-1].partition("=")
                key += f'{{{k}="{v}"}}'
            got = scraped.get(key)
            if got != val or snap_read["counters"].get(flat) != val:
                mismatches.append((flat, val, got,
                                   snap_read["counters"].get(flat)))
        n_hists = 0
        for flat, roll in snap["histograms"].items():
            if not flat.startswith("xprof."):
                continue
            n_hists += 1
            name, _, labels = flat.partition("{")
            key = "sparktorch_" + sanitize_name(name)
            lbl = ""
            if labels:
                k, _, v = labels[:-1].partition("=")
                lbl = f'{{{k}="{v}"}}'
            if scraped.get(f"{key}_count{lbl}") != float(roll["count"]) or \
                    snap_read["histograms"][flat]["count"] != roll["count"]:
                mismatches.append((flat, roll["count"]))
        if mismatches or n_hists == 0:
            raise AssertionError(
                f"xprof /metrics scrape vs JSONL dump mismatch "
                f"(histograms seen: {n_hists}): {mismatches}"
            )

        # ---- comm-fraction drift gate (vs the previous round) ------------
        comm_drift = _check_comm_drift(
            "sharded_trace", analysis.comm_fraction,
            analysis.overlap_fraction, mesh=mesh_ran,
        )

        return {
            "config": "sharded_trace", "unit": "comm_fraction",
            "value": round(analysis.comm_fraction, 4),
            "comm_fraction": round(analysis.comm_fraction, 4),
            "overlap_fraction": round(analysis.overlap_fraction, 4),
            "comm_s": round(analysis.comm_s, 6),
            "compute_s": round(analysis.compute_s, 6),
            "collective_s": {k: round(v, 6)
                             for k, v in analysis.family_s().items()},
            "collective_counts": analysis.family_counts(),
            "n_collective_events": analysis.n_collective_events,
            "n_steps": len(analysis.steps),
            "n_chips": n_dev,
            "mesh": mesh_ran,
            "reconcile": {"steps_wall_s": round(step_wall, 6),
                          "span_wall_s": round(span_wall, 6)},
            "top_ops": analysis.top_ops[:5],
            "scrape_parity": "ok",
            "comm_drift": comm_drift,
            "phase_s": {
                "data": round(_sp_data.duration_s, 3),
                "init": round(_sp_init.duration_s, 3),
                "compile_warmup": round(_sp_warm.duration_s, 3),
                "measure": round(_sp_measure.duration_s, 3),
                "comm_s": round(analysis.comm_s, 6),
            },
        }
    finally:
        if jax.default_backend() == "cpu":
            jax.config.update("jax_compilation_cache_dir", old_cache)


def bench_mesh_tune() -> dict:
    """Mesh auto-tuner gate (``make bench-tune``): run the trace-guided
    tuner (:mod:`sparktorch_tpu.parallel.tune`) on a transformer
    workload over the local rig, then referee it against an EXHAUSTIVE
    measurement of the same candidate space, and FAIL unless

    - the tuner's chosen mesh matches the exhaustively-measured winner,
      or sits within tolerance (``SPARKTORCH_TPU_TUNE_TOL``, default
      10%) of its step wall — compared on the exhaustive pass's OWN
      numbers so run-to-run jitter can't fake a pass;
    - the prune step eliminated >=1 candidate WITHOUT executing it,
      and never eliminated the measured winner — judged at the same
      tolerance (a pruned candidate materially faster than the chosen
      config fails; one inside the noise between the top entries does
      not, because there the "winner" label is itself jitter);
    - the tuner stayed under its execution budget: profiled steps
      executed (warmup captures included) <=
      measure_top_k x steps x (repeats + warmup rounds), and the
      search wall under ``SPARKTORCH_TPU_TUNE_BUDGET_S``
      (default 600s);
    - the full ranking + prune log round-trips through the
      ``tune_result.json`` artifact.

    Scope: the GSPMD mesh zoo (axes=GSPMD_AXES). The pp x schedule
    dimension has its own referee with pipeline-trainer measurement —
    ``make bench-pp-tune``.

    The record reports both rankings, the prune decisions, and the
    chosen budget."""
    import os
    import tempfile

    import jax

    from sparktorch_tpu.models import SequenceClassifier, tiny_transformer
    from sparktorch_tpu.obs import Telemetry
    from sparktorch_tpu.parallel.tune import GSPMD_AXES, TuneResult, autotune
    from sparktorch_tpu.utils.data import DataBatch
    from sparktorch_tpu.utils.serde import ModelSpec

    # Same CPU compile-cache disarm as sharded_trace: candidates
    # execute collective-bearing GSPMD programs (see tests/conftest.py).
    old_cache = jax.config.jax_compilation_cache_dir
    if jax.default_backend() == "cpu":
        jax.config.update("jax_compilation_cache_dir", None)
    try:
        t0 = time.perf_counter()
        tele = Telemetry(run_id="bench_mesh_tune")
        devices = jax.devices()
        n_dev = len(devices)
        rng = np.random.default_rng(0)
        bsz = 8 * n_dev
        seq = 32
        batch = DataBatch(
            x=np.asarray(rng.integers(0, 256, (bsz, seq)).astype(np.int32)),
            y=np.asarray(rng.integers(0, 2, (bsz,)).astype(np.int32)),
            w=np.ones((bsz,), np.float32),
        )
        # Big enough that real layout differences beat this rig's
        # scheduler jitter (tiny models drown in it — same sizing
        # lesson as the fleet bench): ~50-200ms steps, not ~5ms.
        module = SequenceClassifier(tiny_transformer(
            d_model=256, d_ff=1024, max_len=seq))
        spec = ModelSpec(module=module, loss="cross_entropy",
                         optimizer="adam", optimizer_params={"lr": 1e-3})
        steps, repeats, top_k = 4, 3, 4

        # ---- the tuner under test ----------------------------------------
        with tempfile.TemporaryDirectory() as td:
            artifact = os.path.join(td, "tune_result.json")
            tuned = autotune(
                spec, batch, devices, steps=steps, repeats=repeats,
                measure_top_k=top_k, artifact_path=artifact,
                telemetry=tele, axes=GSPMD_AXES,
            )
            # Artifact round-trip: the ranking and prune log must
            # survive the JSON (what `mesh="auto"` consumers read).
            loaded = TuneResult.load(artifact)
        if loaded.to_dict() != tuned.to_dict():
            raise AssertionError("tune_result.json round-trip mismatch")
        if not tuned.to_dict()["ranking"]:
            raise AssertionError("tuner emitted no ranking")
        pruned = tuned.pruned()
        if not pruned:
            raise AssertionError(
                "prune step eliminated no candidate — the analytic "
                "comm model did no work"
            )
        if any(c.measured for c in pruned):
            raise AssertionError("a pruned candidate was executed")

        # ---- tuner execution budget --------------------------------------
        budget_s = float(os.environ.get("SPARKTORCH_TPU_TUNE_BUDGET_S",
                                        "600"))
        # The step budget counts EVERY profiled step the tuner ran —
        # warmup captures included (they execute; discarding their
        # scores doesn't refund their cost).
        step_budget = top_k * steps * (repeats + tuned.warmup_rounds)
        if tuned.executed_steps_total > step_budget:
            raise AssertionError(
                f"tuner executed {tuned.executed_steps_total} profiled "
                f"steps > budget {top_k} x {steps} x "
                f"({repeats} + {tuned.warmup_rounds} warmup)"
            )
        if tuned.wall_s > budget_s:
            raise AssertionError(
                f"tuner wall {tuned.wall_s:.1f}s over the {budget_s:.0f}s "
                f"budget"
            )

        # ---- the exhaustive referee --------------------------------------
        jax.clear_caches()
        gc.collect()
        exhaustive = autotune(
            spec, batch, devices, steps=steps, repeats=repeats,
            exhaustive=True, telemetry=tele, axes=GSPMD_AXES,
        )
        ex_ranked = exhaustive.ranking()
        ex_by_label = {c.label: c for c in ex_ranked}
        winner = ex_ranked[0]
        chosen_label = tuned.best_label

        tol = float(os.environ.get("SPARKTORCH_TPU_TUNE_TOL", "0.10"))
        chosen_ex = ex_by_label.get(chosen_label)
        if chosen_ex is None:
            raise AssertionError(
                f"chosen mesh {chosen_label} missing from the exhaustive "
                f"measurement ({sorted(ex_by_label)})"
            )
        winner_wall = float(winner.measured["step_wall_s"])
        chosen_wall = float(chosen_ex.measured["step_wall_s"])
        if chosen_label != winner.label and \
                chosen_wall > winner_wall * (1.0 + tol):
            raise AssertionError(
                f"tuner chose {chosen_label} "
                f"({chosen_wall * 1e3:.2f}ms/step on the exhaustive rig) "
                f"but the exhaustive winner is {winner.label} "
                f"({winner_wall * 1e3:.2f}ms/step) — "
                f"{(chosen_wall / winner_wall - 1) * 100:.1f}% slower, "
                f"over the {tol * 100:.0f}% tolerance"
            )
        # The prune must never eliminate the measured winner — judged
        # at the same tolerance, because on this rig the top entries
        # sit inside each other's noise and the "winner" identity is
        # a coin flip between them: a pruned candidate is a violation
        # when the exhaustive pass shows it MATERIALLY better than
        # what the tuner chose.
        materially_better = [
            c for c in pruned
            if c.label in ex_by_label
            and float(ex_by_label[c.label].measured["step_wall_s"])
            < chosen_wall / (1.0 + tol)
        ]
        if materially_better:
            raise AssertionError(
                f"the prune step eliminated candidate(s) materially "
                f"faster than the chosen {chosen_label} "
                f"({chosen_wall * 1e3:.2f}ms): "
                + ", ".join(
                    f"{c.label} ({float(ex_by_label[c.label].measured['step_wall_s']) * 1e3:.2f}ms)"
                    for c in materially_better)
                + f" — the comm model mis-ranked the space "
                f"(predicted order: "
                f"{[c.label for c in tuned.candidates]})"
            )

        return {
            "config": "mesh_tune", "unit": "chosen step wall vs best (x)",
            "value": round(chosen_wall / winner_wall, 4),
            "chosen": chosen_label,
            "exhaustive_winner": winner.label,
            "chosen_wall_ms": round(chosen_wall * 1e3, 3),
            "winner_wall_ms": round(winner_wall * 1e3, 3),
            "tolerance": tol,
            "n_candidates": len(tuned.candidates),
            "n_pruned": len(pruned),
            "n_measured_tuner": len(tuned.ranking()),
            "rounds_run": tuned.rounds_run,
            "early_stopped": tuned.early_stopped,
            "noise_floor_ms": round(tuned.noise_floor_s * 1e3, 3),
            "tuner_wall_s": round(tuned.wall_s, 1),
            "exhaustive_wall_s": round(exhaustive.wall_s, 1),
            "tuner_ranking": tuned.to_dict()["ranking"],
            "exhaustive_ranking": [
                {"mesh": c.label,
                 "wall_ms": round(float(c.measured["step_wall_s"]) * 1e3, 3),
                 "exposed": round(float(
                     c.measured["exposed_comm_fraction"]), 3)}
                for c in ex_ranked
            ],
            "pruned": [{"mesh": c.label, "reason": c.reason}
                       for c in pruned],
            "n_chips": n_dev,
            "wall_s": round(time.perf_counter() - t0, 1),
        }
    finally:
        if jax.default_backend() == "cpu":
            jax.config.update("jax_compilation_cache_dir", old_cache)


def _synthetic_rank_trace(rank: int, steps: int = 2) -> dict:
    """A deterministic per-rank Chrome-trace dict: each step has one
    marker, one compute fusion, one all-reduce — with rank-dependent
    timings so the merged gang budget has REAL cross-rank skew to
    gate on (rank r's step walls are (1 + r/4)x rank 0's)."""
    events = []
    scale = 1.0 + rank / 4.0
    t = 1000.0
    for s in range(steps):
        wall = 1000.0 * scale
        events.append({"ph": "X", "pid": 1, "tid": 1, "name": "train_step",
                       "ts": t, "dur": wall,
                       "args": {"step_num": str(s)}})
        events.append({"ph": "X", "pid": 1, "tid": 2, "name": f"fusion.{s}",
                       "ts": t + 50, "dur": 600 * scale})
        events.append({"ph": "X", "pid": 1, "tid": 3,
                       "name": f"all-reduce.{s}",
                       "ts": t + 400, "dur": 400 * scale})
        t += wall
    return {"traceEvents": events}


def bench_gang_obs(n_ranks: int = 3) -> dict:
    """Gang-observability gate (``make bench-gang-obs``): spin N local
    rank exporters, run the fleet collector over them, and FAIL unless

    - the collector's merged scrape carries EVERY per-rank series with
      ``rank``/``host`` labels, and the merged values reconcile with
      the per-rank scrapes (each labeled series equals its rank's own
      scrape; the cross-rank sum equals the sum of per-rank sums);
    - the merged xprof gang budget reconciles with the per-rank
      analyses: per-family comm seconds SUM, per-step walls MAX,
      cross-rank step skew >= 0 (and > 0 here — the synthetic ranks
      are deliberately skewed);
    - a seeded TRUNCATED capture (more steps annotated on the bus than
      markers in the trace) trips the ``xprof.capture_truncated``
      warning exactly once, and a complete capture trips nothing.

    Backend-free (no jax device work): this is the observability
    plane's own gate, runnable on any CI box."""
    import os
    import tempfile

    from sparktorch_tpu.native.gang import GangMetricsExporter
    from sparktorch_tpu.obs import (
        FleetCollector,
        Telemetry,
        analyze_trace,
        mint_run_id,
        parse_prometheus,
        scrape_json,
        scrape_text,
    )
    from sparktorch_tpu.obs.heartbeat import HeartbeatEmitter
    from sparktorch_tpu.obs.xprof import analyze_and_publish

    t0 = time.perf_counter()
    run_id = mint_run_id("bench-gang-obs")
    analyses = []
    exporters = []
    collector = None
    with tempfile.TemporaryDirectory() as hb_dir:
        try:
            for r in range(n_ranks):
                tele = Telemetry(run_id=run_id)
                # Distinct per-rank counter values, so sum/match gates
                # can't pass by accident.
                tele.counter("bench.gang_obs_ticks", r + 1)
                analysis = analyze_trace(_synthetic_rank_trace(r))
                analysis.publish(tele)
                analyses.append(analysis)
                HeartbeatEmitter(hb_dir, rank=r, telemetry=tele,
                                 run_id=run_id).notify_step(10 * (r + 1))
                exporters.append(GangMetricsExporter(
                    heartbeat_dir=hb_dir, telemetry=tele).start())

            collector = FleetCollector(
                {r: exp.url for r, exp in enumerate(exporters)},
                run_id=run_id, poll_interval_s=0,
            ).start(poll_loop=False)
            collector.poll()

            # ---- gate 1: merged scrape vs per-rank scrapes ---------------
            rank_scrapes = [parse_prometheus(scrape_text(e.url + "/metrics"))
                            for e in exporters]
            merged_scrape = parse_prometheus(
                scrape_text(collector.url + "/metrics"))
            host = "127.0.0.1"
            tick = "sparktorch_bench_gang_obs_ticks"
            merged_sum = 0.0
            for r, scrape in enumerate(rank_scrapes):
                own = scrape.get(tick)
                labeled = merged_scrape.get(
                    f'{tick}{{host="{host}",rank="{r}"}}')
                if own != float(r + 1) or labeled != own:
                    raise AssertionError(
                        f"rank {r}: merged series {labeled} != per-rank "
                        f"scrape {own}"
                    )
                merged_sum += labeled
            if merged_sum != sum(r + 1 for r in range(n_ranks)):
                raise AssertionError(
                    f"merged rank-labeled sum {merged_sum} != "
                    f"{sum(r + 1 for r in range(n_ranks))}"
                )
            # Every rank-originated series in the merged view must
            # carry a rank label (collector-own series are exempt).
            merged_snap = scrape_json(collector.url + "/telemetry")
            unlabeled = [
                k for section in ("counters", "gauges", "histograms")
                for k in merged_snap.get(section, {})
                if not k.startswith(("collector.", "xprof.gang_"))
                and "rank=" not in k
            ]
            if unlabeled:
                raise AssertionError(
                    f"merged series missing rank labels: {unlabeled[:5]}"
                )

            # ---- gate 2: gang budget reconciles with per-rank ------------
            gang = scrape_json(collector.url + "/gang")
            xp = gang.get("xprof")
            if not xp or xp.get("n_ranks") != n_ranks:
                raise AssertionError(f"gang xprof missing/short: {xp}")
            fam_sum = {}
            for a in analyses:
                for fam, sec in a.family_s().items():
                    fam_sum[fam] = fam_sum.get(fam, 0.0) + sec
            for fam, sec in fam_sum.items():
                got = xp["collective_s"].get(fam, 0.0)
                if abs(got - sec) > 1e-9:
                    raise AssertionError(
                        f"family {fam}: gang {got} != sum {sec}"
                    )
            for i, step in enumerate(xp["steps"]):
                walls = [a.steps[i].wall_s for a in analyses]
                if abs(step["wall_s"] - max(walls)) > 1e-9:
                    raise AssertionError(
                        f"step {i}: gang wall {step['wall_s']} != "
                        f"max {max(walls)}"
                    )
                if step["skew_s"] < 0 or \
                        abs(step["skew_s"]
                            - (max(walls) - min(walls))) > 1e-9:
                    raise AssertionError(
                        f"step {i}: skew {step['skew_s']} != "
                        f"{max(walls) - min(walls)}"
                    )
            if not xp["step_skew_s"] > 0:
                raise AssertionError(
                    "synthetic ranks are skewed but gang skew is 0"
                )
            hb = gang.get("heartbeats", {})
            if hb.get("n_ranks") != n_ranks or \
                    hb.get("step_skew") != 10 * (n_ranks - 1):
                raise AssertionError(f"merged heartbeat table wrong: {hb}")
            run_ids = set(gang.get("run_ids", {}).values())
            if run_ids != {run_id}:
                raise AssertionError(
                    f"run_id correlation broken: {run_ids} != {{{run_id}}}"
                )

            # ---- gate 3: truncation warning, exactly once ----------------
            trunc_tele = Telemetry(run_id="gang_obs_trunc")
            with tempfile.TemporaryDirectory() as td:
                path = os.path.join(td, "host0.trace.json")
                with open(path, "w") as f:
                    json.dump(  # lint-obs: ok (synthetic trace fixture)
                        _synthetic_rank_trace(0, steps=2), f)
                # Seeded truncation: 4 steps annotated on the bus, only
                # 2 markers survived in the capture.
                analyze_and_publish(td, telemetry=trunc_tele,
                                    expected_steps=4)
                tripped = trunc_tele.counter_value(
                    "xprof.capture_truncated_total")
                if tripped != 1:
                    raise AssertionError(
                        f"truncation warning tripped {tripped}x, want 1"
                    )
                # A COMPLETE capture must not trip it.
                analyze_and_publish(td, telemetry=trunc_tele,
                                    expected_steps=2)
                if trunc_tele.counter_value(
                        "xprof.capture_truncated_total") != 1:
                    raise AssertionError(
                        "complete capture tripped the truncation warning"
                    )
        finally:
            if collector is not None:
                collector.stop()
            for exp in exporters:
                exp.stop()

    # ---- gang drift gate (vs the previous round's gang record) -------
    gang_drift = _check_gang_drift(
        "gang_obs", float(xp["step_skew_s"]), float(xp["comm_fraction"]),
    )

    return {
        "config": "gang_obs", "unit": "ranks merged",
        "value": n_ranks,
        "n_ranks": n_ranks,
        "run_id": run_id,
        "gang_step_skew_s": round(float(xp["step_skew_s"]), 6),
        "gang_comm_s": round(float(xp["comm_s"]), 6),
        "gang_comm_fraction": round(float(xp["comm_fraction"]), 4),
        "gang_drift": gang_drift,
        "merged_series": sum(
            len(merged_snap.get(s, {}))
            for s in ("counters", "gauges", "histograms")
        ),
        "truncation_trips": 1,
        "scrape_reconciled": True,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def bench_hogwild_chaos_soak(rounds: int = 4, iters: int = 16,
                             freeze_rounds: int = 2,
                             worker_steps: int = 60) -> dict:
    """Chaos SOAK gate (``make bench-chaos-soak``): a seeded random
    kill/freeze/drop schedule over many supervised rounds — the
    multi-fault recovery races ``bench-chaos``'s single kill cannot
    catch. Two legs:

    - **hogwild leg** (kills + connection drops): each round runs
      ``train_async`` over real sockets under a random schedule —
      maybe kill a random worker at a random step, drop 0-2 keep-alive
      connections. Every round must complete with restart count ==
      that round's injected kills and an EXACT record count (a killed
      attempt flushes nothing; the rerun repays it — no double
      counting).
    - **freeze leg** (stall preemption): supervised heartbeat-emitting
      workers where a random rank's first attempt goes silent mid-run;
      the barrier deadline must preempt it (cooperatively — the worker
      polls its cancel event) and the restarted attempt must finish.

    FAILS (raises) on any mismatch: restarts != kills,
    stall preemptions != freezes, lost/duplicated records."""
    import tempfile
    import threading
    import time as _time

    import jax

    from sparktorch_tpu.ft import ChaosConfig, FtPolicy, RestartPolicy, inject
    from sparktorch_tpu.ft.policy import BarrierPolicy
    from sparktorch_tpu.ft.supervisor import Supervisor, ThreadWorker
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.obs import Telemetry
    from sparktorch_tpu.obs.heartbeat import HeartbeatEmitter
    from sparktorch_tpu.train.hogwild import train_async
    from sparktorch_tpu.utils.serde import ModelSpec

    rng = np.random.default_rng(7)
    tele = Telemetry(run_id="bench_chaos_soak")
    t_start = time.perf_counter()

    # ---- hogwild leg: kills + drops over real sockets --------------------
    n_workers = len(jax.devices())
    spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3},
                     input_shape=(784,))
    x = rng.normal(0, 1, (1024, 784)).astype(np.float32)
    y = rng.integers(0, 10, (1024,)).astype(np.int32)
    policy = FtPolicy(restart=RestartPolicy(max_restarts=2,
                                            backoff_base_s=0.05), seed=0)
    train_async(spec, x, labels=y, iters=4, mini_batch=64, seed=0)  # warmup

    kills_total = drops_total = 0
    per_round = []
    for r in range(rounds):
        kills = {}
        if rng.random() < 0.75:
            kills[int(rng.integers(0, n_workers))] = int(rng.integers(2, 8))
        drops = int(rng.integers(0, 3))
        cfg = ChaosConfig(kill_worker_at=kills, drop_connections=drops,
                          seed=r)
        with inject(cfg, telemetry=tele) as inj:
            result = train_async(spec, x, labels=y, iters=iters,
                                 mini_batch=64, seed=r, transport="http",
                                 supervise=True, ft_policy=policy,
                                 telemetry=tele)
        restarts = (result.summary or {}).get("ft", {}).get(
            "restarts_total", 0)
        fired = [e["site"] for e in inj.events]
        if restarts != len(kills):
            raise AssertionError(
                f"soak round {r}: {restarts} restarts != "
                f"{len(kills)} injected kills (chaos events: {fired})"
            )
        if fired.count("worker.step") != len(kills):
            raise AssertionError(
                f"soak round {r}: kill schedule {kills} but fired {fired}"
            )
        # Exact records — the no-double-counting invariant: a killed
        # attempt flushes nothing, the restarted attempt reruns the
        # whole round assignment.
        if len(result.metrics) != iters * n_workers:
            raise AssertionError(
                f"soak round {r}: {len(result.metrics)} records != "
                f"{iters * n_workers} expected"
            )
        kills_total += len(kills)
        drops_total += fired.count("transport.request")
        per_round.append({"round": r, "kills": list(kills.items()),
                          "drops": fired.count("transport.request"),
                          "restarts": int(restarts)})

    restarts_bus = sum(
        v for k, v in tele.snapshot()["counters"].items()
        if k.startswith("ft_restarts_total")
    )
    if restarts_bus != kills_total:
        raise AssertionError(
            f"bus ft_restarts_total {restarts_bus} != {kills_total} "
            "injected kills across the soak (double-counted restarts?)"
        )

    # ---- freeze leg: stall-preempted heartbeats through the supervisor --
    freezes_total = 0
    for r in range(freeze_rounds):
        freeze_rank = int(rng.integers(0, 3))
        freeze_at = int(rng.integers(3, 8))
        freezes_total += 1
        with tempfile.TemporaryDirectory() as hb_dir:
            done_counts = {i: 0 for i in range(3)}
            lock = threading.Lock()

            def make_start(rank):
                def start(attempt):
                    # Freshen the slot BEFORE the handle exists: the
                    # frozen file's stale age must not instantly
                    # re-preempt the restarted attempt.
                    HeartbeatEmitter(hb_dir, rank).beat()

                    def target(cancel):
                        emitter = HeartbeatEmitter(hb_dir, rank)
                        frozen = attempt == 0 and rank == freeze_rank
                        for s in range(worker_steps):
                            if cancel.is_set():
                                return  # cooperative preemption
                            if not (frozen and s >= freeze_at):
                                emitter.notify_step(s)
                            _time.sleep(0.02)
                        with lock:
                            done_counts[rank] += 1
                        emitter.close()

                    return ThreadWorker(f"soak{rank}", target,
                                        pass_cancel=True)

                return start

            fpol = FtPolicy(
                restart=RestartPolicy(max_restarts=2, backoff_base_s=0.05),
                barrier=BarrierPolicy(deadline_s=0.3), seed=r,
            )
            sup = Supervisor(policy=fpol, telemetry=tele,
                             heartbeat_dir=hb_dir, name=f"soak_freeze{r}")
            for rank in range(3):
                sup.add(str(rank), make_start(rank), rank=rank)
            sup.run(deadline_s=60)
            if any(v != 1 for v in done_counts.values()):
                raise AssertionError(
                    f"freeze round {r}: completion counts {done_counts} "
                    "(a worker finished twice or never — double-counted)"
                )

    preempts = sum(
        v for k, v in tele.snapshot()["counters"].items()
        if k.startswith("ft_stall_preemptions_total")
    )
    if preempts != freezes_total:
        raise AssertionError(
            f"{preempts} stall preemptions != {freezes_total} injected "
            "freezes"
        )
    restarts_all = sum(
        v for k, v in tele.snapshot()["counters"].items()
        if k.startswith("ft_restarts_total")
    )
    if restarts_all != kills_total + freezes_total:
        raise AssertionError(
            f"total restarts {restarts_all} != kills {kills_total} + "
            f"freezes {freezes_total}"
        )
    return {
        "config": "hogwild_chaos_soak", "unit": "restarts",
        "value": int(restarts_all),
        "rounds": rounds, "freeze_rounds": freeze_rounds,
        "kills": int(kills_total), "freezes": int(freezes_total),
        "drops": int(drops_total),
        "restarts": int(restarts_all),
        "stall_preemptions": int(preempts),
        "records_exact": True,
        "n_chips": n_workers,
        "wall_s": round(time.perf_counter() - t_start, 2),
        "per_round": per_round,
    }


def bench_elastic_ctl(n_parts: int = 36, part_sleep_s: float = 0.4,
                      recovery_bound_s: float = 30.0) -> dict:
    """Elastic control-plane gate (``make bench-elastic``): one
    supervised MULTI-PROCESS run (real ``python -m sparktorch_tpu.ctl.
    worker`` children) must survive, in a single world, the three
    transitions the controller exists for —

    - a seeded NON-COOPERATIVE kill (chaos ``kill_process_at``: raw
      SIGKILL delivered by the controller's own liveness poll, no
      cancel event, no grace) -> restart, recovery latency bounded;
    - a restart-budget EXHAUSTION (one rank crashes on every attempt)
      -> world SHRINK through the native coordinator (generation
      bump), the dead rank's partitions redistributed, run continues;
    - a REJOIN (a new rank added after the shrink) -> world GROW,
      another generation.

    FAILS (raises) unless: every partition completes EXACTLY once
    (atomic rename + skip-if-exists idempotency — no loss, no double
    work), the chaos kill fired exactly once, shrink and grow each
    happened exactly once with the coordinator's generation following,
    and every transition is visible as a generation-tagged event in
    the fleet collector's ``/gang`` view scraped over HTTP. A
    recovery-latency drift gate arms once a prior record is retained
    (``SPARKTORCH_TPU_ELASTIC_DRIFT_TOL``, relative, default 2.0 —
    child-process boot cost breathes with rig load)."""
    import os
    import tempfile
    import threading

    from sparktorch_tpu.ctl import ElasticController, spawn_worker
    from sparktorch_tpu.ft import ChaosConfig, FtPolicy, RestartPolicy, inject
    from sparktorch_tpu.native.gang import GangCoordinator, GangMetricsExporter
    from sparktorch_tpu.obs import Telemetry
    from sparktorch_tpu.obs.collector import FleetCollector, scrape_json

    t_start = time.perf_counter()
    tele = Telemetry(run_id="bench_elastic")
    workdir = tempfile.mkdtemp(prefix="bench_elastic_")
    out = os.path.join(workdir, "parts")
    hb_dir = os.path.join(workdir, "hb")
    os.makedirs(out)
    work = [f"part{i:03d}" for i in range(n_parts)]

    def completed(p):
        return os.path.exists(os.path.join(out, p + ".done"))

    def start_fn(rank, attempt, generation, assignment):
        def workfn(ctx, _parts=tuple(assignment), _rank=rank,
                   _gen=generation, _out=out, _sleep=part_sleep_s):
            import os as _os
            import time as _t

            if _rank == 1:
                raise RuntimeError("rank1 permanently broken")
            for i, p in enumerate(_parts):
                if ctx.should_stop():
                    return
                ctx.notify_step(i)
                path = _os.path.join(_out, p + ".done")
                if _os.path.exists(path):
                    continue
                tmp = path + f".tmp{_os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(f"{_rank}:{_gen}")
                _os.replace(tmp, path)
                _t.sleep(_sleep)

        return spawn_worker(workfn, rank=rank, heartbeat_dir=hb_dir,
                            name=f"rank{rank}", telemetry=tele)

    coord = GangCoordinator(world_size=3, port=0,
                            heartbeat_timeout_ms=30_000)
    exporter = GangMetricsExporter(heartbeat_dir=hb_dir, coordinator=coord,
                                   telemetry=tele, port=0).start()
    collector = FleetCollector({0: exporter.url}, telemetry=tele,
                               poll_interval_s=0.25)
    collector.start(poll_loop=True)
    policy = FtPolicy(restart=RestartPolicy(max_restarts=2,
                                            backoff_base_s=0.05,
                                            backoff_max_s=0.2), seed=0)
    ctl = ElasticController(work, completed, policy=policy, telemetry=tele,
                            coordinator=coord, collector=collector,
                            min_world=1, name="bench_elastic")
    for r in range(3):
        ctl.add_rank(r, start_fn)

    def grower():
        # The rejoin: a NEW rank joins right after the shrink lands,
        # so the gate always sees shrink THEN grow in one run.
        deadline = time.perf_counter() + 120.0
        while time.perf_counter() < deadline and not ctl._stop.is_set():
            if ctl._resizes["shrink"] >= 1:
                ctl.grow(3, start_fn)
                return
            time.sleep(0.05)

    threading.Thread(target=grower, name="bench-elastic-grower",
                     daemon=True).start()
    try:
        with inject(ChaosConfig(seed=11, kill_process_at={0: 2}),
                    telemetry=tele) as inj:
            summary = ctl.run(poll_interval_s=0.05, deadline_s=240.0)
        gang_doc = scrape_json(
            f"http://127.0.0.1:{collector.port}/gang")
    finally:
        collector.stop()
        exporter.stop()
        coord.stop()
    # Post-stop reads below are the PR 10 contract: stop() SNAPSHOTS
    # final native state before freeing it (the pre-snapshot version of
    # this very bench segfaulted here — sparklint SPK501 now guards the
    # class; these two reads are the documented exception).
    coord_generation = coord.generation  # lint-obs: ok (snapshot property, frozen by stop())
    coord_world_size = coord.world_size  # lint-obs: ok (snapshot property, frozen by stop())

    # -- gates ---------------------------------------------------------
    missing = [p for p in work if not completed(p)]
    if missing or summary["work_pending"]:
        raise AssertionError(f"partitions incomplete: {missing}")
    torn = [f for f in os.listdir(out) if ".tmp" in f]
    if torn:
        raise AssertionError(f"torn partition outputs left behind: {torn}")
    if len(os.listdir(out)) != n_parts:
        raise AssertionError(
            f"{len(os.listdir(out))} outputs != {n_parts} partitions")
    kills_fired = [e for e in inj.events if e["site"] == "ctl.process"]
    if len(kills_fired) != 1 or kills_fired[0]["rank"] != 0:
        raise AssertionError(
            f"chaos kill_process_at fired {kills_fired} (want exactly "
            "one SIGKILL on rank 0)")
    if summary["resizes"] != {"shrink": 1, "grow": 1}:
        raise AssertionError(f"resizes {summary['resizes']} != "
                             "{'shrink': 1, 'grow': 1}")
    if summary["removed"] != [1]:
        raise AssertionError(f"removed {summary['removed']} != [1]")
    kinds = [h["kind"] for h in ctl.history]
    for needed in ("restart", "shrink", "grow"):
        if needed not in kinds:
            raise AssertionError(
                f"no {needed!r} event in the controller history {kinds}")
    untagged = [h for h in ctl.history if "generation" not in h]
    if untagged:
        raise AssertionError(f"events missing generation tags: {untagged}")
    if not (coord_generation == ctl.generation == summary["generation"]
            >= 2):
        raise AssertionError(
            f"generation disagreement: coordinator {coord_generation}, "
            f"controller {ctl.generation}, summary "
            f"{summary['generation']} (want agreement, >= 2)")
    if coord_world_size != 3:  # ranks 0, 2 and the joined 3
        raise AssertionError(
            f"coordinator world_size {coord_world_size} != 3 after "
            "shrink+grow")
    # Every transition visible in the collector's /gang answer.
    elastic_doc = gang_doc.get("elastic") or {}
    doc_kinds = [h.get("kind") for h in elastic_doc.get("history", [])]
    for needed in ("restart", "shrink", "grow"):
        if needed not in doc_kinds:
            raise AssertionError(
                f"/gang elastic history lacks {needed!r}: {doc_kinds}")
    if elastic_doc.get("generation") != summary["generation"] or \
            elastic_doc.get("resizes") != summary["resizes"]:
        raise AssertionError(
            f"/gang elastic doc {elastic_doc.get('generation')}/"
            f"{elastic_doc.get('resizes')} disagrees with the run "
            f"summary {summary['generation']}/{summary['resizes']}")
    # Recovery latency: the restart of the SIGKILLed rank, detection
    # to relaunch, bounded (generous — child boot rides rig load).
    recovery = [
        v["max"] for k, v in tele.snapshot()["histograms"].items()
        if k.startswith("ft_recovery_latency_s") and v["count"]
    ]
    if not recovery or max(recovery) > recovery_bound_s:
        raise AssertionError(
            f"recovery latency {recovery} empty or past the "
            f"{recovery_bound_s}s bound")
    # Redistribution really happened: generations past 0 completed
    # partitions too (the shrunk/grown worlds carried the tail).
    by_gen: Dict[str, int] = {}
    for p in work:
        with open(os.path.join(out, p + ".done")) as f:
            _, gen = f.read().split(":")
        by_gen[gen] = by_gen.get(gen, 0) + 1
    if len(by_gen) < 2:
        raise AssertionError(
            f"all partitions completed in one generation ({by_gen}) — "
            "the resizes never redistributed work")

    # -- drift gate (arms once a prior record is retained) -------------
    tol = float(os.environ.get("SPARKTORCH_TPU_ELASTIC_DRIFT_TOL", "2.0"))
    recovery_max = max(recovery)
    prior = _prior_record("elastic_ctl", "recovery_latency_s")
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        prior_lat = float(prior["recovery_latency_s"])
        drift = {
            "status": "checked", "tolerance": tol,
            "prior_ts": prior.get("ts"),
            "prior_recovery_latency_s": round(prior_lat, 3),
            "ratio": round(recovery_max / max(prior_lat, 1e-9), 3),
        }
        if recovery_max > prior_lat * (1.0 + tol) + 1.0:
            raise AssertionError(
                f"recovery latency regressed: {recovery_max:.2f}s vs "
                f"prior {prior_lat:.2f}s (past the {tol} relative "
                f"tolerance + 1s floor); drift: {drift}")

    return {
        "config": "elastic_ctl", "unit": "s (recovery latency)",
        "value": round(recovery_max, 3),
        "recovery_latency_s": round(recovery_max, 3),
        "n_parts": n_parts,
        "restarts": summary["restarts"],
        "resizes": summary["resizes"],
        "removed": summary["removed"],
        "generation": summary["generation"],
        "world_size": summary["world_size"],
        "parts_by_generation": dict(sorted(by_gen.items())),
        "chaos_kills": len(kills_fired),
        "records_exact": True,
        "elastic_drift": drift,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def bench_obs_history(n_pulls: int = 6, slow_delay_s: float = 0.5,
                      for_sweeps: int = 3) -> dict:
    """Metrics-history / SLO-alerting / flight-recorder gate
    (``make bench-obs-history``) — FAILS (raises) unless all three
    retained-observability claims hold end to end:

    - **alerting is causal, not noisy**: against a live 2-shard fleet,
      a seeded degradation (chaos ``slow_shard_s``) must fire the
      sustained ``sharded.shard_pull_latency_s`` p99 breach rule (the
      client hop — the server-side ``wire_latency_s`` can never see
      the injected delay) within its rule window
      (``for_sweeps`` + 2 sweeps of the first breach), exactly one
      episode, visible in the collector's ``/gang`` ``alerts`` section
      over HTTP — while an A/A CONTROL run (identical loop, no chaos)
      fires nothing;
    - **postmortems capture the causal window**: a seeded
      NON-COOPERATIVE process-worker kill (chaos ``kill_process_at``)
      must produce a ``postmortem_<ts>.json`` bundle whose event
      window contains the kill's ``ctl.*`` transition AND the victim
      rank's last spans (recovered from the collector's last-good
      scrape of the dead process's flight-recorder ring), renderable
      by ``timeline --postmortem``;
    - **the memory tier is nearly free**: the collector sweep with
      history + alerts enabled stays within 10%
      (``SPARKTORCH_TPU_OBS_SWEEP_TOL``) of a history-off sweep —
      medians over interleaved sweeps against the same targets, so
      rig noise hits both legs.

    A throughput-shaped drift gate arms once a prior record is
    retained, judged against the WINDOWED median of the newest 3 prior
    rounds (``_prior_window`` — the satellite that moves drift gates
    off single records)."""
    import io
    import os
    import tempfile
    import contextlib

    import jax

    from sparktorch_tpu.ctl import ElasticController, spawn_worker
    from sparktorch_tpu.ft import ChaosConfig, FtPolicy, RestartPolicy, inject
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.net.sharded import ShardedTransport
    from sparktorch_tpu.obs import AlertRule, FleetCollector, Telemetry
    from sparktorch_tpu.obs import timeline as _timeline
    from sparktorch_tpu.obs.blackbox import read_postmortem
    from sparktorch_tpu.obs.collector import scrape_json
    from sparktorch_tpu.native.gang import GangMetricsExporter
    from sparktorch_tpu.serve.fleet import ParamServerFleet
    from sparktorch_tpu.utils.serde import ModelSpec

    t_start = time.perf_counter()
    spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                     optimizer="sgd", optimizer_params={"lr": 1e-2},
                     input_shape=(784,))
    slow_shard = "1"
    threshold_s = slow_delay_s * 0.4  # far above clean serve, far below delayed

    def _alert_leg(chaos_cfg) -> dict:
        """One fleet + collector + rule run; returns the alert story."""
        leg_tele = Telemetry(run_id="bench_obs_alert")
        fleet = ParamServerFleet(spec, n_shards=2,
                                 telemetry=leg_tele).start()
        # Client-observed hop latency, not the server-side
        # wire_latency_s: the chaos delay (like a real network/queue
        # straggler) lands BEFORE the serve handler's clock, on the
        # client's shard hop — which is exactly the series a hot-shard
        # rule must watch.
        rules = [AlertRule(
            name="hot_shard_p99",
            metric="sharded.shard_pull_latency_s",
            labels={"shard": slow_shard},
            kind="sustained", field="p99", op=">",
            threshold=threshold_s, for_sweeps=for_sweeps,
        )]
        collector = FleetCollector.for_fleet(fleet, poll_interval_s=0,
                                             alert_rules=rules)
        collector.start(poll_loop=False)
        first_breach_sweep = None
        fired_sweep = None
        try:
            transport = ShardedTransport(fleet, telemetry=leg_tele)
            zeros = jax.tree.map(
                lambda a: np.zeros_like(np.asarray(a)), fleet.assemble())
            have = -1
            ctx = (inject(chaos_cfg, telemetry=leg_tele) if chaos_cfg
                   else contextlib.nullcontext())
            with ctx:
                for sweep in range(n_pulls):
                    transport.push(zeros)
                    fleet.drain()
                    snap = transport.pull(have)
                    if snap is not None:
                        have = snap[0]
                    collector.poll()
                    state = collector.alerts.doc()["rules"]["hot_shard_p99"]
                    if first_breach_sweep is None and state["streak"] > 0:
                        first_breach_sweep = sweep
                    if fired_sweep is None and state["state"] == "firing":
                        fired_sweep = sweep
            gang = scrape_json(f"{collector.url}/gang")
            hist_rate = scrape_json(
                f"{collector.url}/history?name=collector.scrapes_total"
                f"&query=rate")
            transport.close()
            return {
                "doc": collector.alerts.doc(),
                "gang_alerts": gang.get("alerts") or {},
                "first_breach_sweep": first_breach_sweep,
                "fired_sweep": fired_sweep,
                "history_rate_ok": hist_rate.get("value") is not None,
            }
        finally:
            collector.stop()
            fleet.stop()

    with Telemetry(run_id="bench_obs").span("bench/alert_legs") as _sp_alerts:
        control = _alert_leg(None)
        chaotic = _alert_leg(ChaosConfig(
            seed=7, slow_shard_s={slow_shard: slow_delay_s}))

    # -- gates: A/A control silent, seeded breach fires in-window ------
    ctl_rule = control["doc"]["rules"]["hot_shard_p99"]
    if ctl_rule["episodes"] != 0 or control["gang_alerts"].get("active"):
        raise AssertionError(
            f"A/A control run fired alerts: {ctl_rule} "
            f"(active {control['gang_alerts'].get('active')})")
    hot_rule = chaotic["doc"]["rules"]["hot_shard_p99"]
    if hot_rule["episodes"] != 1 or hot_rule["state"] != "firing":
        raise AssertionError(
            f"seeded degradation did not fire exactly one episode: "
            f"{hot_rule}")
    if chaotic["first_breach_sweep"] is None \
            or chaotic["fired_sweep"] is None \
            or (chaotic["fired_sweep"] - chaotic["first_breach_sweep"]
                > for_sweeps + 1):
        raise AssertionError(
            f"alert missed its rule window: first breach sweep "
            f"{chaotic['first_breach_sweep']}, fired sweep "
            f"{chaotic['fired_sweep']} (for_sweeps={for_sweeps})")
    if "hot_shard_p99" not in (chaotic["gang_alerts"].get("active") or []):
        raise AssertionError(
            f"/gang alerts section does not show the firing rule: "
            f"{chaotic['gang_alerts']}")
    if not (control["history_rate_ok"] and chaotic["history_rate_ok"]):
        raise AssertionError("/history rate query answered null on a "
                             "live collector")

    # -- leg 2: seeded worker kill -> postmortem bundle ----------------
    with Telemetry(run_id="bench_obs").span("bench/postmortem_leg") as _sp_pm:
        tele = Telemetry(run_id="bench_obs_pm")
        workdir = tempfile.mkdtemp(prefix="bench_obs_pm_")
        out = os.path.join(workdir, "parts")
        hb_dir = os.path.join(workdir, "hb")
        pm_dir = os.path.join(workdir, "postmortems")
        os.makedirs(out)
        work = [f"part{i:02d}" for i in range(8)]

        def completed(p):
            return os.path.exists(os.path.join(out, p + ".done"))

        workers = {}
        # The chaos kill fires at rank 0's heartbeat step 2; the bundle
        # gate needs the victim's spans in the collector's last-good
        # snapshot first. Workers park before step 2 until this file
        # appears — the bench writes it once the collector has scraped
        # rank 0's blackbox ring, so a slow rank-1 spawn (the collector
        # starts only after BOTH URLs publish) can't let the kill
        # outrun the first scrape.
        scrape_gate = os.path.join(workdir, "scrape.gate")

        def start_fn(rank, attempt, generation, assignment):
            def workfn(ctx, _parts=tuple(assignment), _rank=rank,
                       _out=out, _gate=scrape_gate):
                import os as _os
                import time as _t

                for i, p in enumerate(_parts):
                    if ctx.should_stop():
                        return
                    if i == 2 and not _os.path.exists(_gate):
                        hold = _t.perf_counter() + 30.0
                        while (not _os.path.exists(_gate)
                               and _t.perf_counter() < hold
                               and not ctx.should_stop()):
                            _t.sleep(0.05)
                    ctx.notify_step(i)
                    # The victim's last evidence: a per-partition span
                    # on its own bus -> flight-recorder ring ->
                    # /telemetry scrape -> collector last-good.
                    with ctx.telemetry.span("work/partition", labels={
                            "part": p}):
                        path = _os.path.join(_out, p + ".done")
                        if not _os.path.exists(path):
                            tmp = path + f".tmp{_os.getpid()}"
                            with open(tmp, "w") as f:
                                f.write(f"{_rank}")
                            _os.replace(tmp, path)
                        _t.sleep(0.25)

            w = spawn_worker(workfn, rank=rank, heartbeat_dir=hb_dir,
                             name=f"rank{rank}", telemetry=tele,
                             ctl_port=0)
            workers[rank] = w
            return w

        policy = FtPolicy(restart=RestartPolicy(max_restarts=2,
                                                backoff_base_s=0.05,
                                                backoff_max_s=0.2), seed=0)
        ctl = ElasticController(work, completed, policy=policy,
                                telemetry=tele, min_world=1,
                                postmortem_dir=pm_dir,
                                name="bench_obs_pm")
        ctl.add_rank(0, start_fn)
        ctl.add_rank(1, start_fn)
        collector = None
        try:
            with inject(ChaosConfig(seed=13, kill_process_at={0: 2}),
                        telemetry=tele) as inj:
                # Launch via run() in a thread? No: run() launches and
                # supervises; the collector needs the workers' exporter
                # URLs, which exist only after launch. Launch first via
                # a short-lived controller thread would race — instead
                # poll the URLs from the handles the start_fn records.
                import threading as _threading

                run_err = []

                def _run():
                    try:
                        ctl.run(poll_interval_s=0.05, deadline_s=120.0)
                    except BaseException as e:  # surfaced below
                        run_err.append(e)

                runner = _threading.Thread(target=_run, daemon=True)
                runner.start()
                deadline = time.perf_counter() + 30.0
                urls = {}
                while time.perf_counter() < deadline and len(urls) < 2:
                    for rank, w in list(workers.items()):
                        if rank not in urls:
                            url = w.ctl_url(timeout_s=0.1)
                            if url:
                                urls[rank] = url
                    time.sleep(0.05)
                if len(urls) < 2:
                    raise AssertionError(
                        f"worker exporters never published URLs: {urls}")
                collector = FleetCollector(urls, telemetry=tele,
                                           poll_interval_s=0.1)
                collector.start(poll_loop=True)
                ctl.collector = collector
                # Open the kill gate only after the victim's ring is in
                # last-good — otherwise the bundle can miss its spans.
                from sparktorch_tpu.obs.blackbox import (
                    events_from_snapshot as _ring_events)
                scraped = time.perf_counter() + 30.0
                while time.perf_counter() < scraped:
                    with collector._lock:
                        st = collector._ranks.get("0")
                        snap = st.snapshot if st is not None else None
                    if snap and any(e.get("kind") == "span"
                                    for e in _ring_events(snap)):
                        break
                    time.sleep(0.05)
                else:
                    raise AssertionError(
                        "collector never scraped rank 0's blackbox ring")
                with open(scrape_gate + ".tmp", "w") as f:
                    f.write("ok")
                os.replace(scrape_gate + ".tmp", scrape_gate)
                runner.join(timeout=120.0)
                if runner.is_alive():
                    raise AssertionError("postmortem leg run() hung")
                if run_err:
                    raise AssertionError(
                        f"postmortem leg failed: {run_err[0]}")
        finally:
            if collector is not None:
                collector.stop()
        missing = [p for p in work if not completed(p)]
        if missing:
            raise AssertionError(f"partitions incomplete: {missing}")
        kills = [e for e in inj.events if e["site"] == "ctl.process"]
        if len(kills) != 1 or kills[0]["rank"] != 0:
            raise AssertionError(f"chaos kill fired {kills} (want one "
                                 f"SIGKILL on rank 0)")
        bundles = sorted(os.listdir(pm_dir)) if os.path.isdir(pm_dir) else []
        if not bundles:
            raise AssertionError("no postmortem bundle written")
        # The KILL's bundle is the first one (restart_scheduled fires
        # postmortems in detection order).
        bundle = read_postmortem(os.path.join(pm_dir, bundles[0]))
        kinds = {str(e.get("kind")) for e in bundle["events"]}
        if not kinds & {"ctl.restart_scheduled", "restart_scheduled"}:
            raise AssertionError(
                f"bundle window lacks the kill's ctl.* transition: "
                f"{sorted(kinds)}")
        victim_spans = [e for e in bundle["events"]
                        if e.get("kind") == "span"
                        and str(e.get("rank")) == "0"]
        if not victim_spans:
            raise AssertionError(
                f"bundle window lacks the victim's last spans "
                f"(kinds {sorted(kinds)})")
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = _timeline.main(["--postmortem",
                                 os.path.join(pm_dir, bundles[0])])
        if rc != 0 or "postmortem:" not in buf.getvalue():
            raise AssertionError(f"timeline --postmortem failed (rc={rc})")

    # -- leg 3: sweep overhead with history+alerts vs history-off ------
    with Telemetry(run_id="bench_obs").span("bench/overhead_leg") as _sp_ovr:
        ovr_tele = Telemetry(run_id="bench_obs_ovr")
        ovr_tele.counter("reqs_total", 10)
        for _ in range(64):
            ovr_tele.observe("lat_s", 0.01)
        exporters = [GangMetricsExporter(telemetry=ovr_tele,
                                         port=0).start()
                     for _ in range(2)]
        targets = {i: e.url for i, e in enumerate(exporters)}
        rules = [AlertRule(name="ovr", metric="lat_s",
                           labels={"rank": "0"}, kind="sustained",
                           field="p99", threshold=1e9, for_sweeps=2)]
        col_on = FleetCollector(targets, poll_interval_s=0,
                                alert_rules=rules)
        col_off = FleetCollector(targets, poll_interval_s=0,
                                 history=False)
        on_walls, off_walls = [], []
        try:
            for _ in range(4):  # warmup both paths
                col_on.poll()
                col_off.poll()
            for i in range(60):
                ovr_tele.counter("reqs_total")
                ovr_tele.observe("lat_s", 0.01)
                # Interleaved, order alternating: scheduler epochs hit
                # both legs equally.
                pair = ((col_on, on_walls), (col_off, off_walls))
                for col, walls in (pair if i % 2 == 0
                                   else reversed(pair)):
                    t0 = time.perf_counter()
                    col.poll()
                    walls.append(time.perf_counter() - t0)
        finally:
            col_on.stop()
            col_off.stop()
            for e in exporters:
                e.stop()
        on_ms = float(np.median(on_walls)) * 1e3
        off_ms = float(np.median(off_walls)) * 1e3
        on_min_ms = float(np.min(on_walls)) * 1e3
        off_min_ms = float(np.min(off_walls)) * 1e3
        tol = float(os.environ.get("SPARKTORCH_TPU_OBS_SWEEP_TOL", "0.10"))
        # Gate on MIN-of-sweeps, not the median: the sweep is a
        # deterministic workload, so its min isolates the real cost
        # while the median breathes ±1ms with this rig's cpu-share
        # scheduler (measured A/B medians swinging -4% to +6% across
        # runs of the SAME code — pure noise against a ~100µs true
        # cost). 0.2ms absolute floor for timer/allocator jitter.
        if on_min_ms > off_min_ms * (1.0 + tol) + 0.2:
            raise AssertionError(
                f"history+alerts sweep overhead past bound: min "
                f"{on_min_ms:.3f}ms vs {off_min_ms:.3f}ms history-off "
                f"(medians {on_ms:.3f}/{off_ms:.3f}ms; tol {tol:.0%} "
                f"+ 0.2ms)")

    # -- drift gate (windowed prior median, arms once retained) --------
    tol = float(os.environ.get("SPARKTORCH_TPU_OBS_DRIFT_TOL", "1.0"))
    prior = _prior_window("obs_history", "sweep_on_ms", k=3)
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        drift = {
            "status": "checked", "tolerance": tol,
            "prior_median_ms": round(prior["median"], 3),
            "prior_n": prior["n"],
            "ratio": round(on_ms / max(prior["median"], 1e-9), 3),
        }
        if on_ms > prior["median"] * (1.0 + tol) + 1.0:
            raise AssertionError(
                f"history-on sweep regressed: {on_ms:.3f}ms vs prior "
                f"windowed median {prior['median']:.3f}ms (past the "
                f"{tol} relative tolerance + 1ms floor); drift: {drift}")

    return {
        "config": "obs_history", "unit": "ms (history-on sweep p50)",
        "value": round(on_ms, 3),
        "sweep_on_ms": round(on_ms, 3),
        "sweep_off_ms": round(off_ms, 3),
        "sweep_on_min_ms": round(on_min_ms, 3),
        "sweep_off_min_ms": round(off_min_ms, 3),
        "sweep_overhead_pct": round(100.0 * (on_min_ms - off_min_ms)
                                    / max(off_min_ms, 1e-9), 2),
        "alert": {
            "threshold_s": threshold_s,
            "for_sweeps": for_sweeps,
            "control_episodes": ctl_rule["episodes"],
            "chaos_episodes": hot_rule["episodes"],
            "first_breach_sweep": chaotic["first_breach_sweep"],
            "fired_sweep": chaotic["fired_sweep"],
        },
        "postmortem": {
            "bundles": len(bundles),
            "victim_spans": len(victim_spans),
            "event_kinds": sorted(kinds)[:12],
        },
        "obs_drift": drift,
        "phase_s": {
            "alert_legs": round(_sp_alerts.duration_s, 3),
            "postmortem_leg": round(_sp_pm.duration_s, 3),
            "overhead_leg": round(_sp_ovr.duration_s, 3),
        },
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def bench_goodput(n_parts: int = 10, part_sleep_s: float = 0.25,
                  n_pulls: int = 4, slow_delay_s: float = 0.5) -> dict:
    """Run-level goodput-ledger gate (``make bench-goodput``) — FAILS
    (raises) unless the time ledger's four claims hold end to end:

    - **attribution is real**: a streaming training run with
      checkpointing shows ``compile`` (init + first-chunk cache miss),
      ``checkpoint`` and ``data_wait`` as nonzero seconds, with the
      MECE invariant holding (buckets + idle sum to wall within 2%,
      ZERO over-attribution) and the run report served per rank and
      run-wide over ``GET /goodput`` + rendered by
      ``timeline --goodput`` with the biggest thief named;
    - **chaos lands in the right bucket**: a seeded ``slow_shard_s``
      delay on the hogwild wire shifts ``exposed_comm``, NOT
      ``compute``, vs an A/A control leg (whose downtime buckets are
      exactly zero);
    - **downtime reconciles**: on a real multi-process elastic run, a
      seeded non-cooperative kill lands at least its measured recovery
      gap in ``restart_downtime`` (the bucket is fed from the same
      detection->relaunch window ``ft_recovery_latency_s`` measures,
      so the two reconcile to a tolerance), the shrink+grow walls land
      in ``resize_downtime``, and the driver ledger stays MECE;
    - **the ledger is nearly free**: one LedgerSpan costs < 1% of the
      measured training step wall (drift-gated against the windowed
      median of prior rounds, ``SPARKTORCH_TPU_GOODPUT_DRIFT_TOL``).
    """
    import contextlib
    import io
    import os
    import tempfile
    import threading

    import jax

    from sparktorch_tpu.ctl import ElasticController, spawn_worker
    from sparktorch_tpu.ft import ChaosConfig, FtPolicy, RestartPolicy, inject
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.native.gang import GangCoordinator, GangMetricsExporter
    from sparktorch_tpu.net.sharded import ShardedTransport
    from sparktorch_tpu.obs import FleetCollector, Telemetry
    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.obs import timeline as _timeline
    from sparktorch_tpu.obs.collector import scrape_json
    from sparktorch_tpu.serve.fleet import ParamServerFleet
    from sparktorch_tpu.train.sync import train_distributed_streaming
    from sparktorch_tpu.utils.serde import ModelSpec

    t_start = time.perf_counter()
    workdir = tempfile.mkdtemp(prefix="bench_goodput_")

    def _mece(doc: dict, leg: str, over_tol_frac: float = 0.02) -> None:
        wall = float(doc["wall_s"])
        total = sum(float(v) for v in doc["buckets"].values())
        if abs(total - wall) > 0.02 * wall:
            raise AssertionError(
                f"{leg}: ledger not MECE — buckets sum {total:.3f}s vs "
                f"wall {wall:.3f}s (> 2%)")
        if float(doc["overattributed_s"]) > over_tol_frac * wall:
            raise AssertionError(
                f"{leg}: {doc['overattributed_s']}s over-attributed "
                f"(double-counted regions) against {wall:.3f}s wall")

    def _zero_downtime(doc: dict, leg: str) -> None:
        for b in ("restart_downtime", "resize_downtime"):
            if float(doc["buckets"][b]) != 0.0:
                raise AssertionError(
                    f"{leg}: A/A run shows nonzero {b} "
                    f"({doc['buckets'][b]}s)")

    # -- leg 1: training attribution (compile/checkpoint/data_wait) ----
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 784)).astype(np.float32)
    y = rng.integers(0, 10, (2048,)).astype(np.int32)
    spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                     optimizer="sgd", optimizer_params={"lr": 1e-2},
                     input_shape=(784,))
    tele0 = Telemetry(run_id="bench_goodput_r0")
    ledger0 = _goodput.GoodputLedger(telemetry=tele0, rank=0)
    with ledger0.activate():
        train_distributed_streaming(
            spec, (x, y), chunk_rows=512, epochs=2, mini_batch=64,
            checkpoint_dir=os.path.join(workdir, "ckpt"),
            checkpoint_every=8, telemetry=tele0,
        )
    doc0 = tele0.get_section(_goodput.SECTION)
    _mece(doc0, "training leg")
    _zero_downtime(doc0, "training leg")
    for bucket, floor in (("compile", 0.01), ("checkpoint", 0.001),
                          ("data_wait", 0.0005), ("compute", 0.001)):
        if float(doc0["buckets"][bucket]) <= floor:
            raise AssertionError(
                f"training leg: {bucket} bucket empty "
                f"({doc0['buckets'][bucket]}s <= {floor}s floor) — "
                f"instrumentation lost: {doc0['buckets']}")
    if doc0["compiles"] < 2 or doc0["n_steps"] <= 0:
        raise AssertionError(
            f"training leg: compiles {doc0['compiles']} (want >= 2: "
            f"init + first chunk) / n_steps {doc0['n_steps']}")
    step_wall_s = ((float(doc0["buckets"]["compute"])
                    + float(doc0["buckets"]["exposed_comm"]))
                   / doc0["n_steps"])

    # Rank 1: a second, flops-declared ledger (a jitted matmul loop),
    # so the merged /goodput report is genuinely per-rank and carries
    # MFU.
    tele1 = Telemetry(run_id="bench_goodput_r1")
    m = 256
    mm = jax.jit(lambda a: a @ a)
    xm = np.ones((m, m), np.float32)
    ledger1 = _goodput.GoodputLedger(telemetry=tele1, rank=1,
                                     flops_per_step=2.0 * m ** 3)
    for _ in range(5):
        c0 = _goodput.jit_cache_size(mm)
        with ledger1.step_span() as sp:
            mm(xm).block_until_ready()
            c1 = _goodput.jit_cache_size(mm)
            if c0 is not None and c1 is not None and c1 > c0:
                sp.rebucket("compile")
    ledger1.set_comm_model(0.1, "estimate")
    doc1 = ledger1.close()
    _mece(doc1, "rank1 leg")

    # -- leg 2: collector merge, GET /goodput, timeline renders --------
    exp0 = GangMetricsExporter(telemetry=tele0, port=0).start()
    exp1 = GangMetricsExporter(telemetry=tele1, port=0).start()
    sink = os.path.join(workdir, "collector_sink.jsonl")
    collector = FleetCollector({0: exp0.url, 1: exp1.url},
                               poll_interval_s=0, jsonl_path=sink)
    collector.start(poll_loop=False)
    try:
        collector.poll()
        run_doc = scrape_json(f"{collector.url}/goodput")
    finally:
        collector.stop()
        exp0.stop()
        exp1.stop()
    ranks_seen = set(run_doc.get("per_rank") or {})
    if not {"0", "1"} <= ranks_seen:
        raise AssertionError(
            f"/goodput per_rank missing ranks: {sorted(ranks_seen)}")
    if not (0.0 < float(run_doc["goodput"]) <= 1.0) or \
            any("goodput" not in r for r in run_doc["per_rank"].values()):
        raise AssertionError(
            f"/goodput fractions malformed: run {run_doc.get('goodput')}")
    thief = run_doc.get("biggest_thief")
    if not thief or thief["bucket"] == "compute":
        raise AssertionError(f"/goodput biggest_thief missing: {thief}")
    if run_doc.get("mfu") is None:
        raise AssertionError("/goodput lacks mfu despite a flops-"
                             "declaring rank")
    expected_thief = max(
        ((b, s) for b, s in run_doc["buckets"].items() if b != "compute"),
        key=lambda kv: kv[1])[0]
    for args_, what in ((["--goodput", sink], "collector sink"),
                        ([ "--goodput", os.path.join(workdir,
                                                     "goodput.json")],
                         "saved /goodput doc")):
        if what == "saved /goodput doc":
            with open(args_[1], "w") as f:
                f.write(json.dumps(run_doc))
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = _timeline.main(args_)
        out_txt = buf.getvalue()
        if rc != 0 or f"biggest thief: {expected_thief}" not in out_txt:
            raise AssertionError(
                f"timeline --goodput ({what}) failed (rc={rc}) or did "
                f"not name the biggest thief {expected_thief!r}:\n"
                f"{out_txt[:800]}")

    # -- leg 3: chaos slow shard shifts exposed_comm, not compute ------
    wire_spec = ModelSpec(module=MnistMLP(), loss="cross_entropy",
                          optimizer="sgd", optimizer_params={"lr": 1e-2},
                          input_shape=(784,))
    wf = jax.jit(lambda a: (a @ a).sum())
    wx = np.ones((512, 512), np.float32)
    wf(wx).block_until_ready()  # compiled OUTSIDE any leg's ledger

    def _wire_leg(chaos_cfg) -> dict:
        leg_tele = Telemetry(run_id="bench_goodput_wire")
        fleet = ParamServerFleet(wire_spec, n_shards=2,
                                 telemetry=leg_tele).start()
        ledger = _goodput.GoodputLedger(telemetry=leg_tele, rank="wire")
        try:
            transport = ShardedTransport(fleet, telemetry=leg_tele)
            have = -1
            ctx = (inject(chaos_cfg, telemetry=leg_tele) if chaos_cfg
                   else contextlib.nullcontext())
            with ctx:
                for _ in range(n_pulls):
                    with ledger.span("exposed_comm", {"site": "pull"}):
                        snap = transport.pull(have)
                        if snap is not None:
                            have = snap[0]
                    with ledger.step_span():
                        wf(wx).block_until_ready()
            transport.close()
            return ledger.close()
        finally:
            fleet.stop()

    wire_ctrl = _wire_leg(None)
    wire_chaos = _wire_leg(ChaosConfig(
        seed=7, slow_shard_s={"1": slow_delay_s}))
    _zero_downtime(wire_ctrl, "wire control leg")
    _zero_downtime(wire_chaos, "wire chaos leg")
    injected = slow_delay_s * n_pulls
    comm_shift = (float(wire_chaos["buckets"]["exposed_comm"])
                  - float(wire_ctrl["buckets"]["exposed_comm"]))
    if comm_shift < 0.8 * injected:
        raise AssertionError(
            f"seeded slow shard did not land in exposed_comm: shift "
            f"{comm_shift:.3f}s vs {injected:.3f}s injected")
    compute_shift = (float(wire_chaos["buckets"]["compute"])
                     - float(wire_ctrl["buckets"]["compute"]))
    if compute_shift > 0.25 * injected:
        raise AssertionError(
            f"seeded slow shard leaked into compute: +"
            f"{compute_shift:.3f}s (vs {injected:.3f}s injected — the "
            f"delay must land in exposed_comm)")

    # -- leg 4: elastic downtime attribution + reconciliation ----------
    out = os.path.join(workdir, "parts")
    hb_dir = os.path.join(workdir, "hb")
    os.makedirs(out)
    work = [f"part{i:03d}" for i in range(n_parts)]

    def completed(p):
        return os.path.exists(os.path.join(out, p + ".done"))

    etele = Telemetry(run_id="bench_goodput_elastic")

    def start_fn(rank, attempt, generation, assignment):
        def workfn(ctx, _parts=tuple(assignment), _rank=rank,
                   _gen=generation, _out=out, _sleep=part_sleep_s):
            import os as _os
            import time as _t

            if _rank == 1:
                raise RuntimeError("rank1 permanently broken")
            for i, p in enumerate(_parts):
                if ctx.should_stop():
                    return
                ctx.notify_step(i)
                path = _os.path.join(_out, p + ".done")
                if _os.path.exists(path):
                    continue
                tmp = path + f".tmp{_os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(f"{_rank}:{_gen}")
                _os.replace(tmp, path)
                _t.sleep(_sleep)

        return spawn_worker(workfn, rank=rank, heartbeat_dir=hb_dir,
                            name=f"rank{rank}", telemetry=etele)

    coord = GangCoordinator(world_size=3, port=0,
                            heartbeat_timeout_ms=30_000)
    policy = FtPolicy(restart=RestartPolicy(max_restarts=2,
                                            backoff_base_s=0.05,
                                            backoff_max_s=0.2), seed=0)
    ctl = ElasticController(work, completed, policy=policy,
                            telemetry=etele, coordinator=coord,
                            min_world=1, name="bench_goodput")
    for r in range(3):
        ctl.add_rank(r, start_fn)

    def grower():
        deadline = time.perf_counter() + 120.0
        while time.perf_counter() < deadline and not ctl._stop.is_set():
            if ctl._resizes["shrink"] >= 1:
                ctl.grow(3, start_fn)
                return
            time.sleep(0.05)

    threading.Thread(target=grower, daemon=True).start()
    eledger = _goodput.GoodputLedger(telemetry=etele, rank="driver")
    try:
        with eledger.activate():
            with inject(ChaosConfig(seed=11, kill_process_at={0: 2}),
                        telemetry=etele) as inj:
                summary = ctl.run(poll_interval_s=0.05, deadline_s=240.0)
    finally:
        coord.stop()
    edoc = etele.get_section(_goodput.SECTION)
    _mece(edoc, "elastic leg")
    missing = [p for p in work if not completed(p)]
    if missing or summary["work_pending"]:
        raise AssertionError(f"elastic leg incomplete: {missing}")
    kills = [e for e in inj.events if e["site"] == "ctl.process"]
    if len(kills) != 1 or summary["resizes"] != {"shrink": 1, "grow": 1}:
        raise AssertionError(
            f"elastic leg chaos schedule wrong: kills {kills}, "
            f"resizes {summary['resizes']}")
    recovery = [
        (v["sum"], v["max"]) for k, v in
        etele.snapshot()["histograms"].items()
        if k.startswith("ft_recovery_latency_s") and v["count"]
    ]
    if not recovery:
        raise AssertionError("no ft_recovery_latency_s samples")
    recovery_sum = sum(s for s, _ in recovery)
    recovery_max = max(mx for _, mx in recovery)
    restart_bucket = float(edoc["buckets"]["restart_downtime"])
    if restart_bucket < recovery_max:
        raise AssertionError(
            f"seeded kill's measured gap {recovery_max:.3f}s not "
            f"covered by restart_downtime {restart_bucket:.3f}s")
    if abs(restart_bucket - recovery_sum) > 0.05 * recovery_sum + 0.05:
        raise AssertionError(
            f"restart_downtime {restart_bucket:.3f}s does not "
            f"reconcile with ft_recovery_latency_s sum "
            f"{recovery_sum:.3f}s (same event window)")
    resize_bucket = float(edoc["buckets"]["resize_downtime"])
    if resize_bucket <= 0 or edoc["counts"].get("resize_downtime", 0) != 2:
        raise AssertionError(
            f"shrink+grow not attributed: resize_downtime "
            f"{resize_bucket}s x{edoc['counts'].get('resize_downtime')}")

    # -- leg 5: ledger overhead vs step wall + drift gate --------------
    bare = _goodput.GoodputLedger(telemetry=None)
    reps = 5000
    t0 = time.perf_counter()
    for _ in range(reps):
        with bare.step_span():
            pass
    span_cost_s = (time.perf_counter() - t0) / reps
    span_us = span_cost_s * 1e6
    overhead_frac = span_cost_s / max(step_wall_s, 1e-9)
    if overhead_frac >= 0.01:
        raise AssertionError(
            f"ledger span overhead {span_us:.2f}us is "
            f"{100 * overhead_frac:.2f}% of the measured "
            f"{step_wall_s * 1e3:.3f}ms step wall (bound: 1%)")

    tol = float(os.environ.get("SPARKTORCH_TPU_GOODPUT_DRIFT_TOL", "1.0"))
    prior = _prior_window("goodput", "ledger_span_us", k=3)
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        drift = {
            "status": "checked", "tolerance": tol,
            "prior_median_us": round(prior["median"], 3),
            "prior_n": prior["n"],
            "ratio": round(span_us / max(prior["median"], 1e-9), 3),
        }
        if span_us > prior["median"] * (1.0 + tol) + 2.0:
            raise AssertionError(
                f"ledger span cost regressed: {span_us:.2f}us vs prior "
                f"windowed median {prior['median']:.2f}us (past the "
                f"{tol} relative tolerance + 2us floor); drift: {drift}")

    return {
        "config": "goodput", "unit": "us (LedgerSpan overhead)",
        "value": round(span_us, 3),
        "ledger_span_us": round(span_us, 3),
        "overhead_pct_of_step": round(100 * overhead_frac, 4),
        "step_wall_ms": round(step_wall_s * 1e3, 3),
        "training": {
            "buckets": doc0["buckets"],
            "goodput": doc0["goodput"],
            "compiles": doc0["compiles"],
            "n_steps": doc0["n_steps"],
        },
        "run_report": {
            "goodput": run_doc["goodput"],
            "n_ranks": run_doc["n_ranks"],
            "biggest_thief": run_doc.get("biggest_thief"),
            "comm_source": run_doc.get("comm_source"),
            "mfu": run_doc.get("mfu"),
        },
        "wire": {
            "injected_s": injected,
            "exposed_comm_shift_s": round(comm_shift, 3),
            "compute_shift_s": round(compute_shift, 3),
        },
        "elastic": {
            "restart_downtime_s": round(restart_bucket, 3),
            "recovery_latency_sum_s": round(recovery_sum, 3),
            "resize_downtime_s": round(resize_bucket, 3),
            "goodput": edoc["goodput"],
            "resizes": summary["resizes"],
        },
        "goodput_drift": drift,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def _profile_hot_planted(stop_t: float) -> float:
    """The seeded hot function bench_profile plants inside a compute
    LedgerSpan: pure-Python arithmetic (no genexpr, no callees), so
    every sample of it lands as SELF time on this very frame — the
    profiler must name it or the attribution chain is broken."""
    acc = 0.0
    while time.perf_counter() < stop_t:
        for i in range(2000):
            acc += i * i
    return acc


def bench_profile(n_steps: int = 30, reps: int = 3,
                  hot_s: float = 1.2) -> dict:
    """Continuous stack-profiler gate (``make bench-profile``) — FAILS
    (raises) unless the sampler's three claims hold end to end:

    - **it is nearly free**: with the sampler running at its default
      rate, the measured training-step wall grows by < 1% vs an A/A
      profiler-off leg (min of interleaved runs, the PR 11 lesson:
      medians swing with scheduler noise), and the per-tick sample
      cost is drift-gated against the windowed median of prior rounds
      (``SPARKTORCH_TPU_PROFILE_DRIFT_TOL``);
    - **attribution is real**: a planted busy-loop inside a
      ``compute`` LedgerSpan surfaces as the top self-time frame of
      the compute bucket with >= 80% of that bucket's samples;
    - **the fleet path works**: two ranks' published sections merge
      into ``GET /profile`` over real HTTP, and
      ``timeline --profile`` renders the planted frame from both a
      saved /profile document and the collector's JSONL sink.
    """
    import contextlib
    import io
    import os
    import tempfile
    import threading

    import jax

    from sparktorch_tpu.native.gang import GangMetricsExporter
    from sparktorch_tpu.obs import FleetCollector, Telemetry
    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.obs import timeline as _timeline
    from sparktorch_tpu.obs.collector import scrape_json
    from sparktorch_tpu.obs.profile import StackProfiler, top_frames

    t_start = time.perf_counter()
    workdir = tempfile.mkdtemp(prefix="bench_profile_")

    # -- leg 1: A/A overhead (profiler off vs on, interleaved) ---------
    m = 768
    step = jax.jit(lambda a: a @ a)
    xm = np.ones((m, m), np.float32)
    step(xm).block_until_ready()  # compile outside both arms
    tick_costs_us: List[float] = []

    def _arm(profiler_on: bool) -> float:
        prof = StackProfiler() if profiler_on else None
        if prof is not None:
            prof.start()
        walls = []
        try:
            for _ in range(n_steps):
                t0 = time.perf_counter()
                step(xm).block_until_ready()
                walls.append(time.perf_counter() - t0)
        finally:
            if prof is not None:
                doc = prof.stop()
                if doc["ticks"] <= 0:
                    raise AssertionError(
                        "profiler-on arm took no sample ticks")
                tick_costs_us.append(float(doc["sample_tick_us"]))
        return min(walls)

    offs, ons = [], []
    for _ in range(reps):
        offs.append(_arm(False))
        ons.append(_arm(True))
    w_off, w_on = min(offs), min(ons)
    overhead_frac = max(w_on - w_off, 0.0) / max(w_off, 1e-9)
    if overhead_frac >= 0.01:
        raise AssertionError(
            f"sampler overhead is {100 * overhead_frac:.2f}% of the "
            f"{w_off * 1e3:.3f}ms step wall (bound: 1%; on "
            f"{w_on * 1e3:.3f}ms vs off {w_off * 1e3:.3f}ms, min of "
            f"{reps} interleaved runs)")
    sample_tick_us = min(tick_costs_us)

    # -- leg 2: planted hot function owns its bucket -------------------
    tele0 = Telemetry(run_id="bench_profile_r0")
    prof0 = StackProfiler(telemetry=tele0, rank=0, hz=250.0,
                          publish_interval_s=0.2)
    prof0.start()
    try:
        with _goodput.span("compute"):
            _profile_hot_planted(time.perf_counter() + hot_s)
    finally:
        doc0 = prof0.stop()
    buckets0 = doc0.get("buckets") or {}
    if "compute" not in buckets0:
        raise AssertionError(
            f"no compute bucket sampled: {sorted(buckets0)}")
    frames = top_frames(doc0, "compute", n=3)
    if not frames or not frames[0][0].startswith("_profile_hot_planted"):
        raise AssertionError(
            f"planted hot function is not the compute bucket's top "
            f"self-time frame: {frames}")
    bucket_samples = int(buckets0["compute"].get("samples") or 0)
    hot_share = frames[0][1] / max(bucket_samples, 1)
    if hot_share < 0.8:
        raise AssertionError(
            f"planted function holds only {100 * hot_share:.1f}% of "
            f"the compute bucket's {bucket_samples} samples "
            f"(want >= 80%)")

    # -- leg 3: 2-rank merge over HTTP + timeline renders --------------
    tele1 = Telemetry(run_id="bench_profile_r1")
    prof1 = StackProfiler(telemetry=tele1, rank=1, hz=250.0,
                          publish_interval_s=0.2)
    release = threading.Event()

    def _rank1_waits():
        with _goodput.span("data_wait", {"site": "bench"}):
            release.wait(timeout=10.0)

    waiter = threading.Thread(target=_rank1_waits, daemon=True)
    waiter.start()
    prof1.start()
    time.sleep(0.3)
    release.set()
    waiter.join(timeout=5.0)
    prof1.stop()

    exp0 = GangMetricsExporter(telemetry=tele0, port=0).start()
    exp1 = GangMetricsExporter(telemetry=tele1, port=0).start()
    sink = os.path.join(workdir, "collector_sink.jsonl")
    collector = FleetCollector({0: exp0.url, 1: exp1.url},
                               poll_interval_s=0, jsonl_path=sink)
    collector.start(poll_loop=False)
    try:
        collector.poll()
        run_doc = scrape_json(f"{collector.url}/profile")
    finally:
        collector.stop()
        exp0.stop()
        exp1.stop()
    ranks_seen = set(run_doc.get("per_rank") or {})
    if not {"0", "1"} <= ranks_seen:
        raise AssertionError(
            f"/profile per_rank missing ranks: {sorted(ranks_seen)}")
    if "data_wait" not in (run_doc.get("buckets") or {}):
        raise AssertionError(
            f"rank1's data_wait bucket lost in the merge: "
            f"{sorted(run_doc.get('buckets') or {})}")
    merged_top = top_frames(run_doc, "compute", n=1)
    if not merged_top or \
            not merged_top[0][0].startswith("_profile_hot_planted"):
        raise AssertionError(
            f"merged /profile lost the planted frame: {merged_top}")

    saved = os.path.join(workdir, "profile.json")
    with open(saved, "w") as f:
        f.write(json.dumps(run_doc))
    for path, what in ((sink, "collector sink"),
                       (saved, "saved /profile doc")):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = _timeline.main([path, "--profile"])
        out_txt = buf.getvalue()
        if rc != 0 or "_profile_hot_planted" not in out_txt:
            raise AssertionError(
                f"timeline --profile ({what}) failed (rc={rc}) or did "
                f"not name the planted frame:\n{out_txt[:800]}")

    # -- drift gate: per-tick sample cost vs prior rounds --------------
    tol = float(os.environ.get("SPARKTORCH_TPU_PROFILE_DRIFT_TOL", "1.0"))
    prior = _prior_window("profile", "sample_tick_us", k=3)
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        drift = {
            "status": "checked", "tolerance": tol,
            "prior_median_us": round(prior["median"], 3),
            "prior_n": prior["n"],
            "ratio": round(sample_tick_us / max(prior["median"], 1e-9), 3),
        }
        if sample_tick_us > prior["median"] * (1.0 + tol) + 2.0:
            raise AssertionError(
                f"sample tick cost regressed: {sample_tick_us:.2f}us "
                f"vs prior windowed median {prior['median']:.2f}us "
                f"(past the {tol} relative tolerance + 2us floor); "
                f"drift: {drift}")

    return {
        "config": "profile", "unit": "us (sample tick cost)",
        "value": round(sample_tick_us, 3),
        "sample_tick_us": round(sample_tick_us, 3),
        "overhead_pct_of_step": round(100 * overhead_frac, 4),
        "step_wall_off_ms": round(w_off * 1e3, 3),
        "step_wall_on_ms": round(w_on * 1e3, 3),
        "hz": float(doc0["hz"]),
        "hot": {
            "ticks": doc0["ticks"],
            "bucket_samples": bucket_samples,
            "hot_share": round(hot_share, 4),
            "top_frame": frames[0][0],
        },
        "run_report": {
            "n_ranks": run_doc["n_ranks"],
            "samples_total": run_doc["samples_total"],
            "buckets": sorted(run_doc["buckets"]),
            "truncated": run_doc["truncated"],
        },
        "profile_drift": drift,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def _health_replay_builder(n_features: int = 10, rows: int = 256) -> dict:
    """Replay builder for ``bench_health`` bundles (the
    ``module:function`` spec stamped into each bundle's meta):
    reconstruct the EXACT jitted step the drill leg trained with —
    same ModelSpec, same mesh, same optimizer — plus state/batch
    pytree TEMPLATES (treedefs and dtypes only; the recorded leaf
    values come from the bundle's npz). The live drill pins
    ``steps_per_call=1``/``mini_batch=None`` so both processes compile
    the same single-step XLA program, which is what makes the bitwise
    comparison meaningful."""
    import jax
    import jax.numpy as jnp

    from sparktorch_tpu.models import Net
    from sparktorch_tpu.parallel.mesh import build_mesh
    from sparktorch_tpu.train.step import create_train_state, make_train_step
    from sparktorch_tpu.utils.data import DataBatch
    from sparktorch_tpu.utils.serde import ModelSpec

    spec = ModelSpec(module=Net(), loss="mse", optimizer="adam",
                     optimizer_params={"lr": 1e-2},
                     input_shape=(n_features,))
    mesh = build_mesh()
    tx = spec.make_optimizer()
    state = create_train_state(
        spec, jax.random.key(0),
        sample_x=jnp.zeros((1, n_features), jnp.float32), tx=tx)
    step_fn = make_train_step(spec.make_module().apply, spec.loss_fn(),
                              tx, mesh)
    batch = DataBatch(
        x=jnp.zeros((rows, n_features), jnp.float32),
        y=jnp.zeros((rows,), jnp.float32),
        w=jnp.ones((rows,), jnp.float32))
    return {"step_fn": step_fn, "state": state, "batch": batch}


def bench_health(poison_step: int = 6, iters: int = 12,
                 aa_steps: int = 20, aa_reps: int = 3) -> dict:
    """Model-health observability gate (``make bench-health``) — FAILS
    (raises) unless the health lane's four claims hold end to end:

    - **detection is real and bounded**: a seeded poison batch
      (``ChaosConfig.poison_batch_at``) on a real ``train_distributed``
      run trips the NaN sentinel AT the poisoned step, within 2 steps
      of the delayed fetch (``detect_lag - fetch_lag <= 2``), with the
      per-leaf grad-norm table carrying dotted param names; the
      latched ``health_nonfinite`` alert fires exactly ONE episode
      across repeated sweeps;
    - **replay is bitwise**: the bundle the sentinel wrote reproduces
      the recorded bad numerics in a FRESH process
      (``python -m sparktorch_tpu.obs.replay`` exits 0, float32 bit
      patterns equal — the only comparison two NaNs can pass);
    - **the lane is attributed and nearly free**: an interleaved A/A
      pair shows the health-on arm's goodput ledger with
      ``data_wait`` > 0 (the delayed fetch lands in
      ``data_wait{site=health}``) while the health-off arm's is
      EXACTLY 0.0, step wall grows < 1% (min of interleaved runs),
      and a clean run raises ZERO anomalies and ZERO alert episodes;
    - **the fleet path works**: the drill rank's section merges into
      ``GET /health`` rank-tagged (never averaged), renders via
      ``timeline --health`` from both the collector sink and a saved
      document, surfaces in ``--follow`` as a ``health.run``
      one-liner, and the postmortem bundle answers "health at death".

    ``note_step`` cost is the drift-gated value
    (``SPARKTORCH_TPU_HEALTH_DRIFT_TOL`` vs the windowed median of
    prior rounds).
    """
    import contextlib
    import io
    import os
    import subprocess
    import sys
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp

    from sparktorch_tpu.ft import ChaosConfig, inject
    from sparktorch_tpu.models import Net
    from sparktorch_tpu.native.gang import GangMetricsExporter
    from sparktorch_tpu.obs import FleetCollector, Telemetry
    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.obs import health as _health
    from sparktorch_tpu.obs import timeline as _timeline
    from sparktorch_tpu.obs.alerts import AlertManager
    from sparktorch_tpu.obs.blackbox import collect_postmortem
    from sparktorch_tpu.obs.collector import scrape_json
    from sparktorch_tpu.obs.history import MetricsHistory
    from sparktorch_tpu.obs.telemetry import wall_ts as _wall_ts
    from sparktorch_tpu.train.sync import train_distributed
    from sparktorch_tpu.utils.serde import ModelSpec

    t_start = time.perf_counter()
    workdir = tempfile.mkdtemp(prefix="bench_health_")
    replay_dir = os.path.join(workdir, "replay")

    # -- leg 1: A/A overhead + attribution delta (clean workload) ------
    # Runs FIRST, in a quiet process (same discipline as
    # bench_profile's A/A): the drill leg's jit/teardown residue
    # would pollute the timing floor. The ledger's per-step cost is
    # FIXED (queue + one delayed scalar fetch, ~tens of us), so quote
    # it against a training-representative step wall: a chained-matmul
    # step (~25ms on this rig's CPU floor) whose timing floor is
    # stable enough for a 1% bound — a single small matmul is both too
    # short (the fixed cost alone busts 1%) and too noisy.
    m = 768

    def _aa_fn(a):
        b = a
        for _ in range(4):
            b = (b @ a) * (1.0 / m)
        return b, (jnp.sum(b) / b.size).astype(jnp.float32)

    aa_step = jax.jit(_aa_fn)
    xm = np.ones((m, m), np.float32)
    out, _ = aa_step(xm)
    out.block_until_ready()  # compile outside both arms

    def _aa_arm(health_on: bool):
        tele_a = Telemetry(
            run_id=f"bench_health_aa_{'on' if health_on else 'off'}")
        led = _goodput.GoodputLedger(telemetry=tele_a, rank="aa")
        hl_a = (_health.TrainHealthLedger(rank="aa", telemetry=tele_a)
                if health_on else None)
        walls, notes = [], []
        with led.activate():
            for _ in range(aa_steps):
                t0 = time.perf_counter()
                o, dev = aa_step(xm)
                o.block_until_ready()
                if hl_a is not None:
                    t1 = time.perf_counter()
                    hl_a.note_step(device={"loss": dev})
                    notes.append(time.perf_counter() - t1)
                walls.append(time.perf_counter() - t0)
            if hl_a is not None:
                hl_a.flush()
        gdoc_a = tele_a.get_section(_goodput.SECTION)
        dw = float(gdoc_a["buckets"]["data_wait"])
        n_anom = (len(hl_a.snapshot()["anomalies"])
                  if hl_a is not None else 0)
        return min(walls), dw, n_anom, notes, tele_a

    gc.collect()
    offs, ons, dw_on, note_walls = [], [], [], []
    tele_clean = None
    for _ in range(aa_reps):
        w, dw, _n, _notes, _t = _aa_arm(False)
        offs.append(w)
        if dw != 0.0:
            raise AssertionError(
                f"health-OFF arm shows data_wait {dw}s — the A/A delta "
                f"is meaningless")
        w, dw, n_anom, notes, tele_clean = _aa_arm(True)
        ons.append(w)
        dw_on.append(dw)
        note_walls += notes
        if n_anom:
            raise AssertionError(
                f"clean health-ON arm raised {n_anom} anomalies — "
                f"false positives")
    if min(dw_on) <= 0.0:
        raise AssertionError(
            f"health-ON arms left data_wait empty ({dw_on}) — the "
            f"delayed fetch is not being attributed")
    # Two witnesses for the 1% bound, either passes: (a) the wall
    # delta of the interleaved A/A pair (min of reps per arm) — the
    # end-to-end statement, but this rig's floor breathes several
    # percent between IDENTICAL arms (a bare even/odd A/A with no
    # ledger shows 1-6% gaps), so on a noisy round it over-reads; (b)
    # the direct witness from the same ON-arm samples: the ledger's
    # entire synchronous footprint is the note_step call (queue + the
    # drained delayed fetch), so its floor against the step-wall floor
    # bounds the true per-step cost without differencing two noisy
    # walls. Fail only when BOTH read over 1%.
    w_off, w_on = min(offs), min(ons)
    aa_frac = max(w_on - w_off, 0.0) / max(w_off, 1e-9)
    note_frac = min(note_walls) / max(w_off, 1e-9)
    overhead_frac = min(aa_frac, note_frac)
    if overhead_frac >= 0.01:
        raise AssertionError(
            f"health lane overhead is over 1% of the "
            f"{w_off * 1e3:.3f}ms step wall by BOTH witnesses: A/A "
            f"wall delta {100 * aa_frac:.2f}% (on {w_on * 1e3:.3f}ms "
            f"vs off {w_off * 1e3:.3f}ms, min of {aa_reps} interleaved "
            f"runs) and direct note_step floor {100 * note_frac:.2f}% "
            f"({min(note_walls) * 1e6:.1f}us)")
    # Zero false positives also at the alert tier: a clean bus sweeps
    # without a single episode.
    clean_hist = MetricsHistory(retention=4)
    clean_mgr = AlertManager(clean_hist, rules=_health.health_alert_rules(),
                             telemetry=tele_clean)
    clean_fired = []
    base_aa = _wall_ts()
    for k in range(2):
        clean_hist.append(tele_clean.snapshot(), ts=base_aa + k)
        clean_fired += [e for e in clean_mgr.evaluate(ts=base_aa + k)
                        if e["event"] == "fired"]
    if clean_fired:
        raise AssertionError(
            f"clean leg fired alerts: "
            f"{[e['alert'] for e in clean_fired]}")

    # -- leg 2: seeded poison drill on a real trainer ------------------
    rng = np.random.default_rng(0)
    n_features, rows = 10, 256
    x = rng.normal(size=(rows, n_features)).astype(np.float32)
    y = rng.normal(size=(rows,)).astype(np.float32)
    spec = ModelSpec(module=Net(), loss="mse", optimizer="adam",
                     optimizer_params={"lr": 1e-2},
                     input_shape=(n_features,))
    tele = Telemetry(run_id="bench_health_drill")
    cfg = _health.HealthConfig(
        warmup_steps=3, replay_dir=replay_dir,
        replay_builder="sparktorch_tpu.bench:_health_replay_builder",
        replay_builder_kwargs={"n_features": n_features, "rows": rows})
    prev_hl = _health.install(None)
    try:
        hl = _health.ensure(tele, rank=0, config=cfg)
        if hl is None:
            raise AssertionError(
                "health lane disabled (SPARKTORCH_TPU_HEALTH=0) — the "
                "gate cannot run")
        ledger = _goodput.GoodputLedger(telemetry=tele, rank=0)
        with ledger.activate(), \
                inject(ChaosConfig(poison_batch_at={0: poison_step}),
                       telemetry=tele):
            train_distributed(spec, x, labels=y, iters=iters, seed=0,
                              steps_per_call=1, telemetry=tele)
        doc = hl.snapshot()
    finally:
        _health.install(prev_hl)

    anomalies = doc["anomalies"]
    if not anomalies:
        raise AssertionError(
            f"poisoned step {poison_step} raised no anomaly: {doc}")
    first = anomalies[0]
    if first["akind"] != "nonfinite" or first["step"] != poison_step:
        raise AssertionError(
            f"first anomaly is {first['akind']} @ step {first['step']}, "
            f"want nonfinite @ {poison_step}: {anomalies[:3]}")
    lag_past_fetch = first["detect_lag"] - cfg.fetch_lag
    if not (0 <= lag_past_fetch <= 2):
        raise AssertionError(
            f"detection lag {first['detect_lag']} steps vs fetch_lag "
            f"{cfg.fetch_lag}: the sentinel must trip within 2 steps "
            f"of the delayed fetch")
    leaves = doc.get("top_grad_leaves") or []
    if not leaves or not any("." in str(k) for k, _ in leaves):
        raise AssertionError(
            f"top grad leaves lack dotted param names: {leaves}")

    # The drill's own readbacks must be attributed: data_wait carries
    # the health fetch (site=health) and the ledger stays MECE.
    gdoc = tele.get_section(_goodput.SECTION)
    if float(gdoc["buckets"]["data_wait"]) <= 0.0:
        raise AssertionError(
            f"health fetches left data_wait empty: {gdoc['buckets']}")
    g_wall = float(gdoc["wall_s"])
    g_total = sum(float(v) for v in gdoc["buckets"].values())
    if abs(g_total - g_wall) > 0.02 * g_wall or \
            float(gdoc["overattributed_s"]) > 0.02 * g_wall:
        raise AssertionError(
            f"drill ledger not MECE: buckets sum {g_total:.3f}s vs "
            f"wall {g_wall:.3f}s, overattributed "
            f"{gdoc['overattributed_s']}s")

    # -- leg 3: the bundle replays BITWISE in a fresh process ----------
    bundles = (doc.get("replay") or {}).get("bundles") or []
    target = f"replay_step{poison_step:06d}_r0.json"
    meta_path = next((b for b in bundles
                      if os.path.basename(b) == target), None)
    if meta_path is None:
        raise AssertionError(
            f"no bundle for the poisoned step {poison_step}: {bundles}")
    with open(meta_path) as f:
        meta = json.load(f)
    if meta["anchor_step"] != poison_step:
        raise AssertionError(
            f"anchor did not re-arm on the poisoned batch: anchor "
            f"{meta['anchor_step']} vs step {poison_step} (replay "
            f"would span {meta['step'] - meta['anchor_step'] + 1} steps)")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "sparktorch_tpu.obs.replay", meta_path],
        capture_output=True, text=True, env=env, timeout=600)
    if proc.returncode != 0 or "bitwise reproduction" not in proc.stdout:
        raise AssertionError(
            f"replay did not reproduce bitwise (rc={proc.returncode}):\n"
            f"{proc.stdout}\n{proc.stderr}")

    # -- leg 4: latched alert, exactly one episode ---------------------
    history = MetricsHistory(retention=8)
    mgr = AlertManager(history, rules=_health.health_alert_rules(),
                       telemetry=tele)
    base = _wall_ts()
    fired = []
    for k in range(3):
        history.append(tele.snapshot(), ts=base + k)
        fired += [e for e in mgr.evaluate(ts=base + k)
                  if e["event"] == "fired"]
    if [e["alert"] for e in fired] != ["health_nonfinite"]:
        raise AssertionError(
            f"want exactly one latched health_nonfinite episode over 3 "
            f"sweeps, got {[(e['alert'], e['episode']) for e in fired]}")

    # -- leg 5: collector merge, GET /health, timeline renders ---------
    exp = GangMetricsExporter(telemetry=tele, port=0).start()
    sink = os.path.join(workdir, "collector_sink.jsonl")
    collector = FleetCollector({0: exp.url}, poll_interval_s=0,
                               jsonl_path=sink)
    collector.start(poll_loop=False)
    try:
        collector.poll()
        run_doc = scrape_json(f"{collector.url}/health")
        pm_path = collect_postmortem(workdir, "bench-health drill",
                                     telemetry=tele, collector=collector)
    finally:
        collector.stop()
        exp.stop()
    if run_doc.get("kind") != "health_run" or \
            "0" not in (run_doc.get("per_rank") or {}):
        raise AssertionError(
            f"/health missing the drill rank: "
            f"{sorted(run_doc.get('per_rank') or {})}")
    worst = run_doc.get("worst") or {}
    if worst.get("akind") != "nonfinite" or worst.get("rank") != "0":
        raise AssertionError(
            f"/health worst anomaly is not the rank-tagged NaN: {worst}")

    saved = os.path.join(workdir, "health.json")
    with open(saved, "w") as f:
        f.write(json.dumps(run_doc))
    for args_, what in ((["--health", sink], "collector sink"),
                        (["--health", saved], "saved /health doc")):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = _timeline.main(args_)
        out_txt = buf.getvalue()
        if rc != 0 or "model health" not in out_txt \
                or "nonfinite" not in out_txt:
            raise AssertionError(
                f"timeline --health ({what}) failed (rc={rc}) or lost "
                f"the anomaly:\n{out_txt[:800]}")

    stop_ev = threading.Event()
    stop_ev.set()
    follow_lines = list(_timeline.follow(sink, poll_s=0.0, stop=stop_ev))
    if not any("health.run" in ln and "worst=nonfinite" in ln
               for ln in follow_lines):
        raise AssertionError(
            f"--follow tail lacks the health.run one-liner:\n"
            + "\n".join(follow_lines[:10]))

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = _timeline.main(["--postmortem", pm_path])
    out_txt = buf.getvalue()
    if rc != 0 or "model health at death" not in out_txt \
            or "nonfinite" not in out_txt:
        raise AssertionError(
            f"postmortem lost the health-at-death view (rc={rc}):\n"
            f"{out_txt[:800]}")

    # -- note_step microbench (the drift-gated value) ------------------
    hl_ub = _health.TrainHealthLedger(
        rank="ub", telemetry=Telemetry(run_id="bench_health_ub"))
    n_ub = 2000
    t0 = time.perf_counter()
    for i in range(n_ub):
        hl_ub.note_step(host={"loss": 1.0 + 1e-4 * i, "grad_norm": 0.5})
    note_step_us = (time.perf_counter() - t0) / n_ub * 1e6

    tol = float(os.environ.get("SPARKTORCH_TPU_HEALTH_DRIFT_TOL", "0.5"))
    prior = _prior_window("health", "note_step_us", k=3)
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        drift = {"status": "ok", "tolerance": tol, "prior": prior,
                 "value": round(note_step_us, 3)}
        if note_step_us > prior["median"] * (1.0 + tol) + 2.0:
            drift["status"] = "regressed"
            raise AssertionError(
                f"note_step cost regressed: {note_step_us:.2f}us vs "
                f"prior windowed median {prior['median']:.2f}us (past "
                f"the {tol} relative tolerance + 2us floor); "
                f"drift: {drift}")

    return {
        "config": "health", "unit": "us (note_step cost)",
        "value": round(note_step_us, 3),
        "note_step_us": round(note_step_us, 3),
        "overhead_pct_of_step": round(100 * overhead_frac, 4),
        "overhead_pct_aa_wall": round(100 * aa_frac, 4),
        "overhead_pct_note_floor": round(100 * note_frac, 4),
        "step_wall_off_ms": round(w_off * 1e3, 3),
        "step_wall_on_ms": round(w_on * 1e3, 3),
        "detect": {
            "step": poison_step, "akind": first["akind"],
            "detect_lag": first["detect_lag"],
            "fetch_lag": cfg.fetch_lag,
            "anomalies_total": sum(doc["counts"].values()),
        },
        "replay": {
            "bundle": os.path.basename(meta_path),
            "anchor_step": meta["anchor_step"],
            "bitwise": True,
        },
        "aa": {
            "data_wait_on_s": round(min(dw_on), 6),
            "data_wait_off_s": 0.0,
            "clean_anomalies": 0,
        },
        "alerts": {"episodes": 1, "clean_episodes": 0},
        "health_drift": drift,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def bench_skew(steps: int = 8, delay_s: float = 0.3,
               from_step: int = 2, stamp_iters: int = 4000) -> dict:
    """Cross-rank step-skew gate (``make bench-skew``) — FAILS (raises)
    unless the skew lane's claims hold end to end:

    - **decomposition is real and lands on the right rank**: a seeded
      ``delay_s``/step straggler on rank 1 (``ChaosConfig.slow_rank_s``,
      fired inside the step loop BEFORE the collective fence) shows up
      in the merged ``GET /skew`` document with >=80% of the injected
      seconds in ``straggler_wait_s``, charged to rank 1 in
      ``wait_by_laggard``, straggler wait dominating wire, and the
      persistent-laggard verdict naming rank 1 with a cause hypothesis;
    - **the alert reaches the controller**: the sustained
      ``skew_straggler_sustained`` rule latches exactly ONE episode
      across repeated collector sweeps, and the firing arrives at an
      ``ElasticController`` as a ``ctl.scale_signal``;
    - **the A/A leg stays quiet**: the identical fence workload with no
      chaos decomposes to ~0 straggler wait with ZERO alert episodes —
      a healthy fleet never pages;
    - **stamping is nearly free**: the per-step boundary stamp (the
      only new work this lane adds to the hot step path — one bounded
      ring append at ``step_span`` exit) costs <1% of a
      training-representative step wall;
    - **the render path works**: ``timeline --skew`` renders the
      verdict from both the collector sink JSONL and a saved ``/skew``
      document, and ``--follow`` emits the ``skew.run`` one-liner
      naming the laggard.

    The stamp cost is the drift-gated value
    (``SPARKTORCH_TPU_SKEW_DRIFT_TOL`` vs the windowed median of prior
    rounds).
    """
    import contextlib
    import io
    import os
    import tempfile
    import threading

    import jax

    from sparktorch_tpu.ctl.elastic import ElasticController
    from sparktorch_tpu.ft import ChaosConfig, inject
    from sparktorch_tpu.ft import chaos as _chaos
    from sparktorch_tpu.native.gang import GangMetricsExporter
    from sparktorch_tpu.obs import FleetCollector, Telemetry
    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.obs import skew as _skew
    from sparktorch_tpu.obs import timeline as _timeline
    from sparktorch_tpu.obs.collector import scrape_json

    t_start = time.perf_counter()
    workdir = tempfile.mkdtemp(prefix="bench_skew_")
    injected_total = delay_s * (steps - from_step)

    def _fleet_leg(tag: str, chaos_cfg):
        """One 2-rank fence workload scraped through a collector with
        the skew rules armed and an ElasticController subscribed:
        returns (run_doc, latched episodes, scale signals, sink path).

        The rank threads stamp the exact shape the trainers do — chaos
        fires BEFORE the step span (a real straggler is late INTO the
        fence), the fence wait rides a nested exposed_comm span inside
        ``step_span`` (so the victim's wait is in the merged
        exposed_comm budget the decomposition splits)."""
        teles = [Telemetry(run_id=f"bench_skew_{tag}") for _ in range(2)]
        leds = [_goodput.GoodputLedger(telemetry=teles[r], rank=r)
                for r in range(2)]
        barrier = threading.Barrier(2)
        errs: list = []

        def rank_fn(r):
            try:
                led = leds[r]
                for i in range(steps):
                    _chaos.straggle(r, i)
                    with led.step_span(step=i):
                        with led.span("exposed_comm"):
                            barrier.wait()
                led.close()
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [threading.Thread(target=rank_fn, args=(r,))
                   for r in range(2)]
        cm = (inject(chaos_cfg, telemetry=teles[0]) if chaos_cfg
              else contextlib.nullcontext())
        with cm:
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        if errs:
            raise AssertionError(f"{tag} rank thread died: {errs[0]!r}")

        exps = [GangMetricsExporter(telemetry=teles[r], port=0).start()
                for r in range(2)]
        sink = os.path.join(workdir, f"sink_{tag}.jsonl")
        collector = FleetCollector(
            {r: exps[r].url for r in range(2)}, poll_interval_s=0,
            jsonl_path=sink, alert_rules=_skew.skew_alert_rules())
        ctl = ElasticController([], lambda w: True,
                                telemetry=collector.telemetry,
                                alerts=collector.alerts)
        collector.start(poll_loop=False)
        try:
            # The sustained rule wants for_sweeps consecutive breaches;
            # one extra sweep proves the latch holds at ONE episode.
            for _ in range(4):
                collector.poll()
            run_doc = scrape_json(f"{collector.url}/skew")
        finally:
            collector.stop()
            for e in exps:
                e.stop()
            ctl.detach_alerts()
        state = collector.alerts.doc()["rules"]["skew_straggler_sustained"]
        return run_doc, int(state["episodes"]), list(ctl.scale_signals), sink

    # -- leg 1: A/A — identical fence, no chaos, must stay quiet -------
    aa_run, aa_eps, aa_signals, _aa_sink = _fleet_leg("aa", None)
    aa_wait = float(aa_run.get("straggler_wait_s") or 0.0)
    if aa_wait > 0.1 * injected_total:
        raise AssertionError(
            f"A/A leg shows {aa_wait:.3f}s straggler wait (injected "
            f"nothing; bound {0.1 * injected_total:.3f}s) — the "
            f"decomposition charges healthy fence jitter as straggling")
    if aa_eps or aa_signals:
        raise AssertionError(
            f"A/A leg paged: {aa_eps} alert episode(s), "
            f"{len(aa_signals)} scale signal(s) — false positives")

    # -- leg 2: seeded straggler on rank 1 -----------------------------
    chaos_run, chaos_eps, chaos_signals, chaos_sink = _fleet_leg(
        "chaos", ChaosConfig(slow_rank_s={1: (from_step, delay_s)}))
    wait = float(chaos_run.get("straggler_wait_s") or 0.0)
    if wait < 0.8 * injected_total:
        raise AssertionError(
            f"injected {injected_total:.2f}s of straggling but only "
            f"{wait:.3f}s landed in straggler_wait_s (<80%) — the "
            f"decomposition is leaking the wait into wire time")
    wire = chaos_run.get("wire_s")
    if wire is None or wait <= float(wire):
        raise AssertionError(
            f"straggler wait {wait:.3f}s does not dominate wire "
            f"{wire} — exposed_comm was not split")
    to_r1 = float((chaos_run.get("wait_by_laggard") or {}).get("1") or 0.0)
    if to_r1 < 0.8 * injected_total:
        raise AssertionError(
            f"only {to_r1:.3f}s of the {injected_total:.2f}s injected "
            f"wait is charged to rank 1: "
            f"{chaos_run.get('wait_by_laggard')}")
    lag = chaos_run.get("laggard") or {}
    if lag.get("rank") != "1" or not lag.get("persistent") \
            or not lag.get("cause"):
        raise AssertionError(
            f"verdict did not name rank 1 as a persistent straggler "
            f"with a cause hypothesis: {lag}")

    # -- leg 3: latched alert -> controller scale signal ---------------
    if chaos_eps != 1:
        raise AssertionError(
            f"want exactly one latched skew_straggler_sustained "
            f"episode over 4 sweeps, got {chaos_eps}")
    if not any(s.get("rule") == "skew_straggler_sustained"
               for s in chaos_signals):
        raise AssertionError(
            f"the latched firing never reached the ElasticController "
            f"as a ctl.scale_signal: {chaos_signals}")

    # -- leg 4: timeline renders from sink + saved doc, follow line ----
    saved = os.path.join(workdir, "skew.json")
    with open(saved, "w") as f:
        f.write(json.dumps(chaos_run))
    for args_, what in ((["--skew", chaos_sink], "collector sink"),
                        (["--skew", saved], "saved /skew doc")):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = _timeline.main(args_)
        out_txt = buf.getvalue()
        if rc != 0 or "step skew" not in out_txt \
                or "persistent straggler" not in out_txt:
            raise AssertionError(
                f"timeline --skew ({what}) failed (rc={rc}) or lost "
                f"the verdict:\n{out_txt[:800]}")
    stop_ev = threading.Event()
    stop_ev.set()
    follow_lines = list(_timeline.follow(chaos_sink, poll_s=0.0,
                                         stop=stop_ev))
    if not any("skew.run" in ln and "laggard=rank 1" in ln
               for ln in follow_lines):
        raise AssertionError(
            f"--follow tail lacks the skew.run one-liner:\n"
            + "\n".join(follow_lines[:10]))

    # -- stamp microbench (the drift-gated value) ----------------------
    # The ONLY work this lane adds to the hot step path: one bounded
    # ring append at step_span exit (the enter/exit perf_counter reads
    # already existed for the goodput bucket). Quote it against a
    # training-representative step wall, same discipline as
    # bench_health: the fence microbench above is all-wait, so its
    # wall is not a denominator any trainer would recognize.
    led_ub = _goodput.GoodputLedger(
        telemetry=Telemetry(run_id="bench_skew_ub"), rank="ub")
    t0 = time.perf_counter()
    for i in range(stamp_iters):
        led_ub.skew.record(i, 1, 0.0, 1.0)
    stamp_us = (time.perf_counter() - t0) / stamp_iters * 1e6

    m = 768
    rep = jax.jit(lambda a: (a @ a) @ (a @ a) * (1.0 / m))
    xm = np.ones((m, m), np.float32)
    rep(xm).block_until_ready()  # compile outside the measurement
    rep_walls = []
    for _ in range(5):
        t0 = time.perf_counter()
        rep(xm).block_until_ready()
        rep_walls.append(time.perf_counter() - t0)
    step_wall = min(rep_walls)
    stamp_frac = (stamp_us * 1e-6) / max(step_wall, 1e-9)
    if stamp_frac >= 0.01:
        raise AssertionError(
            f"step stamp costs {stamp_us:.2f}us — "
            f"{100 * stamp_frac:.3f}% of the {step_wall * 1e3:.3f}ms "
            f"representative step wall (>=1%)")

    tol = float(os.environ.get("SPARKTORCH_TPU_SKEW_DRIFT_TOL", "0.5"))
    prior = _prior_window("skew", "stamp_us", k=3)
    if prior is None:
        drift = {"status": "no_prior_record", "tolerance": tol}
    else:
        drift = {"status": "ok", "tolerance": tol, "prior": prior,
                 "value": round(stamp_us, 3)}
        if stamp_us > prior["median"] * (1.0 + tol) + 2.0:
            drift["status"] = "regressed"
            raise AssertionError(
                f"step stamp cost regressed: {stamp_us:.2f}us vs prior "
                f"windowed median {prior['median']:.2f}us (past the "
                f"{tol} relative tolerance + 2us floor); drift: {drift}")

    return {
        "config": "skew", "unit": "us (step stamp cost)",
        "value": round(stamp_us, 3),
        "stamp_us": round(stamp_us, 3),
        "stamp_pct_of_step": round(100 * stamp_frac, 4),
        "step_wall_ms": round(step_wall * 1e3, 3),
        "decomposition": {
            "injected_s": round(injected_total, 3),
            "straggler_wait_s": round(wait, 3),
            "wire_s": round(float(wire), 3),
            "straggler_fraction": chaos_run.get("straggler_fraction"),
            "attributed_to_rank1_s": round(to_r1, 3),
            "laggard": {"rank": lag.get("rank"),
                        "persistent": bool(lag.get("persistent")),
                        "cause": lag.get("cause")},
        },
        "aa": {"straggler_wait_s": round(aa_wait, 6), "episodes": 0,
               "scale_signals": 0},
        "alerts": {"episodes": 1, "scale_signals": len(chaos_signals)},
        "skew_drift": drift,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def _bert_flops_accounting(module, batch: int, seq: int) -> dict:
    """Honest model-FLOPs accounting for the BERT classifier.

    The round-4 record applied 6·N_total·T, which counts the 23.4M-param
    token-embedding table (and pos-embed) as if every token did a matmul
    against it — but an embedding lookup is a gather, and its backward a
    scatter-add: zero MXU FLOPs. Honest accounting (the standard
    PaLM-appendix / scaling-book decomposition):

      fwd  = 2·N_tok·T  +  4·L·b·s²·d  +  2·N_head·b
      step = 3·fwd                       (backward ≈ 2× forward)

    where N_tok = params applied per token (encoder layers + final LN),
    N_head = params applied per EXAMPLE (pooler + classifier — the 6N·T
    rule overcounts these by s×), and 4·L·b·s²·d is the QKᵀ + AV score
    math the 6N rule misses entirely. The legacy 6N-total number is kept
    alongside for round-over-round comparability."""
    import jax

    params = module.init(jax.random.key(0),
                         np.zeros((1, seq), np.int32))["params"]

    def _count(tree) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))

    n_total = _count(params)
    backbone = params["backbone"]
    n_emb = (_count(backbone["tok_embed"])
             + int(np.prod(backbone["pos_embed"].shape)))
    n_head = _count(params["pooler"]) + _count(params["classifier"])
    n_tok = n_total - n_emb - n_head

    cfg = module.config
    tokens = batch * seq
    attn_fwd = 4 * cfg.n_layers * batch * seq * seq * cfg.d_model
    fwd = 2 * n_tok * tokens + attn_fwd + 2 * n_head * batch
    return {
        "n_params": n_total,
        "n_params_embedding": n_emb,
        "n_params_per_token": n_tok,
        "n_params_per_example_head": n_head,
        "model_flops_per_step": 3 * fwd,
        "legacy_6n_total_flops_per_step": 6 * n_total * tokens,
        "flops_methodology": (
            "3*(2*N_tok*T + 4*L*b*s^2*d + 2*N_head*b): matmul params per "
            "token (embedding gather/scatter and per-example head "
            "excluded from the per-token term) + attention QK^T/AV score "
            "FLOPs; bwd=2x fwd. Cross-checked against XLA "
            "compiled.cost_analysis() flops of the same program."
        ),
    }


def bench_bert_dp() -> dict:
    """BASELINE config 4: BERT-base-shape encoder fine-tune step,
    sync DP — the compute-bound all-reduce stress config. MFU is
    reported with HONEST model-FLOPs (``_bert_flops_accounting``) and
    cross-checked against XLA's own ``cost_analysis`` of the measured
    program; the round-≤4 6N·N_total number rides along as
    ``achieved_tflops_6n_total_legacy``."""
    from sparktorch_tpu.models.transformer import bert_base
    from sparktorch_tpu.utils.serde import ModelSpec

    batch, seq = 128, 128  # batch swept 32/64/128: MXU util peaks here
    rng = np.random.default_rng(0)
    x = rng.integers(0, 30522, (batch, seq)).astype(np.int32)
    y = rng.integers(0, 2, (batch,)).astype(np.int32)
    module = bert_base()
    spec = ModelSpec(module=module, loss="cross_entropy", optimizer="adam",
                     optimizer_params={"lr": 2e-5}, input_shape=(seq,))
    out = _sync_epoch_bench(spec, x, y, batch, iters=10, warmup=2, chunks=3,
                            with_cost_analysis=True)

    acct = _bert_flops_accounting(module, batch, seq)
    steps_per_sec = out["examples_per_sec_per_chip"] * out["n_chips"] / batch
    step_s = 1.0 / max(steps_per_sec, 1e-12)

    def _tflops(flops_per_step: float) -> float:
        return flops_per_step * steps_per_sec / out["n_chips"] / 1e12

    honest = _tflops(acct["model_flops_per_step"])
    rec = {
        "config": "bert_dp", "unit": "examples/sec/chip",
        "n_params": acct["n_params"],
        "n_params_embedding": acct["n_params_embedding"],
        "n_params_per_token": acct["n_params_per_token"],
        "achieved_tflops_per_chip": round(honest, 2),
        "mfu_honest": round(_mfu_honest(honest), 4),
        "achieved_tflops_6n_total_legacy": round(
            _tflops(acct["legacy_6n_total_flops_per_step"]), 2
        ),
        "flops_methodology": acct["flops_methodology"],
        **out,
    }
    # Roofline cross-check from the compiler's own cost model: the
    # minimum step time this program could take on v5e is
    # max(flops/peak_flops, bytes/peak_bw); how close the measured step
    # comes to that bound says whether the gap to peak is the PROGRAM
    # (non-matmul ops, bandwidth) or the EXECUTION (stalls, overhead).
    if out.get("xla_flops_per_step"):
        # cost_analysis flops are PER-DEVICE (see _xla_cost_per_step),
        # so the achieved rate needs no n_chips division.
        xla_flops = out["xla_flops_per_step"]
        rec["xla_tflops_per_chip"] = round(xla_flops / step_s / 1e12, 2)
        t_flops = xla_flops / (V5E_BF16_PEAK_TFLOPS * 1e12)
        t_bytes = ((out["xla_bytes_per_step"] or 0)
                   / (V5E_HBM_GB_PER_S * 1e9))
        rec["roofline_min_step_s"] = round(max(t_flops, t_bytes), 6)
        rec["roofline_bound"] = "flops" if t_flops >= t_bytes else "bytes"
        rec["roofline_attainment"] = round(
            max(t_flops, t_bytes) / step_s, 4
        )
    return rec


def bench_resnet50_inference() -> dict:
    """BASELINE config 5: ResNet-50 batch inference — MEASURED via the
    columnar-ingest -> device streaming path (Parquet row groups of
    raw uint8 pixels -> reader thread -> host->device uint8 wire ->
    normalize + forward + device-side argmax, double-buffered).

    Numbers reported:
    - `stream_rows_per_sec`: sustained end-to-end rate of THIS run
      (a few thousand rows so the suite stays fast);
    - `chip_rate_rows_per_sec_per_chip`: device-resident compute rate
      (the per-chip ceiling when data streams from colocated hosts);
    - `measured_run_*`: the LARGEST >=100k-row measured run on record
      in the benchmarks/ JSONL logs (the r04 1M-row run from
      benchmarks/stream_inference_1m.py once it has landed) — the
      honest long-haul number.
    On this dev rig the end-to-end rate is bound by the tunneled
    host<->device link (~6 MB/s effective), not the chip."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from sparktorch_tpu.inference import (
        BatchPredictor,
        stream_parquet_predict,
        write_rows_parquet,
    )
    from sparktorch_tpu.models.resnet import resnet50
    from sparktorch_tpu.obs import get_telemetry

    tele = get_telemetry()
    module = resnet50()
    rng = np.random.default_rng(0)
    chunk = 256
    n_stream = chunk * 8
    with tempfile.TemporaryDirectory() as d:
        with tele.span("bench/data") as _sp_data:
            x = rng.integers(0, 256, (chunk * 4, 224, 224, 3),
                             dtype=np.uint8)
            path = os.path.join(d, "bench_stream.parquet")
            write_rows_parquet(
                path,
                (rng.integers(0, 256, (chunk, 224, 224, 3), dtype=np.uint8)
                 for _ in range(n_stream // chunk)),
                rows_per_group=chunk,
            )
        with tele.span("bench/init") as _sp_init:
            variables = module.init(jax.random.key(0),
                                    np.zeros((1, 224, 224, 3), np.float32))
            predictor = BatchPredictor(
                module, variables["params"],
                {k: v for k, v in variables.items() if k != "params"},
                chunk=chunk,
                preprocess=lambda v: v.astype(jnp.float32) / 255.0,
                # predict_float argmax on device
                # (torch_distributed.py:112-120)
                postprocess=lambda y: jnp.argmax(y, -1).astype(jnp.int32),
            )
            _sp_init.sync(variables["params"])
        with tele.span("bench/compile_warmup") as _sp_warm:
            _materialize(predictor.predict(x[:chunk]))  # compile
            _sp_warm.synced = True
        n_chips = len(jax.devices())

        with tele.span("bench/measure") as _sp_measure:
            xd = jax.device_put(x)  # device-resident: measures the chip
            _materialize(xd)
            rates = []
            for _ in range(3):  # best-of-3: the dev tunnel is noisy
                t0 = time.perf_counter()
                out = predictor.predict(xd)
                assert out.shape[0] == x.shape[0]
                rates.append(x.shape[0] / (time.perf_counter() - t0))
            per_chip = max(rates) / n_chips

            # End-to-end streaming leg over a real Parquet file (disk
            # -> decode -> wire -> compute -> drain).
            stats = stream_parquet_predict(
                predictor, path, row_shape=(224, 224, 3), dtype=np.uint8,
                batch_rows=4 * chunk,
            )
            _sp_measure.synced = True  # predict() drains per batch

    out = {
        "config": "resnet50_inference", "unit": "examples/sec/chip",
        "examples_per_sec_per_chip": round(per_chip, 1),
        "phase_s": {
            "data": round(_sp_data.duration_s, 3),
            "init": round(_sp_init.duration_s, 3),
            "compile_warmup": round(_sp_warm.duration_s, 3),
            "measure": round(_sp_measure.duration_s, 3),
        },
        "chip_rate_rows_per_sec_per_chip": round(per_chip, 1),
        "stream_rows_per_sec": stats["rows_per_sec"],
        "stream_n_rows": stats["n_rows"],
        "n_chips": n_chips,
        "projected_1M_rows_s_chip_rate": round(
            1_000_000 / (per_chip * n_chips), 1
        ),
        "projected_1M_rows_s_host_stream": round(
            1_000_000 / max(stats["rows_per_sec"], 1e-9), 1
        ),
        "wire_dtype": "uint8 (normalize + argmax fused on device)",
    }
    # Attach the LARGEST measured long-haul run on record across the
    # retained round logs (r03 100k, r04 1M, r05 segments).
    bench_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks")
    big = []
    for name in ("bench_r03_tpu.jsonl", "bench_r04_tpu.jsonl",
                 "bench_r05_tpu.jsonl"):
        try:
            with open(os.path.join(bench_dir, name)) as f:
                runs = [json.loads(line) for line in f if line.strip()]
            big += [r for r in runs
                    if r.get("config") == "resnet50_inference_stream"
                    and r.get("n_rows", 0) >= 100_000]
        except (OSError, ValueError):
            # Missing log or a truncated line from a killed run — skip
            # the attachment, never the benchmark.
            continue
    if big:
        try:
            last = max(big, key=lambda r: r["n_rows"])
            # Read every key BEFORE assigning: a partial attachment
            # from an old-schema row would be worse than none.
            out.update({
                "measured_run_rows": last["n_rows"],
                "measured_run_rows_per_sec": last["steady_rows_per_sec"],
                "measured_run_wall_s": last["wall_s"],
            })
        except KeyError:
            pass
    return out


def bench_long_context_lm() -> dict:
    """Beyond the reference (which has no sequence code at all,
    SURVEY §5): causal-LM training at long context on one chip via the
    Pallas flash-attention kernel (fwd+bwd streaming, no (s,s) logits
    in HBM), plus a dense-vs-flash step-time comparison at a length
    both can run. Multi-chip sequence parallelism (ring attention over
    ``sp``) is exercised by dryrun_multichip and tests; this config is
    the single-chip kernel number."""
    import jax

    from sparktorch_tpu.models import CausalLM
    from sparktorch_tpu.models.transformer import TransformerConfig
    from sparktorch_tpu.utils.serde import ModelSpec

    rng = np.random.default_rng(0)
    vocab, batch, seq = 32768, 2, 8192

    def spec_for(attn: str, s: int) -> ModelSpec:
        cfg = TransformerConfig(
            vocab_size=vocab, d_model=512, n_heads=8, n_layers=4,
            d_ff=2048, max_len=s, attn_impl=attn, remat=True,
        )
        return ModelSpec(module=CausalLM(cfg), loss="cross_entropy",
                         optimizer="adamw", optimizer_params={"lr": 3e-4})

    ids = rng.integers(0, vocab, (batch, seq + 1)).astype(np.int32)
    out = _sync_epoch_bench(spec_for("flash", seq), ids[:, :-1], ids[:, 1:],
                            batch, iters=6, warmup=2, chunks=2)
    tokens_per_sec = out["examples_per_sec_per_chip"] * seq

    # Head-to-head at a length dense can still hold (s^2 logits fit).
    cmp_seq = 2048
    ids_c = rng.integers(0, vocab, (batch, cmp_seq + 1)).astype(np.int32)
    cmp = {}
    for attn in ("dense", "flash"):
        r = _sync_epoch_bench(spec_for(attn, cmp_seq), ids_c[:, :-1],
                              ids_c[:, 1:], batch, iters=6, warmup=2, chunks=2)
        cmp[attn] = r["step_time_p50_s"]
    return {
        "config": "long_context_lm", "unit": "tokens/sec/chip",
        "seq_len": seq,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "flash_vs_dense_step_ratio_at_2k": round(
            cmp["dense"] / cmp["flash"], 3
        ),
        **out,
    }


def bench_moe_lm() -> dict:
    """Beyond the reference: switch-style MoE causal LM on one chip
    (ep=1 layout; the all-to-all layout is exercised by tests and the
    multi-chip dry run). Reports tokens/sec and the MoE-vs-dense
    step-time ratio at matched active params per token."""
    import jax

    from sparktorch_tpu.models import CausalLM
    from sparktorch_tpu.models.transformer import TransformerConfig
    from sparktorch_tpu.utils.serde import ModelSpec

    rng = np.random.default_rng(0)
    vocab, batch, seq = 32768, 8, 1024

    def spec_for(n_experts: int) -> ModelSpec:
        cfg = TransformerConfig(
            vocab_size=vocab, d_model=512, n_heads=8, n_layers=4,
            d_ff=2048, max_len=seq, n_experts=n_experts, moe_every=2,
        )
        return ModelSpec(module=CausalLM(cfg), loss="cross_entropy",
                         optimizer="adamw", optimizer_params={"lr": 3e-4})

    ids = rng.integers(0, vocab, (batch, seq + 1)).astype(np.int32)
    # with_trace: the MoE leg's record carries the per-collective
    # comm/compute budget (dispatch/combine collectives vs expert
    # compute) from an analyzed XLA capture — comm_s/comm_fraction/
    # overlap_fraction in the phase budget, per the obs ISSUE.
    moe = _sync_epoch_bench(spec_for(8), ids[:, :-1], ids[:, 1:], batch,
                            iters=6, warmup=2, chunks=2, with_trace=True)
    dense = _sync_epoch_bench(spec_for(0), ids[:, :-1], ids[:, 1:], batch,
                              iters=6, warmup=2, chunks=2)
    # Comm-fraction drift gate: the MoE capture records a comm_budget
    # every round; once a prior round's record exists, a lost overlap
    # (dispatch/combine no longer hidden under expert compute) fails
    # the bench instead of silently shipping.
    if "comm_fraction" in moe:
        moe["comm_drift"] = _check_comm_drift(
            "moe_lm", moe["comm_fraction"], moe.get("overlap_fraction", 0.0)
        )
    return {
        "config": "moe_lm", "unit": "tokens/sec/chip",
        "n_experts": 8, "seq_len": seq,
        "tokens_per_sec_per_chip": round(
            moe["examples_per_sec_per_chip"] * seq, 1
        ),
        "moe_vs_dense_step_ratio": round(
            moe["step_time_p50_s"] / dense["step_time_p50_s"], 3
        ),
        **moe,
    }


def bench_moe_a2a() -> dict:
    """MoE expert-parallel dispatch gate (``make bench-moe``): on the
    same ep=2 mesh and matched init, the explicit shard_map all-to-all
    dispatch (``moe_ep_dispatch='a2a'``) must beat the legacy
    partitioner-derived token-replication path (``'replicate'`` — jax
    0.4.x GSPMD lowers it to all-gather + all-reduce) on

    - **collective bytes, strictly**: per-device HLO collective result
      bytes (:func:`sparktorch_tpu.obs.xprof.hlo_collective_bytes` —
      static, partitioner-independent, no profiler noise), with the
      a2a leg containing all-to-alls and ZERO all-gathers;
    - **step wall, equal-or-better**: medians over interleaved
      measurement rounds (the rig-noise discipline every gate here
      uses), within ``SPARKTORCH_TPU_MOE_WALL_TOL`` (default 0.05 —
      the byte win must not come at a wall cost);
    - **identical numbers**: both legs' losses agree at rtol 1e-5
      (the dispatch rewrite is a layout choice, pinned here end to
      end, not just in the unit suite).

    The tuner's ep a2a byte term (``predict_comm_bytes``:
    ``ep_all_to_all``) is validated against the measured HLO bytes —
    recorded as ``predicted_vs_hlo_a2a`` and gated to a factor band
    (the model is a monotone ranker, not a simulator; the band catches
    sign/scale regressions like a dropped capacity term).

    Retained (``--log benchmarks/bench_r10_moe.jsonl``) so the drift
    gate arms: the byte-reduction ratio must not collapse vs the
    windowed median of prior rounds (``SPARKTORCH_TPU_MOE_DRIFT_TOL``,
    relative, default 0.25)."""
    import dataclasses as _dc
    import os

    import jax
    import jax.numpy as jnp

    from sparktorch_tpu.models import CausalLM
    from sparktorch_tpu.models.transformer import TransformerConfig
    from sparktorch_tpu.obs.xprof import hlo_collective_bytes
    from sparktorch_tpu.parallel.compat import set_mesh as _set_mesh
    from sparktorch_tpu.parallel.mesh import MeshConfig, build_mesh
    from sparktorch_tpu.parallel.tune import (
        mesh_label,
        predict_comm_bytes,
        transformer_workload,
    )
    from sparktorch_tpu.train.sharded import (
        create_sharded_state,
        make_sharded_train_step,
        shard_batch,
    )
    from sparktorch_tpu.utils.data import DataBatch
    from sparktorch_tpu.utils.serde import ModelSpec

    n_dev = len(jax.devices())
    if n_dev % 2:
        raise AssertionError(
            f"bench moe_a2a needs an even device count for ep=2; got "
            f"{n_dev} (set XLA_FLAGS=--xla_force_host_platform_device_"
            "count=8 on a CPU rig)"
        )
    # Sized so the dispatch/combine traffic is a real fraction of the
    # step (d_model*seq*cf*k capacity blocks per MoE layer) without
    # blowing the CPU rig's step wall.
    base_cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=512,
        max_len=64, n_experts=8, moe_every=1, moe_top_k=2,
        moe_group_size=64,
    )
    mesh = build_mesh(MeshConfig(ep=2))
    mesh_ran = mesh_label(dict(mesh.shape))
    rng = np.random.default_rng(0)
    bsz = 4 * n_dev
    ids = rng.integers(0, base_cfg.vocab_size, (bsz, 65)).astype(np.int32)
    batch = DataBatch(x=jnp.asarray(ids[:, :-1]), y=jnp.asarray(ids[:, 1:]),
                      w=jnp.ones((bsz,), jnp.float32))

    # The persistent compile cache is disarmed for collective-bearing
    # programs on CPU (tests/conftest.py / ROADMAP).
    old_cache = jax.config.jax_compilation_cache_dir
    if jax.default_backend() == "cpu":
        jax.config.update("jax_compilation_cache_dir", None)
    try:
        # Dense CE, not the registry's fused Pallas kernel: on this
        # CPU rig the kernel runs in interpret mode — a while loop the
        # partitioner can only all-gather the (tokens, vocab) logits
        # into — which would put LOSS-path all-gathers in both legs'
        # HLO and blind the "zero all-gathers in the a2a program" gate
        # to the dispatch bytes this bench exists to measure.
        from sparktorch_tpu.utils.losses import cross_entropy_loss

        legs = {}
        for dispatch in ("replicate", "a2a"):
            cfg = _dc.replace(base_cfg, moe_ep_dispatch=dispatch)
            spec = ModelSpec(module=CausalLM(cfg), loss=cross_entropy_loss,
                             optimizer="adamw",
                             optimizer_params={"lr": 1e-3})
            tx = spec.make_optimizer()
            state, shardings = create_sharded_state(
                spec, mesh, jax.random.key(0),
                sample_x=np.asarray(batch.x[:1]), tx=tx,
            )
            step = make_sharded_train_step(
                spec.make_module().apply, spec.loss_fn(), tx, mesh,
                shardings,
            )
            sharded = shard_batch(batch, mesh)
            with _set_mesh(mesh):
                compiled = step.jitted.lower(state, sharded).compile()
            hlo_stats = hlo_collective_bytes(compiled.as_text())
            # Compile+warm outside timing.
            state, m = step(state, sharded)
            jax.block_until_ready(m.loss)
            legs[dispatch] = {
                "step": step, "state": state, "batch": sharded,
                "hlo": hlo_stats, "losses": [float(m.loss)], "walls": [],
            }

        # Interleaved rounds: back-to-back per-leg timing on a shared
        # rig swings whole windows into slow scheduler epochs — the
        # same discipline as bench-tune/bench-ps-fleet.
        steps_per_round, rounds = 3, 4
        for _ in range(rounds):
            for leg in legs.values():
                t0 = time.perf_counter()
                st = leg["state"]
                for _ in range(steps_per_round):
                    st, m = leg["step"](st, leg["batch"])
                jax.block_until_ready(m.loss)
                leg["state"] = st
                leg["walls"].append(
                    (time.perf_counter() - t0) / steps_per_round
                )
                leg["losses"].append(float(m.loss))

        rep, a2a = legs["replicate"], legs["a2a"]

        # ---- gate 1: strictly fewer collective bytes ---------------------
        bytes_rep = rep["hlo"]["total_bytes"]
        bytes_a2a = a2a["hlo"]["total_bytes"]
        if not (0 < bytes_a2a < bytes_rep):
            raise AssertionError(
                f"a2a path must move strictly fewer collective bytes: "
                f"a2a={bytes_a2a} vs replicate={bytes_rep} "
                f"(families: a2a={a2a['hlo']}, rep={rep['hlo']})"
            )
        if a2a["hlo"]["counts"].get("all_to_all", 0) < 4 \
                or a2a["hlo"]["counts"].get("all_gather", 0) != 0:
            raise AssertionError(
                f"a2a leg HLO shape wrong (want >=4 all-to-alls — "
                f"dispatch+combine, fwd+bwd, per MoE layer — and zero "
                f"all-gathers): {a2a['hlo']}"
            )

        # ---- gate 2: equal-or-better step wall ---------------------------
        wall_rep = float(np.median(rep["walls"]))
        wall_a2a = float(np.median(a2a["walls"]))
        wall_tol = float(os.environ.get("SPARKTORCH_TPU_MOE_WALL_TOL",
                                        "0.05"))
        if wall_a2a > wall_rep * (1.0 + wall_tol):
            raise AssertionError(
                f"a2a step wall regressed vs the token-replication "
                f"path: {wall_a2a * 1e3:.2f}ms vs {wall_rep * 1e3:.2f}ms "
                f"(tol {wall_tol:.0%}; walls a2a={a2a['walls']}, "
                f"rep={rep['walls']})"
            )

        # ---- gate 3: layout must not change the math ---------------------
        np.testing.assert_allclose(a2a["losses"], rep["losses"], rtol=1e-5)

        # ---- gate 4: tuner ep byte model vs HLO ground truth -------------
        shape = transformer_workload(base_cfg, global_batch=bsz)
        predicted = predict_comm_bytes(MeshConfig(ep=2), shape, n_dev)
        # predict_comm_bytes models the FORWARD dispatch+combine pair
        # fleet-wide; the compiled HLO is per-device and includes the
        # backward pair -> model ~= hlo_bytes * n_dev / 2.
        hlo_a2a_fleet_fwd = a2a["hlo"]["bytes"]["all_to_all"] * n_dev / 2
        ratio = predicted["ep_all_to_all"] / max(hlo_a2a_fleet_fwd, 1.0)
        if not (0.25 <= ratio <= 4.0):
            raise AssertionError(
                f"tuner ep_all_to_all byte model off the HLO ground "
                f"truth by {ratio:.2f}x (predicted "
                f"{predicted['ep_all_to_all']:.0f}, HLO fwd-pair "
                f"fleet-wide {hlo_a2a_fleet_fwd:.0f}) — the a2a term "
                "no longer tracks the real lowering"
            )

        # ---- gate 5: drift vs retained prior rounds ----------------------
        byte_ratio = bytes_rep / bytes_a2a
        drift_tol = float(os.environ.get("SPARKTORCH_TPU_MOE_DRIFT_TOL",
                                         "0.25"))
        prior = _prior_window("moe_a2a", "collective_byte_ratio",
                              mesh=mesh_ran)
        if prior is None:
            drift = {"status": "no_prior_record", "tolerance": drift_tol}
        else:
            drift = {"status": "checked", "tolerance": drift_tol,
                     "prior": prior}
            if byte_ratio < prior["median"] * (1.0 - drift_tol):
                raise AssertionError(
                    f"moe_a2a: collective byte reduction collapsed "
                    f"{prior['median']:.2f}x -> {byte_ratio:.2f}x "
                    f"(beyond the {drift_tol:.0%} tolerance); {drift}"
                )

        return {
            "config": "moe_a2a", "unit": "x fewer collective bytes",
            "value": round(byte_ratio, 3),
            "collective_byte_ratio": round(byte_ratio, 3),
            "mesh": mesh_ran, "n_chips": n_dev,
            "a2a_step_wall_s": round(wall_a2a, 6),
            "replicate_step_wall_s": round(wall_rep, 6),
            "wall_ratio": round(wall_a2a / wall_rep, 3),
            "a2a_hlo": a2a["hlo"], "replicate_hlo": rep["hlo"],
            "loss_parity_rtol": 1e-5,
            "predicted_vs_hlo_a2a": round(ratio, 3),
            "drift": drift,
        }
    finally:
        if jax.default_backend() == "cpu":
            jax.config.update("jax_compilation_cache_dir", old_cache)


def bench_pp_tune() -> dict:
    """Pipeline-schedule auto-tuning + recompile-tax gate
    (``make bench-pp-tune``, ROADMAP item 4). Two legs:

    **Referee leg** — the tuner searches the dp x pp x schedule x
    virtual_stages space (``axes=('dp','pp')``: the leg's subject is
    the SCHEDULE dimension, not the whole mesh zoo bench-tune already
    referees) on a 4-layer transformer, then an EXHAUSTIVE pass
    measures every candidate; FAILS unless the chosen config sits
    within ``SPARKTORCH_TPU_PP_TUNE_TOL`` (default 15%) of the
    exhaustive winner's step wall, the space actually contained
    measured pp>1 schedule candidates, and pruned candidates were
    never executed.

    **Recompile-tax leg** — a cold ``mesh="auto"`` build (fresh
    tune-result cache) vs a warm one, each inside its own goodput
    ledger; FAILS unless the warm build's ``TuneResult.compile_count``
    drops below the cold path's, the warm tune wall collapses (cache
    hit), and the warm ledger's ``compile`` bucket shows the saving
    in seconds. This is the acceptance gate for "the auto path stops
    compiling its winner twice": the persistent XLA cache (armed for
    the whole bench process) makes the winner's fresh-closure
    recompile a disk hit, and the tune-result cache deletes the
    search.

    The record retains both rankings + the compile bills; drift gate
    vs the ``_prior_window`` median of ``tuner_wall_s`` is ARMED
    (SPARKTORCH_TPU_PP_TUNE_DRIFT_TOL, relative, default 1.0 with a
    5s floor) once a prior round is retained."""
    import os
    import tempfile

    import jax

    from sparktorch_tpu.models import SequenceClassifier, tiny_transformer
    from sparktorch_tpu.obs import Telemetry
    from sparktorch_tpu.obs import goodput as goodput_mod
    from sparktorch_tpu.parallel.tune import autotune, transformer_caps
    from sparktorch_tpu.train.pipeline import PipelineState
    from sparktorch_tpu.train.sharded import (
        make_sharded_train_step,
        shard_batch,
    )
    from sparktorch_tpu.utils.data import DataBatch
    from sparktorch_tpu.utils.serde import ModelSpec

    t0 = time.perf_counter()
    tele = Telemetry(run_id="bench_pp_tune")
    devices = jax.devices()
    n_dev = len(devices)
    rng = np.random.default_rng(0)

    # ---- referee leg: pp x schedule vs exhaustive ---------------------
    bsz, seq = 8 * n_dev, 32
    batch = DataBatch(
        x=np.asarray(rng.integers(0, 256, (bsz, seq)).astype(np.int32)),
        y=np.asarray(rng.integers(0, 2, (bsz,)).astype(np.int32)),
        w=np.ones((bsz,), np.float32),
    )
    # 4 layers: pp in {1, 2, 4}, interleaved V=2 legal at pp=2. Sized
    # so layout differences beat scheduler jitter (the bench-tune
    # sizing lesson).
    cfg = tiny_transformer(d_model=128, d_ff=512, n_layers=4,
                           max_len=seq)
    module = SequenceClassifier(cfg)
    spec = ModelSpec(module=module, loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3})
    # 2 profiled steps x (1 warmup + 2 scored) rounds per candidate:
    # schedule steps on this rig run seconds each, and the referee
    # only needs a stable ORDERING, not tight walls.
    steps, repeats, top_k = 2, 2, 3
    axes = ("dp", "pp")
    # Cap pp at 2 (the caps knob, not the axes): pp=2 already carries
    # every schedule kind (gpipe / 1f1b / interleaved V=2 on the
    # 4-layer stack), and the exhaustive referee measures EVERY
    # candidate — pp=4 schedule steps on the 8-virtual-device CPU rig
    # run ~100x the dp wall and would blow the bench budget without
    # adding a schedule dimension to referee.
    caps = dict(transformer_caps(cfg, seq))
    caps["pp"] = (2,)
    caps["sp"] = (1,)

    tuned = autotune(
        spec, batch, devices, axes=axes, caps=caps, steps=steps,
        repeats=repeats, measure_top_k=top_k, telemetry=tele,
    )
    tuner_wall_s = tuned.wall_s
    pruned = tuned.pruned()
    if any(c.measured for c in pruned):
        raise AssertionError("a pruned candidate was executed")
    pp_cands = [c for c in tuned.candidates if c.axes.get("pp", 1) > 1]
    if not pp_cands:
        raise AssertionError("search space contained no pp>1 candidate")
    if not any(c.schedule for c in pp_cands):
        raise AssertionError("pp candidates carry no schedule meta")
    scheds = {c.schedule["schedule"] for c in pp_cands if c.schedule}
    if not {"gpipe", "1f1b"} <= scheds:
        raise AssertionError(
            f"schedule dims missing from the space: {sorted(scheds)}")

    jax.clear_caches()
    gc.collect()
    exhaustive = autotune(
        spec, batch, devices, axes=axes, caps=caps, steps=steps,
        repeats=repeats, exhaustive=True, telemetry=tele,
    )
    ex_ranked = exhaustive.ranking()
    if not any(c.axes.get("pp", 1) > 1 for c in ex_ranked):
        raise AssertionError(
            "exhaustive referee measured no pp>1 candidate — the "
            "schedule path never executed"
        )
    ex_by_label = {c.label: c for c in ex_ranked}
    winner = ex_ranked[0]
    tol = float(os.environ.get("SPARKTORCH_TPU_PP_TUNE_TOL", "0.15"))
    chosen_ex = ex_by_label.get(tuned.best_label)
    if chosen_ex is None:
        raise AssertionError(
            f"chosen {tuned.best_label} missing from the exhaustive "
            f"measurement ({sorted(ex_by_label)})"
        )
    winner_wall = float(winner.measured["step_wall_s"])
    chosen_wall = float(chosen_ex.measured["step_wall_s"])
    if tuned.best_label != winner.label and \
            chosen_wall > winner_wall * (1.0 + tol):
        raise AssertionError(
            f"tuner chose {tuned.best_label} "
            f"({chosen_wall * 1e3:.2f}ms on the exhaustive rig) but "
            f"the exhaustive winner is {winner.label} "
            f"({winner_wall * 1e3:.2f}ms) — over the {tol * 100:.0f}% "
            f"tolerance"
        )

    # ---- recompile-tax leg: cold vs warm mesh='auto' ------------------
    small = SequenceClassifier(tiny_transformer(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_len=8))
    small_spec = ModelSpec(module=small, loss="cross_entropy",
                           optimizer="adam",
                           optimizer_params={"lr": 1e-3})
    small_batch = DataBatch(
        x=np.asarray(rng.integers(0, 64, (2 * n_dev, 8)).astype(np.int32)),
        y=np.asarray(rng.integers(0, 2, (2 * n_dev,)).astype(np.int32)),
        w=np.ones((2 * n_dev,), np.float32),
    )

    def _auto_build_and_step():
        """One mesh='auto' build + first step under a fresh ledger;
        returns (tune_result, ledger snapshot, build wall)."""
        led = goodput_mod.GoodputLedger(telemetry=None, rank=0)
        tb = time.perf_counter()
        with led.activate():
            run = make_sharded_train_step(
                small.apply, small_spec.loss_fn(),
                small_spec.make_optimizer(),
                mesh="auto", spec=small_spec, sample_batch=small_batch,
                tune_kwargs={"steps": 1, "repeats": 1, "min_rounds": 1,
                             "measure_top_k": 2, "cache": True},
            )
            state = run.state
            if isinstance(state, PipelineState):
                out = run(state, small_batch)
            else:
                out = run(state, shard_batch(small_batch, run.mesh))
            jax.block_until_ready(jax.tree.leaves(out)[:1])
        wall = time.perf_counter() - tb
        led.close()
        return run.tune_result, led.snapshot(), wall

    with tempfile.TemporaryDirectory() as tune_cache_dir:
        # Sandbox BOTH caches the auto path touches: the tune-result
        # cache (cold-vs-warm is the leg's subject) and the XLA-cache
        # arming knob — if this config runs in a process where the
        # bench harness has not already armed a cache dir,
        # _maybe_arm_xla_cache must land in the sandbox, never in the
        # operator's ~/.cache.
        old_env = {k: os.environ.get(k)
                   for k in ("SPARKTORCH_TPU_TUNE_CACHE",
                             "SPARKTORCH_TPU_XLA_CACHE")}
        os.environ["SPARKTORCH_TPU_TUNE_CACHE"] = tune_cache_dir
        os.environ["SPARKTORCH_TPU_XLA_CACHE"] = os.path.join(
            tune_cache_dir, "xla")
        try:
            cold_result, cold_doc, cold_wall = _auto_build_and_step()
            jax.clear_caches()
            gc.collect()
            warm_result, warm_doc, warm_wall = _auto_build_and_step()
        finally:
            for k, v in old_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    if not warm_result.cache_hit:
        raise AssertionError("warm mesh='auto' build missed the "
                             "tune-result cache")
    if warm_result.compile_count >= cold_result.compile_count:
        raise AssertionError(
            f"cache-warm compile_count {warm_result.compile_count} did "
            f"not drop below the cold path's "
            f"{cold_result.compile_count}"
        )
    cold_compile_s = float(cold_doc["buckets"]["compile"])
    warm_compile_s = float(warm_doc["buckets"]["compile"])
    if cold_compile_s <= 0:
        raise AssertionError("cold build's goodput compile bucket is "
                             "empty — the tune LedgerSpans never landed")
    if warm_compile_s >= cold_compile_s:
        raise AssertionError(
            f"goodput compile bucket shows no saving: cold "
            f"{cold_compile_s:.2f}s vs warm {warm_compile_s:.2f}s"
        )
    # A cache-hit TuneResult reports the wall THIS process paid (the
    # lookup), not the stored search's — so the collapse is direct.
    if warm_result.wall_s > 0.2 * cold_result.wall_s + 0.5:
        raise AssertionError(
            f"warm tune wall {warm_result.wall_s:.2f}s did not "
            f"collapse vs cold {cold_result.wall_s:.2f}s (cache hit "
            f"should skip the search)"
        )

    # ---- drift gate vs the windowed prior ----------------------------
    drift = {"status": "no_prior_record"}
    prior = _prior_window("pp_tune", "tuner_wall_s", k=3)
    if prior is not None:
        dtol = float(os.environ.get("SPARKTORCH_TPU_PP_TUNE_DRIFT_TOL",
                                    "1.0"))
        floor_s = 5.0
        bound = prior["median"] * (1.0 + dtol) + floor_s
        if tuner_wall_s > bound:
            raise AssertionError(
                f"tuner wall {tuner_wall_s:.1f}s drifted past "
                f"{bound:.1f}s (prior median {prior['median']:.1f}s "
                f"over {prior['n']} rounds, tol {dtol})"
            )
        drift = {"status": "checked", "prior_median_s": prior["median"],
                 "bound_s": round(bound, 1), "tolerance": dtol}

    return {
        "config": "pp_tune", "unit": "chosen step wall vs best (x)",
        "value": round(chosen_wall / winner_wall, 4),
        "chosen": tuned.best_label,
        "chosen_schedule": tuned.best_schedule,
        "exhaustive_winner": winner.label,
        "chosen_wall_ms": round(chosen_wall * 1e3, 3),
        "winner_wall_ms": round(winner_wall * 1e3, 3),
        "tolerance": tol,
        "n_candidates": len(tuned.candidates),
        "n_pp_candidates": len(pp_cands),
        "schedules_in_space": sorted(scheds),
        "n_pruned": len(pruned),
        "tuner_wall_s": round(tuner_wall_s, 1),
        "exhaustive_wall_s": round(exhaustive.wall_s, 1),
        "exhaustive_ranking": [
            {"mesh": c.label,
             "wall_ms": round(float(c.measured["step_wall_s"]) * 1e3, 3),
             "bubble": round(float(
                 c.predicted.get("pp_bubble_fraction", 0.0)), 3)}
            for c in ex_ranked
        ],
        "compile_count_cold": cold_result.compile_count,
        "compile_count_warm": warm_result.compile_count,
        "compile_s_cold": round(cold_compile_s, 2),
        "compile_s_warm": round(warm_compile_s, 2),
        "tune_wall_cold_s": round(cold_result.wall_s, 2),
        "tune_wall_warm_s": round(warm_result.wall_s, 3),
        "build_wall_cold_s": round(cold_wall, 1),
        "build_wall_warm_s": round(warm_wall, 1),
        "drift": drift,
        "n_chips": n_dev,
        "wall_s": round(time.perf_counter() - t0, 1),
    }


CONFIGS: Dict[str, Callable[[], dict]] = {
    "mnist_mlp_sync": bench_mnist_mlp_sync,
    "mnist_cnn_sync": bench_mnist_cnn_sync,
    "lazy_cnn_sync": bench_lazy_cnn_sync,
    "resnet18_hogwild": bench_resnet18_hogwild,
    "hogwild_wire": bench_hogwild_wire,
    "hogwild_chaos": bench_hogwild_chaos,
    "hogwild_chaos_soak": bench_hogwild_chaos_soak,
    "elastic_ctl": bench_elastic_ctl,
    "obs_history": bench_obs_history,
    "goodput": bench_goodput,
    "profile": bench_profile,
    "health": bench_health,
    "skew": bench_skew,
    "hogwild_ps_fleet": bench_hogwild_ps_fleet,
    "serve_online": bench_serve_online,
    "rpc_trace": bench_rpc_trace,
    "sharded_trace": bench_sharded_trace,
    "gang_obs": bench_gang_obs,
    "mesh_tune": bench_mesh_tune,
    "pp_tune": bench_pp_tune,
    "moe_a2a": bench_moe_a2a,
    "bert_dp": bench_bert_dp,
    "resnet50_inference": bench_resnet50_inference,
    "long_context_lm": bench_long_context_lm,
    "moe_lm": bench_moe_lm,
}


def _headline() -> dict:
    """The driver's ONE-JSON-line metric — same workload as round 1.

    Round 4: the value is the MEDIAN of >=5 interleaved paired-span
    slope samples (see ``_sync_epoch_bench``), with best/spread/raw
    samples carried alongside so regression vs noise is decidable from
    the line itself; every run also appends the full record to
    ``benchmarks/bench_r05_tpu.jsonl``."""
    out = bench_mnist_cnn_sync()
    per_chip = out["examples_per_sec_per_chip"]
    rec = {
        "metric": "examples/sec/chip (MNIST-CNN sync DP, batch 1024)",
        "value": per_chip,
        "unit": "examples/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_BASELINE_EXAMPLES_PER_SEC, 3),
        "best": out["rate_best"],
        "spread_pct": out["rate_spread_pct"],
        "n_samples": len(out["rate_samples"]),
        "estimator": "median of paired-span slopes (cancels per-sync link RTT)",
    }
    try:
        import os

        log = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks", "bench_r05_tpu.jsonl")
        with open(log, "a") as f:
            f.write(json.dumps({
                **out, "source": "headline",
                "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            }) + "\n")
    except OSError:
        pass  # read-only checkout: the headline line still prints
    return rec


def main(argv: Optional[List[str]] = None) -> None:
    # Persistent compilation cache: repeated configs (and the warmup
    # pattern above) hit disk instead of recompiling — also what a
    # production deployment should run with.
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/sparktorch_tpu_jit_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    parser = argparse.ArgumentParser(prog="sparktorch-tpu-bench")
    parser.add_argument("--config", default="headline",
                        choices=["headline", "all", *CONFIGS])
    parser.add_argument("--log", default=None,
                        help="append raw result records to this JSONL file")
    parser.add_argument("--telemetry-dump", default=None, metavar="PATH",
                        help="append the run's full telemetry snapshot "
                             "(counters, gauges, histogram/span roll-ups) "
                             "as one JSONL line — the CLI twin of the "
                             "param server's /metrics route")
    args = parser.parse_args(argv)

    def _dump_telemetry() -> None:
        if args.telemetry_dump:
            from sparktorch_tpu.obs import get_telemetry

            get_telemetry().dump(args.telemetry_dump)

    if args.config == "headline":
        print(json.dumps(_headline()))
        _dump_telemetry()
        return

    names = list(CONFIGS) if args.config == "all" else [args.config]
    records = []
    for i, name in enumerate(names):
        if i:
            # Fresh device/executable state per config: carried-over
            # compiled programs and live buffers from earlier configs
            # measurably depress later ones (~20-25% on the CNN
            # config); with the persistent compile cache on disk,
            # clearing costs little.
            jax.clear_caches()
            gc.collect()
        rec = CONFIGS[name]()
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        records.append(rec)
        print(json.dumps(rec))
    if args.log:
        with open(args.log, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    _dump_telemetry()


if __name__ == "__main__":
    main()
