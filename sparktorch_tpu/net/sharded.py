"""Client-side scatter/gather over a sharded parameter-server fleet.

The single hogwild server caps aggregate pull bandwidth at one socket
loop no matter how many chips train — the exact bottleneck the
reference never fixed (one Flask process on the driver,
``server.py:33-149``). The production shape is Li et al.'s
parameter-server fleet (OSDI '14): the tensor tree hash-partitioned
across N server shards, every worker talking to all of them. This
module is the CLIENT half:

- :class:`HashRing` — consistent hashing over leaf paths (md5 points,
  virtual nodes), shared verbatim by the server fleet
  (:mod:`sparktorch_tpu.serve.fleet`) so both sides compute the same
  owner for every tensor. Adding or draining a shard remaps only
  ~1/N of the keys, never the whole tree — that is what makes LIVE
  resharding possible.
- :class:`ShardedTransport` — the hogwild transport contract
  (``pull`` / ``push`` / ``post_loss`` / ``alive`` / ``stats``) over
  one :class:`~sparktorch_tpu.net.transport.BinaryTransport` per
  shard. Pulls fan out as per-tensor DELTA requests (``/delta.bin``:
  only leaves whose version advanced ship; optional int8 payloads
  with server-side error feedback) and reassemble into the full tree
  from a client-side leaf cache; pushes split the gradient tree by
  ring ownership and scatter in parallel.
- Fault degradation: a shard that stops answering degrades the
  transport (its leaves freeze at the cached values, its gradient
  partials are dropped and counted) for a GRACE WINDOW; only a shard
  dead past the grace fails the worker. The fleet's monitor restarts
  a dead shard frontend well inside the default grace, so a seeded
  shard kill costs some staleness, not the run.
- Topology refresh: every delta reply carries ``X-Ring-Version``; a
  mismatch against the client's ring triggers a re-fetch of
  ``/fleet.json`` (any shard serves it), so workers learn about
  add/drain within one pull — no control channel needed.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Mapping, Optional, Tuple

import numpy as np

from sparktorch_tpu.net import wire
from sparktorch_tpu.net.transport import (
    BinaryTransport,
    TransportError,
    _new_phase_stats,
    _tree_to_host,
)

Path = Tuple[str, ...]

_RING_REPLICAS = 64  # virtual nodes per shard: evens out md5 arcs


def _hash64(token: str) -> int:
    return int.from_bytes(hashlib.md5(token.encode()).digest()[:8], "big")


class HashRing:
    """Consistent hashing of leaf paths onto shard ids.

    Deterministic across processes (md5, not the salted builtin
    ``hash``), so a server fleet and every remote client agree on
    ownership from the shard-id list alone. ``replicas`` virtual
    points per shard keep the arcs even; add/remove moves only the
    keys on the changed arcs (~1/N of the space).
    """

    def __init__(self, shard_ids=(), replicas: int = _RING_REPLICAS):
        self.replicas = int(replicas)
        self._points: List[Tuple[int, str]] = []  # sorted (hash, sid)
        self._ids: List[str] = []
        for sid in shard_ids:
            self.add(sid)

    def add(self, shard_id) -> None:
        sid = str(shard_id)
        if sid in self._ids:
            raise ValueError(f"shard {sid!r} already on the ring")
        self._ids.append(sid)
        for i in range(self.replicas):
            bisect.insort(self._points, (_hash64(f"{sid}#{i}"), sid))

    def remove(self, shard_id) -> None:
        sid = str(shard_id)
        if sid not in self._ids:
            raise ValueError(f"shard {sid!r} not on the ring")
        self._ids.remove(sid)
        self._points = [p for p in self._points if p[1] != sid]

    @property
    def shard_ids(self) -> List[str]:
        return list(self._ids)

    def owner(self, path: Path) -> str:
        """The shard owning ``path`` (first ring point clockwise of
        the key's hash)."""
        if not self._points:
            raise ValueError("empty ring")
        h = _hash64("/".join(path))
        i = bisect.bisect_right(self._points, (h, "￿"))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def assignment(self, paths) -> Dict[str, List[Path]]:
        """``{shard_id: [paths]}`` — every shard present, even when
        empty (a fresh shard owns no keys until one hashes to it)."""
        out: Dict[str, List[Path]] = {sid: [] for sid in self._ids}
        for path in paths:
            out[self.owner(tuple(path))].append(tuple(path))
        return out


class StaticFleetView:
    """A fixed shard map for clients of a fleet that never reshapes
    (tests, single-host bench rigs)."""

    def __init__(self, shards: Mapping[Any, str],
                 replicas: int = _RING_REPLICAS):
        self._doc = {
            "ring_version": 1,
            "replicas": int(replicas),
            "shards": {str(s): url for s, url in shards.items()},
        }

    def describe(self) -> Dict[str, Any]:
        return self._doc


class HttpFleetView:
    """Fleet topology fetched from any shard's (or the gateway's)
    ``/fleet.json`` — the remote-worker discovery path."""

    def __init__(self, url: str, timeout: float = 5.0):
        self._transport = BinaryTransport(url, quant=None, timeout=timeout)

    def describe(self) -> Dict[str, Any]:
        return self._transport.fetch_json("/fleet.json")

    def close(self) -> None:
        self._transport.close()


class _ShardClient:
    __slots__ = ("sid", "transport", "have", "epoch", "first_fail",
                 "synced")

    def __init__(self, sid: str, transport: BinaryTransport):
        self.sid = sid
        self.transport = transport
        self.have = -1                 # last version pulled from this shard
        self.epoch: Optional[int] = None  # slot boot nonce last seen
        self.first_fail: Optional[float] = None  # degrade-window start
        # True once this shard's leaves have merged into the cache at
        # least once. NOT derivable from `have` — an epoch resync
        # resets have to -1 while the cache stays fully populated.
        self.synced = False


class ShardedTransport:
    """Scatter/gather hogwild transport over a param-server fleet.

    Worker-owned like :class:`BinaryTransport` (per-worker
    connections, residuals, and leaf cache); the internal fan-out
    threads touch disjoint shards (and disjoint leaf-cache keys), so
    the tensor path is lock-free — only the shared stats counters
    take a lock.

    ``fleet`` is anything with ``describe() ->`` the ``/fleet.json``
    document (a :class:`~sparktorch_tpu.serve.fleet.ParamServerFleet`
    in-process, an :class:`HttpFleetView` remotely, or a
    :class:`StaticFleetView`). ``quant`` compresses pushes (bf16
    default / int8+EF); ``pull_quant='int8'`` asks the fleet for int8
    DELTA pulls with server-side error feedback — halving the
    dominant pull direction again on top of the delta savings.
    ``grace_s`` bounds how long a dead shard degrades the gang before
    it fails the worker.
    """

    def __init__(self, fleet, quant: Optional[str] = "bf16",
                 pull_quant: Optional[str] = None,
                 error_feedback: bool = True,
                 grace_s: float = 30.0,
                 parallel_fan: Optional[bool] = None,
                 telemetry=None, run_id: Optional[str] = None,
                 **transport_kwargs):
        if pull_quant not in (None, "int8"):
            raise ValueError(f"pull_quant {pull_quant!r}; use None or 'int8'")
        self._fleet = fleet
        self.quant = quant
        self.pull_quant = pull_quant
        self.error_feedback = error_feedback
        self.grace_s = float(grace_s)
        # Fan-out strategy: thread-parallel requests only pay off when
        # the per-shard wire wait dominates (remote shards, big
        # fleets) — on a local fleet the executor's wakeup latency
        # under a busy GIL COSTS more than the overlapped RTTs save
        # (measured: sequential fan halves swarm p99 on loopback).
        # None = auto by fleet size at request time.
        self.parallel_fan = parallel_fan
        self.telemetry = telemetry
        self.run_id = run_id
        # Dead-shard probes must fail INSIDE the grace window, not
        # after the single-server wire's generous defaults — and that
        # includes the per-attempt socket timeouts: the reconnect
        # deadline is only checked BETWEEN attempts, so a wedged shard
        # (connection accepted, no reply) is bounded by pull_timeout,
        # not deadline_s. Keep deadline_s > pull_timeout (the
        # transport's documented invariant: a healthy slow pull is
        # never killed mid-request by the deadline). Deltas are small;
        # a fleet serving huge frames over slow links should raise
        # grace_s (all four knobs scale with it) or override directly.
        transport_kwargs.setdefault("retries", 2)
        transport_kwargs.setdefault("pull_timeout", max(1.0, grace_s / 3))
        transport_kwargs.setdefault(
            "timeout", min(10.0, max(1.0, grace_s / 3)))
        transport_kwargs.setdefault("deadline_s", max(1.0, grace_s / 2))
        self._transport_kwargs = transport_kwargs
        self._clients: Dict[str, _ShardClient] = {}
        self._ring: Optional[HashRing] = None
        self._ring_version = -1
        # ONE push-residual store for the whole fleet, keyed by leaf
        # PATH and injected into every per-shard transport. Residuals
        # follow the leaf, not the shard: when add/drain migrates a
        # leaf to a new owner, its accumulated quantization noise
        # folds into the next push to the NEW shard instead of
        # orphaning one window's worth in the old transport. Fan-out
        # threads touch disjoint paths (ring ownership), so the dict
        # needs no lock.
        self._push_residuals: Optional[Dict[Path, np.ndarray]] = (
            {} if (error_feedback and quant is not None) else None
        )
        self._leaves: Dict[Path, np.ndarray] = {}
        self._executor: Optional[ThreadPoolExecutor] = None
        self._own = self._fresh_own()
        # Guards _own counters touched from fan-out threads (the dict
        # slots are shared even though the SHARDS are disjoint).
        self._own_lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        self._refresh()

    # -- stats (the hogwild budget contract) -------------------------------

    @staticmethod
    def _fresh_own() -> dict:
        st = _new_phase_stats()
        st.update({"reconnects": 0, "shards": 0, "shard_failures": 0,
                   "pushes_skipped": 0, "delta_leaves": 0})
        return st

    @property
    def stats(self) -> dict:
        """Aggregated view: fan-out WALL times measured here (summing
        the per-shard walls would overstate parallel time), byte and
        reconnect counters summed from the per-shard transports."""
        out = dict(self._own)
        out["shards"] = len(self._clients)
        for c in self._clients.values():
            ct = c.transport.stats
            out["pull_bytes"] += ct.get("pull_bytes", 0)
            out["push_bytes"] += ct.get("push_bytes", 0)
            out["reconnects"] += ct.get("reconnects", 0)
        return out

    @stats.setter
    def stats(self, value) -> None:
        # The worker loop installs a fresh dict per round; reset the
        # per-shard transports too so bytes aren't double-counted.
        self._own = self._fresh_own()
        for c in self._clients.values():
            c.transport.stats = _new_phase_stats()

    # -- topology ----------------------------------------------------------

    def _refresh(self) -> None:
        """(Re)build the ring + per-shard clients from the fleet's
        topology document. Existing clients (and their connections,
        residuals, have-versions) survive; removed shards close."""
        with self._refresh_lock:
            doc = self._fleet.describe()
            version = int(doc.get("ring_version", 0))
            if version == self._ring_version and self._clients:
                return
            shards: Dict[str, str] = {
                str(s): u for s, u in (doc.get("shards") or {}).items()
            }
            ring = HashRing(replicas=int(doc.get("replicas",
                                                 _RING_REPLICAS)))
            for sid in shards:
                ring.add(sid)
            for sid in list(self._clients):
                if sid not in shards:
                    self._clients.pop(sid).transport.close()
            for sid, url in shards.items():
                if sid not in self._clients:
                    self._clients[sid] = _ShardClient(
                        sid,
                        BinaryTransport(
                            url, quant=self.quant,
                            error_feedback=self.error_feedback,
                            telemetry=self.telemetry, run_id=self.run_id,
                            residuals=self._push_residuals,
                            **self._transport_kwargs,
                        ),
                    )
            self._ring = ring
            self._ring_version = version
            if self._executor is not None:
                self._executor.shutdown(wait=False)
                self._executor = None

    def _pool(self) -> ThreadPoolExecutor:
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=max(1, len(self._clients)),
                thread_name_prefix="sharded-transport",
            )
        return self._executor

    def _fan(self, fn, items: list) -> list:
        """Apply ``fn`` across shards: thread-parallel for big/remote
        fleets, sequential over the keep-alive connections otherwise
        (see ``parallel_fan``)."""
        parallel = (self.parallel_fan if self.parallel_fan is not None
                    else len(items) > 4)
        if parallel and len(items) > 1:
            return list(self._pool().map(fn, items))
        return [fn(item) for item in items]

    def _count(self, name: str, labels: Optional[dict] = None) -> None:
        if self.telemetry is not None:
            self.telemetry.counter(name, labels=labels or {})

    def _tracer(self):
        """This transport's rpc tracer (the worker bus's), resolved
        once — re-resolving through the registry's global lock per
        shard hop would make fan-out threads contend on it. The
        sharded fan-out owns the REQUEST root: one ``pull``/``push``
        root span per operation, one ``shard_*`` child per shard hop,
        with the per-shard BinaryTransports only propagating."""
        tracer = getattr(self, "_tracer_cached", None)
        if tracer is None:
            from sparktorch_tpu.obs.rpctrace import tracer_for

            tracer = self._tracer_cached = tracer_for(self.telemetry)
        return tracer

    # -- fault degradation -------------------------------------------------

    def _degrade(self, client: _ShardClient, exc: BaseException,
                 op: str) -> None:
        """A shard failed one operation: degrade (freeze its leaves /
        drop its partial) inside the grace window, fail the worker
        beyond it. Counted either way — silent brown-outs are how
        sharded systems rot."""
        now = time.monotonic()
        if client.first_fail is None:
            client.first_fail = now
        with self._own_lock:
            self._own["shard_failures"] += 1
        self._count("sharded_shard_failures_total",
                    {"shard": client.sid, "op": op})
        if now - client.first_fail > self.grace_s:
            raise TransportError(
                f"shard {client.sid} dead past the {self.grace_s}s grace "
                f"window ({op})"
            ) from exc

    # -- hogwild transport contract ----------------------------------------

    def pull(self, have_version: int):
        """Fan a delta pull across every shard, merge the advanced
        leaves into the cached tree, and return ``(version, tree)``
        when anything moved — None when every shard said 304. The
        composite version is the sum of shard versions (what the
        worker hands back; the real freshness state is per-shard)."""
        st = self._own
        t0 = time.perf_counter()
        clients = list(self._clients.values())
        with self._tracer().root_span("pull", kind="client",
                                      shards=len(clients)) as root:
            results = self._fan(
                lambda c: self._pull_shard(c, root.ctx), clients)
        st["pull_s"] += time.perf_counter() - t0
        st["pulls"] += 1
        fresh = any(r and r.get("fresh") for r in results)
        ring_versions = [r["ring_version"] for r in results
                         if r and r.get("ring_version") is not None]
        if ring_versions and max(ring_versions) > self._ring_version:
            self._refresh()
        version = sum(c.have for c in self._clients.values() if c.have > 0)
        if not fresh:
            # A from-scratch caller (have_version < 0: a supervisor-
            # RESTARTED worker reusing this transport, or a new round)
            # must get parameters even when every shard said 304 — the
            # cached assembled tree IS the current state as of this
            # sweep. Without this, a restarted worker's first pull
            # returns None and it trains on params=None.
            if (not callable(have_version) and int(have_version) < 0
                    and self._leaves):
                st["pull_fresh"] += 1
                return version, wire.unflatten_tree(
                    list(self._leaves.items()))
            return None
        st["pull_fresh"] += 1
        return version, wire.unflatten_tree(list(self._leaves.items()))

    def _pull_shard(self, client: _ShardClient,
                    trace_parent=None) -> Optional[dict]:
        # Client-observed per-shard hop latency, as a HISTOGRAM: this
        # is where a straggling shard actually shows (server-side
        # wire_latency_s times the handler, not the wire — a
        # network/queueing delay lands here and only here), which
        # makes it the series the collector's hot-shard alert rules
        # watch for sustained p99 breaches.
        hop_t0 = time.perf_counter()
        try:
            return self._pull_shard_inner(client, trace_parent)
        finally:
            if self.telemetry is not None:
                self.telemetry.observe("sharded.shard_pull_latency_s",
                                       time.perf_counter() - hop_t0,
                                       labels={"shard": client.sid})

    def _pull_shard_inner(self, client: _ShardClient,
                          trace_parent=None) -> Optional[dict]:
        with self._tracer().child_span("shard_pull", trace_parent,
                                       kind="client",
                                       shard=client.sid) as tsp:
            # tsp.ctx when this hop records; else the ROOT's context
            # (possibly the shared unsampled one) so the per-shard
            # transport propagates the root's sampling decision
            # instead of minting an independent root per shard — a
            # 99%-unsampled sharded pull must not fill the ring with
            # shard-level "requests" (or trip the SLO hatch per hop).
            hop_ctx = tsp.ctx or trace_parent
            try:
                res = client.transport.pull_delta(lambda: client.have,
                                                  quant=self.pull_quant,
                                                  _trace=hop_ctx)
                epoch = res.get("epoch")
                if (epoch is not None and client.epoch is not None
                        and epoch != client.epoch):
                    # The shard's slot was rebuilt (restart, re-add):
                    # its version counter restarted, so our
                    # have-version is meaningless — full resync from -1.
                    client.have = -1
                    self._count("sharded_epoch_resyncs_total",
                                {"shard": client.sid})
                    res = client.transport.pull_delta(
                        lambda: client.have, quant=self.pull_quant,
                        _trace=hop_ctx)
                    epoch = res.get("epoch")
                if epoch is not None:
                    client.epoch = epoch
            except (TransportError, wire.WireError, OSError) as e:
                tsp.set_error(e)
                if not client.synced:
                    # Never synced: there are no cached leaves to
                    # freeze, so "degrading" would hand the worker a
                    # PARTIAL tree (missing this shard's ~1/N of the
                    # model) and crash it inside flax instead. Fail the
                    # pull loudly; the worker (or its supervisor)
                    # retries after the monitor's restart. (A dedicated
                    # flag, not have<0: an epoch resync resets `have`
                    # while the cache stays complete — a flaky resync
                    # retry must take the grace-window path like any
                    # other mid-run failure.)
                    raise TransportError(
                        f"shard {client.sid} unreachable before its "
                        f"first sync — no cached leaves to degrade to"
                    ) from e
                self._degrade(client, e, "pull")
                # The hop stays IN the trace, closed with error status
                # and marked degraded: a grace-window brown-out must be
                # visible in the request tree, not an absent branch.
                tsp.annotate(degraded=True)
                return None
            client.first_fail = None
            if res.get("fresh"):
                client.have = int(res["version"])
                client.synced = True
                with self._own_lock:
                    self._own["delta_leaves"] += len(res["leaves"])
                # Disjoint key ranges per shard: concurrent merges from
                # the fan-out threads never write the same path.
                self._leaves.update(res["leaves"])
            return res

    def push(self, grads) -> None:
        """Split the gradient tree by ring ownership and scatter the
        partial trees to their shards in parallel. Quantization
        residuals live in ONE path-keyed store shared by every shard
        transport (see ``_push_residuals``), so error feedback stays
        exact per tensor even across a reshard that migrates the leaf
        to a different owner."""
        st = self._own
        t0 = time.perf_counter()
        host = _tree_to_host(grads)
        flat = dict(wire.flatten_tree(host))
        groups = self._ring.assignment(flat)
        t1 = time.perf_counter()
        st["push_materialize_s"] += t1 - t0

        def _push_one(item, trace_parent=None) -> None:
            sid, paths = item
            if not paths:
                return
            client = self._clients[sid]
            partial = wire.unflatten_tree([(p, flat[p]) for p in paths])
            with self._tracer().child_span("shard_push", trace_parent,
                                           kind="client",
                                           shard=sid) as tsp:
                try:
                    # Root ctx fallback like _pull_shard: an unsampled
                    # request must suppress per-shard root minting.
                    client.transport.push(partial,
                                          _trace=tsp.ctx or trace_parent)
                    client.first_fail = None
                except (TransportError, wire.WireError, OSError) as e:
                    # Hogwild tolerates a lost gradient partial the
                    # same way it tolerates staleness; a shard in its
                    # grace window costs updates, not the run.
                    tsp.set_error(e)
                    tsp.annotate(degraded=True)
                    with self._own_lock:
                        self._own["pushes_skipped"] += 1
                    self._count("sharded_pushes_skipped_total",
                                {"shard": sid})
                    self._degrade(client, e, "push")

        with self._tracer().root_span("push", kind="client",
                                      shards=len(self._clients)) as root:
            self._fan(lambda item: _push_one(item, root.ctx),
                      list(groups.items()))
        st["push_wire_s"] += time.perf_counter() - t1
        st["pushes"] += 1

    def post_loss(self, loss: float) -> bool:
        """Early-stop vote, preferring the lowest-id shard but FAILING
        OVER to the next live one — every shard shares the fleet's
        windowed stopper, so a dead vote shard in its grace window
        must not swallow loss samples (a deferred stop decision and a
        skewed window once it recovers). Returns False only when no
        shard can take the vote."""
        t0 = time.perf_counter()
        out = False
        for sid in sorted(self._clients):
            client = self._clients[sid]
            try:
                out = client.transport.post_loss(loss)
                client.first_fail = None
                break
            except (TransportError, OSError) as e:
                self._degrade(client, e, "post_loss")
        self._own["poll_s"] += time.perf_counter() - t0
        return out

    def alive(self) -> bool:
        self._refresh()
        for client in self._clients.values():
            try:
                if client.transport.alive():
                    return True
            except (TransportError, OSError):
                continue
        return False

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None
        for client in self._clients.values():
            client.transport.close()
