"""Persistent binary-wire client for the hogwild parameter server.

The reference's client (``hogwild.py:31-62``) opens a FRESH TCP
connection per call and ships dill both ways — on the hot loop that
is a connect + slow-start + pickle round-trip per iteration.
:class:`BinaryTransport` replaces all three:

- **keep-alive**: one ``http.client.HTTPConnection`` per worker,
  reused across pulls/pushes (the server speaks HTTP/1.1); a dropped
  connection is redialed with exponential backoff.
- **binary frames** (:mod:`sparktorch_tpu.net.wire`): pushes scatter-
  write the gradient arrays' own memory onto the socket (no pickle,
  no join); pulls decode ``np.frombuffer`` views of the body.
- **version-tagged pulls**: ``X-Have-Version`` + the server's 304
  reply mean an up-to-date worker's pull is a header exchange, never
  a parameter transfer.
- **quantized pushes** with client-side error feedback: ``bf16``
  (default — gradients tolerate the 8-bit mantissa, bytes halve) or
  ``int8`` (4x, DGC-style residual feedback keeps the trajectory
  unbiased).

The interface matches ``train.hogwild``'s transport contract
(``pull`` / ``push`` / ``post_loss`` / ``alive`` / ``stats``), so
worker loops can't tell the wires apart — only the clock can.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import time
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.net import wire

_TIMEOUT = 10.0        # hogwild.py:34-38 parity for push/poll
_PULL_TIMEOUT = 180.0  # full-snapshot pulls get the generous deadline
                       # (see train/hogwild.py:_HTTP_PULL_TIMEOUT)
# Total wall-clock cap on one request's reconnect loop. Without it, a
# DEAD server costs retries x the per-request timeout (3 x 180s on the
# pull path) before the worker learns anything. Must exceed ONE pull
# timeout — the deadline is only checked between attempts, never
# mid-request, so a healthy slow pull is never killed by it.
_RECONNECT_DEADLINE = 240.0


def _new_phase_stats() -> dict:
    """Same accounting dict as ``train.hogwild._new_phase_stats`` —
    duplicated here (not imported) so net/ never imports train/."""
    return {
        "pull_s": 0.0, "pull_bytes": 0, "pulls": 0, "pull_fresh": 0,
        "push_wire_s": 0.0, "push_materialize_s": 0.0,
        "push_bytes": 0, "pushes": 0,
        "poll_s": 0.0,
        "reconnects": 0,  # redials after a connection-level failure
    }


class TransportError(RuntimeError):
    """The server answered with an unexpected status, or stayed
    unreachable through every retry."""


class BinaryTransport:
    """Zero-copy binary client for one hogwild worker.

    Not thread-safe by design: each worker owns its transport (and
    therefore its connection and its error-feedback residuals), like
    the dill ``HttpTransport`` before it.
    """

    def __init__(self, url: str, quant: Optional[str] = "bf16",
                 error_feedback: bool = True,
                 timeout: float = _TIMEOUT,
                 pull_timeout: float = _PULL_TIMEOUT,
                 retries: int = 3, backoff_s: float = 0.05,
                 deadline_s: Optional[float] = _RECONNECT_DEADLINE,
                 telemetry=None, run_id: Optional[str] = None,
                 residuals: Optional[Dict[Tuple[str, ...],
                                          np.ndarray]] = None):
        parts = urlsplit(url if "//" in url else f"http://{url}")
        if parts.scheme not in ("", "http"):
            raise ValueError(f"BinaryTransport speaks http only, got {url!r}")
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port or 80
        if quant not in (None, "bf16", "int8"):
            raise ValueError(f"quant {quant!r}; use None, 'bf16' or 'int8'")
        self.quant = quant
        # Error-feedback residuals, path -> np.ndarray. bf16's residual
        # is small but free to track; int8 genuinely needs it.
        # ``residuals`` lets an owner inject a SHARED path-keyed store:
        # the sharded fan-out keys residuals by leaf path at the
        # ShardedTransport level, so a leaf that migrates between
        # shards on add/drain keeps its accumulated noise instead of
        # orphaning it in the old shard's transport.
        self._residuals: Optional[Dict[Tuple[str, ...], np.ndarray]] = (
            residuals if residuals is not None
            else ({} if (error_feedback and quant is not None) else None)
        )
        self.timeout = timeout
        self.pull_timeout = pull_timeout
        self.retries = max(1, retries)
        self.backoff_s = backoff_s
        # Reconnect-loop wall-clock cap: a dead server fails fast with
        # a clear error instead of spending retries x request-timeout.
        # None = uncapped (the pre-deadline behavior).
        self.deadline_s = deadline_s
        self.telemetry = telemetry
        # Run-ID correlation (16-bit tag in the frame header's reserved
        # bytes): every push this worker sends names its gang run, and
        # a pulled frame carrying a DIFFERENT nonzero tag — a worker
        # pointed at another run's server — is counted and warned, not
        # silently trained on.
        from sparktorch_tpu.obs.collector import run_tag as _rt

        self.run_tag = _rt(run_id)
        self.stats = _new_phase_stats()
        self._conn: Optional[http.client.HTTPConnection] = None

    def _rpct(self):
        """The rpctrace module, imported lazily (net/ stays importable
        without dragging the obs package in at module load) and cached
        per transport."""
        mod = getattr(self, "_rpctrace_mod", None)
        if mod is None:
            from sparktorch_tpu.obs import rpctrace as mod

            self._rpctrace_mod = mod
        return mod

    def _tracer(self):
        """This transport's tracer, resolved ONCE: the bus is fixed
        for the transport's life, and re-resolving through the global
        registry's lock per request would put a process-wide lock hop
        on the exact hot path the overhead gate bounds."""
        tracer = getattr(self, "_tracer_cached", None)
        if tracer is None:
            tracer = self._tracer_cached = self._rpct().tracer_for(
                self.telemetry)
        return tracer

    @contextlib.contextmanager
    def _trace_root(self, name: str, trace):
        """Yield the span context this request propagates: the
        caller's, when one was handed down (a ShardedTransport owns
        the per-shard hop span and this transport only propagates),
        else a freshly minted ROOT — a worker-side push/pull against a
        single server is itself the request."""
        if trace is not None:
            yield trace
            return
        with self._tracer().root_span(name, kind="client",
                                      host=self.host,
                                      port=self.port) as sp:
            yield sp.ctx

    def _trace_header(self, headers: Dict[str, str], ctx) -> Dict[str, str]:
        """Inject ``X-Trace-Context`` for sampled requests (head-based
        sampling: unsampled requests must cost the server nothing)."""
        if ctx is not None and ctx.sampled:
            headers[self._rpct().TRACE_HEADER] = ctx.to_header()
        return headers

    def _count_reconnect(self) -> None:
        self.stats["reconnects"] = self.stats.get("reconnects", 0) + 1
        tele = self.telemetry
        if tele is None:
            from sparktorch_tpu.obs import get_telemetry

            tele = self.telemetry = get_telemetry()
        tele.counter("transport_reconnects_total",
                     labels={"host": self.host, "port": self.port})

    # -- connection management --------------------------------------------

    def _connection(self, timeout: float) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout
            )
        else:
            # Reuse the kept-alive socket; only the deadline changes.
            self._conn.timeout = timeout
            if self._conn.sock is not None:
                self._conn.sock.settimeout(timeout)
        return self._conn

    def _drop_connection(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def close(self) -> None:
        self._drop_connection()

    def _request(self, method: str, path: str, body=None,
                 headers=None,
                 timeout: float = _TIMEOUT,
                 retry_on_timeout: bool = False
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """One request over the persistent connection, with reconnect +
        exponential backoff on connection-level failures. Returns
        ``(status, body, reply_headers)``.

        ``headers`` may be a dict or a CALLABLE re-evaluated on every
        attempt: a retried pull must re-read its live version state at
        send time, not replay the value captured before the first
        attempt — between a failed send and its reconnect the client's
        merged state can advance, and replaying the stale
        ``X-Have-Version`` would make the server re-ship (or worse,
        304-skip) tensors the client already holds.

        Timeouts retry only when the caller marks the request
        IDEMPOTENT (pulls/polls): a timed-out POST may have completed
        server-side, and re-sending would double-apply a gradient.
        A connection REFUSED/RESET before the response, by contrast,
        is always safe to retry — including the keep-alive race where
        the server closed an idle socket as we wrote to it.
        """
        retriable: tuple = (ConnectionError, http.client.HTTPException,
                            OSError)
        last: Optional[BaseException] = None
        t_start = time.monotonic()
        for attempt in range(self.retries):
            if (attempt > 0 and self.deadline_s is not None
                    and time.monotonic() - t_start > self.deadline_s):
                raise TransportError(
                    f"{method} {path}: reconnect deadline "
                    f"({self.deadline_s}s) exceeded after {attempt} "
                    f"attempts — server unreachable"
                ) from last
            conn = self._connection(timeout)
            try:
                act = _chaos.fire("transport.request", method=method,
                                  path=path, attempt=attempt)
                if act and act.get("drop"):
                    # Injected connection loss: fail THIS attempt the
                    # way a server-closed keep-alive socket would, so
                    # the real reconnect+backoff path runs.
                    raise ConnectionResetError("chaos: connection dropped")
                hdrs = headers() if callable(headers) else (headers or {})
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()  # drain so the connection is reusable
                return resp.status, data, dict(resp.headers)
            except TimeoutError as e:
                self._drop_connection()
                last = e
                if not retry_on_timeout:
                    raise
            except retriable as e:
                self._drop_connection()
                last = e
            self._count_reconnect()
            if attempt + 1 < self.retries:
                time.sleep(self.backoff_s * (2 ** attempt))
        raise TransportError(
            f"{method} {path} failed after {self.retries} attempts"
        ) from last

    # -- hogwild transport contract ---------------------------------------

    def _check_run_tag(self, body) -> None:
        frame_tag = wire.frame_run_tag(body)
        if frame_tag and self.run_tag and frame_tag != self.run_tag:
            tele = self.telemetry
            if tele is None:
                from sparktorch_tpu.obs import get_telemetry

                tele = self.telemetry = get_telemetry()
            tele.counter("transport_run_tag_mismatches_total",
                         labels={"host": self.host, "port": self.port})

    def pull(self, have_version, _trace=None):
        """``(version, params)`` newer than ``have_version``, or None
        when the server's snapshot is not newer (its 304 reply — the
        ETag-style exchange that costs ~100 header bytes, not a model).

        ``have_version`` may be a CALLABLE returning the live value:
        it is re-read on every reconnect attempt (see ``_request``).
        ``_trace`` (a sampled SpanContext) propagates a caller-owned
        request trace instead of minting a root here."""
        st = self.stats
        with self._trace_root("pull", _trace) as tctx:
            t0 = time.perf_counter()
            status, body, _ = self._request(
                "GET", "/parameters.bin",
                headers=lambda: self._trace_header(
                    {"X-Have-Version": str(int(
                        have_version() if callable(have_version)
                        else have_version
                    ))}, tctx),
                timeout=self.pull_timeout, retry_on_timeout=True,
            )
            st["pull_s"] += time.perf_counter() - t0
            st["pulls"] += 1
            if status == 304:
                return None
            if status != 200:
                raise TransportError(f"/parameters.bin -> {status}")
            st["pull_fresh"] += 1
            st["pull_bytes"] += len(body)
            self._check_run_tag(body)
            version, tree = wire.decode(body)
            return version, tree

    def pull_delta(self, have_version,
                   quant: Optional[str] = None,
                   _trace=None) -> Dict[str, Any]:
        """Per-tensor delta pull from the fleet's ``/delta.bin`` route.

        ``have_version`` (int or callable, re-read per reconnect
        attempt) is the client's last version FROM THIS SERVER; the
        reply carries only leaves whose per-tensor version advanced.
        ``quant='int8'`` asks the server for int8 leaves with
        server-side error feedback (the reply dequantizes here).

        Returns a dict: ``fresh`` (False on 304), ``version``,
        ``leaves`` (``{path: array}``), ``leaf_versions``, ``nbytes``,
        plus the resync metadata every reply carries — ``epoch`` (the
        server slot's boot nonce; a change means the server state was
        rebuilt and the client must re-pull from -1) and
        ``ring_version`` (bumped on shard add/drain; a change means
        refresh the shard map).
        """
        st = self.stats
        with self._trace_root("pull", _trace) as tctx:
            t0 = time.perf_counter()

            def _headers() -> Dict[str, str]:
                hv = have_version() if callable(have_version) \
                    else have_version
                h = {"X-Have-Version": str(int(hv))}
                if quant:
                    h["X-Pull-Quant"] = quant
                return self._trace_header(h, tctx)

            status, body, rhdrs = self._request(
                "GET", "/delta.bin", headers=_headers,
                timeout=self.pull_timeout, retry_on_timeout=True,
            )
            st["pull_s"] += time.perf_counter() - t0
            st["pulls"] += 1
            out: Dict[str, Any] = {
                "fresh": False, "version": None, "leaves": {},
                "leaf_versions": {}, "nbytes": 0,
                "epoch": _int_header(rhdrs, "X-Slot-Epoch"),
                "ring_version": _int_header(rhdrs, "X-Ring-Version"),
            }
            if status == 304:
                return out
            if status != 200:
                raise TransportError(f"/delta.bin -> {status}")
            st["pull_fresh"] += 1
            st["pull_bytes"] += len(body)
            self._check_run_tag(body)
            version, leaves, leaf_versions = wire.decode_delta(body)
            out.update(fresh=True, version=version, leaves=leaves,
                       leaf_versions=leaf_versions, nbytes=len(body))
            return out

    def fetch_json(self, path: str, timeout: Optional[float] = None) -> Any:
        """GET + parse a small JSON control route (``/fleet.json``)
        over the SAME keep-alive connection and retry discipline as
        the data wire."""
        status, body, _ = self._request(
            "GET", path, timeout=timeout or self.timeout,
            retry_on_timeout=True,
        )
        if status != 200:
            raise TransportError(f"{path} -> {status}")
        try:
            return json.loads(body)
        except ValueError as e:
            raise TransportError(f"{path}: invalid JSON: {e}") from e

    def push(self, grads, _trace=None) -> None:
        """Encode (optionally quantize with error feedback) and POST
        the gradient tree. The materialize fence is timed apart from
        the wire, matching the dill transport's honest accounting.
        A sampled trace context (minted here, or handed down via
        ``_trace``) rides the frame's header extension, with the
        ENCODE (materialize+quantize+frame) and SOCKET halves
        attributed as separate child spans."""
        st = self.stats
        tracer = self._tracer()
        with self._trace_root("push", _trace) as tctx:
            t0 = time.perf_counter()
            # np.asarray FENCES the device: the gradient compute drains
            # here, so this term is compute+download, and the request
            # below is pure wire + server apply.
            with tracer.child_span("encode", tctx, kind="internal") as _sp:
                host = _tree_to_host(grads)
                if self.quant is not None:
                    leaves, _ = wire.quantize_tree(host, self.quant,
                                                   self._residuals)
                else:
                    leaves = wire.flatten_tree(host)
                buffers = wire.encode(leaves, run_tag=self.run_tag,
                                      trace=tctx)
            nbytes = wire.frame_nbytes(buffers)
            t1 = time.perf_counter()
            st["push_materialize_s"] += t1 - t0
            # The buffer LIST (not an iterator): http.client scatter-
            # sends each part, and a connection-level retry can
            # re-iterate it — an exhausted iterator would under-send
            # the declared length.
            with tracer.child_span("socket", tctx, kind="internal",
                                   host=self.host, port=self.port):
                status, _, _ = self._request(
                    "POST", "/update.bin", body=buffers,
                    headers={"Content-Length": str(nbytes),
                             "Content-Type": wire.CONTENT_TYPE},
                    timeout=self.timeout,
                )
            if status != 200:
                raise TransportError(f"/update.bin -> {status}")
            st["push_wire_s"] += time.perf_counter() - t1
            st["push_bytes"] += nbytes
            st["pushes"] += 1

    def post_loss(self, loss: float) -> bool:
        """Early-stop vote; JSON (the one non-tensor exchange — tiny,
        and keeping it readable beats keeping it binary)."""
        t0 = time.perf_counter()
        payload = json.dumps({"loss": float(loss)}).encode()
        status, body, _ = self._request(
            "POST", "/losses.json", body=payload,
            headers={"Content-Type": "application/json"},
            timeout=self.timeout,
        )
        if status != 200:
            raise TransportError(f"/losses.json -> {status}")
        self.stats["poll_s"] += time.perf_counter() - t0
        return bool(json.loads(body)["stop"])

    def alive(self) -> bool:
        status, _, _ = self._request("GET", "/", timeout=self.timeout,
                                     retry_on_timeout=True)
        return status == 200


def _int_header(headers: Dict[str, str], name: str) -> Optional[int]:
    """Parse an int reply header; None when absent or garbled (an old
    server that doesn't send it must read as 'unknown', not 0)."""
    raw = headers.get(name)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _tree_to_host(tree: Any):
    """Materialize device arrays to host numpy, preserving structure.
    Kept jax-optional: plain numpy trees pass through without
    importing jax (bench_wire runs device-free)."""
    try:
        import jax

        return jax.tree.map(lambda a: np.asarray(a), tree)
    except ImportError:  # pragma: no cover - jax always present in-repo
        if isinstance(tree, dict):
            return {k: _tree_to_host(v) for k, v in tree.items()}
        return np.asarray(tree)
