"""sparktorch_tpu.net — the binary wire subsystem.

A framed zero-copy tensor protocol (:mod:`~sparktorch_tpu.net.wire`)
and a persistent keep-alive client (:class:`BinaryTransport`) that
replace dill on the hogwild parameter-server hot path. See the
README's "Networking" section for the frame layout and semantics.
"""

from sparktorch_tpu.net.wire import (
    CONTENT_TYPE as WIRE_CONTENT_TYPE,
    QuantLeaf,
    WireError,
    decode,
    encode,
    flatten_tree,
    frame_bytes,
    frame_nbytes,
    quantize_tree,
    tree_nbytes,
    unflatten_tree,
)
from sparktorch_tpu.net.transport import BinaryTransport, TransportError
from sparktorch_tpu.net.sharded import (
    HashRing,
    HttpFleetView,
    ShardedTransport,
    StaticFleetView,
)

__all__ = [
    "HashRing",
    "HttpFleetView",
    "ShardedTransport",
    "StaticFleetView",
    "WIRE_CONTENT_TYPE",
    "QuantLeaf",
    "WireError",
    "decode",
    "encode",
    "flatten_tree",
    "frame_bytes",
    "frame_nbytes",
    "quantize_tree",
    "tree_nbytes",
    "unflatten_tree",
    "BinaryTransport",
    "TransportError",
]
