"""Framed zero-copy binary tensor wire format.

The reference ships every hogwild push/pull as a dill blob
(``hogwild.py:31-62``): each call pickles the full tree (one memcpy
per array plus pickle-machine overhead per node) and unpickles it on
the far side (another memcpy per array). This module replaces that
with a self-describing frame whose payload IS the tensors' memory:

    offset  size  field
    0       4     magic  b"STWR"
    4       1     wire format version (1)
    5       1     flags (reserved, 0)
    6       2     run tag (uint16 LE; 0 = untagged) — the 16-bit
                  correlation tag of the gang run_id
                  (:func:`sparktorch_tpu.obs.collector.run_tag`), so
                  every frame on the wire names the run it belongs to
                  and a server can flag cross-run traffic. Pre-run-id
                  encoders wrote 0 here (the field was reserved), so
                  old frames parse as untagged.
    8       8     snapshot version tag (int64 LE; -1 = untagged)
    16      4     table length in bytes (uint32 LE)
    20      8     payload length in bytes (uint64 LE)
    28      ...   table: UTF-8 JSON list of per-tensor entries
    28+T    ...   payload: raw C-contiguous little-endian buffers

The table mirrors the tree: interior nodes are JSON objects (each
dict key travels ONCE, like pickle's memo — the table stays smaller
than dill's per-array overhead), leaves are ``[dtype-str, shape]``
(plus ``{"scale": s, "d": dequant-dtype}`` for int8-quantized
tensors). Wire version 2 — the DELTA frame the sharded fleet's
``/delta.bin`` route serves — extends each leaf entry to
``[dtype, shape, quant-or-null, leaf_version]``: a per-tensor version
tag beside the frame's global snapshot version, so a pull can ship
only the tensors whose version advanced and the client can merge them
into its cached tree. Version-1 frames stay byte-identical (old
decoders never see a v2 frame unless they ask the delta route for
one, and then they fail loudly on the version byte — the
mixed-version-gang story rides the unchanged v1 wire).
Offsets are implicit: payload buffers are laid out in the
table's depth-first traversal order, which JSON preserves. Encoding
never copies tensor bytes: :func:`encode` returns the header plus
``memoryview``s of the arrays themselves, ready for scatter-write
onto a socket. Decoding is ``np.frombuffer`` views into the received
body — zero copies until ``jax.device_put`` uploads to HBM.

Trees are nested string-keyed mappings of array leaves — exactly the
shape of Flax param/grad pytrees. Paths travel as JSON lists, so keys
containing any delimiter round-trip untouched.

Quantized pushes (:func:`quantize_tree`) implement the
error-feedback scheme of Deep Gradient Compression (Lin et al.,
2018) / 1-bit SGD: the quantization residual is kept client-side and
added to the next push, so the compression error averages out over
steps instead of accumulating as bias.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

try:  # jax's numpy dtype extensions (bfloat16); always present with jax
    import ml_dtypes
    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - jax deps always ship ml_dtypes
    ml_dtypes = None
    _BFLOAT16 = None

MAGIC = b"STWR"
WIRE_VERSION = 1
# Delta frames: same header/payload layout, but leaf table entries
# carry a 4th element (the per-tensor version tag) and the tree may be
# PARTIAL (only the advanced leaves). A separate wire version so v1
# decoders reject delta frames loudly instead of mis-merging them.
WIRE_VERSION_DELTA = 2
# magic, version, flags, run tag, snapshot version, table len, payload len
_HEADER = struct.Struct("<4sBBHqIQ")
HEADER_SIZE = _HEADER.size

# Flags bit 0: a TRACE-CONTEXT extension follows the fixed header —
# 16 bytes trace_id + 8 bytes span_id + 1 flag byte (bit 0 = sampled),
# the distributed-RPC span context of the request this frame belongs
# to (:mod:`sparktorch_tpu.obs.rpctrace`). Versioned alongside the
# run-tag bytes: untraced frames carry flags=0 and stay BYTE-IDENTICAL
# to the pre-trace wire; a pre-trace decoder handed a traced frame
# fails loudly on its length check (the table offset moved) instead of
# mis-reading tensors — the same posture as v1-vs-v2 delta frames.
FLAG_TRACE = 0x01
_TRACE_EXT = struct.Struct("<16s8sB")
TRACE_EXT_SIZE = _TRACE_EXT.size

CONTENT_TYPE = "application/x-sparktorch-wire"

Buffers = List[Union[bytes, memoryview]]


class WireError(ValueError):
    """Malformed frame: bad magic, truncated body, out-of-bounds table."""


# ---------------------------------------------------------------------------
# Tree <-> leaves
# ---------------------------------------------------------------------------


def _flatten(tree: Any, prefix: Tuple[str, ...],
             out: List[Tuple[Tuple[str, ...], np.ndarray]]) -> None:
    if isinstance(tree, Mapping):
        for k in tree:
            if not isinstance(k, str):
                raise WireError(
                    f"wire trees are string-keyed mappings; got key {k!r}"
                )
            _flatten(tree[k], prefix + (k,), out)
    elif isinstance(tree, (list, tuple)):
        raise WireError(
            "wire trees are nested dicts of arrays; lists/tuples are not "
            f"encodable (at path {'/'.join(prefix) or '<root>'})"
        )
    else:
        out.append((prefix, np.asarray(tree)))


def flatten_tree(tree: Any) -> List[Tuple[Tuple[str, ...], np.ndarray]]:
    """``tree`` -> ordered ``[(path, array), ...]``. A bare array is a
    single leaf with the empty path."""
    out: List[Tuple[Tuple[str, ...], np.ndarray]] = []
    _flatten(tree, (), out)
    return out


def unflatten_tree(leaves: Sequence[Tuple[Tuple[str, ...], Any]]) -> Any:
    if len(leaves) == 1 and leaves[0][0] == ():
        return leaves[0][1]
    tree: Dict[str, Any] = {}
    for path, value in leaves:
        if not path:
            raise WireError("root leaf mixed with pathed leaves")
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = value
    return tree


# ---------------------------------------------------------------------------
# Dtype spelling: explicit little-endian numpy dtype strings on the wire
# ("<f4", "<i4", "|i1", ...); bfloat16 (no numpy letter) by name.
# ---------------------------------------------------------------------------


def _dtype_str(dtype: np.dtype) -> str:
    if _BFLOAT16 is not None and dtype == _BFLOAT16:
        return "bfloat16"
    # .newbyteorder("<") pins native-endian ('=') spellings to explicit
    # LE; 1-byte dtypes keep their '|' marker.
    return dtype.newbyteorder("<").str


def _dtype_of(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BFLOAT16 is None:
            raise WireError("bfloat16 payload but ml_dtypes is unavailable")
        return _BFLOAT16
    try:
        return np.dtype(name)
    except TypeError as e:
        raise WireError(f"unknown wire dtype {name!r}") from e


def _wire_array(arr: np.ndarray) -> np.ndarray:
    """C-contiguous little-endian view/copy of ``arr`` (copy only when
    the source is non-contiguous or big-endian)."""
    # Not ascontiguousarray: that helper promotes 0-d arrays to 1-d,
    # which would corrupt the shape table.
    a = arr if arr.flags.c_contiguous else np.ascontiguousarray(arr)
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    return a


# ---------------------------------------------------------------------------
# Quantization with client-side error feedback
# ---------------------------------------------------------------------------


class QuantLeaf:
    """An int8-quantized leaf: data + scale + the dtype to dequantize
    back into. Produced by :func:`quantize_tree`, consumed by
    :func:`encode`."""

    __slots__ = ("data", "scale", "dequant_dtype")

    def __init__(self, data: np.ndarray, scale: float, dequant_dtype: str):
        self.data = data
        self.scale = float(scale)
        self.dequant_dtype = dequant_dtype


def _is_float(arr: np.ndarray) -> bool:
    if np.issubdtype(arr.dtype, np.floating):
        return True
    return _BFLOAT16 is not None and arr.dtype == _BFLOAT16


def quantize_leaf_int8(
    value: np.ndarray, residual: Optional[np.ndarray] = None
) -> Tuple[QuantLeaf, np.ndarray]:
    """Symmetric per-tensor int8 quantization of ONE float leaf, with
    the error-feedback residual returned to the caller (add it to the
    next quantization of the same leaf). The per-leaf primitive under
    :func:`quantize_tree`, exposed so the fleet's server-side pull
    quantization can keep residuals per (path, version) instead of
    per whole-tree call."""
    value = np.asarray(value, dtype=np.float32)
    if residual is not None:
        value = value + residual
    amax = float(np.max(np.abs(value))) if value.size else 0.0
    scale = amax / 127.0 if amax > 0 else 1.0
    q = np.clip(np.rint(value / scale), -127, 127).astype(np.int8)
    return QuantLeaf(q, scale, "<f4"), value - q.astype(np.float32) * scale


def quantize_tree(
    tree: Any,
    mode: str,
    residuals: Optional[Dict[Tuple[str, ...], np.ndarray]] = None,
) -> Tuple[List[Tuple[Tuple[str, ...], Any]], Dict[Tuple[str, ...], np.ndarray]]:
    """Compress float leaves for the push wire.

    ``mode='bf16'`` casts float leaves to bfloat16 (the TPU's native
    matmul dtype — gradients tolerate the 8-bit mantissa and the bytes
    halve). ``mode='int8'`` quantizes symmetrically to int8 with one
    per-tensor scale (4x smaller than f32).

    When ``residuals`` (a dict the caller owns, initially empty) is
    given, the quantization error of THIS push is stored there and
    added to the NEXT push — error feedback, so compression noise
    averages out over steps instead of biasing the trajectory.
    Integer leaves pass through untouched. Returns ``(leaves,
    residuals)`` ready for :func:`encode`.
    """
    if mode not in ("bf16", "int8"):
        raise ValueError(f"quantize mode {mode!r}; use 'bf16' or 'int8'")
    if mode == "bf16" and _BFLOAT16 is None:
        # Mirror the decode-side guard: astype(None) would silently
        # widen to float64 and DOUBLE the wire bytes.
        raise WireError("bf16 quantization requires ml_dtypes")
    new_residuals: Dict[Tuple[str, ...], np.ndarray] = {}
    leaves: List[Tuple[Tuple[str, ...], Any]] = []
    for path, arr in flatten_tree(tree):
        if not _is_float(arr) or arr.size == 0:
            leaves.append((path, arr))
            continue
        value = np.asarray(arr, dtype=np.float32)
        if residuals is not None and path in residuals:
            value = value + residuals[path]
        if mode == "bf16":
            q = value.astype(_BFLOAT16)
            if residuals is not None:
                new_residuals[path] = value - q.astype(np.float32)
            leaves.append((path, q))
        else:
            # value already carries the residual (added above); pass
            # residual=None so it isn't applied twice.
            qleaf, err = quantize_leaf_int8(value)
            if residuals is not None:
                new_residuals[path] = err
            leaves.append((path, qleaf))
    if residuals is not None:
        # Update, never residuals.clear(): the sharded transport
        # shares ONE path-keyed store across per-shard partial pushes,
        # and a whole-store clear on shard A's partial would wipe
        # shard B's (and every migrated leaf's) accumulated noise.
        # Every float leaf of THIS call lands in new_residuals (floats
        # always quantize; int/empty leaves never hold residuals), so
        # the update alone replaces exactly this call's entries;
        # entries for paths that left the tree go stale and harmless.
        residuals.update(new_residuals)
    return leaves, (residuals if residuals is not None else {})


# ---------------------------------------------------------------------------
# Encode / decode
# ---------------------------------------------------------------------------


def _encode_node(node: Any, table_out: Any, buffers: Buffers,
                 offset: int, prefix: Tuple[str, ...] = (),
                 leaf_versions: Optional[Mapping] = None) -> int:
    """Depth-first walk emitting each leaf's descriptor and buffer in
    lockstep, so decode can recompute offsets from traversal order."""
    if isinstance(node, Mapping):
        for k in node:
            if not isinstance(k, str):
                # json.dumps would coerce the key to a string and the
                # decoded tree would come back with a DIFFERENT key.
                raise WireError(
                    f"wire trees are string-keyed mappings; got key {k!r}"
                )
            entry: Any
            child = node[k]
            if isinstance(child, Mapping):
                entry = {}
                offset = _encode_node(child, entry, buffers, offset,
                                      prefix + (k,), leaf_versions)
            else:
                entry = []
                offset = _encode_node(child, entry, buffers, offset,
                                      prefix + (k,), leaf_versions)
            table_out[k] = entry
        return offset
    # Leaf: table_out is the (mutable, empty) descriptor list.
    if isinstance(node, (list, tuple)):
        # np.asarray would silently merge a list of arrays into one
        # tensor and decode back a DIFFERENT structure — refuse.
        raise WireError(
            "wire trees are nested dicts of arrays; lists/tuples are "
            "not encodable"
        )
    if isinstance(node, QuantLeaf):
        arr = _wire_array(node.data)
        quant: Any = {"scale": node.scale, "d": node.dequant_dtype}
    else:
        arr = _wire_array(np.asarray(node))
        quant = None
    if leaf_versions is None:
        # v1 entry: [dtype, shape] (+quant) — byte-stable legacy shape.
        table_out.extend([_dtype_str(arr.dtype), list(arr.shape)]
                         + ([quant] if quant is not None else []))
    else:
        # v2 entry: [dtype, shape, quant-or-null, leaf_version].
        table_out.extend([_dtype_str(arr.dtype), list(arr.shape), quant,
                          int(leaf_versions.get(prefix, -1))])
    if arr.nbytes:
        # A uint8 view flattens any dtype (incl. bfloat16, whose
        # PEP-3118 format memoryview can't export) without copying.
        buffers.append(memoryview(arr.reshape(-1).view(np.uint8)))
    return offset + arr.nbytes


def encode(tree_or_leaves: Any, version: int = -1,
           run_tag: int = 0,
           leaf_versions: Optional[Mapping] = None,
           trace: Optional[Any] = None) -> Buffers:
    """Frame a tree (or pre-flattened/quantized leaves) for the wire.

    Returns ``[header+table bytes, buffer, buffer, ...]`` where each
    buffer is a ``memoryview`` of the array's own memory — no tensor
    bytes are copied here. Write the parts sequentially (sockets and
    ``http.client`` both take iterables) or join with
    :func:`frame_bytes` when one contiguous body is needed.

    ``leaf_versions`` (a ``{path-tuple: int}`` mapping) switches the
    frame to wire version 2: each leaf entry carries its per-tensor
    version tag and the tree may be a PARTIAL delta. Leave it None for
    the byte-stable v1 frames old decoders understand.

    ``trace`` (anything with ``trace_id``/``span_id``/``sampled`` —
    an :class:`~sparktorch_tpu.obs.rpctrace.SpanContext`) embeds the
    request's distributed-tracing context as the ``FLAG_TRACE`` header
    extension. Only SAMPLED contexts travel (head-based sampling:
    unsampled requests must cost the far side nothing); ``None`` or an
    unsampled context leaves the frame byte-identical to the pre-trace
    wire.
    """
    if isinstance(tree_or_leaves, list) and (
        not tree_or_leaves
        or (isinstance(tree_or_leaves[0], tuple)
            and isinstance(tree_or_leaves[0][0], tuple))
    ):
        tree = unflatten_tree(tree_or_leaves)
    else:
        tree = tree_or_leaves

    buffers: Buffers = []
    if isinstance(tree, Mapping):
        table: Any = {}
        payload_len = _encode_node(tree, table, buffers, 0, (),
                                   leaf_versions)
    else:  # single-leaf root
        table = []
        payload_len = _encode_node(tree, table, buffers, 0, (),
                                   leaf_versions)

    wire_ver = WIRE_VERSION if leaf_versions is None else WIRE_VERSION_DELTA
    table_bytes = json.dumps(table, separators=(",", ":")).encode()
    flags = 0
    ext = b""
    if trace is not None and getattr(trace, "sampled", False):
        try:
            ext = _TRACE_EXT.pack(bytes.fromhex(str(trace.trace_id)),
                                  bytes.fromhex(str(trace.span_id)), 1)
        except (ValueError, struct.error) as e:
            raise WireError(f"malformed trace context {trace!r}") from e
        flags |= FLAG_TRACE
    header = _HEADER.pack(MAGIC, wire_ver, flags, int(run_tag) & 0xFFFF,
                          int(version), len(table_bytes), payload_len)
    return [header + ext + table_bytes, *buffers]


def frame_nbytes(buffers: Buffers) -> int:
    """Total frame length without joining (Content-Length)."""
    return sum(len(b) for b in buffers)


def frame_bytes(buffers: Buffers) -> bytes:
    """Join the frame into one contiguous body (the single copy that a
    cache or a non-scatter writer pays)."""
    return b"".join(buffers)


def frame_run_tag(data: Union[bytes, bytearray, memoryview]) -> int:
    """The 16-bit run tag from a frame header (0 = untagged) without
    decoding the body — the cheap cross-run correlation check a server
    runs per request. Raises :class:`WireError` on a non-frame."""
    mv = memoryview(data)
    if len(mv) < HEADER_SIZE:
        raise WireError(f"frame truncated: {len(mv)} < header {HEADER_SIZE}")
    magic, wire_ver, _flags, tag, _v, _t, _p = _HEADER.unpack_from(mv, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    return int(tag)


def frame_trace(data: Union[bytes, bytearray, memoryview]):
    """The distributed-tracing span context embedded in a frame's
    ``FLAG_TRACE`` header extension, as an
    :class:`~sparktorch_tpu.obs.rpctrace.SpanContext` — or None on an
    untraced frame. Header-only peek like :func:`frame_run_tag` (a
    server decides whether to open a serve span BEFORE paying the
    body decode). Raises :class:`WireError` on a non-frame or a
    truncated extension."""
    mv = memoryview(data)
    if len(mv) < HEADER_SIZE:
        raise WireError(f"frame truncated: {len(mv)} < header {HEADER_SIZE}")
    magic, _ver, flags, _tag, _v, _t, _p = _HEADER.unpack_from(mv, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if not flags & FLAG_TRACE:
        return None
    if len(mv) < HEADER_SIZE + TRACE_EXT_SIZE:
        raise WireError("frame truncated inside the trace extension")
    trace_id, span_id, tflags = _TRACE_EXT.unpack_from(mv, HEADER_SIZE)
    from sparktorch_tpu.obs.rpctrace import SpanContext

    return SpanContext.from_parts(trace_id.hex(), span_id.hex(),
                                  bool(tflags & 1))


def _decode_impl(
    data: Union[bytes, bytearray, memoryview]
) -> Tuple[int, Any, Dict[Tuple[str, ...], int]]:
    """Shared v1/v2 decode: ``(version, tree, {path: leaf_version})``
    (the version map is empty for v1 frames)."""
    mv = memoryview(data)
    if len(mv) < HEADER_SIZE:
        raise WireError(f"frame truncated: {len(mv)} < header {HEADER_SIZE}")
    magic, wire_ver, flags, _res, version, table_len, payload_len = (
        _HEADER.unpack_from(mv, 0)
    )
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if wire_ver not in (WIRE_VERSION, WIRE_VERSION_DELTA):
        raise WireError(f"unsupported wire version {wire_ver}")
    # The optional trace-context extension shifts the table offset;
    # its content is the transport layer's business (frame_trace) —
    # decode only needs to step over it.
    ext_len = TRACE_EXT_SIZE if flags & FLAG_TRACE else 0
    body_off = HEADER_SIZE + ext_len
    if len(mv) != body_off + table_len + payload_len:
        raise WireError(
            f"frame length {len(mv)} != header+table+payload "
            f"{body_off + table_len + payload_len}"
        )
    try:
        table = json.loads(bytes(mv[body_off:body_off + table_len]))
    except ValueError as e:
        raise WireError(f"corrupt tensor table: {e}") from e
    if not isinstance(table, (dict, list)):
        raise WireError("tensor table is neither object nor leaf")

    payload = mv[body_off + table_len:]
    leaf_versions: Dict[Tuple[str, ...], int] = {}

    def read_leaf(entry: list, offset: int,
                  path: Tuple[str, ...]) -> Tuple[Any, int]:
        try:
            dtype = _dtype_of(entry[0])
            shape = tuple(int(d) for d in entry[1])
            quant = entry[2] if len(entry) > 2 else None
            if quant is not None:
                # Validate HERE so a malformed quant slot is a
                # WireError (-> the server's 400), not a stray
                # TypeError/KeyError escaping from the math below.
                quant = (float(quant["scale"]),
                         _dtype_of(quant["d"]).newbyteorder("="))
            if wire_ver == WIRE_VERSION_DELTA:
                if len(entry) < 4:
                    raise WireError(
                        f"delta frame leaf missing version tag: {entry!r}"
                    )
                leaf_versions[path] = int(entry[3])
        except (IndexError, KeyError, TypeError, ValueError) as e:
            if isinstance(e, WireError):
                raise
            raise WireError(f"malformed table entry {entry!r}") from e
        if any(d < 0 for d in shape):
            raise WireError(f"negative dim in shape {shape}")
        # Python ints, not np.prod: an attacker-sized dim must raise
        # (via the bounds check below), never overflow int64 to 0.
        count = 1
        for d in shape:
            count *= d
        nbytes = count * dtype.itemsize
        if offset + nbytes > payload_len:
            raise WireError(
                f"tensor spans [{offset}, {offset + nbytes}) outside "
                f"payload of {payload_len}"
            )
        try:
            arr = np.frombuffer(payload, dtype=dtype, count=count,
                                offset=offset).reshape(shape)
        except ValueError as e:
            raise WireError(f"unreadable tensor {entry!r}: {e}") from e
        if arr.dtype.byteorder == "<" and dtype.itemsize > 1:
            # Normalize to native byte order: a view on LE hosts
            # (astype(copy=False) never copies there), a converted
            # copy on BE hosts.
            arr = arr.astype(dtype.newbyteorder("="), copy=False)
        if quant is not None:
            scale, dq = quant
            arr = arr.astype(dq) * np.asarray(scale, dtype=dq)
        return arr, offset + nbytes

    def read_node(node: Any, offset: int,
                  path: Tuple[str, ...]) -> Tuple[Any, int]:
        if isinstance(node, dict):
            out = {}
            for k, child in node.items():
                out[k], offset = read_node(child, offset, path + (k,))
            return out, offset
        if not isinstance(node, list):
            raise WireError(f"malformed table node {node!r}")
        return read_leaf(node, offset, path)

    tree, consumed = read_node(table, 0, ())
    if consumed != payload_len:
        raise WireError(
            f"payload length {payload_len} != tensor bytes {consumed}"
        )
    return int(version), tree, leaf_versions


def decode(data: Union[bytes, bytearray, memoryview]) -> Tuple[int, Any]:
    """``(snapshot_version, tree)`` from a received frame (v1 or v2 —
    a v2 frame's per-leaf tags are simply dropped here; use
    :func:`decode_delta` to keep them).

    Array leaves are read-only ``np.frombuffer`` views into ``data`` —
    zero-copy; quantized tensors are dequantized (the one place the
    bytes are touched). Raises :class:`WireError` on anything
    malformed or truncated.
    """
    version, tree, _ = _decode_impl(data)
    return version, tree


def decode_delta(
    data: Union[bytes, bytearray, memoryview]
) -> Tuple[int, Dict[Tuple[str, ...], Any], Dict[Tuple[str, ...], int]]:
    """``(snapshot_version, {path: leaf}, {path: leaf_version})`` from
    a delta (v2) frame — flat by path, ready to merge into a client's
    cached tree. Raises :class:`WireError` on a v1 frame (a delta
    consumer must never silently treat a full snapshot as a delta of
    everything — though semantically close, the bug it would mask is a
    server ignoring ``X-Have-Version``)."""
    mv = memoryview(data)
    if len(mv) >= HEADER_SIZE:
        wire_ver = _HEADER.unpack_from(mv, 0)[1]
        if wire_ver == WIRE_VERSION:
            raise WireError("expected a delta (v2) frame, got v1")
    version, tree, vers = _decode_impl(data)
    return version, dict(flatten_tree(tree)), vers


def tree_nbytes(tree: Any) -> int:
    """Payload bytes a plain (unquantized) encode of ``tree`` ships."""
    return sum(np.asarray(a).nbytes for _, a in flatten_tree(tree))
