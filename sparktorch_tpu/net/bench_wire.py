"""Dill-vs-binary wire microbenchmark (the ``make bench-wire`` gate).

Builds a transformer-sized state dict (encoder layers + embedding
table, ~tens of MB of f32 — the shape of what the hogwild wire
actually ships), then round-trips it through both wires:

- **dill**: ``dill.dumps`` -> one blob -> ``dill.loads`` (the
  reference's wire, ``hogwild.py:31-62``);
- **binary**: :func:`wire.encode` -> scatter-joined body (the copy a
  socket write performs either way) -> :func:`wire.decode`
  (``np.frombuffer`` views).

Prints one JSON line and EXITS NON-ZERO if the binary wire does not
beat dill on BOTH bytes on the wire and encode+decode wall time —
a CI-style smoke gate for the zero-copy claim. The quantized (bf16)
binary row rides along for scale but is lossy, so it never gates.

CLI: ``python -m sparktorch_tpu.net.bench_wire [--layers N]
[--d-model D] [--vocab V] [--repeats R]``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Callable, Dict, List, Optional

import dill
import numpy as np

from sparktorch_tpu.net import wire


def transformer_state_dict(layers: int = 4, d_model: int = 768,
                           vocab: int = 8192, seed: int = 0) -> dict:
    """A nested state dict with transformer-shaped tensors (qkv/o
    projections, 4x FFN, layernorms, embedding table) — the realistic
    mix of a few big matrices and many small vectors that a wire
    format has to handle well at BOTH ends of the size range."""
    rng = np.random.default_rng(seed)

    def w(*shape):
        return rng.standard_normal(shape).astype(np.float32)

    tree: dict = {
        "embed": {"table": w(vocab, d_model)},
        "pos_embed": w(512, d_model),
    }
    for i in range(layers):
        tree[f"layer_{i}"] = {
            "attn": {
                "query": {"kernel": w(d_model, d_model), "bias": w(d_model)},
                "key": {"kernel": w(d_model, d_model), "bias": w(d_model)},
                "value": {"kernel": w(d_model, d_model), "bias": w(d_model)},
                "out": {"kernel": w(d_model, d_model), "bias": w(d_model)},
            },
            "mlp": {
                "up": {"kernel": w(d_model, 4 * d_model),
                       "bias": w(4 * d_model)},
                "down": {"kernel": w(4 * d_model, d_model),
                         "bias": w(d_model)},
            },
            "ln1": {"scale": w(d_model), "bias": w(d_model)},
            "ln2": {"scale": w(d_model), "bias": w(d_model)},
        }
    return tree


def _time_best(fn: Callable[[], object], repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run(layers: int = 4, d_model: int = 768, vocab: int = 8192,
        repeats: int = 3) -> Dict[str, object]:
    tree = transformer_state_dict(layers, d_model, vocab)
    payload_mb = wire.tree_nbytes(tree) / 1e6

    # dill roundtrip (version tag shipped like the pull wire does).
    dill_body = dill.dumps((7, tree))
    dill_enc_s = _time_best(lambda: dill.dumps((7, tree)), repeats)
    dill_dec_s = _time_best(lambda: dill.loads(dill_body), repeats)

    # binary roundtrip: encode (headers only — tensor memory is NOT
    # copied) + the one join a non-scatter writer would pay + decode.
    bin_body = wire.frame_bytes(wire.encode(tree, version=7))
    bin_enc_s = _time_best(
        lambda: wire.frame_bytes(wire.encode(tree, version=7)), repeats
    )
    bin_hdr_s = _time_best(lambda: wire.encode(tree, version=7), repeats)
    bin_dec_s = _time_best(lambda: wire.decode(bin_body), repeats)

    # Lossy bf16 row, reported but never gating.
    leaves, _ = wire.quantize_tree(tree, "bf16")
    bf16_body = wire.frame_bytes(wire.encode(leaves, version=7))

    roundtrip_dill = dill_enc_s + dill_dec_s
    roundtrip_bin = bin_enc_s + bin_dec_s
    record: Dict[str, object] = {
        "bench": "wire_micro",
        "state_dict_mb": round(payload_mb, 2),
        "n_tensors": len(wire.flatten_tree(tree)),
        "dill_bytes": len(dill_body),
        "binary_bytes": len(bin_body),
        "binary_bf16_bytes": len(bf16_body),
        "dill_encode_s": round(dill_enc_s, 5),
        "dill_decode_s": round(dill_dec_s, 5),
        "binary_encode_s": round(bin_enc_s, 5),
        "binary_encode_headers_only_s": round(bin_hdr_s, 6),
        "binary_decode_s": round(bin_dec_s, 6),
        "roundtrip_speedup": round(
            roundtrip_dill / max(roundtrip_bin, 1e-12), 2),
        "bytes_saved": len(dill_body) - len(bin_body),
        "ok": (len(bin_body) < len(dill_body)
               and roundtrip_bin < roundtrip_dill),
    }
    return record


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="sparktorch-tpu-bench-wire")
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--d-model", type=int, default=768)
    parser.add_argument("--vocab", type=int, default=8192)
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args(argv)
    record = run(args.layers, args.d_model, args.vocab, args.repeats)
    print(json.dumps(record))
    if not record["ok"]:
        print("bench-wire FAILED: binary wire must beat dill on both "
              "bytes and encode+decode wall time", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
