"""Trace-guided auto-tuning of mesh/parallelism configs.

The repo can *measure* exactly where step time goes (per-collective
comm/compute/overlap budgets from :mod:`sparktorch_tpu.obs.xprof`) and
can *run* every dp/fsdp/tp/sp/ep mesh combination — but picking the
mesh for a workload was still a human. This module closes the loop,
Alpa/AutoSharding-style but grounded in MEASURED traces rather than a
static cost model alone:

1. **Enumerate** every legal :class:`MeshConfig` for the device count:
   axis products must divide the device world, and each axis is capped
   by the model dims the sharding rules lay out over it (``tp`` must
   divide heads/FFN/vocab, ``sp`` the sequence, ``ep`` the expert
   count, the batch axes the global batch).
2. **Prune** the space with a cheap analytic comm-volume model — bytes
   moved per step per candidate from param/activation shapes, no
   execution. The model is a PRUNER, not a predictor: it only has to
   rank badly-communicating layouts below plausible ones.
3. **Measure** the survivors: compile every survivor once (outside
   any capture — a capture containing the multi-second XLA compile
   overflows the profiler buffer), then run INTERLEAVED rounds of a
   few profiled steps per candidate — the same
   medians-over-interleaved-repeats discipline the fleet bench uses,
   because on a cpu-share rig whole measurement windows land in slow
   scheduler epochs and back-to-back candidate timings swing 10x.
   Each round's capture is analyzed offline
   (:class:`~sparktorch_tpu.obs.xprof.TraceAnalysis`); candidates are
   scored by the median step wall across all rounds with an
   exposed-comm tiebreak, and the round loop early-stops once the
   best candidate's lead exceeds the measurement noise floor (the
   cross-candidate max of p75-p25 step-wall spreads).
4. **Emit** the search as an artifact (``tune_result.json``: full
   ranking, per-candidate budgets, prune decisions, chosen mesh) and
   as an ``xprof_tune`` telemetry section + ``xprof.tune_*`` metrics,
   so the collector and ``obs.timeline --tune`` can render it.

The winner is a usable fast path, not a report:
``make_sharded_train_step(mesh="auto", spec=..., sample_batch=...)``
runs this search and trains on the chosen mesh
(:mod:`sparktorch_tpu.train.sharded`), and ``make bench-tune`` gates
the tuner against an exhaustive measurement of the same space.

CLI::

    python -m sparktorch_tpu.parallel.tune --model tiny --batch 32 \
        --out tune_result.json

Everything through step (2) is backend-free (no device execution), so
enumeration, pruning, and scoring are tier-1-testable on synthetic
shapes; only :func:`measure_candidate` touches the accelerator.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from sparktorch_tpu.obs.log import get_logger
from sparktorch_tpu.parallel.mesh import ALL_AXES, AXIS_DP, MeshConfig

_LOG = get_logger("sparktorch_tpu.parallel.tune")

# The full default search space, ``pp`` included: pp>1 candidates are
# measured through the PIPELINE trainer's schedule path
# (train/pipeline.py — gpipe / 1f1b / interleaved-1f1b), everything
# else through the GSPMD trainer. Callers that only ever build GSPMD
# steps can pass ``axes=GSPMD_AXES`` to keep the pp-less space.
DEFAULT_AXES: Tuple[str, ...] = ("dp", "fsdp", "tp", "sp", "ep", "pp")

# The pp-less space the tuner searched before pipeline schedules were
# opened (PR 7-13 behavior; scripted decision tests pin against it).
GSPMD_AXES: Tuple[str, ...] = ("dp", "fsdp", "tp", "sp", "ep")

# Schedule search dims for pp>1 candidates. "interleaved" is the
# interleaved 1F1B schedule (virtual_stages>1 chunks per device);
# it reaches make_pp_train_step as schedule='1f1b' + virtual_stages=V.
PP_SCHEDULES = ("gpipe", "1f1b", "interleaved")

ARTIFACT_KIND = "tune"


def pp_bubble_fraction(schedule: str, n_stages: int, n_micro: int,
                       virtual_stages: int = 1) -> float:
    """Pipeline bubble (idle fraction of the schedule) — the textbook
    (S-1)/(M+S-1) for gpipe AND 1f1b (1F1B reorders the bubble for
    memory, not away: same ticks, same idle — Narayanan et al.), and
    the V-scaled interleaved variant (S-1)/(V*M+S-1): V chunks per
    device shrink the warmup/drain ramps V-fold at the price of V x
    the stage-boundary traffic (the trade the cost model ranks)."""
    if schedule not in PP_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(of {PP_SCHEDULES})")
    S = int(n_stages)
    M = max(1, int(n_micro))
    V = max(1, int(virtual_stages))
    if S <= 1:
        return 0.0
    if schedule == "interleaved":
        return (S - 1) / (V * M + S - 1)
    return (S - 1) / (M + S - 1)


def pp_schedule_ticks(schedule: str, n_stages: int, n_micro: int,
                      virtual_stages: int = 1) -> int:
    """Schedule ticks per step — the pp launch count the alpha term
    charges (each tick moves one activation block over the stage
    ring, fwd or combined fwd+bwd): M+S-1 for gpipe's scanned
    forward (backward rides the transposed scan), M+2S-2 combined
    ticks for 1F1B, and the chunk-granular V*M+2S-2 for interleaved
    (V x the hops — the bytes that buy the smaller bubble)."""
    if schedule not in PP_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(of {PP_SCHEDULES})")
    S = int(n_stages)
    M = max(1, int(n_micro))
    V = max(1, int(virtual_stages))
    if S <= 1:
        return 0
    if schedule == "gpipe":
        return M + S - 1
    if schedule == "1f1b":
        return M + 2 * S - 2
    return V * M + 2 * S - 2


# ---------------------------------------------------------------------------
# Search space: legal MeshConfig candidates
# ---------------------------------------------------------------------------


def transformer_caps(cfg, seq_len: Optional[int] = None) -> Dict[str, Tuple[int, ...]]:
    """Per-axis divisibility caps for a :class:`TransformerConfig`,
    mirroring what :mod:`sparktorch_tpu.parallel.sharding_rules`
    actually lays out over each axis: an axis size is legal iff it
    divides EVERY listed dim (``_spec_fits`` would otherwise silently
    fall back to replication and the axis would waste devices).

    - ``tp``: qkv heads, the FFN inner dim, and the vocab (embedding
      rows ride ``P(tp, fsdp)``);
    - ``fsdp``: the model dim (the embedding's fsdp-sharded column);
    - ``sp``: the sequence length;
    - ``ep``: the expert count (dense model -> ep stays 1). The ep
      axis is a first-class search dimension: dispatch/combine are
      explicit shard_map all-to-alls with a mesh-anchored group
      partition (models.transformer.MoEFFN), so measured ep candidates
      reflect the real scaling layout, not the degraded partitioner-
      derived lowering the pre-rewrite tuner had to distrust (the old
      "defer ep re-validation" caveat is closed — stale entries are
      fenced off by the cache-key schema bump);
    - ``pp``: the layer count.
    """
    return {
        "fsdp": (cfg.d_model,),
        "tp": (cfg.n_heads, cfg.d_ff, cfg.vocab_size),
        "sp": (int(seq_len or cfg.max_len),),
        "ep": (cfg.n_experts,) if cfg.n_experts > 0 else (1,),
        "pp": (cfg.n_layers,),
    }


def _legal(axis_size: int, dims: Sequence[int]) -> bool:
    return all(d > 0 and d % axis_size == 0 for d in dims) if dims \
        else True


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_candidates(
    n_devices: int,
    caps: Mapping[str, Sequence[int]],
    global_batch: int,
    axes: Sequence[str] = DEFAULT_AXES,
    max_candidates: Optional[int] = None,
) -> List[MeshConfig]:
    """Every legal :class:`MeshConfig` for ``n_devices``: the non-dp
    axis product divides the device count (dp absorbs the rest), each
    axis size divides its cap dims, and the batch axes (dp*fsdp)
    divide the global batch. Deterministic order: ascending by the
    (fsdp, tp, sp, ep, pp) size tuple, so the pure-dp config is always
    candidate 0 and goldens can assert exact lists."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    axes = tuple(axes)
    for ax in axes:
        if ax not in ALL_AXES:
            raise ValueError(f"unknown mesh axis {ax!r} (of {ALL_AXES})")
    fixed_axes = [a for a in ALL_AXES if a != AXIS_DP]
    choices: Dict[str, List[int]] = {}
    for ax in fixed_axes:
        if ax not in axes:
            choices[ax] = [1]
            continue
        choices[ax] = [d for d in _divisors(n_devices)
                       if _legal(d, tuple(caps.get(ax, ())))]

    out: List[MeshConfig] = []
    import itertools

    for combo in itertools.product(*(choices[a] for a in fixed_axes)):
        fixed = math.prod(combo)
        if n_devices % fixed != 0:
            continue
        dp = n_devices // fixed
        if AXIS_DP not in axes and dp != 1:
            continue
        sizes = dict(zip(fixed_axes, combo))
        if sizes["pp"] > 1 and sizes["fsdp"] > 1:
            # No trainer runs pp x fsdp: the pipeline trainer shards
            # params over pp (dp x pp x tp x sp x ep only), the GSPMD
            # trainer has no schedule. Not a legal layout anywhere.
            continue
        if global_batch % (dp * sizes["fsdp"]) != 0:
            continue
        if not _legal(dp, tuple(caps.get(AXIS_DP, ()))):
            continue
        out.append(MeshConfig(dp=dp, **sizes))
    out.sort(key=lambda c: (c.fsdp, c.tp, c.sp, c.ep, c.pp))
    if max_candidates is not None and len(out) > max_candidates:
        # Truncation here is in ENUMERATION order, blind to cost —
        # callers that can rank first (autotune does) should cap
        # after the cost model instead.
        _LOG.warning(
            f"[sparktorch_tpu:tune] enumeration truncated "
            f"{len(out)} -> {max_candidates} candidates "
            f"(enumeration order, not cost order)"
        )
        out = out[:max_candidates]
    return out


# ---------------------------------------------------------------------------
# Analytic comm-volume model (the pruner — no execution)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WorkloadShape:
    """Byte-level skeleton of one training step, enough to rank mesh
    candidates by communication volume without running anything.

    ``param_bytes`` is the FULL (unsharded) parameter footprint;
    ``tp_param_bytes`` the subset the sharding rules lay out over
    ``tp`` (the big matmul weights — for a transformer, nearly all of
    it). Activations are modeled as ``tokens x d_model`` blocks."""

    param_bytes: float
    tp_param_bytes: float = 0.0
    global_batch: int = 1
    seq_len: int = 1
    d_model: int = 1
    n_layers: int = 1
    n_moe_layers: int = 0
    dtype_bytes: int = 4
    # MoE capacity expansion: the dispatch/combine all-to-alls move
    # (tokens x capacity_factor x top_k) capacity slots, not raw
    # tokens — the a2a byte term scales by both (validated against the
    # explicit shard_map lowering by `make bench-moe`).
    moe_capacity_factor: float = 1.0
    moe_top_k: int = 1


def transformer_workload(cfg, global_batch: int,
                         seq_len: Optional[int] = None) -> WorkloadShape:
    """Analytic parameter/activation shape for a transformer config
    (counts the matmul weights; biases/layernorms are noise at this
    resolution)."""
    d, ff, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    moe = sum(cfg.moe_pattern()) if cfg.n_experts > 0 else 0
    dense = cfg.n_layers - moe
    per_dense = 4 * d * d + 2 * d * ff
    per_moe = 4 * d * d + cfg.n_experts * 2 * d * ff
    matmul_params = v * d + dense * per_dense + moe * per_moe
    dtype = 4  # params/grads travel f32 on the wire-level collectives
    return WorkloadShape(
        param_bytes=float(matmul_params) * dtype,
        tp_param_bytes=float(matmul_params) * dtype,
        global_batch=int(global_batch),
        seq_len=int(seq_len or cfg.max_len),
        d_model=d,
        n_layers=cfg.n_layers,
        n_moe_layers=moe,
        dtype_bytes=dtype,
        moe_capacity_factor=float(getattr(cfg, "capacity_factor", 1.0))
        if moe else 1.0,
        moe_top_k=int(max(1, min(getattr(cfg, "moe_top_k", 1),
                                 cfg.n_experts)))
        if moe else 1,
    )


# Per-collective launch/rendezvous latency expressed in EQUIVALENT
# BYTES (the LogP alpha/beta ratio: latency x bandwidth). Small-tensor
# workloads are latency-bound — a pure byte count would rank a config
# with 4 tiny activation all-reduces per layer "cheaper" than one
# bucketed gradient all-reduce and prune the actual winner. The CPU
# rig's in-process rendezvous is orders slower than ICI, hence the
# much larger equivalent.
DEFAULT_ALPHA_BYTES = {"cpu": 1 << 20, "gpu": 1 << 18, "tpu": 1 << 17}

# Explicit override wins over both the probe and the table (the knob
# the ROADMAP's alpha-calibration follow-up promised to keep).
ALPHA_ENV = "SPARKTORCH_TPU_TUNE_ALPHA_BYTES"

# Tune-result cache knob: "0" disables, a path overrides the default
# cache directory (~/.cache/sparktorch_tpu/tune). The cache is keyed
# by a (workload dims, global batch, device fingerprint, search
# space) hash, so a ``mesh="auto"`` RE-RUN of the same workload on
# the same rig loads the cached winner instead of re-searching (and
# re-compiling every candidate).
TUNE_CACHE_ENV = "SPARKTORCH_TPU_TUNE_CACHE"

# One probe per (backend, device-count) per process: the measurement
# costs two tiny compiles (~1-2s on the CPU rig), and every
# mesh="auto" call in a session shares the same rig.
_ALPHA_PROBE_CACHE: Dict[Tuple[str, int], float] = {}


def alpha_bytes_for_backend(backend: Optional[str] = None) -> float:
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    return float(DEFAULT_ALPHA_BYTES.get(backend,
                                         DEFAULT_ALPHA_BYTES["tpu"]))


def calibrate_alpha_bytes(devices: Optional[Sequence[Any]] = None,
                          big_nbytes: int = 4 << 20,
                          repeats: int = 7) -> float:
    """Ground the per-launch alpha in a MEASUREMENT instead of the
    order-of-magnitude table: time one TINY all-reduce (its wall is
    ~pure launch/rendezvous latency) and one BIG one (bandwidth-
    dominated), derive the rig's collective bandwidth from their
    difference, and convert the tiny latency to equivalent bytes —
    the LogP alpha x beta product the cost model's ``total_cost``
    wants. MIN of ``repeats`` timed runs after a compile+warmup pass:
    first-dispatch walls on this rig are 3-10x inflated, and the
    cpu-share scheduler lands whole runs in slow epochs — the fastest
    observed run is the only stable estimate of what the collective
    costs when the rig isn't fighting itself (medians here swung 6x
    between processes).

    Clamped to [16KB, 16MB]: a probe gone sideways (scheduler spike,
    1-device world) must perturb the ranking, not capsize it. Raises
    on no/one device — callers fall back to the table."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from sparktorch_tpu.train.step import shard_map_compat

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n < 2:
        raise ValueError("alpha probe needs >= 2 devices")
    key = (str(devices[0].platform), n)
    cached = _ALPHA_PROBE_CACHE.get(key)
    if cached is not None:
        return cached

    mesh = Mesh(np.array(devices), ("probe",))

    def _timed_psum(per_dev_elems: int) -> float:
        fn = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum(x, "probe"), mesh=mesh,
            in_specs=P("probe"), out_specs=P(),
        ))
        x = jnp.zeros((n, per_dev_elems), jnp.float32)
        fn(x).block_until_ready()  # compile + warmup outside the clock
        walls = []
        for _ in range(repeats):
            t0 = time.perf_counter()  # lint-obs: ok (alpha micro-probe min-of-runs timing, not run attribution)
            fn(x).block_until_ready()
            walls.append(time.perf_counter() - t0)  # lint-obs: ok (alpha micro-probe)
        return float(np.min(walls))

    t_tiny = _timed_psum(1)
    big_per_dev = max(1, int(big_nbytes) // 4)
    t_big = _timed_psum(big_per_dev)
    # Model-consistent byte count for the big probe: the same ring
    # all-reduce accounting predict_comm_bytes uses (2(n-1)/n x shard
    # bytes per device, summed over devices) — alpha must come out in
    # the units the prune key adds it to.
    model_bytes = n * (2.0 * (n - 1) / n) * big_per_dev * 4.0
    bandwidth = model_bytes / max(t_big - t_tiny, 1e-6)
    alpha = t_tiny * bandwidth
    alpha = float(min(max(alpha, 1 << 14), 1 << 24))
    _ALPHA_PROBE_CACHE[key] = alpha
    _LOG.info(
        f"[sparktorch_tpu:tune] alpha probe: tiny all-reduce "
        f"{t_tiny * 1e3:.3f}ms, {big_nbytes >> 20}MB all-reduce "
        f"{t_big * 1e3:.3f}ms -> alpha {alpha / 1e6:.2f}MB-eq "
        f"(table default {alpha_bytes_for_backend() / 1e6:.2f}MB-eq)"
    )
    return alpha


def resolve_alpha_bytes(devices: Optional[Sequence[Any]] = None
                        ) -> Tuple[float, str]:
    """The alpha the search should use, with its provenance:
    ``(value, 'env' | 'probe' | 'default')``. Priority: the env
    override, then the per-rig micro-probe, then the backend table
    (probe failure degrades to the table with a warning — calibration
    must never kill a search)."""
    env = os.environ.get(ALPHA_ENV)
    if env:
        try:
            return float(env), "env"
        except ValueError:
            _LOG.warning(
                f"[sparktorch_tpu:tune] bad {ALPHA_ENV}={env!r}; ignoring"
            )
    try:
        return calibrate_alpha_bytes(devices), "probe"
    except Exception as e:
        _LOG.warning(
            f"[sparktorch_tpu:tune] alpha probe failed "
            f"({type(e).__name__}: {e}); using the backend table"
        )
        return alpha_bytes_for_backend(), "default"


def predict_comm_bytes(config: MeshConfig, shape: WorkloadShape,
                       n_devices: int,
                       alpha_bytes: float = 0.0,
                       schedule_meta: Optional[Mapping[str, Any]] = None,
                       ) -> Dict[str, float]:
    """Communication cost of ONE step of ``shape`` under ``config`` —
    ring/bidirectional collective byte models summed over devices,
    plus an alpha term (``alpha_bytes`` equivalent bytes per logical
    collective) for launch/rendezvous latency. Returns per-mechanism
    byte totals, the ``collective_ops`` count, ``total_bytes`` (beta
    term only), and ``total_cost`` (the prune key: bytes + alpha).

    ``schedule_meta`` (pp>1 candidates: ``{"schedule", "virtual_
    stages", "n_micro"}``) makes the ``pp_send_recv`` term schedule-
    aware: interleaved chunks multiply the stage-boundary bytes by V,
    and the term grows the schedule's BUBBLE factor
    (:func:`pp_bubble_fraction` — (S-1)/(M+S-1) for gpipe/1f1b, the
    V-scaled interleaved variant) as a multiplicative penalty, so a
    schedule that idles (S-1)/(M+S-1) of its devices ranks behind one
    that doesn't even at equal wire bytes; the alpha term charges one
    launch per schedule tick (:func:`pp_schedule_ticks`). Without the
    meta a pp>1 config keeps the flat pre-schedule terms.

    Deliberately coarse (no link topology, no overlap): its one job
    is a monotone ranking — more replicated gradient bytes, more
    exposed activation traffic, or more collective launches MUST
    predict more comm — so the pruner never has to execute the
    obviously-worst layouts. The measured phase owns the final
    ranking."""
    sizes = config.resolve(n_devices)
    dp, fsdp, tp = sizes["dp"], sizes["fsdp"], sizes["tp"]
    sp, ep, pp = sizes["sp"], sizes["ep"], sizes["pp"]
    pp_meta = schedule_meta if pp > 1 and schedule_meta else None
    pp_sched = str(pp_meta["schedule"]) if pp_meta else "gpipe"
    pp_v = int(pp_meta.get("virtual_stages", 1)) if pp_meta else 1
    pp_m = int(pp_meta.get("n_micro", 1)) if pp_meta else 1
    pp_bubble = (pp_bubble_fraction(pp_sched, pp, pp_m, pp_v)
                 if pp_meta else 0.0)

    # Per-device parameter/gradient residency after layout: with
    # tp>1 the rule-matched weights shard over tp; EVERYTHING not
    # tp-sharded (including those same weights when tp==1) falls back
    # to fsdp sharding.
    tp_bytes = shape.tp_param_bytes if tp > 1 else 0.0
    rest_bytes = max(shape.param_bytes - tp_bytes, 0.0)
    grad_dev = tp_bytes / tp + rest_bytes / fsdp

    # Activation block per device: the tokens this device computes.
    tokens_dev = (shape.global_batch / (dp * fsdp)) * (shape.seq_len / sp)
    act_dev = tokens_dev * shape.d_model * shape.dtype_bytes

    per_dev = {
        # dp gradient ring all-reduce of the per-device grad shard.
        "dp_all_reduce": (2.0 * (dp - 1) / dp) * grad_dev if dp > 1 else 0.0,
        # fsdp: param all-gather (fwd) + grad reduce-scatter (bwd).
        "fsdp_gather_scatter": (2.0 * (fsdp - 1) / fsdp) * rest_bytes
        if fsdp > 1 else 0.0,
        # tp: two activation all-reduces per layer (attn-out, mlp-out).
        "tp_all_reduce": shape.n_layers * 2 * (2.0 * (tp - 1) / tp) * act_dev
        if tp > 1 else 0.0,
        # sp: ring-attention k/v block rotation, (sp-1) hops per layer.
        "sp_ppermute": shape.n_layers * (sp - 1) * 2.0 * act_dev
        if sp > 1 else 0.0,
        # ep: dispatch + combine all-to-alls per MoE layer. The
        # explicit shard_map lowering (models.transformer._ep_relayout)
        # exchanges (G, e, cap, d) CAPACITY blocks — tokens expanded by
        # capacity_factor x top_k — with each member keeping its own
        # 1/ep slice resident, hence the (ep-1)/ep wire fraction.
        # Grounded against HLO-measured collective bytes and step wall
        # by `make bench-moe` (the bench_moe_a2a gates).
        "ep_all_to_all": (
            shape.n_moe_layers * 2 * ((ep - 1) / ep) * act_dev
            * shape.moe_capacity_factor * shape.moe_top_k
        )
        if ep > 1 else 0.0,
        # pp: stage-boundary activation sends, fwd + bwd. Interleaved
        # chunks hop V x as often (each device's V chunks each hand
        # off), and the schedule's bubble rides as a multiplicative
        # penalty — idle devices are a cost the byte terms alone
        # cannot see (the measured phase sees it as step wall).
        "pp_send_recv": (2.0 * ((pp - 1) / pp) * act_dev * pp_v
                         * (1.0 + pp_bubble))
        if pp > 1 else 0.0,
    }
    out = {k: n_devices * v for k, v in per_dev.items()}
    out["total_bytes"] = sum(out.values())
    # Logical collective launches per step (the alpha term's count):
    # the bucketed dp grad reduction is ONE launch; tp pays two per
    # layer; sp pays one ppermute per ring hop per layer; a pipeline
    # schedule pays one ppermute per tick per direction.
    ops = (
        (1 if dp > 1 else 0)
        + (2 if fsdp > 1 else 0)
        + (shape.n_layers * 2 if tp > 1 else 0)
        + (shape.n_layers * (sp - 1) if sp > 1 else 0)
        + (shape.n_moe_layers * 2 if ep > 1 else 0)
        + ((2 * pp_schedule_ticks(pp_sched, pp, pp_m, pp_v)
            if pp_meta else 2 * (pp - 1)) if pp > 1 else 0)
    )
    out["collective_ops"] = float(ops)
    out["total_cost"] = out["total_bytes"] + float(alpha_bytes) * ops
    # Bookkeeping (NOT a byte term — added after the totals): what
    # bubble the pp term charged, for artifacts and goldens.
    out["pp_bubble_fraction"] = pp_bubble
    return out


# ---------------------------------------------------------------------------
# Candidates and results
# ---------------------------------------------------------------------------


# Candidate fates. Note there is no "skipped": the early stop ends
# the ROUND loop (every surviving candidate keeps its rounds so far),
# it never leaves a candidate half-decided.
STATUS_MEASURED = "measured"
STATUS_PRUNED = "pruned"
STATUS_FAILED = "failed"


def mesh_label(sizes: Mapping[str, int]) -> str:
    """Compact prom-label-safe spelling: ``dp4xtp2`` (axes of size 1
    omitted; the trivial mesh is ``dp1``)."""
    parts = [f"{a}{sizes[a]}" for a in ALL_AXES if sizes.get(a, 1) > 1]
    return "x".join(parts) if parts else "dp1"


def schedule_suffix(meta: Mapping[str, Any]) -> str:
    """Label suffix for a pipeline-scheduled candidate:
    ``gpipe_m4`` / ``1f1b_m4`` / ``int2_m8`` (interleaved, V chunks,
    M microbatches). Prom-label-safe like :func:`mesh_label`."""
    sched = str(meta["schedule"])
    v = int(meta.get("virtual_stages", 1))
    m = int(meta.get("n_micro", 1))
    name = f"int{v}" if sched == "interleaved" else sched
    return f"{name}_m{m}"


def candidate_label(axes: Mapping[str, int],
                    schedule: Optional[Mapping[str, Any]] = None) -> str:
    base = mesh_label(axes)
    return f"{base}-{schedule_suffix(schedule)}" if schedule else base


@dataclasses.dataclass
class Candidate:
    """One point of the search space and everything decided about it.
    pp>1 candidates carry a ``schedule`` dict (``{"schedule":
    gpipe|1f1b|interleaved, "virtual_stages": V, "n_micro": M}``) —
    the same mesh under two schedules is two candidates."""

    axes: Dict[str, int]
    predicted: Dict[str, float]
    status: str = "pending"
    reason: Optional[str] = None
    measured: Optional[Dict[str, Any]] = None
    score: Optional[float] = None
    schedule: Optional[Dict[str, Any]] = None

    @property
    def predicted_bytes(self) -> float:
        return float(self.predicted.get("total_bytes", 0.0))

    @property
    def predicted_cost(self) -> float:
        """The prune key: beta (bytes) + alpha (launch) terms."""
        return float(self.predicted.get("total_cost",
                                        self.predicted_bytes))

    @property
    def label(self) -> str:
        return candidate_label(self.axes, self.schedule)

    def mesh_config(self) -> MeshConfig:
        sizes = {a: int(self.axes.get(a, 1)) for a in ALL_AXES}
        return MeshConfig(**sizes)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "axes": dict(self.axes),
            "label": self.label,
            "predicted": {k: round(float(v), 2)
                          for k, v in self.predicted.items()},
            "status": self.status,
            "reason": self.reason,
            "measured": dict(self.measured) if self.measured else None,
            "score": self.score,
            "schedule": dict(self.schedule) if self.schedule else None,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Candidate":
        return cls(
            axes={k: int(v) for k, v in (d.get("axes") or {}).items()},
            predicted=dict(d.get("predicted") or {}),
            status=str(d.get("status", "pending")),
            reason=d.get("reason"),
            measured=dict(d["measured"]) if d.get("measured") else None,
            score=d.get("score"),
            schedule=dict(d["schedule"]) if d.get("schedule") else None,
        )


@dataclasses.dataclass
class TuneResult:
    """The whole search: every candidate with its fate, the winner,
    and the bookkeeping a gate needs to audit the decision."""

    n_devices: int
    global_batch: int
    best: Dict[str, int]
    candidates: List[Candidate]
    noise_floor_s: float
    early_stopped: bool
    steps_per_candidate: int     # profiled steps per candidate PER ROUND
    wall_s: float
    exposed_weight: float
    rounds_run: int = 0          # scored interleaved rounds executed
    warmup_rounds: int = 0       # discarded warmup rounds per candidate
    executed_steps_total: int = 0  # ALL profiled steps run, incl. warmup
    candidates_dropped: int = 0  # past the max_candidates cap (logged)
    caps: Dict[str, List[int]] = dataclasses.field(default_factory=dict)
    run_id: Optional[str] = None
    alpha_bytes: float = 0.0     # the per-launch alpha the prune used
    alpha_source: str = "default"  # arg | env | probe | default
    cache_hit: bool = False      # loaded from the tune-result cache
    cache_key: Optional[str] = None  # (workload, rig) fingerprint hash
    # The winner's pipeline schedule when best has pp>1 (None for
    # GSPMD winners): {"schedule", "virtual_stages", "n_micro"} — what
    # make_sharded_train_step(mesh="auto") builds the pp step from.
    best_schedule: Optional[Dict[str, Any]] = None
    # The search's total compile bill — every candidate the tuner
    # compiled (count + summed walls). The mesh='auto' step builder
    # ADDS its own fresh-closure recompile of the winner here the
    # moment the goodput cache-miss probe sees it, so "the auto path
    # compiles its winner twice" is a visible number on the live
    # result, not a README caveat. (The artifact/cache entry carries
    # the search-time bill; a cache HIT run's only compile is the
    # winner's own.)
    compile_count: int = 0
    compile_s_total: float = 0.0

    def best_config(self) -> MeshConfig:
        sizes = {a: int(self.best.get(a, 1)) for a in ALL_AXES}
        return MeshConfig(**sizes)

    @property
    def best_label(self) -> str:
        return candidate_label(self.best, self.best_schedule)

    def ranking(self) -> List[Candidate]:
        """Measured candidates, best (lowest score) first."""
        measured = [c for c in self.candidates
                    if c.status == STATUS_MEASURED and c.score is not None]
        return sorted(measured, key=lambda c: c.score)

    def pruned(self) -> List[Candidate]:
        return [c for c in self.candidates if c.status == STATUS_PRUNED]

    def measured_steps_total(self) -> int:
        return sum(
            int((c.measured or {}).get("n_steps", 0))
            for c in self.candidates if c.status == STATUS_MEASURED
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": ARTIFACT_KIND,
            "run_id": self.run_id,
            "n_devices": self.n_devices,
            "global_batch": self.global_batch,
            "best": dict(self.best),
            "best_schedule": (dict(self.best_schedule)
                              if self.best_schedule else None),
            "best_label": self.best_label,
            "noise_floor_s": self.noise_floor_s,
            "early_stopped": self.early_stopped,
            "steps_per_candidate": self.steps_per_candidate,
            "rounds_run": self.rounds_run,
            "warmup_rounds": self.warmup_rounds,
            "measured_steps_total": self.measured_steps_total(),
            "executed_steps_total": self.executed_steps_total,
            "candidates_dropped": self.candidates_dropped,
            "wall_s": self.wall_s,
            "exposed_weight": self.exposed_weight,
            "alpha_bytes": self.alpha_bytes,
            "alpha_source": self.alpha_source,
            "cache_hit": self.cache_hit,
            "cache_key": self.cache_key,
            "compile_count": self.compile_count,
            "compile_s_total": round(self.compile_s_total, 6),
            "caps": {k: list(v) for k, v in self.caps.items()},
            "n_candidates": len(self.candidates),
            "n_measured": sum(c.status == STATUS_MEASURED
                              for c in self.candidates),
            "n_pruned": sum(c.status == STATUS_PRUNED
                            for c in self.candidates),
            "ranking": [c.label for c in self.ranking()],
            "candidates": [c.to_dict() for c in self.candidates],
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "TuneResult":
        if d.get("kind") != ARTIFACT_KIND:
            raise ValueError(
                f"not a tune artifact (kind={d.get('kind')!r})"
            )
        return cls(
            n_devices=int(d["n_devices"]),
            global_batch=int(d["global_batch"]),
            best={k: int(v) for k, v in d["best"].items()},
            candidates=[Candidate.from_dict(c)
                        for c in d.get("candidates", [])],
            noise_floor_s=float(d.get("noise_floor_s", 0.0)),
            early_stopped=bool(d.get("early_stopped", False)),
            steps_per_candidate=int(d.get("steps_per_candidate", 0)),
            rounds_run=int(d.get("rounds_run", 0)),
            warmup_rounds=int(d.get("warmup_rounds", 0)),
            executed_steps_total=int(d.get("executed_steps_total", 0)),
            candidates_dropped=int(d.get("candidates_dropped", 0)),
            wall_s=float(d.get("wall_s", 0.0)),
            exposed_weight=float(d.get("exposed_weight", 0.0)),
            caps={k: [int(x) for x in v]
                  for k, v in (d.get("caps") or {}).items()},
            run_id=d.get("run_id"),
            alpha_bytes=float(d.get("alpha_bytes", 0.0)),
            alpha_source=str(d.get("alpha_source", "default")),
            cache_hit=bool(d.get("cache_hit", False)),
            cache_key=d.get("cache_key"),
            best_schedule=(dict(d["best_schedule"])
                           if d.get("best_schedule") else None),
            compile_count=int(d.get("compile_count", 0)),
            compile_s_total=float(d.get("compile_s_total", 0.0)),
        )

    def save(self, path: str) -> str:
        """Write the ``tune_result.json`` artifact atomically (tmp +
        rename: a killed tuner must not leave a torn artifact that a
        later ``mesh="auto"`` run half-parses)."""
        import os

        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2)  # lint-obs: ok (tune artifact persistence, not telemetry)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "TuneResult":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # -- bus publication ---------------------------------------------------

    def publish(self, telemetry=None) -> None:
        """Put the search on the telemetry bus under ``xprof.tune_*``
        names (the same contract as
        :meth:`~sparktorch_tpu.obs.xprof.TraceAnalysis.publish`):
        per-candidate wall samples, outcome counters, winner gauges,
        one condensed ``xprof_tune`` event, and the full document as
        the ``xprof_tune`` snapshot section — so a ``/telemetry``
        scrape, a collector merge, and ``obs.timeline --tune`` all
        render the same search."""
        from sparktorch_tpu.obs.telemetry import get_telemetry

        tele = telemetry or get_telemetry()
        for c in self.candidates:
            tele.counter("xprof.tune_candidates_total",
                         labels={"outcome": c.status})
            if c.status == STATUS_MEASURED and c.measured:
                tele.observe("xprof.tune_candidate_step_wall_s",
                             float(c.measured.get("step_wall_s", 0.0)),
                             labels={"mesh": c.label})
        tele.counter("xprof.tune_runs_total")
        best = self.ranking()
        if best:
            tele.gauge("xprof.tune_best_step_wall_s",
                       float(best[0].measured.get("step_wall_s", 0.0)))
            tele.gauge("xprof.tune_best_exposed_fraction",
                       float(best[0].measured.get(
                           "exposed_comm_fraction", 0.0)))
        tele.gauge("xprof.tune_noise_floor_s", self.noise_floor_s)
        tele.gauge("xprof.tune_wall_s", self.wall_s)
        tele.event(
            "xprof_tune",
            best=self.best_label,
            n_candidates=len(self.candidates),
            n_measured=sum(c.status == STATUS_MEASURED
                           for c in self.candidates),
            n_pruned=sum(c.status == STATUS_PRUNED
                         for c in self.candidates),
            early_stopped=self.early_stopped,
            noise_floor_s=self.noise_floor_s,
            wall_s=self.wall_s,
            ranking=[c.label for c in self.ranking()][:8],
        )
        tele.set_section("xprof_tune", self.to_dict())


# ---------------------------------------------------------------------------
# Scoring (the xprof hook)
# ---------------------------------------------------------------------------


def score_wall(median_wall_s: float, exposed_fraction: float,
               exposed_weight: float) -> float:
    """THE scoring formula — LOWER is better. The decision variable
    is the median step wall (robust to one GC pause on a noisy rig);
    the exposed-comm fraction rides as a multiplicative penalty
    (``wall * (1 + w * exposed)``) so that two configs inside each
    other's noise tie-break toward the one whose collectives hide
    under compute — that one keeps its rank when compute grows.
    Shared by :func:`score_analysis` (single capture — what the
    golden-fixture test pins) and the interleaved-round aggregation
    (:func:`_aggregate_rounds` — the production decision path), so
    the pinned formula IS the deciding one."""
    return median_wall_s * (1.0 + exposed_weight * exposed_fraction)


def score_analysis(analysis, exposed_weight: float = 0.25
                   ) -> Tuple[float, Dict[str, Any]]:
    """Score one candidate's :class:`TraceAnalysis` via
    :func:`score_wall`. Returns ``(score, measured_record)``."""
    stats = analysis.step_wall_stats()
    exposed = analysis.exposed_comm_fraction
    score = score_wall(stats["median_s"], exposed, exposed_weight)
    measured = {
        "step_wall_s": stats["median_s"],
        "step_wall_mean_s": stats["mean_s"],
        "spread_s": stats["spread_s"],
        "n_steps": stats["n"],
        "comm_fraction": analysis.comm_fraction,
        "overlap_fraction": analysis.overlap_fraction,
        "exposed_comm_fraction": exposed,
        "comm_s": analysis.comm_s,
        "compute_s": analysis.compute_s,
        "n_collective_events": analysis.n_collective_events,
        "collective_counts": analysis.family_counts(),
    }
    return score, measured


# ---------------------------------------------------------------------------
# Measurement (the only part that touches the accelerator)
# ---------------------------------------------------------------------------


def prepare_candidate(spec, config: MeshConfig, batch, devices,
                      tx=None, seq_sharded: bool = False,
                      telemetry=None) -> Callable[[int], Dict[str, Any]]:
    """Compile ``spec`` under ``config`` and return a ROUND RUNNER:
    ``runner(steps)`` captures one fresh XLA profile around ``steps``
    train steps (state carried across rounds), analyzes it offline,
    and returns the round record (``walls`` per step, comm/overlap/
    exposed fractions, collective counts). Compilation happens here,
    OUTSIDE any capture — a capture containing the multi-second XLA
    compile floods the profiler buffer and the step markers vanish
    (see obs/xprof WATCH note). Raises on compile failure (the caller
    records the candidate as failed and moves on). The runner carries
    ``runner.compile_s``."""
    import tempfile

    import jax

    from sparktorch_tpu.obs.xprof import analyze_trace
    from sparktorch_tpu.parallel.compat import set_mesh as _set_mesh
    from sparktorch_tpu.parallel.mesh import build_mesh
    from sparktorch_tpu.train.sharded import (
        create_sharded_state,
        make_sharded_train_step,
        shard_batch,
    )
    from sparktorch_tpu.utils.tracing import profile_run

    from sparktorch_tpu.obs import goodput as _goodput

    tx = tx or spec.make_optimizer()
    module = spec.make_module()
    mesh = build_mesh(config, devices)
    # The whole build-and-first-dispatch is one compile LedgerSpan:
    # tune-time compile seconds land in an armed run ledger's
    # ``compile`` bucket (and the span's duration is the compile bill
    # the TuneResult stamps) instead of vanishing into idle.
    with _goodput.span("compile", {"site": "tune"}) as _comp:
        state, shardings = create_sharded_state(
            spec, mesh, jax.random.key(0), sample_x=batch.x[:1], tx=tx,
        )
        # No profile_dir here: the runner owns its per-round captures.
        step = make_sharded_train_step(
            module.apply, spec.loss_fn(), tx, mesh, shardings,
            seq_sharded=seq_sharded, telemetry=telemetry,
        )
        sharded = shard_batch(batch, mesh, seq_sharded=seq_sharded)
        with _set_mesh(mesh):
            state, m = step.jitted(state, sharded)  # compile, uncaptured
        jax.block_until_ready(m.loss)
    compile_s = _comp.duration_s
    ledger = _goodput.active()
    if ledger is not None and ledger.telemetry is not None:
        # The site-labeled counter note_compile used to emit; the
        # LedgerSpan carries the seconds, this carries the count.
        ledger.telemetry.counter("goodput.compiles_total",
                                 labels={"site": "tune"})
    carried = {"state": state}

    def runner(steps: int) -> Dict[str, Any]:
        with tempfile.TemporaryDirectory() as profile_dir:
            # analyze=False: 1 capture per (candidate, round) — the
            # per-round budgets aggregate into ONE published tune
            # record; auto-publishing every capture would spam the
            # xprof.* series with per-round samples.
            with profile_run(profile_dir, telemetry=telemetry,
                             analyze=False):
                st = carried["state"]
                for _ in range(steps):
                    st, metrics = step(st, sharded)
                    # Drain per step so each step's device work lands
                    # inside its own attribution slice.
                    jax.block_until_ready(metrics.loss)
                carried["state"] = st
            analysis = analyze_trace(profile_dir)
        if not analysis.steps:
            raise RuntimeError("profiler emitted no usable capture")
        return {
            "walls": [s.wall_s for s in analysis.steps],
            "comm_fraction": analysis.comm_fraction,
            "overlap_fraction": analysis.overlap_fraction,
            "exposed_comm_fraction": analysis.exposed_comm_fraction,
            "n_collective_events": analysis.n_collective_events,
            "counts": analysis.family_counts(),
            "loss": float(metrics.loss),
        }

    runner.compile_s = compile_s
    return runner


def prepare_pipeline_candidate(spec, config: MeshConfig, batch, devices,
                               tx=None, seq_sharded: bool = False,
                               telemetry=None,
                               schedule_meta: Optional[Mapping[str, Any]]
                               = None) -> Callable[[int], Dict[str, Any]]:
    """The pp>1 analog of :func:`prepare_candidate`: build the
    candidate through the PIPELINE trainer's schedule path
    (:func:`sparktorch_tpu.train.pipeline.make_pp_train_step`) —
    gpipe / 1f1b / interleaved per ``schedule_meta`` — and return the
    same round-runner contract. The measured walls therefore include
    the schedule's real bubble and stage-boundary traffic, which is
    the whole point of opening pp to the search.

    MoE candidates with ep>1 thread the a2a grouping OPT-IN through
    the built step (``pp_moe_group_size`` — the same group-size choice
    the gpipe-ep dryrun config makes), so the measured step runs the
    all-to-all dispatch layout the mesh pays for, not the replicated
    fallback."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.obs.xprof import analyze_trace
    from sparktorch_tpu.parallel.mesh import build_mesh
    from sparktorch_tpu.train.pipeline import build_pp_schedule_step
    from sparktorch_tpu.utils.data import DataBatch
    from sparktorch_tpu.utils.tracing import profile_run

    if not schedule_meta:
        raise ValueError("pp>1 candidate without a schedule meta")
    rows = int(batch.x.shape[0])
    seq = int(batch.x.shape[1]) if batch.x.ndim >= 2 else 1
    mesh = build_mesh(config, devices)
    b = DataBatch(
        x=jnp.asarray(np.asarray(batch.x), jnp.int32),
        y=jnp.asarray(np.asarray(batch.y), jnp.int32),
        w=jnp.asarray(np.asarray(batch.w), jnp.float32),
    )
    # Same compile LedgerSpan contract as the GSPMD prepare: the
    # schedule build + first dispatch is the candidate's compile bill.
    # The build itself is the ONE shared recipe
    # (pipeline.build_pp_schedule_step) the mesh='auto' winner also
    # goes through — measured layout == production layout by
    # construction.
    with _goodput.span("compile", {"site": "tune"}) as _comp:
        state, step, _cfg, _head = build_pp_schedule_step(
            spec, mesh, schedule_meta, rows, seq, tx=tx,
            sample_x=batch.x[:1],
        )
        state, loss = step(state, b)  # compile, uncaptured
        jax.block_until_ready(loss)
    compile_s = _comp.duration_s
    ledger = _goodput.active()
    if ledger is not None and ledger.telemetry is not None:
        # The site-labeled counter note_compile used to emit; the
        # LedgerSpan carries the seconds, this carries the count.
        ledger.telemetry.counter("goodput.compiles_total",
                                 labels={"site": "tune"})
    carried = {"state": state}

    def runner(steps: int) -> Dict[str, Any]:
        with tempfile.TemporaryDirectory() as profile_dir:
            with profile_run(profile_dir, telemetry=telemetry,
                             analyze=False):
                st = carried["state"]
                for _ in range(steps):
                    st, loss_ = step(st, b)
                    jax.block_until_ready(loss_)
                carried["state"] = st
            analysis = analyze_trace(profile_dir)
        if not analysis.steps:
            raise RuntimeError("profiler emitted no usable capture")
        return {
            "walls": [s.wall_s for s in analysis.steps],
            "comm_fraction": analysis.comm_fraction,
            "overlap_fraction": analysis.overlap_fraction,
            "exposed_comm_fraction": analysis.exposed_comm_fraction,
            "n_collective_events": analysis.n_collective_events,
            "counts": analysis.family_counts(),
            "loss": float(loss_),
        }

    runner.compile_s = compile_s
    return runner


def _aggregate_rounds(rounds: List[Dict[str, Any]], compile_s: float,
                      exposed_weight: float
                      ) -> Tuple[float, Dict[str, Any]]:
    """Fold a candidate's round records into ``(score, measured)`` —
    the same formula as :func:`score_analysis`, over the pooled
    walls."""
    from sparktorch_tpu.obs.xprof import wall_stats

    walls = [w for r in rounds for w in r["walls"]]
    stats = wall_stats(walls)
    exposed = sum(r["exposed_comm_fraction"] for r in rounds) / len(rounds)
    score = score_wall(stats["median_s"], exposed, exposed_weight)
    counts: Dict[str, int] = {}
    for r in rounds:
        for fam, n in (r.get("counts") or {}).items():
            counts[fam] = counts.get(fam, 0) + int(n)
    measured = {
        "step_wall_s": stats["median_s"],
        "spread_s": stats["spread_s"],
        "n_steps": stats["n"],
        "rounds": len(rounds),
        "comm_fraction": sum(r["comm_fraction"]
                             for r in rounds) / len(rounds),
        "overlap_fraction": sum(r["overlap_fraction"]
                                for r in rounds) / len(rounds),
        "exposed_comm_fraction": exposed,
        "n_collective_events": sum(r["n_collective_events"]
                                   for r in rounds),
        "collective_counts": counts,
        "compile_s": compile_s,
    }
    return score, measured


# ---------------------------------------------------------------------------
# The driver
# ---------------------------------------------------------------------------


def workload_for(spec, batch, seq_len: Optional[int] = None
                 ) -> Tuple[WorkloadShape, Optional[Any]]:
    """(WorkloadShape, transformer config or None) for a ModelSpec +
    representative batch. Transformer modules get the analytic shape;
    anything else gets its parameter bytes from an abstract init trace
    (``jax.eval_shape`` — no device execution) with no tp share."""
    module = spec.make_module()
    cfg = getattr(module, "config", None)
    global_batch = int(batch.x.shape[0])
    if cfg is not None and hasattr(cfg, "d_model"):
        seq = seq_len or (batch.x.shape[1] if batch.x.ndim >= 2
                          else cfg.max_len)
        return transformer_workload(cfg, global_batch, seq), cfg
    import jax
    import numpy as np

    abstract = jax.eval_shape(
        lambda k: module.init(k, np.asarray(batch.x[:1])),
        jax.random.key(0),
    )
    param_bytes = float(sum(
        math.prod(leaf.shape) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(abstract)
    ))
    return WorkloadShape(param_bytes=param_bytes,
                         global_batch=global_batch), None


def pp_schedule_metas(sizes: Mapping[str, int], cfg,
                      global_batch: int,
                      max_virtual: int = 4) -> List[Dict[str, Any]]:
    """Legal schedule candidates for one pp>1 mesh: ``gpipe`` and
    ``1f1b`` (V=1), plus every ``interleaved`` V in [2, max_virtual]
    with ``n_layers % (pp*V) == 0`` — each fanned out over EVERY legal
    ``n_micro`` (M <= max(2*pp, 4) dividing the per-dp-shard rows;
    interleaved additionally needs M % pp == 0). The schedule-aware
    bubble term (S-1)/(M+S-1) and the per-tick alpha charge pull in
    opposite directions — more microbatches shrink the bubble but pay
    more launches — so M is a real search dimension the cost model
    ranks, not a heuristic pick; the cap keeps the fan-out bounded
    (microbatches beyond ~2S shave little bubble but still multiply
    ticks). Empty when the pipeline trainer cannot run this mesh at
    all (non-transformer spec, MoE x tp, sp>1 without ring attention,
    no legal microbatch split, non-uniform dense/MoE stage pattern) —
    those meshes simply don't enter the candidate list, mirroring
    ``make_pp_train_step``'s own validation."""
    S = int(sizes.get("pp", 1))
    if S <= 1 or cfg is None or not hasattr(cfg, "n_layers"):
        return []
    dp = int(sizes.get("dp", 1)) * int(sizes.get("fsdp", 1))
    tp = int(sizes.get("tp", 1))
    sp = int(sizes.get("sp", 1))
    ep = int(sizes.get("ep", 1))
    n_layers = int(cfg.n_layers)
    if n_layers % S != 0 or dp < 1 or global_batch % dp != 0:
        return []
    per_shard = global_batch // dp
    pattern = (tuple(cfg.moe_pattern())
               if getattr(cfg, "n_experts", 0) > 0 else ())
    has_moe = any(pattern)
    if has_moe and tp > 1:
        return []                 # experts shard over ep, not tp
    if ep > 1 and not has_moe:
        return []                 # nothing to shard over ep
    if sp > 1 and getattr(cfg, "attn_impl", "dense") != "ring":
        return []                 # sp needs global attention via ring

    def _uniform(n_chunks: int) -> bool:
        """Every chunk must hold the same dense/MoE sequence (the
        trainer's stage/chunk-pattern validation)."""
        if not has_moe:
            return True
        if n_layers % n_chunks:
            return False
        c = n_layers // n_chunks
        chunks = [pattern[i * c:(i + 1) * c] for i in range(n_chunks)]
        return all(ch == chunks[0] for ch in chunks)

    def _legal_ms(multiple: int) -> List[int]:
        cap = max(2 * S, 4)
        return [m for m in range(multiple, min(per_shard, cap) + 1,
                                 multiple)
                if per_shard % m == 0]

    metas: List[Dict[str, Any]] = []
    if _uniform(S):
        for m in _legal_ms(1):
            metas.append({"schedule": "gpipe", "virtual_stages": 1,
                          "n_micro": m})
            metas.append({"schedule": "1f1b", "virtual_stages": 1,
                          "n_micro": m})
    ms_int = _legal_ms(S)         # interleaved ticks need M % pp == 0
    if ms_int:
        # range is empty when max_virtual < 2: a caller disabling
        # interleaving gets exactly gpipe + 1f1b.
        for v in range(2, int(max_virtual) + 1):
            if n_layers % (S * v) != 0 or not _uniform(S * v):
                continue
            for m in ms_int:
                metas.append({"schedule": "interleaved",
                              "virtual_stages": v, "n_micro": m})
    return metas


# ---------------------------------------------------------------------------
# Tune-result cache (ROADMAP item-4 follow-up)
# ---------------------------------------------------------------------------


def device_fingerprint(devices: Sequence[Any]) -> Dict[str, Any]:
    """What makes this rig THIS rig for mesh selection: backend,
    device kinds, and count. Deliberately excludes the calibrated
    alpha (a measurement input that jitters run to run — two runs on
    the same hardware must share a cache entry)."""
    kinds = sorted({str(getattr(d, "device_kind", "?")) for d in devices})
    platforms = sorted({str(getattr(d, "platform", "?")) for d in devices})
    return {"n_devices": len(devices), "platforms": platforms,
            "kinds": kinds}


def _tx_cache_tag(tx) -> Optional[str]:
    """Coarse deterministic optimizer fingerprint for the tune-result
    cache: the STRUCTURE of its init state on a probe param (adam's
    moment leaves vs sgd's empty state — the state tree is what fsdp
    shards and the measured step applies). Hyperparameters like the
    learning rate don't change which mesh wins and deliberately don't
    key; optax transforms carry no stable repr, so structure is the
    only deterministic handle."""
    if tx is None:
        return None
    try:
        import jax as _jax

        state = tx.init({"w": np.zeros((1,), np.float32)})
        leaves, treedef = _jax.tree_util.tree_flatten(state)
        dtypes = [str(getattr(leaf, "dtype", type(leaf).__name__))
                  for leaf in leaves]
        return f"{treedef}:{dtypes}"
    except Exception:  # noqa: BLE001 - an exotic tx degrades, not dies
        return type(tx).__name__


def tune_cache_key(shape: WorkloadShape, caps: Mapping[str, Sequence[int]],
                   axes: Sequence[str], devices: Sequence[Any],
                   seq_sharded: bool, measure_top_k: int,
                   exposed_weight: float, *, max_candidates: int = 64,
                   steps: int = 4, repeats: int = 3,
                   min_rounds: int = 2, noise_mult: float = 2.0,
                   tx_tag: Optional[str] = None,
                   alpha_override: Optional[str] = None) -> str:
    """Deterministic hash of everything that decides WHICH mesh wins:
    the workload's dims (model shape + global batch), the rig
    fingerprint, and the search space/scoring/measurement knobs
    (``max_candidates`` can TRUNCATE the candidate list — an entry
    searched under a tighter cap must not satisfy a wider re-run;
    the round/step knobs decide measurement fidelity; ``tx_tag``
    distinguishes optimizers by state structure; ``alpha_override``
    keys an EXPLICIT alpha — kwarg or env — which deterministically
    changes the prune ranking, while the probe-measured alpha stays
    excluded because it jitters). Two calls with the same key would
    re-run the identical search — which is exactly what the cache
    skips."""
    import hashlib

    doc = {
        # Bump when the cost model, scoring, or enumeration changes
        # behavior: an on-disk entry searched by obsolete logic must
        # not satisfy the new version's key. Schema 2: the MoE
        # dispatch rewrite (explicit shard_map all-to-alls, mesh-
        # anchored group partition, capacity-aware ep byte term) —
        # entries measured under the degraded partitioner-derived
        # lowering must not satisfy an ep search against the new one.
        # Schema 3: pipeline schedules opened to the search (pp>1
        # candidates x {gpipe, 1f1b, interleaved} x virtual_stages,
        # schedule-aware bubble/tick terms in the cost model, winners
        # may carry a best_schedule) — a pre-rewrite entry searched
        # with pp locked to 1 must not satisfy the opened space.
        # Schema 4: n_micro opened to the search (every legal M <=
        # max(2*pp, 4) fans out per schedule x V instead of the
        # deterministic largest-M pick) — an entry whose candidates
        # were enumerated under the single-M heuristic must not
        # satisfy the widened space.
        "schema": 4,
        "moe_dispatch": "shard_map_a2a",
        "pp_schedules": list(PP_SCHEDULES),
        "shape": dataclasses.asdict(shape),
        "caps": {k: sorted(int(x) for x in v) for k, v in caps.items()},
        "axes": list(axes),
        "device": device_fingerprint(devices),
        "seq_sharded": bool(seq_sharded),
        "measure_top_k": int(measure_top_k),
        "exposed_weight": float(exposed_weight),
        "max_candidates": int(max_candidates),
        "measure": [int(steps), int(repeats), int(min_rounds),
                    float(noise_mult)],
        "tx": tx_tag,
        "alpha_override": alpha_override,
    }
    blob = json.dumps(doc, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def _tune_cache_dir() -> Optional[str]:
    """The cache directory, or None when disabled
    (``SPARKTORCH_TPU_TUNE_CACHE=0``). A non-flag env value is a
    directory override."""
    env = os.environ.get(TUNE_CACHE_ENV)
    if env is not None:
        env = env.strip()
        if env in ("0", "false", "off"):
            return None
        if env not in ("", "1", "true", "on"):
            return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "sparktorch_tpu", "tune")


def _cache_load(key: str) -> Optional[TuneResult]:
    cache_dir = _tune_cache_dir()
    if cache_dir is None:
        return None
    path = os.path.join(cache_dir, f"tune_{key}.json")
    try:
        result = TuneResult.load(path)
    except (OSError, ValueError, KeyError):
        return None  # absent or torn: a cache never fails a search
    return result


def _cache_store(key: str, result: TuneResult) -> None:
    cache_dir = _tune_cache_dir()
    if cache_dir is None:
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        result.save(os.path.join(cache_dir, f"tune_{key}.json"))
    except OSError:
        pass  # read-only home: the search result still returns


def autotune(
    spec,
    batch,
    devices: Optional[Sequence[Any]] = None,
    *,
    tx=None,
    caps: Optional[Mapping[str, Sequence[int]]] = None,
    axes: Sequence[str] = DEFAULT_AXES,
    steps: int = 4,
    repeats: int = 3,
    warmup_rounds: int = 1,
    min_rounds: int = 2,
    measure_top_k: int = 4,
    exposed_weight: float = 0.25,
    noise_mult: float = 2.0,
    exhaustive: bool = False,
    seq_sharded: Optional[bool] = None,
    alpha_bytes: Optional[float] = None,
    max_candidates: int = 64,
    artifact_path: Optional[str] = None,
    telemetry=None,
    measure_fn: Optional[Callable] = None,
    cache: bool = False,
) -> TuneResult:
    """Search mesh configs for ``spec`` on ``batch``; return the
    :class:`TuneResult` whose ``best_config()`` is the chosen mesh.

    The ``measure_top_k`` survivors of the comm-volume prune are
    compiled once each, then measured in INTERLEAVED rounds of
    ``steps`` profiled steps per candidate (up to ``repeats`` scored
    rounds, after ``warmup_rounds`` discarded ones — the FIRST
    capture per candidate is systematically inflated by profiler
    init, XLA autotuning, and allocator warmup and must not vote) —
    back-to-back per-candidate timing on a cpu-share rig lands
    whole windows in slow scheduler epochs and swings 10x; the
    interleave samples every candidate across the same epochs, and
    the pooled median cancels them. The round loop early-stops after
    ``min_rounds`` once the leader's margin over the runner-up
    exceeds ``noise_mult x`` the noise floor (cross-candidate max of
    p75-p25 wall spreads). ``exhaustive=True`` disables pruning and
    the early stop — every legal candidate is measured for all
    rounds (the ``make bench-tune`` referee mode). ``measure_fn``
    (same signature as :func:`prepare_candidate`) lets tests pin the
    decision logic without a backend. ``cache=True`` keys the result
    by a (workload dims, rig fingerprint, search space) hash and
    loads a prior run's winner instead of re-searching (artifact
    records ``cache_hit``; ``SPARKTORCH_TPU_TUNE_CACHE=0`` opts out,
    a path value relocates the cache directory)."""
    t_start = time.perf_counter()  # lint-obs: ok (artifact wall_s stat; compile regions carry their own LedgerSpans)
    if devices is None:
        import jax

        devices = jax.devices()
    n_devices = len(devices)
    global_batch = int(batch.x.shape[0])

    shape, cfg = workload_for(spec, batch)
    if seq_sharded is None:
        # Sequence sharding needs token-level targets (y carries a
        # sequence dim); a classifier's scalar labels cannot split
        # over sp.
        seq_sharded = getattr(batch.y, "ndim", 1) >= 2
    if caps is None:
        caps = transformer_caps(cfg, shape.seq_len) if cfg is not None \
            else {"tp": (1,), "sp": (1,), "ep": (1,), "pp": (1,)}
    caps = dict(caps)
    if not seq_sharded:
        caps["sp"] = (1,)

    # Tune-result cache: a re-run of the same (workload dims, rig
    # fingerprint, search space) loads the cached winner instead of
    # re-searching — checked BEFORE the alpha probe, which is itself
    # seconds of compile. Only real searches participate: a scripted
    # measure_fn (tests) or exhaustive referee run must never be
    # satisfied — or poisoned — by a cache entry, and
    # SPARKTORCH_TPU_TUNE_CACHE=0 kills it globally.
    cache_key: Optional[str] = None
    use_cache = (cache and measure_fn is None and not exhaustive
                 and _tune_cache_dir() is not None)
    if use_cache:
        cache_key = tune_cache_key(shape, caps, axes, devices,
                                   seq_sharded, measure_top_k,
                                   exposed_weight,
                                   max_candidates=max_candidates,
                                   steps=steps, repeats=repeats,
                                   min_rounds=min_rounds,
                                   noise_mult=noise_mult,
                                   tx_tag=_tx_cache_tag(tx),
                                   alpha_override=(
                                       str(alpha_bytes)
                                       if alpha_bytes is not None
                                       else os.environ.get(ALPHA_ENV)))
        cached = _cache_load(cache_key)
        if cached is not None:
            cached.cache_hit = True
            cached.cache_key = cache_key
            # The compile bill is per-RUN, not per-search: a cache hit
            # compiled nothing here, and the live result (and the
            # artifact a hit run writes) must report what THIS process
            # paid — zero so far; the mesh='auto' builder adds the
            # winner's own compile when it happens. The cache ENTRY on
            # disk keeps the original search-time bill.
            cached.compile_count = 0
            cached.compile_s_total = 0.0
            # Same per-RUN semantics for the wall: the entry stores
            # the original search's wall, but THIS process only paid
            # the lookup — the bench's warm-vs-cold tune-wall gate
            # reads exactly this number.
            cached.wall_s = time.perf_counter() - t_start  # lint-obs: ok (artifact stat)
            cached.publish(telemetry)
            if artifact_path:
                cached.save(artifact_path)
            _LOG.info(
                f"[sparktorch_tpu:tune] cache HIT {cache_key}: "
                f"{cached.best_label} (search skipped; "
                f"{TUNE_CACHE_ENV}=0 to disable)"
            )
            return cached

    # Enumerate the FULL legal space — the cost model is what decides
    # what gets dropped, never enumeration order.
    configs = enumerate_candidates(n_devices, caps, global_batch,
                                   axes=axes)
    if not configs:
        raise ValueError(
            f"no legal mesh for {n_devices} devices / batch "
            f"{global_batch} under caps {caps}"
        )
    alpha_source = "arg"
    if alpha_bytes is None:
        # Per-rig calibration: env override > one-time micro-probe
        # (a tiny all-reduce timed at search start) > backend table.
        alpha_bytes, alpha_source = resolve_alpha_bytes(devices)
    # pp=1 meshes are one candidate each (the GSPMD trainer); a pp>1
    # mesh fans out into one candidate PER legal schedule (gpipe /
    # 1f1b / interleaved-V), each with its own schedule-aware
    # prediction — and drops out entirely when the pipeline trainer
    # cannot run it (pp_schedule_metas mirrors its validation; the
    # spec-level gates — cross-entropy family, untied embeddings —
    # mirror train_distributed_pipeline's).
    pp_trainable = (
        cfg is not None
        and str(getattr(spec, "loss", "cross_entropy")) in (
            "cross_entropy", "cross_entropy_fused", "nll")
        and not bool(getattr(cfg, "tie_embeddings", False))
    )
    candidates = []
    for c in configs:
        sizes = c.resolve(n_devices)
        if sizes.get("pp", 1) > 1:
            if not pp_trainable:
                continue
            for meta in pp_schedule_metas(sizes, cfg, global_batch):
                candidates.append(Candidate(
                    axes=sizes,
                    predicted=predict_comm_bytes(
                        c, shape, n_devices, alpha_bytes=alpha_bytes,
                        schedule_meta=meta),
                    schedule=meta,
                ))
            continue
        candidates.append(Candidate(
            axes=sizes,
            predicted=predict_comm_bytes(c, shape, n_devices,
                                         alpha_bytes=alpha_bytes),
        ))
    # Predicted order, cheapest comm first; ties keep enumeration
    # order (the sort is stable), so the whole pass is deterministic.
    candidates.sort(key=lambda c: c.predicted_cost)
    candidates_dropped = 0
    if len(candidates) > max_candidates:
        # Combinatorial-explosion guard, applied AFTER the cost
        # ranking so what falls off is the model's worst tail — and
        # loudly, not silently (the dropped count rides the artifact).
        candidates_dropped = len(candidates) - max_candidates
        _LOG.warning(
            f"[sparktorch_tpu:tune] {candidates_dropped} worst-"
            f"predicted candidates dropped past the "
            f"max_candidates={max_candidates} cap"
        )
        candidates = candidates[:max_candidates]

    to_measure = candidates if exhaustive else candidates[:measure_top_k]
    measure_ids = {id(c) for c in to_measure}
    for rank, c in enumerate(candidates):
        if id(c) in measure_ids:
            continue
        c.status = STATUS_PRUNED
        c.reason = (
            f"comm_model: rank {rank} of {len(candidates)} "
            f"({c.predicted_cost / 1e6:.2f}MB-eq/step predicted vs "
            f"{candidates[0].predicted_cost / 1e6:.2f}MB-eq best)"
        )

    # Phase A: compile every survivor (outside any capture). A layout
    # the partitioner rejects becomes a failed candidate, never a
    # failed search. Each successful prepare is one XLA compile —
    # counted + summed into the result's compile bill. The real
    # prepare paths time their build inside a ``compile`` LedgerSpan,
    # so tune-time compile seconds land in an armed goodput ledger by
    # themselves; only an injected measure_fn (scripted tests) still
    # goes through note_compile, or its declared bill would vanish.
    runners: List[Tuple[Candidate, Callable]] = []
    compile_count = 0
    compile_s_total = 0.0
    import inspect as _inspect

    def _accepts_schedule(fn) -> bool:
        try:
            params = _inspect.signature(fn).parameters
        except (TypeError, ValueError):
            return False
        return "schedule_meta" in params or any(
            p.kind is _inspect.Parameter.VAR_KEYWORD
            for p in params.values())

    for cand in to_measure:
        if measure_fn is not None:
            prepare = measure_fn
        elif cand.schedule is not None:
            prepare = prepare_pipeline_candidate
        else:
            prepare = prepare_candidate
        kwargs: Dict[str, Any] = {}
        if cand.schedule is not None and _accepts_schedule(prepare):
            kwargs["schedule_meta"] = cand.schedule
        try:
            runner = prepare(
                spec, cand.mesh_config(), batch, devices, tx=tx,
                seq_sharded=seq_sharded, telemetry=telemetry, **kwargs,
            )
        except Exception as e:  # one bad layout must not kill the search
            cand.status = STATUS_FAILED
            cand.reason = f"{type(e).__name__}: {e}"
            _LOG.warning(f"[sparktorch_tpu:tune] candidate {cand.label} "
                         f"failed to prepare: {cand.reason}")
            continue
        compile_count += 1
        cand_compile_s = float(getattr(runner, "compile_s", 0.0))
        compile_s_total += cand_compile_s
        if measure_fn is not None:
            from sparktorch_tpu.obs import goodput as _goodput

            _goodput.note_compile(cand_compile_s, site="tune")
        runners.append((cand, runner))

    # Phase B: interleaved measurement rounds. Every live candidate
    # runs `steps` captured steps per round; scores re-aggregate over
    # the pooled walls after each round.
    rounds: Dict[int, List[Dict[str, Any]]] = {id(c): [] for c, _ in runners}
    noise_floor = 0.0
    early_stopped = False
    rounds_run = 0
    executed_steps = 0  # EVERY profiled step run, warmup included
    for raw_rnd in range(warmup_rounds + repeats):
        warming = raw_rnd < warmup_rounds
        rnd = raw_rnd - warmup_rounds
        live = [(c, r) for c, r in runners if c.status != STATUS_FAILED]
        if not live:
            break
        for cand, runner in live:
            try:
                executed_steps += steps
                record = runner(steps)
                if warming:
                    continue  # warmup capture: executed, never scored
                rounds[id(cand)].append(record)
            except Exception as e:
                cand.status = STATUS_FAILED
                cand.reason = f"{type(e).__name__}: {e}"
                cand.score = None
                cand.measured = None
                _LOG.warning(f"[sparktorch_tpu:tune] candidate "
                             f"{cand.label} failed mid-measure: "
                             f"{cand.reason}")
                continue
            score, record = _aggregate_rounds(
                rounds[id(cand)], getattr(runner, "compile_s", 0.0),
                exposed_weight,
            )
            cand.status = STATUS_MEASURED
            cand.score = float(score)
            cand.measured = record
        if warming:
            continue
        rounds_run = rnd + 1
        measured = [c for c, _ in runners if c.status == STATUS_MEASURED]
        if not measured:
            continue
        noise_floor = max((float(c.measured.get("spread_s", 0.0))
                           for c in measured), default=0.0)
        ranked = sorted(measured, key=lambda c: c.score)
        _LOG.info(
            f"[sparktorch_tpu:tune] round {rnd + 1}/{repeats}: "
            + ", ".join(
                f"{c.label} {c.measured['step_wall_s'] * 1e3:.2f}ms"
                for c in ranked)
            + f" (noise floor {noise_floor * 1e3:.2f}ms)"
        )
        if exhaustive or rnd + 1 >= repeats or rnd + 1 < min_rounds \
                or len(ranked) < 2:
            continue
        margin = noise_mult * noise_floor
        if ranked[1].score - ranked[0].score > margin:
            early_stopped = True
            _LOG.info(
                f"[sparktorch_tpu:tune] early stop after round "
                f"{rnd + 1}: {ranked[0].label} leads "
                f"{ranked[1].label} by "
                f"{(ranked[1].score - ranked[0].score) * 1e3:.2f}ms "
                f"> noise margin {margin * 1e3:.2f}ms"
            )
            break
    measured = [c for c, _ in runners if c.status == STATUS_MEASURED]
    if not measured:
        raise RuntimeError(
            "auto-tune measured no candidate successfully: "
            + "; ".join(f"{c.label}: {c.reason}" for c in to_measure)
        )

    best = min(measured, key=lambda c: c.score)
    result = TuneResult(
        n_devices=n_devices,
        global_batch=global_batch,
        best=dict(best.axes),
        best_schedule=(dict(best.schedule) if best.schedule else None),
        candidates=candidates,
        noise_floor_s=noise_floor,
        early_stopped=early_stopped,
        steps_per_candidate=steps,
        rounds_run=rounds_run,
        warmup_rounds=warmup_rounds,
        executed_steps_total=executed_steps,
        candidates_dropped=candidates_dropped,
        wall_s=time.perf_counter() - t_start,  # lint-obs: ok (artifact stat)
        exposed_weight=exposed_weight,
        caps={k: list(v) for k, v in caps.items()},
        run_id=getattr(telemetry, "run_id", None),
        alpha_bytes=float(alpha_bytes),
        alpha_source=alpha_source,
        cache_key=cache_key,
        compile_count=compile_count,
        compile_s_total=compile_s_total,
    )
    result.publish(telemetry)
    if artifact_path:
        result.save(artifact_path)
    if use_cache and cache_key is not None:
        _cache_store(cache_key, result)
    _LOG.info(
        f"[sparktorch_tpu:tune] chose {result.best_label} from "
        f"{len(candidates)} candidates "
        f"({len(result.pruned())} pruned without execution, "
        f"{len(measured)} measured, early_stop={early_stopped}) "
        f"in {result.wall_s:.1f}s"
    )
    return result


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _cli_spec(model: str, seq: int):
    from sparktorch_tpu.models import (
        MnistMLP,
        SequenceClassifier,
        bert_base,
        tiny_transformer,
    )
    from sparktorch_tpu.utils.serde import ModelSpec

    if model == "tiny":
        module = SequenceClassifier(tiny_transformer(max_len=seq))
    elif model == "bert":
        module = bert_base(max_len=seq)
    elif model == "mlp":
        module = MnistMLP()
    else:
        raise SystemExit(f"unknown --model {model!r} (tiny|bert|mlp)")
    return ModelSpec(module=module, loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3})


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    import numpy as np

    parser = argparse.ArgumentParser(
        prog="python -m sparktorch_tpu.parallel.tune",
        description="Trace-guided mesh auto-tuner: enumerate legal "
                    "mesh configs, prune by analytic comm volume, "
                    "measure survivors under the XLA profiler, emit "
                    "the winner + full ranking as tune_result.json.",
    )
    parser.add_argument("--model", default="tiny",
                        help="tiny | bert | mlp (synthetic workload)")
    parser.add_argument("--batch", type=int, default=32,
                        help="global batch size")
    parser.add_argument("--seq", type=int, default=16,
                        help="sequence length (transformer models)")
    parser.add_argument("--steps", type=int, default=4,
                        help="profiled steps per candidate per round")
    parser.add_argument("--repeats", type=int, default=3,
                        help="interleaved measurement rounds")
    parser.add_argument("--top-k", type=int, default=4,
                        help="candidates measured after the prune")
    parser.add_argument("--exhaustive", action="store_true",
                        help="measure every legal candidate (no prune, "
                             "no early stop)")
    parser.add_argument("--out", default="tune_result.json",
                        help="artifact path")
    args = parser.parse_args(argv)

    spec = _cli_spec(args.model, args.seq)
    from sparktorch_tpu.utils.data import DataBatch

    rng = np.random.default_rng(0)
    if args.model == "mlp":
        x = rng.normal(size=(args.batch, 784)).astype(np.float32)
        y = rng.integers(0, 10, (args.batch,)).astype(np.int32)
    else:
        x = rng.integers(0, 256, (args.batch, args.seq)).astype(np.int32)
        y = rng.integers(0, 2, (args.batch,)).astype(np.int32)
    batch = DataBatch(x=x, y=y, w=np.ones((args.batch,), np.float32))

    result = autotune(
        spec, batch, steps=args.steps, repeats=args.repeats,
        measure_top_k=args.top_k, exhaustive=args.exhaustive,
        artifact_path=args.out,
    )
    doc = result.to_dict()
    print(json.dumps({
        "best": doc["best_label"],
        "mesh": doc["best"],
        "n_candidates": doc["n_candidates"],
        "n_pruned": doc["n_pruned"],
        "n_measured": doc["n_measured"],
        "early_stopped": doc["early_stopped"],
        "noise_floor_s": round(doc["noise_floor_s"], 6),
        "wall_s": round(doc["wall_s"], 2),
        "artifact": args.out,
        "ranking": doc["ranking"],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
