"""Multi-host bring-up: gang rendezvous -> PJRT distributed init.

The end-to-end analog of the reference's executor bootstrap: Spark
barrier-schedules one task per executor, each task computes
MASTER_ADDR from the driver host and joins a gloo group with
rank=partition_index+1 (``distributed.py:98-110``;
``torch_distributed.py:305``). Here:

1. host 0 starts the native :class:`GangCoordinator` (C++, TCP);
2. every host registers (rank, jax-coordinator address), enters
   barrier 0 — gang semantics: nobody proceeds until the world is
   complete;
3. the rank-0 address from the peer table seeds
   ``jax.distributed.initialize``; libtpu/PJRT then forms the global
   device set and XLA collectives ride ICI/DCN;
4. heartbeats keep running — a dead host fails the next barrier fast
   instead of wedging the pod in a collective.

Single-host (the common dev case) short-circuits all of it.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

import jax

DEFAULT_JAX_COORD_PORT = 8476
DEFAULT_GANG_PORT = 8475

# The gang worker for THIS process's current multi-host run, if any.
# Training loops poll it via check_gang() between compiled steps so a
# dead peer raises GangFailure on the survivors promptly instead of
# wedging them in the next collective.
_ACTIVE_WORKER = None


def register_gang_worker(worker) -> None:
    global _ACTIVE_WORKER
    _ACTIVE_WORKER = worker


def check_gang() -> None:
    """Raise GangFailure if this process's gang has failed; no-op when
    no multi-host gang is active (the common single-host case). A
    worker that has been close()d is dropped from the registry here,
    so a later (e.g. retried single-host) training in the same process
    doesn't trip over a stale dead gang."""
    global _ACTIVE_WORKER
    worker = _ACTIVE_WORKER
    if worker is None:
        return
    if worker.closed:
        _ACTIVE_WORKER = None
        return
    worker.check()


def notify_gang_step(step: int) -> None:
    """Publish this process's training progress on its gang heartbeat
    (rank/host-attributed; see obs.heartbeat) so the driver — or any
    process sharing the heartbeat directory — can read per-rank step
    skew. No-op without an active gang or a heartbeat directory.
    Trainers call it next to check_gang(), once per compiled dispatch
    — file-write cost only when heartbeats are actually enabled."""
    worker = _ACTIVE_WORKER
    if worker is None or worker.closed:
        return
    hb = getattr(worker, "heartbeat", None)
    if hb is not None:
        hb.notify_step(step)


def _local_ip() -> str:
    # SPARK_LOCAL_IP is honored for drop-in parity with the
    # reference's address resolution (distributed.py:35-36).
    env = os.environ.get("SPARK_LOCAL_IP")
    if env:
        return env
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def bringup_multihost(
    rank: int,
    world_size: int,
    coordinator_host: Optional[str] = None,
    gang_port: int = DEFAULT_GANG_PORT,
    jax_coord_port: int = DEFAULT_JAX_COORD_PORT,
    heartbeat_timeout_ms: int = 30_000,
    start_coordinator: Optional[bool] = None,
    ft_policy=None,
    run_id: Optional[str] = None,
    telemetry=None,
    controller=None,
    ctl_port: int = 0,
):
    """Rendezvous the gang and initialize JAX's distributed runtime.

    Returns (coordinator_or_None, worker_or_None); keep the worker
    alive for the life of training (its heartbeat is the liveness
    signal) and ``close()`` both on shutdown.

    ``start_coordinator``: by default rank 0 hosts the coordinator.
    Pass False when an external process (e.g. the Spark driver in the
    pyspark adapter's barrier mode) already runs one — otherwise rank
    0 would try to bind the same port a second time.

    ``ft_policy`` (an :class:`sparktorch_tpu.ft.FtPolicy`) arms the
    fault-tolerant bring-up: the coordinator opens a re-registration
    grace window (``rejoin_grace_s`` — a supervisor-restarted rank can
    rejoin a failed gang on a fresh generation instead of being
    refused), and REGISTRATION retries under the policy's backoff —
    a restarted rank dialing a coordinator that has not yet opened the
    new generation must not give up on the first DEAD/refused reply.

    Run-ID correlation: the coordinator mints a gang-unique ``run_id``
    (or adopts the one passed in) and announces it in its OK replies;
    every rank stamps it on its telemetry events and heartbeat records
    (``telemetry=`` wires this rank's run-scoped bus through to the
    gang worker), so a fleet collector (:class:`obs.FleetCollector`)
    can join the per-rank streams into one gang timeline.

    ``controller`` arms the elastic control plane end to end: pass
    ``True`` (or a :class:`sparktorch_tpu.ctl.CtlRegistry`) and this
    rank starts a :class:`~sparktorch_tpu.native.gang.
    GangMetricsExporter` (on ``ctl_port``; 0 = ephemeral) serving its
    metrics/heartbeats PLUS ``POST /ctl`` with ``kill``/``drain``
    verbs — so an :class:`sparktorch_tpu.ctl.ElasticController` (or
    its collector fan-out) can manage this rank with no local process
    handle. The exporter rides the returned worker as
    ``worker.ctl_exporter`` (its ``.url`` is what you register with
    the controller/collector); ``drain`` sets
    ``worker.drain_requested``, which training loops may poll for a
    graceful world change; ``kill`` hard-exits the process (reply
    first, then ``os._exit`` — the controller's restart/resize path
    takes it from there).
    """
    if world_size <= 1:
        return None, None

    from sparktorch_tpu.native.gang import (
        GangCoordinator,
        GangFailure,
        GangWorker,
    )
    from sparktorch_tpu.obs.collector import mint_run_id

    if start_coordinator is None:
        start_coordinator = rank == 0
    coord = None
    if start_coordinator:
        grace_ms = (int(ft_policy.rejoin_grace_s * 1000)
                    if ft_policy is not None else 0)
        coord = GangCoordinator(world_size=world_size, port=gang_port,
                                heartbeat_timeout_ms=heartbeat_timeout_ms,
                                rejoin_grace_ms=grace_ms,
                                run_id=run_id or mint_run_id())
        gang_port = coord.port
        coordinator_host = coordinator_host or _local_ip()
    elif coordinator_host is None:
        coordinator_host = os.environ.get("SPARKTORCH_TPU_GANG_HOST", "127.0.0.1")

    my_addr = f"{_local_ip()}:{jax_coord_port}"
    if ft_policy is None:
        worker = GangWorker(coordinator_host, gang_port, rank, my_addr,
                            telemetry=telemetry)
    else:
        rng = ft_policy.rng()
        attempt = 0
        while True:
            try:
                worker = GangWorker(coordinator_host, gang_port, rank,
                                    my_addr, telemetry=telemetry)
                break
            except GangFailure:
                if attempt >= ft_policy.restart.max_restarts:
                    raise
                import time as _time

                _time.sleep(ft_policy.restart.delay_s(attempt, rng))
                attempt += 1
    worker.barrier(0)  # full gang assembled
    peers = worker.world()

    jax.distributed.initialize(
        coordinator_address=peers[0],
        num_processes=world_size,
        process_id=rank,
    )
    register_gang_worker(worker)
    if controller:
        import threading as _threading

        from sparktorch_tpu.ctl.route import CtlRegistry
        from sparktorch_tpu.ctl.worker import _hard_exit_soon
        from sparktorch_tpu.native.gang import GangMetricsExporter

        ctl = CtlRegistry() if controller is True else controller
        drain = _threading.Event()
        worker.drain_requested = drain
        # kill: reply-then-die (the 200 must reach the controller's
        # socket before the process vanishes, or a successful kill
        # reads as a transport error and gets retried at a corpse).
        ctl.register("kill", lambda code=86: _hard_exit_soon(int(code)))
        ctl.register("drain", lambda: (drain.set(), True)[1])
        ctl.register("ping", lambda: {"rank": rank, "pid": os.getpid(),
                                      "addr": my_addr})
        worker.ctl_exporter = GangMetricsExporter(
            coordinator=coord, telemetry=telemetry, port=ctl_port,
            ctl=ctl,
        ).start()
    return coord, worker
