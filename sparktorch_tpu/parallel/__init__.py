"""Mesh construction, sharding rules, multi-host launch, and the
trace-guided mesh auto-tuner.

Submodules import jax at module level (mesh/sharding_rules) or lazily
(tune's measurement path); this package init re-exports only the
names the trainers and benches reach for, without forcing the heavy
imports on ``import sparktorch_tpu.parallel`` alone.
"""

__all__ = [
    "MeshConfig",
    "build_mesh",
    "local_mesh",
    "autotune",
    "TuneResult",
    "enumerate_candidates",
]


def __getattr__(name):
    if name in ("MeshConfig", "build_mesh", "local_mesh"):
        from sparktorch_tpu.parallel import mesh

        return getattr(mesh, name)
    if name in ("autotune", "TuneResult", "enumerate_candidates"):
        from sparktorch_tpu.parallel import tune

        return getattr(tune, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
