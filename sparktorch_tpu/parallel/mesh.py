"""Device-mesh construction and sharding vocabulary.

The reference's world model is "gloo rank per Spark executor, world =
partitions + 1, driver is a phantom rank 0" with TCP rendezvous on a
hardcoded port (``distributed.py:98-110``; ``torch_distributed.py:305``).

TPU-native replacement: a named :class:`jax.sharding.Mesh` over the
pod slice. Ranks disappear — parallelism is expressed as sharding
annotations on one compiled program, and XLA lowers the communication
onto ICI/DCN. The axes:

- ``dp``   data parallel (the reference's only strategy, §2.4)
- ``fsdp`` data parallel with parameter sharding (zero-style)
- ``tp``   tensor/model parallel
- ``sp``   sequence/context parallel (ring attention rides this axis)
- ``ep``   expert parallel

Multi-host bring-up goes through :func:`initialize_distributed`
(PJRT coordinator — the analog of the reference's MASTER_ADDR/PORT
rendezvous at ``distributed.py:101-105``, minus the phantom rank: the
driver dispatches, it does not participate).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"
ALL_AXES = (AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_EP, AXIS_PP)
# Axes over which the batch dimension is split (and grads are summed).
BATCH_AXES = (AXIS_DP, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """How to carve the device set into named axes.

    ``dp=None`` means "absorb all devices not claimed by other axes".
    """

    dp: Optional[int] = None
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> dict:
        fixed = self.fsdp * self.tp * self.sp * self.ep * self.pp
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fsdp*tp*sp*ep*pp={fixed}"
            )
        dp = self.dp if self.dp is not None else n_devices // fixed
        total = dp * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.fsdp}x{self.tp}x{self.sp}x{self.ep}"
                f"x{self.pp} = {total} != {n_devices} devices"
            )
        return {
            AXIS_DP: dp,
            AXIS_FSDP: self.fsdp,
            AXIS_TP: self.tp,
            AXIS_SP: self.sp,
            AXIS_EP: self.ep,
            AXIS_PP: self.pp,
        }


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create the named mesh. Axis order puts ``dp`` outermost so that
    gradient all-reduces ride contiguous ICI neighborhoods."""
    config = config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in ALL_AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, ALL_AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (batch, ...) arrays: batch split over dp+fsdp."""
    return NamedSharding(mesh, P(BATCH_AXES))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_param_sharding(mesh: Mesh, leaf) -> NamedSharding:
    """Shard a parameter leaf over the fsdp axis along its largest
    divisible dimension; replicate if nothing divides."""
    n = mesh.shape[AXIS_FSDP]
    if n <= 1 or leaf.ndim == 0:
        return replicated(mesh)
    dims = sorted(range(leaf.ndim), key=lambda d: -leaf.shape[d])
    for d in dims:
        if leaf.shape[d] % n == 0 and leaf.shape[d] >= n:
            spec = [None] * leaf.ndim
            spec[d] = AXIS_FSDP
            return NamedSharding(mesh, P(*spec))
    return replicated(mesh)


def param_shardings(mesh: Mesh, params) -> object:
    """Pytree of shardings for a param pytree (fsdp-aware)."""
    return jax.tree.map(lambda leaf: fsdp_param_sharding(mesh, leaf), params)


def local_mesh(n: Optional[int] = None, **axes) -> Mesh:
    """Convenience for tests: mesh over the first ``n`` local devices."""
    devs = jax.devices()[: (n or len(jax.devices()))]
    return build_mesh(MeshConfig(**axes), devs)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host PJRT bring-up.

    The analog of the reference's gloo rendezvous
    (``distributed.py:101-105``): instead of MASTER_ADDR + hardcoded
    port 3333 + rank=partition_index+1, each host process calls this
    with a coordinator address; JAX's distributed runtime forms the
    global device set. Env fallbacks mirror the reference's
    ``SPARK_LOCAL_IP`` convention (``distributed.py:35-36``).
    """
    if jax.process_count() > 1:
        return  # already initialized
    coordinator_address = coordinator_address or os.environ.get(
        "SPARKTORCH_TPU_COORDINATOR"
    )
    if coordinator_address is None:
        return  # single-process mode
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
