"""JAX version compatibility shims.

The codebase targets current JAX, but deployment images pin older
releases (this container ships 0.4.x). Two APIs the hot paths use
landed after 0.4.37; both have exact equivalents there:

- ``jax.lax.axis_size(name)`` — the static size of a mapped axis.
  Equivalent: ``jax.lax.psum(1, name)``, which JAX constant-folds to
  the axis size from the static axis env (no collective is emitted).
- ``jax.set_mesh(mesh)`` as a context manager — the ambient mesh.
  Equivalent: ``with mesh:`` (``Mesh.__enter__``), which is what
  resolves shard_map/with_sharding_constraint axis names here.

Call sites import from this module so the same wheel runs on both
sides of the API change.
"""

from __future__ import annotations

import jax


def axis_size(name) -> jax.Array:
    """Static size of the mapped axis ``name`` (int under tracing)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh
