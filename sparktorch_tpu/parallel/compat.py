"""JAX version compatibility shims.

The codebase targets current JAX, but deployment images pin older
releases (this container ships 0.4.x). Two APIs the hot paths use
landed after 0.4.37; both have exact equivalents there:

- ``jax.lax.axis_size(name)`` — the static size of a mapped axis.
  Equivalent: ``jax.lax.psum(1, name)``, which JAX constant-folds to
  the axis size from the static axis env (no collective is emitted).
- ``jax.set_mesh(mesh)`` as a context manager — the ambient mesh.
  Equivalent: ``with mesh:`` (``Mesh.__enter__``), which is what
  resolves shard_map/with_sharding_constraint axis names here.

Call sites import from this module so the same wheel runs on both
sides of the API change.
"""

from __future__ import annotations

import jax


def axis_size(name) -> jax.Array:
    """Static size of the mapped axis ``name`` (int under tracing)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def ambient_gspmd_mesh():
    """The ambient concrete :class:`~jax.sharding.Mesh` when we are in
    GSPMD context, else None.

    "GSPMD context" means a mesh is installed (``set_mesh`` / ``with
    mesh:``) and NONE of its axis names is bound as a manual mapped
    axis — inside a ``shard_map`` (or pmap) body every mesh axis is
    Manual, sharding constraints are meaningless-to-wrong there, and
    collective islands must not nest. The 0.4.x runtime has no
    ``get_abstract_mesh``/axis-types API, so this is the one
    version-portable detection point: the physical mesh comes off the
    thread-local resource env that ``Mesh.__enter__`` installs, and
    Manual-ness is probed through the trace-state axis env (a bound
    axis name resolves; an unbound one raises NameError). Fails CLOSED:
    any API drift returns None, which callers treat as "no mesh" — the
    plain single-device code path, never a wrong collective."""
    try:
        from jax._src.mesh import thread_resources

        mesh = thread_resources.env.physical_mesh
        if mesh is None or mesh.empty:
            return None
        frame = jax.core.axis_frame  # AttributeError on newer jax -> closed
        for name in mesh.axis_names:
            try:
                frame(name)
                return None  # bound => Manual (shard_map/pmap body)
            except NameError:
                continue
        return mesh
    except Exception:  # noqa: BLE001 - fail closed across jax versions
        return None
