"""Parameter sharding rules: param path -> PartitionSpec.

The reference replicates the full model on every executor
(``distributed.py:112-115``) — its only layout. Here layouts are
first-class: rules map parameter tree paths to mesh axes, XLA GSPMD
inserts the collectives. Megatron-style conventions for transformers:

- qkv / mlp-in kernels: column-parallel over ``tp`` (output dim)
- attention-out / mlp-out kernels: row-parallel over ``tp`` (input
  dim; GSPMD adds the all-reduce after the matmul)
- embeddings: vocab dim over ``tp``
- everything else: optionally ``fsdp``-sharded on the largest
  divisible dim, else replicated
"""

from __future__ import annotations

import re
from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparktorch_tpu.parallel.mesh import (
    AXIS_EP,
    AXIS_FSDP,
    AXIS_TP,
    BATCH_AXES,
    fsdp_param_sharding,
)


# ---------------------------------------------------------------------------
# MoE dispatch/combine layouts (the shard_mapped all-to-all region)
# ---------------------------------------------------------------------------
#
# The MoE hot path has exactly two layouts, and the dispatch/combine
# all-to-alls are the relayout between them (models.transformer
# ``_ep_relayout`` — an explicit shard_map island, NOT a partitioner-
# derived reshard; jax 0.4.37's GSPMD lowers the constraint-derived
# version to all-gather + all-reduce, full token replication):
#
# - GROUPS layout: routing groups shard over every batch axis AND ep —
#   each ep member routes only its share of the groups. Routing,
#   dispatch-plan construction and the gate-weighted combine all run
#   here, fully device-local.
# - EXPERTS layout: the experts dim shards over ep (groups stay over
#   the batch axes only) — the dense expert FFN runs here, against the
#   ep-sharded expert weights laid out by the param rules below.

# (G, g, d) routed tokens / (G, g, e, cap) dispatch plans: groups over
# dp+fsdp+ep, everything else local.
MOE_GROUPS_TOKENS_SPEC = P(BATCH_AXES + (AXIS_EP,), None, None)
# (G, e, cap, d) capacity blocks, groups layout (pre-dispatch /
# post-combine side of the all-to-alls).
MOE_GROUPS_BLOCKS_SPEC = P(BATCH_AXES + (AXIS_EP,), None, None, None)
# (G, e, cap, d) capacity blocks, experts layout (the expert-FFN side).
MOE_EXPERTS_BLOCKS_SPEC = P(BATCH_AXES, AXIS_EP, None, None)


# (path regex, spec builder taking leaf ndim) — first match wins.
_TRANSFORMER_RULES = [
    # qkv DenseGeneral kernel (d_model, 3, heads, head_dim): heads on tp.
    (re.compile(r".*attn/qkv/kernel$"), lambda nd: P(*([None] * (nd - 2) + [AXIS_TP, None]))),
    (re.compile(r".*attn/qkv/bias$"), lambda nd: P(*([None] * (nd - 2) + [AXIS_TP, None])) if nd >= 2 else P()),
    # attention out DenseGeneral kernel (heads, head_dim, d_model): row-parallel.
    (re.compile(r".*attn/proj/kernel$"), lambda nd: P(*([AXIS_TP] + [None] * (nd - 1)))),
    # MLP column then row parallel.
    (re.compile(r".*mlp_in/kernel$"), lambda nd: P(*([None] * (nd - 1) + [AXIS_TP]))),
    (re.compile(r".*mlp_in/bias$"), lambda nd: P(AXIS_TP) if nd == 1 else P()),
    (re.compile(r".*mlp_out/kernel$"), lambda nd: P(*([AXIS_TP] + [None] * (nd - 1)))),
    # Embeddings: vocab over tp, model dim over fsdp.
    (re.compile(r".*tok_embed/embedding$"), lambda nd: P(AXIS_TP, AXIS_FSDP)),
    (re.compile(r".*lm_head/kernel$"), lambda nd: P(None, AXIS_TP)),
    # Mixture-of-experts: experts dim over ep; the FFN's inner dim
    # additionally over tp (column then row parallel, like the dense
    # MLP). The router is tiny and stays replicated (no rule).
    (re.compile(r".*moe_w_in$"), lambda nd: P(AXIS_EP, None, AXIS_TP)),
    (re.compile(r".*moe_b_in$"), lambda nd: P(AXIS_EP, AXIS_TP)),
    (re.compile(r".*moe_w_out$"), lambda nd: P(AXIS_EP, AXIS_TP, None)),
    (re.compile(r".*moe_b_out$"), lambda nd: P(AXIS_EP, None)),
]


def _path_str(path) -> str:
    parts = []
    for key in path:
        name = getattr(key, "key", None) or getattr(key, "name", None) or str(key)
        parts.append(str(name))
    return "/".join(parts)


def transformer_rules(mesh: Mesh) -> Callable:
    """Rules callable: (path, leaf) -> NamedSharding."""

    def rule(path, leaf) -> NamedSharding:
        path_s = _path_str(path)
        nd = getattr(leaf, "ndim", 0)
        shape = getattr(leaf, "shape", ())
        for pattern, builder in _TRANSFORMER_RULES:
            if pattern.match(path_s):
                spec = builder(nd)
                if _spec_fits(spec, shape, mesh):
                    return NamedSharding(mesh, spec)
                break
        return fsdp_param_sharding(mesh, leaf)

    return rule


def _spec_fits(spec: P, shape, mesh: Mesh) -> bool:
    for dim, axis in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        total = 1
        for a in axes:
            total *= mesh.shape[a]
        if total > 1 and dim % total != 0:
            return False
    return True


def shard_params(params, mesh: Mesh, rules: Optional[Callable] = None):
    """Pytree of NamedShardings for a (possibly abstract) param tree."""
    rules = rules or transformer_rules(mesh)
    return jax.tree_util.tree_map_with_path(rules, params)
