"""sparktorch_tpu.ft — the fault-tolerance subsystem.

Three parts: declarative policies (:mod:`ft.policy`), the gang
supervisor that acts on heartbeats and process liveness
(:mod:`ft.supervisor`), and the seeded chaos-injection harness that
makes the recovery paths testable (:mod:`ft.chaos`).

``policy`` and ``chaos`` import nothing from the rest of the package,
so the injection points buried in ``net/``, ``serve/``, ``obs/`` and
``train/`` can import them without cycles; the supervisor (which needs
``obs``) loads lazily via module ``__getattr__``.
"""

from sparktorch_tpu.ft.chaos import (
    ChaosConfig,
    ChaosInjector,
    ChaosKill,
    ChaosServerError,
    inject,
)
# Re-bind the submodule under its own name: the from-import above
# must not leave `ft.chaos` pointing at anything but the module.
from sparktorch_tpu.ft import chaos  # noqa: F401  (module, not symbol)
from sparktorch_tpu.ft.policy import (
    BarrierPolicy,
    FtPolicy,
    RestartPolicy,
    StragglerPolicy,
)

_LAZY = ("Supervisor", "ThreadWorker", "ProcessWorker", "WorkerFailed",
         "WorkerPreempted", "supervise_run")


def __getattr__(name):
    if name in _LAZY:
        from sparktorch_tpu.ft import supervisor

        return getattr(supervisor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "ChaosConfig",
    "ChaosInjector",
    "ChaosKill",
    "ChaosServerError",
    "inject",
    "BarrierPolicy",
    "FtPolicy",
    "RestartPolicy",
    "StragglerPolicy",
    *_LAZY,
]
