"""Seeded, deterministic fault injection for the recovery paths.

Recovery code that is never executed is theoretical. This module puts
named INJECTION POINTS behind the hot paths — the hogwild worker loop,
the binary transport's request path, the parameter server's wire
routes, the heartbeat emitter — and a :class:`ChaosInjector` that
decides, deterministically from an explicit config (plus a seeded RNG
for the probabilistic modes), when each point fires:

- kill a worker/rank at step N (one-shot by default, so the
  supervisor-restarted worker survives its rerun);
- freeze a rank's heartbeats from step N (alive-but-silent — the
  failure mode the barrier deadline exists for);
- drop the keep-alive connection under the next transport request
  (exercises reconnect + backoff);
- force server 500s on the next K pushes, or truncate the next K
  binary pull frames (exercises the client's error paths without
  burning the server's tolerated-error budget).

Install is process-global (``with chaos(config): ...``) because the
faults must reach code deep inside worker threads without threading a
handle through every layer; ``fire()`` is a single global read + None
check when no injector is installed, so production paths pay nothing.

This module imports nothing from the rest of the package — injection
points in ``net/``, ``serve/``, ``obs/`` and ``train/`` can all import
it without cycles.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Mapping, Optional


class ChaosKill(RuntimeError):
    """Raised at an injection point to kill the enclosing worker."""


class ChaosServerError(RuntimeError):
    """Raised server-side to force an HTTP 500 on a wire route."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """What to break, and when. All fields are explicit (worker/rank ->
    step, or a countdown budget), so a config replays identically;
    ``seed`` exists for future probabilistic modes and to label runs."""

    seed: int = 0
    # worker/rank -> step: raise ChaosKill at the 'worker.step' site
    # once the worker reaches that step. One-shot per worker by
    # default (kill_times) so the restarted worker's rerun survives.
    kill_worker_at: Mapping[int, int] = dataclasses.field(
        default_factory=dict)
    kill_times: int = 1
    # rank -> step: stop publishing heartbeat files from that step on
    # (the process stays alive — a freeze, not a death).
    freeze_heartbeat_at: Mapping[int, int] = dataclasses.field(
        default_factory=dict)
    # Drop the client's keep-alive connection under the next K
    # transport requests (simulates the server closing the socket /
    # a network blip mid-run).
    drop_connections: int = 0
    # Force a 500 on the next K gradient pushes, server-side.
    server_error_pushes: int = 0
    # Truncate the next K binary pull bodies server-side (client must
    # fail with WireError, never hang or half-decode).
    truncate_pull_frames: int = 0
    # shard id -> Nth request (1-based) at which that param-server
    # fleet shard's HTTP frontend dies mid-conversation (one-shot, so
    # the monitor-restarted frontend survives). Clients must degrade
    # to the remaining ring inside their grace window; the fleet
    # monitor must bring the shard back.
    kill_shard_at: Mapping[Any, int] = dataclasses.field(
        default_factory=dict)
    # shard id -> seconds of injected latency on EVERY request that
    # shard's HTTP frontend serves while the config is installed — the
    # straggler-shard fault: the shard stays correct, just slow, which
    # is exactly what per-request tracing must attribute (the slow
    # hop named as the critical path, not inferred from aggregates).
    slow_shard_s: Mapping[Any, float] = dataclasses.field(
        default_factory=dict)
    # replica id -> Nth admitted request (1-based) at which that
    # SERVING replica dies mid-admission (one-shot, so a monitor-
    # restarted replica survives its rerun) — the router-eviction
    # fault, mirroring kill_shard_at: the router must fail the hop,
    # evict, and re-route the request with zero drops.
    kill_replica_at: Mapping[Any, int] = dataclasses.field(
        default_factory=dict)
    # replica id -> seconds of injected latency on every request that
    # replica admits while the config is installed — the straggler-
    # replica fault (correct, just slow): load-aware routing must
    # shift traffic away, and a traced request's replica hop must
    # name it.
    slow_replica_s: Mapping[Any, float] = dataclasses.field(
        default_factory=dict)
    # worker/rank -> step: at the 'data.batch' site, tell the trainer
    # to poison its resident batch (NaN in the feature rows — see
    # poison_batch) before dispatching that step. One-shot per worker:
    # the drill needs exactly one bad step, then clean recovery
    # steps for the detectors/alerts to resolve against.
    poison_batch_at: Mapping[int, int] = dataclasses.field(
        default_factory=dict)
    # rank -> (from_step, delay_s): make that TRAIN rank a straggler —
    # the 'train.rank' site (fired inside the step loop, before the
    # step's collective fence) returns {"delay": delay_s} on EVERY
    # step >= from_step, so the rank arrives late at the fence and its
    # peers' exposed waits are attributable to it. Persistent, not
    # one-shot: the skew referee's sustained straggler-fraction rule
    # exists precisely for a rank that stays slow.
    slow_rank_s: Mapping[int, Any] = dataclasses.field(
        default_factory=dict)
    # rank -> step: deliver a raw SIGKILL to that rank's PROCESS
    # worker once its heartbeat reports reaching the step — the
    # NON-COOPERATIVE death the thread deployment can never exercise
    # (no cancel event, no grace, a worker wedged on the GIL dies
    # anyway). Fired at the 'ctl.process' site by the supervising
    # handle's own liveness poll; one-shot per rank so the restarted
    # worker's rerun survives.
    kill_process_at: Mapping[int, int] = dataclasses.field(
        default_factory=dict)


class ChaosInjector:
    """Evaluates a :class:`ChaosConfig` at each named site.

    Thread-safe: worker threads, HTTP handler threads, and heartbeat
    threads all consult the same injector. ``events`` records every
    fault actually fired (site + context) for tests and post-mortems.
    """

    def __init__(self, config: ChaosConfig,
                 telemetry: Optional[Any] = None):
        self.config = config
        self.telemetry = telemetry
        self.events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._kills_fired: Dict[int, int] = {}
        self._drops_left = int(config.drop_connections)
        self._errors_left = int(config.server_error_pushes)
        self._truncs_left = int(config.truncate_pull_frames)
        self._shard_requests: Dict[str, int] = {}
        self._shard_kills_fired: set = set()
        self._replica_requests: Dict[str, int] = {}
        self._replica_kills_fired: set = set()
        self._process_kills_fired: set = set()
        self._poisons_fired: set = set()

    def _record(self, site: str, **ctx: Any) -> None:
        self.events.append({"site": site, **ctx})
        if self.telemetry is not None:
            self.telemetry.counter("chaos_injections_total",
                                   labels={"site": site})

    def fire(self, site: str, **ctx: Any) -> Optional[Dict[str, Any]]:
        """Evaluate one injection point. Returns an action dict for
        sites the caller must act on (drop/truncate/skip), raises for
        kill/error sites, or returns None (the overwhelmingly common
        case: nothing to inject here)."""
        cfg = self.config
        if site == "worker.step":
            worker = ctx.get("worker")
            at = cfg.kill_worker_at.get(worker)
            if at is not None and ctx.get("step", -1) >= at:
                with self._lock:
                    fired = self._kills_fired.get(worker, 0)
                    if fired >= cfg.kill_times:
                        return None
                    self._kills_fired[worker] = fired + 1
                    self._record(site, **ctx)
                raise ChaosKill(
                    f"chaos: killed worker {worker} at step {ctx.get('step')}"
                )
        elif site == "heartbeat.beat":
            rank = ctx.get("rank")
            at = cfg.freeze_heartbeat_at.get(rank)
            if at is not None:
                step = ctx.get("step")
                # at <= 0 freezes from the first beat; otherwise only
                # once the rank has reported reaching that step.
                if at <= 0 or (step is not None and step >= at):
                    with self._lock:
                        self._record(site, rank=rank, step=step)
                    return {"skip": True}
        elif site == "transport.request":
            with self._lock:
                if self._drops_left > 0:
                    self._drops_left -= 1
                    self._record(site, **ctx)
                    return {"drop": True}
        elif site == "param_server.update":
            forced = False
            with self._lock:
                if self._errors_left > 0:
                    self._errors_left -= 1
                    self._record(site, **ctx)
                    forced = True
            if forced:
                raise ChaosServerError("chaos: forced server error")
        elif site == "param_server.pull":
            with self._lock:
                if self._truncs_left > 0:
                    self._truncs_left -= 1
                    self._record(site, **ctx)
                    return {"truncate": True}
        elif site == "fleet.shard":
            shard = str(ctx.get("shard"))
            action: Dict[str, Any] = {}
            delay = next((float(v) for k, v in cfg.slow_shard_s.items()
                          if str(k) == shard), None)
            if delay:
                with self._lock:
                    self._record(site, shard=shard,
                                 route=ctx.get("route"), delay_s=delay)
                action["delay"] = delay
            at = next((int(v) for k, v in cfg.kill_shard_at.items()
                       if str(k) == shard), None)
            if at is not None:
                with self._lock:
                    count = self._shard_requests.get(shard, 0) + 1
                    self._shard_requests[shard] = count
                    if count >= at and shard not in self._shard_kills_fired:
                        # One-shot per shard: the restarted frontend's
                        # requests must survive their rerun.
                        self._shard_kills_fired.add(shard)
                        self._record(site, shard=shard,
                                     route=ctx.get("route"))
                        action["die"] = True
            return action or None
        elif site == "data.batch":
            # Poison-batch injection (the model-health drill): the
            # trainer must act on {"poison": True} by replacing its
            # batch with a NaN-poisoned copy BEFORE dispatch, so the
            # health ledger's replay anchor records the poisoned
            # batch. One-shot per worker.
            worker = ctx.get("worker")
            at = cfg.poison_batch_at.get(worker)
            if at is not None and ctx.get("step", -1) >= at:
                with self._lock:
                    if worker in self._poisons_fired:
                        return None
                    self._poisons_fired.add(worker)
                    self._record(site, **ctx)
                return {"poison": True}
        elif site == "train.rank":
            # Straggler injection: the trainer sleeps {"delay": s}
            # before its step span / collective fence, so the delay is
            # visible to the cross-rank skew referee as a late arrival
            # (never hidden inside the victim's own measured step).
            rank = ctx.get("rank")
            spec = next((v for k, v in cfg.slow_rank_s.items()
                         if str(k) == str(rank)), None)
            if spec is not None:
                from_step, delay = int(spec[0]), float(spec[1])
                step = ctx.get("step")
                if delay > 0 and step is not None and step >= from_step:
                    with self._lock:
                        self._record(site, rank=rank, step=step,
                                     delay_s=delay)
                    return {"delay": delay}
        elif site == "ctl.process":
            # Non-cooperative process kill: the handle's liveness poll
            # asks "should this rank die NOW?" with the step its
            # heartbeat last reported. None until the step is reached;
            # one SIGKILL action per rank, ever (the restarted rerun
            # must survive).
            rank = ctx.get("rank")
            at = cfg.kill_process_at.get(rank)
            if at is not None:
                step = ctx.get("step")
                if step is not None and step >= at:
                    with self._lock:
                        if rank in self._process_kills_fired:
                            return None
                        self._process_kills_fired.add(rank)
                        self._record(site, rank=rank, step=step)
                    return {"sigkill": True}
        elif site == "serve.replica":
            # Same shape as 'fleet.shard': an optional straggler delay
            # plus a one-shot Nth-request kill, keyed by replica id.
            replica = str(ctx.get("replica"))
            action = {}
            delay = next((float(v) for k, v in cfg.slow_replica_s.items()
                          if str(k) == replica), None)
            if delay:
                with self._lock:
                    self._record(site, replica=replica, delay_s=delay)
                action["delay"] = delay
            at = next((int(v) for k, v in cfg.kill_replica_at.items()
                       if str(k) == replica), None)
            if at is not None:
                with self._lock:
                    count = self._replica_requests.get(replica, 0) + 1
                    self._replica_requests[replica] = count
                    if count >= at \
                            and replica not in self._replica_kills_fired:
                        # One-shot per replica: the monitor-restarted
                        # replica's requests survive their rerun.
                        self._replica_kills_fired.add(replica)
                        self._record(site, replica=replica)
                        action["die"] = True
            return action or None
        return None


# ---------------------------------------------------------------------------
# Process-global installation
# ---------------------------------------------------------------------------

_ACTIVE: Optional[ChaosInjector] = None
_ACTIVE_LOCK = threading.Lock()


def install(injector: ChaosInjector) -> ChaosInjector:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = injector
    return injector


def uninstall() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def active() -> Optional[ChaosInjector]:
    return _ACTIVE


def fire(site: str, **ctx: Any) -> Optional[Dict[str, Any]]:
    """The call every injection point makes. Free when chaos is off."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.fire(site, **ctx)


def straggle(rank: Any, step: int) -> float:
    """The 'train.rank' injection point, packaged: fire the site and
    sleep any injected straggler delay. Trainers call this inside the
    step loop BEFORE the step span / collective fence, so the delay
    shows up to the cross-rank skew referee as a late fence arrival
    (the laggard's unattributed time), never as inflated step compute.
    Returns the seconds slept (0.0 when chaos is off — one global
    read, like every other site)."""
    act = fire("train.rank", rank=rank, step=step)
    if act and act.get("delay"):
        delay = float(act["delay"])
        time.sleep(delay)
        return delay
    return 0.0


def poison_batch(batch: Any) -> Any:
    """NaN-poison the first feature row of a DataBatch-shaped pytree
    (the action a {"poison": True} verdict from the 'data.batch' site
    demands). Returns a NEW batch — device buffers are immutable, and
    the fresh identity is load-bearing: the health ledger re-anchors
    its replay snapshot on batch-identity change, so the recorded
    bundle holds exactly the poisoned bytes that dispatched."""
    import jax.numpy as jnp

    x = jnp.asarray(batch.x).at[0].set(jnp.nan)
    try:
        return batch._replace(x=x)
    except AttributeError:
        return type(batch)(x=x, y=batch.y, w=batch.w)


@contextlib.contextmanager
def inject(config_or_injector, telemetry: Optional[Any] = None):
    """Install an injector for a with-block; always uninstalls.

    (Named ``inject``, not ``chaos``: the package re-exports this
    beside the ``ft.chaos`` SUBMODULE, and shadowing the module name
    would break the injection points' ``from sparktorch_tpu.ft import
    chaos`` imports.)"""
    inj = (config_or_injector
           if isinstance(config_or_injector, ChaosInjector)
           else ChaosInjector(config_or_injector, telemetry=telemetry))
    install(inj)
    try:
        yield inj
    finally:
        uninstall()
