"""The gang supervisor: owns workers, watches liveness, applies policy.

What the ROADMAP calls "heartbeat-driven orchestration": all the raw
signals already exist — per-rank heartbeat files with step attribution
(``obs.heartbeat``), the gang exporter serving the same table over
HTTP (``native.gang.GangMetricsExporter``), thread/process liveness —
but until now nothing *acted* on them. The :class:`Supervisor` does:

- **restart-on-death** with exponential backoff + deterministic
  jitter under a per-worker budget (``RestartPolicy``);
- **straggler detection** from cross-rank step skew (warn at N steps,
  optionally preempt at M) read from heartbeat files or a gang
  exporter's ``/heartbeats`` route (``StragglerPolicy``);
- **stall deadlines**: a worker whose heartbeat AGE exceeds the
  barrier deadline while its handle still looks alive is treated as
  wedged and preempted (``BarrierPolicy``).

Recovery is observable: every restart bumps ``ft_restarts_total``
(labelled by worker), straggler episodes bump
``ft_straggler_warnings_total`` / ``ft_straggler_preemptions_total``,
and the death->running-again latency lands in the
``ft_recovery_latency_s`` histogram — all on the same telemetry bus
the trainers and the param server share, so one ``/metrics`` scrape
(or JSONL dump) tells the whole recovery story.

Workers run as threads (the hogwild deployment inside ``train_async``)
or real processes; the HANDLE CONTRACT is tiny on purpose — ``name``,
``error`` (None until a failure is known), ``is_alive()``,
``join(timeout)``, ``kill()`` — and has three implementations:
:class:`ThreadWorker` (cooperative kill via a cancel Event),
:class:`ProcessWorker` here (a bare ``multiprocessing.Process``
terminate), and :class:`sparktorch_tpu.ctl.proc.ProcessWorker` (the
control-plane one: spawned ``python -m sparktorch_tpu.ctl.worker``
children, heartbeat-file liveness, and a ``kill()`` that escalates
SIGTERM -> grace -> SIGKILL, so even a worker wedged on the GIL
actually dies). Restarted sync ranks resume from the latest finalized
checkpoint (auto-discovered via ``utils.checkpoint.latest_step``);
restarted hogwild workers rejoin by pulling the current server version
(their first pull is ``have_version=-1``).

Budget exhaustion is pluggable: by default a worker that spends its
restart budget fails the run (:class:`WorkerFailed`); a supervisor
constructed with ``on_exhausted=`` can ABSORB the failure instead —
the elastic controller's shrink path (:mod:`sparktorch_tpu.ctl.
elastic`) redistributes the dead rank's work and the run continues in
a smaller world.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from sparktorch_tpu.ft.policy import FtPolicy
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.obs.log import get_logger
from sparktorch_tpu.obs.telemetry import get_telemetry


class WorkerFailed(RuntimeError):
    """A supervised worker failed and its restart budget is spent."""


class WorkerPreempted(RuntimeError):
    """A cooperative worker observed its cancel event and stopped.

    Raised by worker loops that poll the :class:`ThreadWorker` cancel
    event (the hogwild ``_worker_loop`` polls between windows), so a
    supervisor ``kill()`` — straggler preemption, stall deadline —
    actually stops a thread-based worker instead of merely flagging
    it. The supervisor treats the death of a ``preempting`` worker as
    a restart under budget, whatever it raised."""


class ThreadWorker:
    """Thread-backed worker handle. The target either returns (clean
    exit) or raises (failure — captured, surfaced via ``error``).
    ``kill()`` is cooperative: it sets ``cancel`` (an Event the target
    may poll); threads cannot be preempted — process workers can."""

    def __init__(self, name: str, target: Callable[..., Any],
                 pass_cancel: bool = False):
        self.name = name
        self.error: Optional[BaseException] = None
        self.cancel = threading.Event()

        def run():
            try:
                target(self.cancel) if pass_cancel else target()
            except BaseException as e:  # surfaced to the supervisor
                self.error = e

        self._thread = threading.Thread(
            target=run, name=f"ft-worker-{name}", daemon=True
        )
        self._thread.start()

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def kill(self) -> None:
        self.cancel.set()


class ProcessWorker:
    """``multiprocessing.Process`` handle: non-zero exitcode = failure,
    ``kill()`` is a real terminate. The process must already be
    started (or ``start()``ed by the factory that returns it)."""

    def __init__(self, process: Any):
        self.process = process
        if not process.is_alive() and process.exitcode is None:
            process.start()

    @property
    def name(self) -> str:
        return getattr(self.process, "name", "process")

    @property
    def error(self) -> Optional[BaseException]:
        code = self.process.exitcode
        if code is None or code == 0:
            return None
        return WorkerFailed(f"{self.name}: exit code {code}")

    def is_alive(self) -> bool:
        return self.process.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self.process.join(timeout)

    def kill(self) -> None:
        self.process.terminate()


class _Supervised:
    """One worker's supervision state."""

    __slots__ = ("name", "rank", "start_fn", "handle", "restarts",
                 "done", "failed", "warned", "preempting",
                 "restart_at", "detected_at")

    def __init__(self, name: str, start_fn, rank: Optional[int]):
        self.name = name
        self.rank = rank
        self.start_fn = start_fn
        self.handle = None
        self.restarts = 0
        self.done = False
        self.failed: Optional[BaseException] = None
        self.warned = False      # straggler episode latch
        self.preempting = False  # kill() issued, waiting for death
        # Scheduled restart: backoff waits here (checked by the poll
        # loop), never as an inline sleep — a 5s backoff for one
        # worker must not freeze death detection for the others.
        self.restart_at: Optional[float] = None
        self.detected_at: Optional[float] = None


class Supervisor:
    """Owns a set of workers and runs them to completion under policy.

    ``heartbeat_dir`` and/or ``exporter_url`` wire the liveness/skew
    source (heartbeat files, or a ``GangMetricsExporter``'s
    ``/heartbeats`` route); without either, supervision still covers
    death-and-restart from handle liveness alone.
    """

    def __init__(self, policy: Optional[FtPolicy] = None,
                 telemetry=None, heartbeat_dir: Optional[str] = None,
                 exporter_url: Optional[str] = None,
                 on_exhausted=None,
                 name: str = "supervisor",
                 postmortem_dir: Optional[str] = None,
                 postmortem_window_s: float = 30.0,
                 collector=None):
        self.policy = policy or FtPolicy()
        self.telemetry = telemetry or get_telemetry()
        self.heartbeat_dir = heartbeat_dir
        self.exporter_url = exporter_url
        # Flight-recorder postmortems: with a ``postmortem_dir``, every
        # detected death/preemption folds the available blackbox rings
        # (this bus's, plus each scraped rank's last-good when a
        # ``collector`` is attached) into one bundle — the evidence of
        # WHY a worker died no longer dies with its process.
        self.postmortem_dir = postmortem_dir
        self.postmortem_window_s = float(postmortem_window_s)
        self.collector = collector
        if postmortem_dir:
            from sparktorch_tpu.obs.blackbox import attach_recorder

            attach_recorder(self.telemetry)
        # ``on_exhausted(name, rank, error) -> bool``: called when a
        # worker dies past its restart budget. True = the failure was
        # ABSORBED (an elastic controller shrank the world and
        # redistributed the work) — the worker is marked done and the
        # run continues; False/None keeps the original fail-the-run
        # behavior.
        self.on_exhausted = on_exhausted
        self.name = name
        self._rng = self.policy.rng()
        self._workers: List[_Supervised] = []
        self._log = get_logger("sparktorch_tpu.ft.supervisor")

    # -- registration ------------------------------------------------------

    def add(self, name: str, start_fn: Callable[[int], Any],
            rank: Optional[int] = None) -> None:
        """Register a worker. ``start_fn(attempt)`` must (re)start the
        worker and return its handle; attempt 0 is the first launch.
        ``rank`` links the worker to its heartbeat record for
        straggler/stall policies."""
        self._workers.append(_Supervised(name, start_fn, rank))

    # -- heartbeat / skew source -------------------------------------------

    def _report(self) -> Optional[Dict[str, Any]]:
        if self.heartbeat_dir:
            from sparktorch_tpu.obs.heartbeat import gang_report

            return gang_report(self.heartbeat_dir)
        if self.exporter_url:
            # The scrape must DEGRADE, never crash the poll loop: an
            # exporter answering 500, a torn JSON body, a server that
            # vanished mid-poll, or a well-formed reply with a shape
            # this reader doesn't expect (non-dict, junk rank keys)
            # all reduce to "no report this tick" — a warning plus the
            # ft_scrape_errors_total counter, while death-and-restart
            # supervision from handle liveness continues untouched.
            from sparktorch_tpu.obs.collector import ScrapeError, scrape_json

            url = self.exporter_url.rstrip("/") + "/heartbeats"
            try:
                report = scrape_json(url, timeout=2.0)
                if not isinstance(report, dict):
                    raise ScrapeError(f"{url}: not a JSON object")
                # The exporter serialized rank keys as strings; re-key
                # (junk keys are a malformed reply, same degradation).
                report["ranks"] = {
                    int(k): v for k, v in (report.get("ranks") or {}).items()
                }
                return report
            except (ScrapeError, ValueError, TypeError, AttributeError) as e:
                self.telemetry.counter("ft_scrape_errors_total",
                                       labels={"source": "exporter"})
                self._log.warning(
                    f"[sparktorch_tpu:ft] exporter scrape failed "
                    f"(skew/stall policies skip this tick): {e}"
                )
                return None
        return None

    # -- policy application ------------------------------------------------

    def _postmortem(self, reason: str, worker: Optional[str] = None,
                    rank: Optional[int] = None) -> None:
        """Best-effort bundle write on a detected death/preemption:
        evidence must never take supervision down with it."""
        if not self.postmortem_dir:
            return
        from sparktorch_tpu.obs.blackbox import collect_postmortem

        try:
            collect_postmortem(
                self.postmortem_dir,
                f"{worker or self.name}: {reason}",
                telemetry=self.telemetry,
                collector=self.collector,
                history=getattr(self.collector, "history", None),
                window_s=self.postmortem_window_s,
                rank=rank,
            )
            self.telemetry.counter("ft_postmortems_total")
        except Exception as e:  # noqa: BLE001 - best-effort evidence
            self.telemetry.counter("ft_postmortem_failures_total")
            self._log.warning(
                f"[sparktorch_tpu:ft] postmortem write failed: "
                f"{type(e).__name__}: {e}")

    def _schedule_restart(self, w: _Supervised, reason: str) -> None:
        """Death detected: either spend a restart slot (schedule the
        relaunch for after the backoff) or fail the worker for good.
        The backoff is a TIMESTAMP the poll loop checks, not a sleep —
        supervision of the other workers never pauses."""
        self._postmortem(reason, worker=w.name, rank=w.rank)
        policy = self.policy.restart
        if w.restarts >= policy.max_restarts:
            err = WorkerFailed(
                f"{w.name}: restart budget ({policy.max_restarts}) "
                f"exhausted ({reason})"
            )
            if self.on_exhausted is not None and self.on_exhausted(
                    w.name, w.rank, err):
                # Absorbed (elastic shrink): this worker's share moved
                # elsewhere; it is done, not failed.
                w.done = True
                self.telemetry.counter("ft_budget_absorbed_total",
                                       labels={"worker": w.name})
                self.telemetry.event("ft_budget_absorbed", worker=w.name,
                                     reason=reason)
                return
            w.failed = w.failed or err
            return
        delay = policy.delay_s(w.restarts, self._rng)
        w.detected_at = time.perf_counter()
        w.restart_at = w.detected_at + delay
        self._log.warning(
            f"[sparktorch_tpu:ft] worker {w.name} {reason}; restart "
            f"{w.restarts + 1}/{policy.max_restarts} in {delay:.3f}s"
        )
        self.telemetry.event("ft_restart_scheduled", worker=w.name,
                             reason=reason, delay_s=delay)

    def _do_restart(self, w: _Supervised) -> None:
        attempt = w.restarts + 1
        old = w.handle
        if old is not None:
            # Retire the replaced handle's on-disk residue (a ctl
            # ProcessWorker's payload/url files); thread handles have
            # no cleanup and are skipped.
            getattr(old, "cleanup", lambda: None)()
        w.handle = w.start_fn(attempt)
        w.restarts = attempt
        w.preempting = False
        w.warned = False
        w.restart_at = None
        labels = {"worker": w.name}
        self.telemetry.counter("ft_restarts_total", labels=labels)
        # Death-detection -> running-again, INCLUDING the backoff wait
        # (that is real downtime the policy chose to spend).
        latency = (time.perf_counter()  # lint-obs: ok (recovery clock pair, ledger-fed below)
                   - (w.detected_at or time.perf_counter()))  # lint-obs: ok (fallback read of the same clock)
        self.telemetry.observe("ft_recovery_latency_s", latency,
                               labels=labels)
        # Same window, same number, into the goodput ledger's
        # restart_downtime bucket — the reconciliation the bench gate
        # checks.
        _goodput.add("restart_downtime", latency)
        self.telemetry.event("ft_restart", worker=w.name, attempt=attempt)

    def _apply_skew_policies(self) -> None:
        report = self._report()
        if not report:
            return
        strag = self.policy.straggler
        ranks = report.get("ranks", {})
        by_rank = {w.rank: w for w in self._workers if w.rank is not None}
        # Stall deadline: heartbeat age beyond the barrier deadline on
        # a handle that still looks alive = wedged -> preempt.
        deadline = self.policy.barrier.deadline_s
        if deadline and deadline > 0:
            for rank, rec in ranks.items():
                w = by_rank.get(rank)
                if (w is None or w.done or w.failed or w.preempting
                        or w.handle is None or not w.handle.is_alive()):
                    continue
                if rec.get("alive") and rec["last_seen_age_s"] > deadline:
                    self._log.warning(
                        f"[sparktorch_tpu:ft] rank {rank} heartbeat "
                        f"age {rec['last_seen_age_s']:.1f}s > deadline "
                        f"{deadline}s; preempting"
                    )
                    self.telemetry.counter(
                        "ft_stall_preemptions_total",
                        labels={"worker": w.name},
                    )
                    w.preempting = True
                    w.handle.kill()
        if strag is None:
            return
        skew = report.get("step_skew")
        steps = {r: rec.get("step") for r, rec in ranks.items()
                 if rec.get("step") is not None}
        if skew is None or len(steps) < max(2, strag.min_ranks):
            return
        if skew < strag.warn_skew_steps:
            # Episode over (the laggard caught up): re-arm the warn
            # latches so the NEXT lagging episode warns again.
            for w in self._workers:
                w.warned = False
            return
        laggard_rank = min(steps, key=steps.get)
        w = by_rank.get(laggard_rank)
        if w is None or w.done or w.failed:
            return
        if skew >= strag.warn_skew_steps and not w.warned:
            w.warned = True
            self.telemetry.counter("ft_straggler_warnings_total",
                                   labels={"worker": w.name})
            self._log.warning(
                f"[sparktorch_tpu:ft] rank {laggard_rank} lags by "
                f"{skew} steps (warn threshold "
                f"{strag.warn_skew_steps})"
            )
        if (strag.preempt_skew_steps and strag.preempt_skew_steps > 0
                and skew >= strag.preempt_skew_steps
                and not w.preempting and w.handle is not None
                and w.handle.is_alive()):
            self.telemetry.counter("ft_straggler_preemptions_total",
                                   labels={"worker": w.name})
            self._log.warning(
                f"[sparktorch_tpu:ft] rank {laggard_rank} lags by "
                f"{skew} steps >= preempt threshold "
                f"{strag.preempt_skew_steps}; preempting"
            )
            w.preempting = True
            w.handle.kill()

    # -- main loop ---------------------------------------------------------

    def run(self, poll_interval_s: float = 0.05,
            deadline_s: Optional[float] = None) -> Dict[str, Any]:
        """Start every worker and supervise until all are done (or one
        fails past its budget). Returns a summary dict; raises
        :class:`WorkerFailed` on unrecovered failure."""
        t0 = time.perf_counter()
        for w in self._workers:
            w.handle = w.start_fn(0)
        while True:
            pending = False
            for w in self._workers:
                if w.done or w.failed:
                    continue
                if w.restart_at is not None:
                    # Waiting out the backoff; relaunch when due.
                    if time.perf_counter() >= w.restart_at:
                        self._do_restart(w)
                    pending = True
                    continue
                if w.handle.is_alive():
                    pending = True
                    continue
                err = w.handle.error
                if err is None and not w.preempting:
                    w.done = True
                    continue
                # Death (or a preempt landing): restart under budget.
                reason = (f"failed: {type(err).__name__}: {err}"
                          if err is not None else "preempted")
                self._schedule_restart(w, reason)
                if w.failed is None:
                    pending = True
            self._apply_skew_policies()
            if not pending:
                break
            if (deadline_s is not None
                    and time.perf_counter() - t0 > deadline_s):
                raise WorkerFailed(
                    f"{self.name}: supervision deadline {deadline_s}s "
                    "exceeded with workers still running"
                )
            time.sleep(poll_interval_s)
        failures = [w for w in self._workers if w.failed]
        summary = {
            "workers": len(self._workers),
            "restarts": {w.name: w.restarts for w in self._workers
                         if w.restarts},
            "failed": [w.name for w in failures],
            "wall_s": time.perf_counter() - t0,
        }
        if failures:
            raise WorkerFailed(
                f"{self.name}: {len(failures)} worker(s) failed past "
                f"their restart budget: {summary['failed']}"
            ) from failures[0].failed
        return summary


def supervise_run(fn: Callable[..., Any],
                  policy: Optional[FtPolicy] = None,
                  telemetry=None,
                  retry_on: tuple = (Exception,),
                  checkpoint_dir: Optional[str] = None,
                  name: str = "gang") -> Any:
    """Gang-LEVEL recovery for synchronous training: run
    ``fn(attempt=k, resume=bool)`` and, when it dies with a retriable
    error (a ``GangFailure``, a chaos kill, a failed Spark stage),
    restart the WHOLE attempt under the restart policy.

    ``resume`` is True only when a finalized checkpoint actually
    exists (auto-discovered via ``utils.checkpoint.latest_step`` when
    ``checkpoint_dir`` is given), so a first-attempt crash before any
    save restarts from scratch instead of erroring on an empty
    directory. Restart metrics land on the same bus as the worker-
    level supervisor's (``ft_restarts_total{worker=<name>}``).
    """
    policy = policy or FtPolicy()
    tele = telemetry or get_telemetry()
    log = get_logger("sparktorch_tpu.ft.supervisor")
    rng = policy.rng()
    attempt = 0
    while True:
        resume = False
        if checkpoint_dir:
            from sparktorch_tpu.utils.checkpoint import latest_step

            resume = attempt > 0 and latest_step(checkpoint_dir) is not None
        try:
            return fn(attempt=attempt, resume=resume)
        except retry_on as e:
            if attempt >= policy.restart.max_restarts:
                raise
            t_detect = time.perf_counter()
            delay = policy.restart.delay_s(attempt, rng)
            log.warning(
                f"[sparktorch_tpu:ft] {name} attempt {attempt} failed "
                f"({type(e).__name__}: {e}); restarting in {delay:.3f}s"
            )
            time.sleep(delay)
            attempt += 1
            tele.counter("ft_restarts_total", labels={"worker": name})
            latency = time.perf_counter() - t_detect  # lint-obs: ok (recovery clock pair, ledger-fed below)
            tele.observe("ft_recovery_latency_s", latency,
                         labels={"worker": name})
            _goodput.add("restart_downtime", latency)
            tele.event("ft_restart", worker=name, attempt=attempt,
                       reason=f"{type(e).__name__}: {e}")
