"""Declarative fault-tolerance policies.

The reference's failure model is all-or-nothing: barrier-mode training
dies with the whole Spark stage when one task fails
(``distributed.py:209-277``), and the hogwild server merely *tolerates*
a bounded error count without ever recovering a lost worker (SURVEY
§L3). These dataclasses are the knobs the :class:`ft.supervisor.
Supervisor` acts on instead — restart budgets with exponential backoff
and deterministic jitter, straggler thresholds on cross-rank step
skew, and liveness deadlines for workers that are alive-but-wedged.

Policies are plain frozen dataclasses so they dill/pickle cleanly
(they ride into Spark closures and Estimator Params) and so a test can
assert on exactly the policy a run used.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """Restart-on-death: exponential backoff + jitter under a budget.

    ``max_restarts`` is PER WORKER (each supervised rank gets its own
    budget); a worker that exhausts it fails the run. Jitter is drawn
    from the supervisor's seeded RNG — two supervisors with the same
    policy seed replay identical delays, which keeps chaos tests
    deterministic."""

    max_restarts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 5.0
    jitter: float = 0.2  # +- fraction of the delay

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Backoff before restart ``attempt`` (0-based: the delay
        before the first restart is the base)."""
        base = min(self.backoff_max_s,
                   self.backoff_base_s * (2.0 ** max(0, attempt)))
        if self.jitter <= 0:
            return base
        return max(0.0, base * (1.0 + self.jitter * rng.uniform(-1.0, 1.0)))


@dataclasses.dataclass(frozen=True)
class StragglerPolicy:
    """Cross-rank step-skew thresholds, read from the heartbeat table
    (``obs.heartbeat.gang_report``'s ``step_skew``): WARN once per
    lagging episode at ``warn_skew_steps``, PREEMPT (kill + restart,
    charged to the worker's restart budget) at ``preempt_skew_steps``.
    ``preempt_skew_steps <= 0`` disables preemption (warn-only)."""

    warn_skew_steps: int = 50
    preempt_skew_steps: int = 0
    min_ranks: int = 2  # skew needs at least two step reports


@dataclasses.dataclass(frozen=True)
class BarrierPolicy:
    """Deadlines for workers that are alive but not progressing.

    ``deadline_s`` bounds a rank's heartbeat AGE: a process that stops
    publishing beats for this long while its handle still looks alive
    (frozen in a wedged collective, a hung barrier) is treated as dead
    and preempted. Needs a heartbeat source wired into the supervisor;
    without one, only process/thread death is detectable."""

    deadline_s: float = 30.0


@dataclasses.dataclass(frozen=True)
class FtPolicy:
    """The full declarative policy the supervisor applies.

    ``seed`` drives the jitter RNG (determinism); ``rejoin_grace_s``
    is forwarded to the native gang coordinator as its re-registration
    grace window, so a supervisor-restarted rank can rejoin a failed
    gang (generation bump) instead of being refused forever."""

    restart: RestartPolicy = dataclasses.field(
        default_factory=RestartPolicy)
    straggler: Optional[StragglerPolicy] = dataclasses.field(
        default_factory=StragglerPolicy)
    barrier: BarrierPolicy = dataclasses.field(
        default_factory=BarrierPolicy)
    seed: int = 0
    rejoin_grace_s: float = 30.0

    def rng(self) -> random.Random:
        return random.Random(self.seed)
