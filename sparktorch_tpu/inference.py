"""Wrap already-trained models for batch inference.

Reference: ``sparktorch/inference.py`` —
``convert_to_serialized_torch`` (:8-15), ``create_spark_torch_model``
(:18-39), ``attach_pytorch_model_to_pipeline`` (:42-61).

Here a "trained model" is a Flax module + trained variables; the
wrapped :class:`SparkTorchModel` runs the compiled chunked forward
(no per-row UDF).
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparktorch_tpu.ml.estimator import SparkTorchModel, _encode_bundle
from sparktorch_tpu.ml.pipeline import PipelineModel
from sparktorch_tpu.parallel.mesh import batch_sharding, replicated
from sparktorch_tpu.utils.serde import ModelSpec


class BatchPredictor:
    """Mesh-parallel batch inference engine.

    The reference's inference is a batch-1 Python UDF per DataFrame
    row (``torch_distributed.py:106-120``); its 1M-row ResNet-50
    config (BASELINE.md #5) runs that loop per partition. Here: fixed
    static chunks, ONE compiled forward, and — with a mesh — the chunk
    batch dim sharded over dp(+fsdp) so all chips run inference
    concurrently on their slice (params replicated; XLA inserts
    nothing but the initial broadcast).
    """

    def __init__(self, module, params, model_state=None,
                 mesh: Optional[Mesh] = None, chunk: int = 1024):
        self.module = module
        self.mesh = mesh
        n_shards = 1
        if mesh is not None:
            from sparktorch_tpu.parallel.mesh import BATCH_AXES

            for ax in BATCH_AXES:
                n_shards *= mesh.shape[ax]
        c = max(chunk, n_shards)
        self.chunk = ((c + n_shards - 1) // n_shards) * n_shards
        self._n_shards = n_shards

        def fwd(params, model_state, x):
            variables = {"params": params, **(model_state or {})}
            return self.module.apply(variables, x)

        if mesh is not None:
            self._params = jax.device_put(params, replicated(mesh))
            self._model_state = jax.device_put(model_state or {}, replicated(mesh))
            self._fwd = jax.jit(
                fwd,
                in_shardings=(
                    jax.tree.map(lambda _: replicated(mesh), params),
                    jax.tree.map(lambda _: replicated(mesh), model_state or {}),
                    batch_sharding(mesh),
                ),
            )
            self._x_sharding = batch_sharding(mesh)
        else:
            self._params = params
            self._model_state = model_state or {}
            self._fwd = jax.jit(fwd)
            self._x_sharding = None

    def predict(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        if n == 0:
            # Probe one padded shard-batch for the output shape.
            probe = np.zeros((self._n_shards, *x.shape[1:]), x.dtype)
            arr = jnp.asarray(probe)
            if self._x_sharding is not None:
                arr = jax.device_put(arr, self._x_sharding)
            out = np.asarray(self._fwd(self._params, self._model_state, arr))
            return out[:0]
        outs = []
        ns = self._n_shards
        for start in range(0, n, self.chunk):
            part = x[start : start + self.chunk]
            real = part.shape[0]
            if real < self.chunk:
                # Steady-state calls keep ONE compiled shape; a single
                # small call pads only to shard divisibility.
                target = self.chunk if n > self.chunk else ((real + ns - 1) // ns) * ns
                if target != real:
                    pad = np.zeros((target - real, *part.shape[1:]), part.dtype)
                    part = np.concatenate([part, pad])
            arr = jnp.asarray(part)
            if self._x_sharding is not None:
                arr = jax.device_put(arr, self._x_sharding)
            out = np.asarray(self._fwd(self._params, self._model_state, arr))
            outs.append(out[:real])
        return np.concatenate(outs) if len(outs) > 1 else outs[0]

    def predict_stream(self, batches: Iterable[np.ndarray]) -> Iterator[np.ndarray]:
        """Partition-parallel streaming inference: feed numpy batches
        (e.g. parquet row groups), get predictions per batch — the
        shape of the reference's per-partition UDF path, compiled."""
        for batch in batches:
            yield self.predict(np.asarray(batch))


def _bundle_spec(model: Any, variables: Optional[dict], loss: str = "mse"):
    if variables is None:
        raise ValueError(
            "pass trained variables (the dict returned by module.init/"
            "training) — Flax modules carry no weights"
        )
    variables = dict(variables)
    params = variables.pop("params", variables)
    spec = ModelSpec(module=model, loss=loss)
    return spec, params, variables


def convert_to_serialized(model: Any, variables: dict) -> str:
    """Serialize a trained (module, variables) pair to the model
    string format used by :class:`SparkTorchModel`.

    Parity: ``convert_to_serialized_torch`` (inference.py:8-15).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return _encode_bundle(spec, params, model_state)


def create_spark_torch_model(
    model: Any,
    variables: Optional[dict] = None,
    inputCol: str = "features",
    predictionCol: str = "predicted",
    useVectorOut: bool = False,
) -> SparkTorchModel:
    """Wrap a trained model as a transformer without running ``fit``.

    Parity: ``create_spark_torch_model`` (inference.py:18-39).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return SparkTorchModel(
        inputCol=inputCol,
        predictionCol=predictionCol,
        modStr=_encode_bundle(spec, params, model_state),
        useVectorOut=useVectorOut,
    )


def attach_model_to_pipeline(
    pipeline_model: PipelineModel,
    spark_model: SparkTorchModel,
) -> PipelineModel:
    """Append an inference stage to a fitted pipeline.

    Parity: ``attach_pytorch_model_to_pipeline`` (inference.py:42-61).
    """
    return PipelineModel(list(pipeline_model.stages) + [spark_model])


# Reference-compatible name.
attach_pytorch_model_to_pipeline = attach_model_to_pipeline
