"""Wrap already-trained models for batch inference.

Reference: ``sparktorch/inference.py`` —
``convert_to_serialized_torch`` (:8-15), ``create_spark_torch_model``
(:18-39), ``attach_pytorch_model_to_pipeline`` (:42-61).

Here a "trained model" is a Flax module + trained variables; the
wrapped :class:`SparkTorchModel` runs the compiled chunked forward
(no per-row UDF).
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from sparktorch_tpu.ml.estimator import SparkTorchModel, _encode_bundle
from sparktorch_tpu.ml.pipeline import PipelineModel
from sparktorch_tpu.utils.serde import ModelSpec


def _bundle_spec(model: Any, variables: Optional[dict], loss: str = "mse"):
    if variables is None:
        raise ValueError(
            "pass trained variables (the dict returned by module.init/"
            "training) — Flax modules carry no weights"
        )
    variables = dict(variables)
    params = variables.pop("params", variables)
    spec = ModelSpec(module=model, loss=loss)
    return spec, params, variables


def convert_to_serialized(model: Any, variables: dict) -> str:
    """Serialize a trained (module, variables) pair to the model
    string format used by :class:`SparkTorchModel`.

    Parity: ``convert_to_serialized_torch`` (inference.py:8-15).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return _encode_bundle(spec, params, model_state)


def create_spark_torch_model(
    model: Any,
    variables: Optional[dict] = None,
    inputCol: str = "features",
    predictionCol: str = "predicted",
    useVectorOut: bool = False,
) -> SparkTorchModel:
    """Wrap a trained model as a transformer without running ``fit``.

    Parity: ``create_spark_torch_model`` (inference.py:18-39).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return SparkTorchModel(
        inputCol=inputCol,
        predictionCol=predictionCol,
        modStr=_encode_bundle(spec, params, model_state),
        useVectorOut=useVectorOut,
    )


def attach_model_to_pipeline(
    pipeline_model: PipelineModel,
    spark_model: SparkTorchModel,
) -> PipelineModel:
    """Append an inference stage to a fitted pipeline.

    Parity: ``attach_pytorch_model_to_pipeline`` (inference.py:42-61).
    """
    return PipelineModel(list(pipeline_model.stages) + [spark_model])


# Reference-compatible name.
attach_pytorch_model_to_pipeline = attach_model_to_pipeline
