"""Wrap already-trained models for batch inference.

Reference: ``sparktorch/inference.py`` —
``convert_to_serialized_torch`` (:8-15), ``create_spark_torch_model``
(:18-39), ``attach_pytorch_model_to_pipeline`` (:42-61).

Here a "trained model" is a Flax module + trained variables; the
wrapped :class:`SparkTorchModel` runs the compiled chunked forward
(no per-row UDF).
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparktorch_tpu.ml.estimator import SparkTorchModel, _encode_bundle
from sparktorch_tpu.ml.pipeline import PipelineModel
from sparktorch_tpu.parallel.mesh import batch_sharding, replicated
from sparktorch_tpu.utils.serde import ModelSpec


class BatchPredictor:
    """Mesh-parallel batch inference engine.

    The reference's inference is a batch-1 Python UDF per DataFrame
    row (``torch_distributed.py:106-120``); its 1M-row ResNet-50
    config (BASELINE.md #5) runs that loop per partition. Here: fixed
    static chunks, ONE compiled forward, and — with a mesh — the chunk
    batch dim sharded over dp(+fsdp) so all chips run inference
    concurrently on their slice (params replicated; XLA inserts
    nothing but the initial broadcast).
    """

    def __init__(self, module, params, model_state=None,
                 mesh: Optional[Mesh] = None, chunk: int = 1024,
                 preprocess=None, postprocess=None, telemetry=None):
        """``preprocess``/``postprocess`` (optional jax fns) are fused
        INTO the compiled forward. preprocess lets the wire carry the
        raw column dtype (e.g. uint8 pixels straight out of Parquet)
        with the cast/normalize on device — 4x less host->device
        traffic than shipping float32. postprocess shrinks the
        READBACK the same way (e.g. ``lambda y: jnp.argmax(y, -1)`` —
        the reference's predict_float argmax, ``torch_distributed.py:
        112-120``, computed on device: 1 value/row over the wire
        instead of the logits row). Both matter most when hosts are
        remote from the chips."""
        from sparktorch_tpu.obs import get_telemetry

        self.module = module
        self.mesh = mesh
        # Serving metrics on the shared bus: rows/batches served,
        # request latency percentiles, and batch fill (real rows over
        # padded chunk rows — low fill means the compiled shape is
        # oversized for the traffic).
        self.telemetry = telemetry or get_telemetry()
        n_shards = 1
        if mesh is not None:
            from sparktorch_tpu.parallel.mesh import BATCH_AXES

            for ax in BATCH_AXES:
                n_shards *= mesh.shape[ax]
        c = max(chunk, n_shards)
        self.chunk = ((c + n_shards - 1) // n_shards) * n_shards
        self._n_shards = n_shards

        def fwd(params, model_state, x):
            if preprocess is not None:
                x = preprocess(x)
            variables = {"params": params, **(model_state or {})}
            out = self.module.apply(variables, x)
            if postprocess is not None:
                out = postprocess(out)
            return out

        if mesh is not None:
            self._params = jax.device_put(params, replicated(mesh))
            self._model_state = jax.device_put(model_state or {}, replicated(mesh))
            self._fwd = jax.jit(
                fwd,
                in_shardings=(
                    jax.tree.map(lambda _: replicated(mesh), params),
                    jax.tree.map(lambda _: replicated(mesh), model_state or {}),
                    batch_sharding(mesh),
                ),
            )
            self._x_sharding = batch_sharding(mesh)
        else:
            # Pin params/state to ONE device ONCE. Leaving them as
            # host numpy re-ships the full model through every jitted
            # call — on remote-attached chips that halves throughput
            # (measured 26 -> 55 rows/s for ResNet-50 over the
            # tunnel). The device is EXPLICIT: a tree assembled off a
            # param-server fleet arrives committed to scattered shard
            # devices, and a bare device_put would keep that torn
            # placement and fail the jit.
            self._params = jax.device_put(params, self._device)
            self._model_state = jax.device_put(model_state or {},
                                               self._device)
            self._fwd = jax.jit(fwd)
            self._x_sharding = None

    @property
    def _device(self):
        # Never stored on the instance: jax Device handles don't
        # pickle, and a dill-dumped fitted model must round-trip.
        return jax.devices()[0]

    def update_params(self, params, model_state=None) -> None:
        """Swap the served weights in place (the LIVE-update path the
        online serving tier drives from its background weight puller).

        The new trees are device-put with the same placement the
        constructor used, then installed by attribute assignment
        (atomic per attribute under the GIL): a concurrent ``predict``
        chunk sees old or new params wholesale, never a torn tree.
        Params and model_state are two separate assignments, though —
        a caller that must flip them TOGETHER between batches (the
        continuous batcher's contract) should hold the coherent pair
        in its own versioned slot and execute from that snapshot,
        which is exactly what :class:`sparktorch_tpu.serve.infer.
        InferenceReplica` does; it calls through here only so this
        predictor's direct ``predict`` path serves the same weights.
        """
        if self.mesh is not None:
            self._params = jax.device_put(params, replicated(self.mesh))
            if model_state is not None:
                self._model_state = jax.device_put(
                    model_state, replicated(self.mesh))
        else:
            self._params = jax.device_put(params, self._device)
            if model_state is not None:
                self._model_state = jax.device_put(model_state,
                                                   self._device)

    def _chunks(self, x, n: int):
        """Yield (padded_part, real_rows) chunks of ONE compiled shape
        (the last small chunk pads only to shard divisibility)."""
        ns = self._n_shards
        for start in range(0, n, self.chunk):
            part = x[start : start + self.chunk]
            real = part.shape[0]
            if real < self.chunk:
                target = (
                    self.chunk if n > self.chunk
                    else ((real + ns - 1) // ns) * ns
                )
                if target != real:
                    if isinstance(part, np.ndarray):
                        pad = np.zeros((target - real, *part.shape[1:]),
                                       part.dtype)
                        part = np.concatenate([part, pad])
                    else:  # device-resident input pads on-device
                        pad = jnp.zeros((target - real, *part.shape[1:]),
                                        part.dtype)
                        part = jnp.concatenate([part, pad])
            self.telemetry.observe("inference.batch_fill",
                                   real / max(1, part.shape[0]))
            yield part, real

    def _put(self, part):
        # jax.device_put, NOT jnp.asarray: asarray routes a host numpy
        # array through a conversion path that costs ~40x more than the
        # direct transfer on remote-attached chips (measured 6.7s vs
        # 0.17s for a 37 MB uint8 chunk over the dev tunnel).
        if self._x_sharding is not None:
            return jax.device_put(part, self._x_sharding)
        if isinstance(part, np.ndarray):
            return jax.device_put(part)
        return jnp.asarray(part)

    def predict(self, x) -> np.ndarray:
        """Chunked forward over ``x`` (numpy or an already-device-
        resident jax array — the latter skips host transfers).

        The loop is double-buffered: chunk i+1's host→device copy is
        enqueued and chunk i+1's forward dispatched BEFORE chunk i's
        result is read back, so the (blocking) readback of one chunk
        overlaps the transfer+compute of the next (JAX dispatch is
        async). Device memory stays O(2 chunks) — outputs are drained
        as the loop advances, never accumulated on device (a 1M-row
        run would otherwise hold the full logits array in HBM).
        """
        n = x.shape[0]
        if n == 0:
            # Probe one padded shard-batch for the output shape.
            probe = np.zeros((self._n_shards, *x.shape[1:]), x.dtype)
            out = np.asarray(
                self._fwd(self._params, self._model_state, self._put(probe))
            )
            return out[:0]
        import time as _time

        t0 = _time.perf_counter()
        parts = self._chunks(x, n)
        host = []
        nxt = next(parts, None)
        dev = self._put(nxt[0]) if nxt else None
        prev = None  # (device_out, real) one chunk behind
        while nxt is not None:
            _, real = nxt
            out = self._fwd(self._params, self._model_state, dev)
            nxt = next(parts, None)
            if nxt is not None:
                dev = self._put(nxt[0])  # overlaps with the fwd above
            if prev is not None:
                host.append(np.asarray(prev[0])[: prev[1]])
            prev = (out, real)
        host.append(np.asarray(prev[0])[: prev[1]])
        out = np.concatenate(host) if len(host) > 1 else host[0]
        # The readback loop above drained the device, so this latency
        # covers transfer+compute honestly (not just dispatch).
        tele = self.telemetry
        tele.observe("inference.predict_s", _time.perf_counter() - t0,
                     labels={"path": "host"})
        tele.counter("inference.requests", labels={"path": "host"})
        tele.counter("inference.rows", float(n), labels={"path": "host"})
        return out

    def predict_device(self, x, in_flight: int = 3):
        """Chunked forward with no device->host readbacks: returns ONE
        device array of predictions (padding trimmed), leaving the
        download — and therefore the sync cadence — to the caller.

        Why this exists: on tunnel-attached chips every readback costs
        a full link round-trip, and dispatch/block_until_ready UNDER-
        report (async work queues without executing — ROUND4_NOTES,
        'honest timing'). The ordinary ``predict`` interleaves one
        readback per chunk; this path emits none, so a long streaming
        run can fence at its own cadence (e.g. one data-dependent
        scalar per reader batch — the only fence that truly bounds the
        queue on this platform) instead of once per chunk.
        ``in_flight`` paces via ``block_until_ready`` as best-effort
        backpressure; callers needing a HARD bound must fence with a
        readback themselves (see benchmarks/stream_inference_1m.py)."""
        n = x.shape[0]
        if n == 0:
            # Shape probe WITHOUT the readback predict() does — one
            # readback is exactly what this method exists to avoid.
            probe = np.zeros((self._n_shards, *x.shape[1:]), x.dtype)
            out = self._fwd(self._params, self._model_state,
                            self._put(probe))
            return out[:0]
        import time as _time

        t0 = _time.perf_counter()
        outs = []
        pending = []
        for part, real in self._chunks(x, n):
            dev = self._put(part)
            out = self._fwd(self._params, self._model_state, dev)
            outs.append(out[:real] if real != out.shape[0] else out)
            pending.append(out)
            if len(pending) >= max(2, in_flight):
                # Transfer-free backpressure: bound live input buffers.
                pending.pop(0).block_until_ready()
        tele = self.telemetry
        # Dispatch latency only — this path deliberately never fences
        # (see docstring); the caller's eventual download is the sync.
        tele.observe("inference.predict_s", _time.perf_counter() - t0,
                     labels={"path": "device"})
        tele.counter("inference.requests", labels={"path": "device"})
        tele.counter("inference.rows", float(n), labels={"path": "device"})
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs)

    def predict_stream(self, batches: Iterable[np.ndarray]) -> Iterator[np.ndarray]:
        """Partition-parallel streaming inference: feed numpy batches
        (e.g. parquet row groups), get predictions per batch — the
        shape of the reference's per-partition UDF path, compiled."""
        for batch in batches:
            yield self.predict(np.asarray(batch))


def write_rows_parquet(path: str, rows: Iterable[np.ndarray],
                       column: str = "features",
                       rows_per_group: int = 1024) -> int:
    """Write row batches (each a (n, ...) ndarray, any fixed dtype) to
    a Parquet file as raw fixed-size binary — the columnar on-disk
    format the streaming inference path ingests. Returns rows written.

    No compression: synthetic/pixel payloads barely compress and the
    bench must measure the wire, not the codec.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    writer = None
    total = 0
    try:
        for batch in rows:
            batch = np.ascontiguousarray(batch)
            n = batch.shape[0]
            nbytes = batch[0].nbytes if n else 0
            arr = pa.FixedSizeBinaryArray.from_buffers(
                pa.binary(nbytes), n,
                [None, pa.py_buffer(batch.tobytes())],
            )
            table = pa.table({column: arr})
            if writer is None:
                writer = pq.ParquetWriter(path, table.schema,
                                          compression="NONE")
            writer.write_table(table, row_group_size=rows_per_group)
            total += n
    finally:
        if writer is not None:
            writer.close()
    return total


def stream_parquet_predict(
    predictor: BatchPredictor,
    path: str,
    row_shape,
    dtype=np.uint8,
    column: str = "features",
    batch_rows: Optional[int] = None,
    drain=None,
    prefetch: int = 2,
    skip_rows: int = 0,
    max_rows: Optional[int] = None,
    device_outputs: bool = False,
) -> dict:
    """Columnar-ingest -> device streaming inference: the measured
    BASELINE config-5 path (the reference feeds DataFrame partitions
    to a batch-1 row UDF, ``torch_distributed.py:96-127``; here Parquet
    row groups stream through a reader thread into the predictor's
    double-buffered compiled forward).

    Pipeline: a READER thread iterates Parquet record batches, decodes
    the fixed-size-binary column into (n, *row_shape) arrays of the
    raw column dtype, and fills a bounded queue; the main thread feeds
    the predictor, whose double buffering overlaps each chunk's
    host->device transfer + forward with the previous chunk's
    readback. Disk/decode, wire, and compute all overlap — sustained
    rate ~= the slowest stage, not the sum.

    ``drain`` (optional callable) receives each prediction batch
    (e.g. to write results out); defaults to discarding after a shape
    check. Returns timing stats incl. per-stage busy times so overlap
    is visible: wall << read_busy + predict_busy when pipelined.

    ``skip_rows``/``max_rows`` window the stream (resume support for
    long runs): the reader drops the first ``skip_rows`` rows (sliced
    at record-batch granularity) and ends after ``max_rows`` rows.

    ``device_outputs=True`` routes through ``predict_device``: drain
    receives DEVICE arrays and no device->host readback happens inside
    the stream — required for sustained rates on tunnel-attached chips
    whose upload fast-path degrades after the first readback (see
    ``predict_device``). ``predict_busy`` then measures dispatch, not
    completion; the wall time stays honest (the caller's final
    download syncs everything).
    """
    import queue as _queue
    import threading
    import time as _time

    import pyarrow.parquet as pq

    q: "_queue.Queue" = _queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()
    reader_err: list = []
    read_busy = [0.0]

    row_elems = int(np.prod(row_shape))
    itemsize = np.dtype(dtype).itemsize

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.25)
                return True
            except _queue.Full:
                continue
        return False

    def reader():
        try:
            pf = pq.ParquetFile(path)
            it = iter(pf.iter_batches(
                batch_size=batch_rows or predictor.chunk, columns=[column]
            ))
            to_skip = max(0, int(skip_rows))
            budget = max_rows if max_rows is not None else float("inf")
            while budget > 0:
                # Time the iterator pull itself: the Parquet disk IO +
                # Arrow decode happen inside __next__, and they are the
                # bulk of read_busy — timing only the numpy reshape
                # (as before) made a 14 GB read look like 0.014 s and
                # voided the overlap_factor claim.
                t0 = _time.perf_counter()
                rb = next(it, None)
                if rb is None or stop.is_set():
                    read_busy[0] += _time.perf_counter() - t0
                    return
                col = rb.column(0)
                if to_skip >= len(col):
                    to_skip -= len(col)
                    read_busy[0] += _time.perf_counter() - t0
                    continue
                buf = col.buffers()[-1]
                arr = np.frombuffer(
                    buf, dtype=dtype, count=len(col) * row_elems,
                    offset=col.offset * row_elems * itemsize,
                ).reshape(len(col), *row_shape)
                if to_skip:
                    arr = arr[to_skip:]
                    to_skip = 0
                if arr.shape[0] > budget:
                    arr = arr[: int(budget)]
                budget -= arr.shape[0]
                read_busy[0] += _time.perf_counter() - t0
                if not _put(arr):
                    return
        except BaseException as e:  # pragma: no cover - surfaced below
            reader_err.append(e)
        finally:
            # Best-effort end-of-stream sentinel; bail as soon as the
            # consumer signalled stop (it no longer reads the queue).
            # The consumer does NOT rely on the sentinel arriving — it
            # also treats (reader dead + queue empty) as end-of-stream
            # — so a full queue here cannot wedge either side.
            while not stop.is_set():
                try:
                    q.put(None, timeout=0.25)
                    break
                except _queue.Full:
                    continue

    tele = predictor.telemetry
    t = threading.Thread(target=reader, daemon=True)
    t_start = _time.perf_counter()
    t.start()
    n_rows = 0
    n_batches = 0
    predict_busy = 0.0
    try:
        while True:
            try:
                item = q.get(timeout=1.0)
                # Depth AFTER the pop: 0 means the reader is the
                # bottleneck (compute starves); ~prefetch means the
                # predictor is (queue saturated).
                tele.observe("inference.queue_depth", q.qsize())
            except _queue.Empty:
                # Sentinel-free end detection: a dead reader with an
                # empty queue is end-of-stream (or a reader crash —
                # surfaced below) even if its sentinel was dropped.
                # The reader may have enqueued final items between the
                # timeout expiring and the liveness check — only an
                # Empty queue observed AFTER seeing it dead ends the
                # stream, so nothing enqueued before death is lost.
                if not t.is_alive():
                    try:
                        item = q.get_nowait()
                    except _queue.Empty:
                        break
                else:
                    continue
            if item is None:
                break
            t0 = _time.perf_counter()
            out = (predictor.predict_device(item) if device_outputs
                   else predictor.predict(item))
            predict_busy += _time.perf_counter() - t0
            assert out.shape[0] == item.shape[0]
            if drain is not None:
                drain(out)
            n_rows += item.shape[0]
            n_batches += 1
    finally:
        stop.set()
        t.join(timeout=30)
    if reader_err:
        raise reader_err[0]
    wall = _time.perf_counter() - t_start
    tele.counter("inference.stream_runs")
    tele.counter("inference.stream_rows", float(n_rows))
    return {
        "n_rows": n_rows,
        "n_batches": n_batches,
        "wall_s": round(wall, 3),
        "rows_per_sec": round(n_rows / max(wall, 1e-9), 2),
        "read_busy_s": round(read_busy[0], 3),
        "predict_busy_s": round(predict_busy, 3),
        # > 1.0 means the stages genuinely overlapped (pipelining won
        # wall time vs running them back to back).
        "overlap_factor": round(
            (read_busy[0] + predict_busy) / max(wall, 1e-9), 3
        ),
    }


def _bundle_spec(model: Any, variables: Optional[dict], loss: str = "mse"):
    if variables is None:
        raise ValueError(
            "pass trained variables (the dict returned by module.init/"
            "training) — Flax modules carry no weights"
        )
    variables = dict(variables)
    params = variables.pop("params", variables)
    spec = ModelSpec(module=model, loss=loss)
    return spec, params, variables


def convert_to_serialized(model: Any, variables: dict) -> str:
    """Serialize a trained (module, variables) pair to the model
    string format used by :class:`SparkTorchModel`.

    Parity: ``convert_to_serialized_torch`` (inference.py:8-15).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return _encode_bundle(spec, params, model_state)


def create_spark_torch_model(
    model: Any,
    variables: Optional[dict] = None,
    inputCol: str = "features",
    predictionCol: str = "predicted",
    useVectorOut: bool = False,
) -> SparkTorchModel:
    """Wrap a trained model as a transformer without running ``fit``.

    Parity: ``create_spark_torch_model`` (inference.py:18-39).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return SparkTorchModel(
        inputCol=inputCol,
        predictionCol=predictionCol,
        modStr=_encode_bundle(spec, params, model_state),
        useVectorOut=useVectorOut,
    )


def attach_model_to_pipeline(
    pipeline_model: PipelineModel,
    spark_model: SparkTorchModel,
) -> PipelineModel:
    """Append an inference stage to a fitted pipeline.

    Parity: ``attach_pytorch_model_to_pipeline`` (inference.py:42-61).
    """
    return PipelineModel(list(pipeline_model.stages) + [spark_model])


# Reference-compatible name.
attach_pytorch_model_to_pipeline = attach_model_to_pipeline
