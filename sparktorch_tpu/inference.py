"""Wrap already-trained models for batch inference.

Reference: ``sparktorch/inference.py`` —
``convert_to_serialized_torch`` (:8-15), ``create_spark_torch_model``
(:18-39), ``attach_pytorch_model_to_pipeline`` (:42-61).

Here a "trained model" is a Flax module + trained variables; the
wrapped :class:`SparkTorchModel` runs the compiled chunked forward
(no per-row UDF).
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparktorch_tpu.ml.estimator import SparkTorchModel, _encode_bundle
from sparktorch_tpu.ml.pipeline import PipelineModel
from sparktorch_tpu.parallel.mesh import batch_sharding, replicated
from sparktorch_tpu.utils.serde import ModelSpec


class BatchPredictor:
    """Mesh-parallel batch inference engine.

    The reference's inference is a batch-1 Python UDF per DataFrame
    row (``torch_distributed.py:106-120``); its 1M-row ResNet-50
    config (BASELINE.md #5) runs that loop per partition. Here: fixed
    static chunks, ONE compiled forward, and — with a mesh — the chunk
    batch dim sharded over dp(+fsdp) so all chips run inference
    concurrently on their slice (params replicated; XLA inserts
    nothing but the initial broadcast).
    """

    def __init__(self, module, params, model_state=None,
                 mesh: Optional[Mesh] = None, chunk: int = 1024):
        self.module = module
        self.mesh = mesh
        n_shards = 1
        if mesh is not None:
            from sparktorch_tpu.parallel.mesh import BATCH_AXES

            for ax in BATCH_AXES:
                n_shards *= mesh.shape[ax]
        c = max(chunk, n_shards)
        self.chunk = ((c + n_shards - 1) // n_shards) * n_shards
        self._n_shards = n_shards

        def fwd(params, model_state, x):
            variables = {"params": params, **(model_state or {})}
            return self.module.apply(variables, x)

        if mesh is not None:
            self._params = jax.device_put(params, replicated(mesh))
            self._model_state = jax.device_put(model_state or {}, replicated(mesh))
            self._fwd = jax.jit(
                fwd,
                in_shardings=(
                    jax.tree.map(lambda _: replicated(mesh), params),
                    jax.tree.map(lambda _: replicated(mesh), model_state or {}),
                    batch_sharding(mesh),
                ),
            )
            self._x_sharding = batch_sharding(mesh)
        else:
            self._params = params
            self._model_state = model_state or {}
            self._fwd = jax.jit(fwd)
            self._x_sharding = None

    def _chunks(self, x, n: int):
        """Yield (padded_part, real_rows) chunks of ONE compiled shape
        (the last small chunk pads only to shard divisibility)."""
        ns = self._n_shards
        for start in range(0, n, self.chunk):
            part = x[start : start + self.chunk]
            real = part.shape[0]
            if real < self.chunk:
                target = (
                    self.chunk if n > self.chunk
                    else ((real + ns - 1) // ns) * ns
                )
                if target != real:
                    if isinstance(part, np.ndarray):
                        pad = np.zeros((target - real, *part.shape[1:]),
                                       part.dtype)
                        part = np.concatenate([part, pad])
                    else:  # device-resident input pads on-device
                        pad = jnp.zeros((target - real, *part.shape[1:]),
                                        part.dtype)
                        part = jnp.concatenate([part, pad])
            yield part, real

    def _put(self, part):
        arr = jnp.asarray(part)
        if self._x_sharding is not None:
            arr = jax.device_put(arr, self._x_sharding)
        return arr

    def predict(self, x) -> np.ndarray:
        """Chunked forward over ``x`` (numpy or an already-device-
        resident jax array — the latter skips host transfers).

        The loop is double-buffered: chunk i+1's host→device copy is
        enqueued and chunk i+1's forward dispatched BEFORE chunk i's
        result is read back, so the (blocking) readback of one chunk
        overlaps the transfer+compute of the next (JAX dispatch is
        async). Device memory stays O(2 chunks) — outputs are drained
        as the loop advances, never accumulated on device (a 1M-row
        run would otherwise hold the full logits array in HBM).
        """
        n = x.shape[0]
        if n == 0:
            # Probe one padded shard-batch for the output shape.
            probe = np.zeros((self._n_shards, *x.shape[1:]), x.dtype)
            out = np.asarray(
                self._fwd(self._params, self._model_state, self._put(probe))
            )
            return out[:0]
        parts = self._chunks(x, n)
        host = []
        nxt = next(parts, None)
        dev = self._put(nxt[0]) if nxt else None
        prev = None  # (device_out, real) one chunk behind
        while nxt is not None:
            _, real = nxt
            out = self._fwd(self._params, self._model_state, dev)
            nxt = next(parts, None)
            if nxt is not None:
                dev = self._put(nxt[0])  # overlaps with the fwd above
            if prev is not None:
                host.append(np.asarray(prev[0])[: prev[1]])
            prev = (out, real)
        host.append(np.asarray(prev[0])[: prev[1]])
        return np.concatenate(host) if len(host) > 1 else host[0]

    def predict_stream(self, batches: Iterable[np.ndarray]) -> Iterator[np.ndarray]:
        """Partition-parallel streaming inference: feed numpy batches
        (e.g. parquet row groups), get predictions per batch — the
        shape of the reference's per-partition UDF path, compiled."""
        for batch in batches:
            yield self.predict(np.asarray(batch))


def _bundle_spec(model: Any, variables: Optional[dict], loss: str = "mse"):
    if variables is None:
        raise ValueError(
            "pass trained variables (the dict returned by module.init/"
            "training) — Flax modules carry no weights"
        )
    variables = dict(variables)
    params = variables.pop("params", variables)
    spec = ModelSpec(module=model, loss=loss)
    return spec, params, variables


def convert_to_serialized(model: Any, variables: dict) -> str:
    """Serialize a trained (module, variables) pair to the model
    string format used by :class:`SparkTorchModel`.

    Parity: ``convert_to_serialized_torch`` (inference.py:8-15).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return _encode_bundle(spec, params, model_state)


def create_spark_torch_model(
    model: Any,
    variables: Optional[dict] = None,
    inputCol: str = "features",
    predictionCol: str = "predicted",
    useVectorOut: bool = False,
) -> SparkTorchModel:
    """Wrap a trained model as a transformer without running ``fit``.

    Parity: ``create_spark_torch_model`` (inference.py:18-39).
    """
    spec, params, model_state = _bundle_spec(model, variables)
    return SparkTorchModel(
        inputCol=inputCol,
        predictionCol=predictionCol,
        modStr=_encode_bundle(spec, params, model_state),
        useVectorOut=useVectorOut,
    )


def attach_model_to_pipeline(
    pipeline_model: PipelineModel,
    spark_model: SparkTorchModel,
) -> PipelineModel:
    """Append an inference stage to a fitted pipeline.

    Parity: ``attach_pytorch_model_to_pipeline`` (inference.py:42-61).
    """
    return PipelineModel(list(pipeline_model.stages) + [spark_model])


# Reference-compatible name.
attach_pytorch_model_to_pipeline = attach_model_to_pipeline
