"""Process-backed worker handles for the gang supervisor.

The ft Supervisor's handle contract (``name`` / ``error`` /
``is_alive`` / ``join`` / ``kill``) has had two implementations:
threads (cooperative kill — a cancel Event the loop must poll) and a
thin ``multiprocessing.Process`` wrapper. Neither covers the failure
mode production actually fears: a worker **wedged on the GIL or inside
a native call**, which no cooperative cancel will ever reach. This
module adds the real one:

- :class:`ProcessWorker` spawns ``python -m sparktorch_tpu.ctl.worker``
  as a detached child with a dill payload file (what to run: a
  callable, a fleet shard server, an inference replica, a hogwild
  worker — see :mod:`sparktorch_tpu.ctl.worker` for the entry kinds);
- liveness is the PID (``is_alive``) plus the child's heartbeat FILE
  (rank-attributed, same directory protocol every supervisor and the
  collector already read);
- ``kill()`` is **non-cooperative preemption**: SIGTERM (the child's
  entry installs a handler that sets the cancel event, so a healthy
  worker drains at the next window boundary), then after ``grace_s``
  a SIGKILL — a worker wedged past its grace dies anyway. Chaos can
  therefore kill a worker holding the GIL (``kill_process_at``),
  which the thread deployment could never exercise.

The ``ctl.process`` chaos site lives in :meth:`ProcessWorker.is_alive`:
when a seeded :class:`~sparktorch_tpu.ft.ChaosConfig` maps this rank
to a kill step, the poll that observes the child's heartbeat reach
that step delivers a raw SIGKILL — no SIGTERM, no cancel event, no
cooperation — which is exactly the non-cooperative death the restart
path must survive. Chaos off costs one global None check per poll.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional

import dill

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.obs.log import get_logger
from sparktorch_tpu.obs.telemetry import wall_ts

_LOG = get_logger("sparktorch_tpu.ctl.proc")

# Exit codes the worker entry uses (see ctl/worker.py): distinguish a
# drain (SIGTERM honored, work intentionally incomplete) from a crash
# so the controller can tell "I stopped it" from "it died".
EXIT_OK = 0
EXIT_FAILED = 1
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: SIGTERM received before completion

_DEFAULT_GRACE_S = 5.0


class ProcessWorker:
    """One spawned worker process, presented through the supervisor
    handle contract. Construct via :func:`spawn_worker` (which writes
    the payload) or adapt an existing ``subprocess.Popen``."""

    def __init__(self, name: str, process: subprocess.Popen,
                 rank: Optional[int] = None,
                 heartbeat_dir: Optional[str] = None,
                 grace_s: float = _DEFAULT_GRACE_S,
                 payload_path: Optional[str] = None,
                 telemetry=None):
        self.name = name
        self.process = process
        self.rank = rank
        self.heartbeat_dir = heartbeat_dir
        self.grace_s = float(grace_s)
        self.payload_path = payload_path
        self.telemetry = telemetry
        self.preempted = False  # kill() was issued by a supervisor
        self.sigkilled = False  # the grace escalation (or chaos) fired
        self._kill_thread: Optional[threading.Thread] = None

    # -- liveness ----------------------------------------------------------

    @property
    def pid(self) -> int:
        return self.process.pid

    def is_alive(self) -> bool:
        alive = self.process.poll() is None
        inj = _chaos.active()
        if (alive and inj is not None and self.rank is not None
                and self.rank in getattr(inj.config, "kill_process_at",
                                         {})):
            # Seeded non-cooperative kill: the supervisor's own poll
            # delivers it the moment the child's heartbeat reports the
            # configured step — SIGKILL straight away, no cancel
            # event, no grace. One-shot per rank (the injector owns
            # the latch), so the restarted child survives its rerun.
            act = _chaos.fire("ctl.process", rank=self.rank,
                              step=self.heartbeat_step())
            if act and act.get("sigkill"):
                self.sigkilled = True
                try:
                    os.kill(self.process.pid, signal.SIGKILL)
                except OSError:
                    pass
        return alive

    @property
    def error(self) -> Optional[BaseException]:
        code = self.process.poll()
        if code is None or code == EXIT_OK:
            return None
        from sparktorch_tpu.ft.supervisor import WorkerFailed

        if code == EXIT_PREEMPTED:
            return WorkerFailed(f"{self.name}: preempted (drained by "
                                f"SIGTERM before completion)")
        if code < 0:
            return WorkerFailed(
                f"{self.name}: killed by signal {-code}"
            )
        return WorkerFailed(f"{self.name}: exit code {code}")

    def join(self, timeout: Optional[float] = None) -> None:
        try:
            self.process.wait(timeout)
        except subprocess.TimeoutExpired:
            pass

    # -- heartbeat-file liveness ------------------------------------------

    def heartbeat_record(self) -> Optional[Dict[str, Any]]:
        """This rank's current heartbeat record (None without a
        heartbeat dir, before the first beat, or on a torn file)."""
        if self.heartbeat_dir is None or self.rank is None:
            return None
        path = os.path.join(self.heartbeat_dir,
                            f"gang_hb_rank{int(self.rank)}.json")
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None

    def heartbeat_step(self) -> Optional[int]:
        rec = self.heartbeat_record()
        step = (rec or {}).get("step")
        return int(step) if step is not None else None

    def heartbeat_age_s(self, now: Optional[float] = None) -> Optional[float]:
        rec = self.heartbeat_record()
        if not rec or rec.get("ts") is None:
            return None
        return max(0.0, (now if now is not None else wall_ts())
                   - float(rec["ts"]))

    # -- preemption --------------------------------------------------------

    def kill(self, grace_s: Optional[float] = None) -> None:
        """Non-cooperative preemption: SIGTERM now (the worker entry
        translates it into the cancel event, so a HEALTHY worker
        drains and exits ``EXIT_PREEMPTED``), SIGKILL after the grace
        window for a worker too wedged to react. Idempotent; the
        escalation runs on a daemon thread so the supervisor's poll
        loop never blocks on a dying child."""
        self.preempted = True
        if self.process.poll() is not None:
            return
        try:
            self.process.terminate()
        except OSError:
            return
        grace = self.grace_s if grace_s is None else float(grace_s)
        if self._kill_thread is not None:
            return

        def escalate():
            try:
                self.process.wait(grace)
            except subprocess.TimeoutExpired:
                self.sigkilled = True
                if self.telemetry is not None:
                    self.telemetry.counter(
                        "ctl.sigkill_escalations_total",
                        labels={"worker": self.name})
                _LOG.warning(
                    f"[sparktorch_tpu:ctl] worker {self.name} ignored "
                    f"SIGTERM for {grace}s; escalating to SIGKILL"
                )
                try:
                    self.process.kill()
                except OSError:
                    pass

        self._kill_thread = threading.Thread(
            target=escalate, name=f"ctl-kill-{self.name}", daemon=True)
        self._kill_thread.start()

    def ctl_url(self, timeout_s: float = 10.0) -> Optional[str]:
        """The child's exporter/control URL (see
        :func:`worker_ctl_url`); None without a ``ctl_port``."""
        return worker_ctl_url(self, timeout_s=timeout_s)

    def cleanup(self) -> None:
        """Remove the payload file (the worker read it at startup)."""
        if self.payload_path:
            for path in (self.payload_path, self.payload_path + ".url"):
                try:
                    os.unlink(path)
                except OSError:
                    pass


def spawn_worker(fn: Optional[Callable[..., Any]] = None, *,
                 kind: str = "callable",
                 kwargs: Optional[Mapping[str, Any]] = None,
                 name: Optional[str] = None,
                 rank: Optional[int] = None,
                 heartbeat_dir: Optional[str] = None,
                 ctl_port: Optional[int] = None,
                 grace_s: float = _DEFAULT_GRACE_S,
                 env: Optional[Mapping[str, str]] = None,
                 cwd: Optional[str] = None,
                 payload_dir: Optional[str] = None,
                 telemetry=None) -> ProcessWorker:
    """Spawn one worker process running the ctl entry.

    ``kind`` selects the entry (see :mod:`sparktorch_tpu.ctl.worker`):
    ``"callable"`` runs ``fn(ctx)`` (dill-shipped — closures work);
    ``"shard_server"`` / ``"replica_server"`` / ``"hogwild_worker"``
    run the corresponding subsystem entry point with ``kwargs``. Every
    kind gets a :class:`~sparktorch_tpu.ctl.worker.WorkerContext`:
    rank, the SIGTERM-wired cancel event, a heartbeat emitter when
    ``heartbeat_dir`` is given, and (with ``ctl_port`` — 0 for
    ephemeral) a :class:`~sparktorch_tpu.native.gang.
    GangMetricsExporter` serving ``/metrics`` + ``POST /ctl`` with
    kill/drain verbs; the bound URL is published next to the payload
    (``<payload>.url``) for :attr:`ProcessWorker.ctl_url`.
    """
    name = name or (f"rank{rank}" if rank is not None else "worker")
    payload: Dict[str, Any] = {
        "kind": kind,
        "fn": fn,
        "kwargs": dict(kwargs or {}),
        "name": name,
        "rank": rank,
        "heartbeat_dir": heartbeat_dir,
        "ctl_port": ctl_port,
    }
    fd, payload_path = tempfile.mkstemp(
        prefix=f"ctl_worker_{name}_", suffix=".dill", dir=payload_dir)
    with os.fdopen(fd, "wb") as f:
        dill.dump(payload, f)
    child_env = dict(os.environ)
    # The child must not inherit a device grab: default it onto CPU
    # unless the caller says otherwise (a real multi-host deployment
    # passes its own platform env through ``env=``).
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    # The child must resolve this package no matter what ``cwd`` the
    # controller runs under (an uninstalled checkout imports via the
    # parent's sys.path, which the child does not inherit). Same for
    # the module DEFINING a shipped callable: dill pickles a function
    # from an importable module by reference, so the child must be
    # able to import it (a fn defined in __main__ ships by value and
    # needs nothing).
    extra = [os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))]
    mod = sys.modules.get(getattr(fn, "__module__", None) or "")
    mod_file = getattr(mod, "__file__", None)
    if mod_file and getattr(mod, "__name__", "") != "__main__":
        extra.append(os.path.dirname(os.path.abspath(mod_file)))
    parts = [p for p in child_env.get("PYTHONPATH", "").split(os.pathsep)
             if p]
    child_env["PYTHONPATH"] = os.pathsep.join(
        [p for p in extra if p not in parts] + parts)
    if env:
        child_env.update({str(k): str(v) for k, v in env.items()})
    process = subprocess.Popen(
        [sys.executable, "-m", "sparktorch_tpu.ctl.worker", payload_path],
        env=child_env, cwd=cwd,
        # The child's stdout/stderr flow to the parent's (an operator
        # tailing the controller sees worker tracebacks); no pipes to
        # fill up and wedge a silent child.
    )
    return ProcessWorker(name, process, rank=rank,
                         heartbeat_dir=heartbeat_dir, grace_s=grace_s,
                         payload_path=payload_path, telemetry=telemetry)


def worker_ctl_url(worker: ProcessWorker,
                   timeout_s: float = 10.0) -> Optional[str]:
    """The child's control/observability URL (``<payload>.url``,
    written by the entry once its exporter binds). None when the
    worker was spawned without ``ctl_port`` or hasn't bound within
    the timeout."""
    if not worker.payload_path:
        return None
    path = worker.payload_path + ".url"
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                url = f.read().strip()
            if url:
                return url
        except OSError:
            pass
        if worker.process.poll() is not None:
            return None
        time.sleep(0.05)
    return None
