"""The elastic gang controller: live world resize under supervision.

The ft :class:`~sparktorch_tpu.ft.supervisor.Supervisor` answers one
question — "this worker died, restart it?" — and when the restart
budget runs out, the run fails. That is the wrong terminal state for a
gang with redistributable work: production pods (the PyTorch Elastic /
TorchX rendezvous shape) **shrink the world** instead — the dead
rank's share moves to the survivors, the coordinator opens a new
generation, and training continues; a recovered (or brand-new) host
later **grows** it back. This controller implements that, driver-side,
over the pieces the repo already has:

- **membership = generation**: every world change (shrink, grow)
  bumps the generation — through the native
  :class:`~sparktorch_tpu.native.gang.GangCoordinator.resize` when a
  coordinator is attached (its barrier waiters release, everyone
  re-registers) — and relaunches the surviving members with the new
  generation's work assignment. The weight-0 padding protocol is what
  makes the redistribution safe for training math: a world of N-1
  pads where a world of N didn't, and the weighted-mean loss cannot
  tell the difference (regression-pinned in ``tests/test_ctl.py``).
- **work = partitions with idempotent completion**: the unit of
  redistribution is an opaque partition id; the deployment says what
  "complete" means (typically: the partition's atomically-renamed
  output file exists). A restarted or reassigned worker skips
  completed partitions, so records stay EXACT across any schedule of
  kills, shrinks, and grows.
- **collector-driven supervision**: beside handle liveness, the
  controller reads the fleet collector's ``/gang`` view and
  distinguishes **"exporter vanished"** (scrape failing while the
  rank's heartbeat — or its local handle — still shows life: degrade,
  count, keep supervising by handle) from **"rank died"** (heartbeat
  age past the barrier deadline: preempt/restart, and on budget
  exhaustion, shrink).
- **remote ranks**: a member registered with a ``ctl_url`` and no
  local handle is managed over ``POST /ctl`` (kill/drain) — the
  controller supervises ranks it never spawned.

Every transition is observable: generation-tagged ``ctl.*`` events and
counters on the bus, and the whole world document as the ``elastic``
telemetry section — which the :class:`~sparktorch_tpu.obs.collector.
FleetCollector` folds into ``/gang`` when they share a bus.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from sparktorch_tpu.ft.policy import FtPolicy
from sparktorch_tpu.ft.supervisor import WorkerFailed
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.obs.log import get_logger
from sparktorch_tpu.obs.telemetry import get_telemetry, wall_ts

_LOG = get_logger("sparktorch_tpu.ctl.elastic")

ELASTIC_SECTION = "elastic"
_HISTORY_CAP = 64


def round_robin_assign(ranks: Sequence[int],
                       partitions: Sequence[Any]) -> Dict[int, List[Any]]:
    """The default work assignment: deterministic round-robin of the
    pending partitions over the rank list (sorted, so every generation
    computes the same layout from the same inputs)."""
    ranks = sorted(ranks)
    out: Dict[int, List[Any]] = {r: [] for r in ranks}
    for i, part in enumerate(partitions):
        out[ranks[i % len(ranks)]].append(part)
    return out


class _Member:
    __slots__ = ("rank", "start_fn", "ctl_url", "handle", "restarts",
                 "done", "removed", "restart_at", "detected_at",
                 "exporter_gone", "draining", "assignment")

    def __init__(self, rank: int, start_fn, ctl_url: Optional[str]):
        self.rank = rank
        self.start_fn = start_fn      # None for purely remote ranks
        self.ctl_url = ctl_url
        self.handle = None
        self.restarts = 0
        self.done = False
        self.removed = False          # shrunk out of the world
        self.restart_at: Optional[float] = None
        self.detected_at: Optional[float] = None
        self.exporter_gone = False    # degradation episode latch
        self.draining = False         # resize drain in flight
        self.assignment: List[Any] = []  # partitions of the last launch


class ElasticController:
    """Supervise a gang of (process) workers with live world resize.

    ``start_fn(rank, attempt, generation, assignment)`` must (re)start
    rank's worker over the given partition list and return a handle
    satisfying the supervisor contract (``ProcessWorker`` is the
    intended one; ``ThreadWorker`` works for tests). ``completed_fn``
    decides partition completion (idempotency lives there).

    ``collector`` (a FleetCollector sharing this bus) or ``gang_url``
    (any ``/gang`` endpoint) arms collector-driven supervision;
    ``coordinator`` (a GangCoordinator) makes resizes real gang
    membership events.
    """

    def __init__(self, work: Sequence[Any],
                 completed_fn: Callable[[Any], bool],
                 policy: Optional[FtPolicy] = None,
                 telemetry=None,
                 assign_fn: Callable[..., Dict[int, List[Any]]] = round_robin_assign,
                 coordinator=None,
                 collector=None,
                 gang_url: Optional[str] = None,
                 ctl_token: Optional[str] = None,
                 min_world: int = 1,
                 drain_grace_s: float = 5.0,
                 name: str = "elastic",
                 alerts=None,
                 on_scale_signal: Optional[Callable[[Dict[str, Any]],
                                                    Any]] = None,
                 postmortem_dir: Optional[str] = None,
                 postmortem_window_s: float = 30.0,
                 postmortem_min_interval_s: float = 0.0):
        self.work = list(work)
        self.completed_fn = completed_fn
        self.policy = policy or FtPolicy()
        self.telemetry = telemetry or get_telemetry()
        self.assign_fn = assign_fn
        self.coordinator = coordinator
        self.collector = collector
        self.gang_url = gang_url
        self.ctl_token = ctl_token
        self.min_world = int(min_world)
        self.drain_grace_s = float(drain_grace_s)
        self.name = name
        self._rng = self.policy.rng()
        self._members: Dict[int, _Member] = {}
        self._lock = threading.Lock()
        self._pending_grow: List[_Member] = []
        self._stop = threading.Event()
        self.generation = (int(coordinator.generation)
                           if coordinator is not None else 0)
        self.history: List[Dict[str, Any]] = []
        self._resizes = {"shrink": 0, "grow": 0}
        self._gang_check_ts = 0.0
        # SLO alerting consumer (ROADMAP item 3's "signals the
        # collector already serves"): subscribing to an AlertManager
        # turns every latched firing — a sustained hot-shard p99
        # breach, a 429-rate burn — into a generation-tagged
        # ``ctl.scale_signal`` this controller logs (and hands to the
        # ``on_scale_signal`` policy hook, where a deployment attaches
        # its split/drain/scale decision).
        self.scale_signals: List[Dict[str, Any]] = []
        self.on_scale_signal = on_scale_signal
        self.alerts = alerts
        # Flight-recorder postmortems: when a rank dies, is preempted,
        # or an alert fires, fold every available blackbox ring (this
        # bus's + each scraped rank's last-good) into one bundle under
        # ``postmortem_dir``.
        self.postmortem_dir = postmortem_dir
        self.postmortem_window_s = float(postmortem_window_s)
        self._postmortem_min_interval_s = float(postmortem_min_interval_s)
        self._last_postmortem_ts = 0.0
        self.postmortems: List[str] = []
        if postmortem_dir:
            from sparktorch_tpu.obs.blackbox import attach_recorder

            attach_recorder(self.telemetry)
        # Subscribe LAST: _on_alert runs on the collector's poll
        # thread and reads the postmortem attributes above — a firing
        # delivered mid-__init__ must not hit a half-built controller.
        if alerts is not None:
            alerts.subscribe(self._on_alert)

    # -- alert consumption -------------------------------------------------

    def _on_alert(self, event: Dict[str, Any]) -> None:
        """AlertManager subscriber: a FIRED alert becomes a scale
        signal (event + counter + the policy hook); a RESOLVED one
        clears it. Runs on the collector's poll thread — must never
        raise into the alert fan-out."""
        what = event.get("event")
        if what == "fired":
            signal = {
                "rule": event.get("alert"),
                "rule_kind": event.get("rule_kind"),
                "metric": event.get("metric"),
                "labels": event.get("labels"),
                "value": event.get("value"),
                "episode": event.get("episode"),
                "ts": event.get("ts"),
            }
            self.scale_signals.append(signal)
            self.telemetry.counter("ctl.scale_signals_total",
                                   labels={"rule": str(event.get("alert"))})
            self._event("scale_signal", **signal)
            _LOG.warning(
                f"[sparktorch_tpu:ctl] scale signal from alert "
                f"{event.get('alert')} (value={event.get('value')})")
            if self.on_scale_signal is not None:
                try:
                    self.on_scale_signal(dict(event))
                except Exception as e:  # noqa: BLE001 - policy hook
                    _LOG.warning(f"[sparktorch_tpu:ctl] on_scale_signal "
                                 f"raised: {type(e).__name__}: {e}")
            self._write_postmortem(
                f"alert {event.get('alert')} fired", rank=None)
        elif what == "resolved":
            self._event("scale_signal_cleared",
                        rule=event.get("alert"),
                        episode=event.get("episode"))

    # -- postmortems -------------------------------------------------------

    def _write_postmortem(self, reason: str,
                          rank: Optional[int] = None) -> Optional[str]:
        """Best-effort bundle write (death/preempt/alert triggers):
        evidence collection must never take down supervision."""
        if not self.postmortem_dir:
            return None
        now = time.perf_counter()  # lint-obs: ok (throttle clock, not a measured region)
        if self._postmortem_min_interval_s and \
                now - self._last_postmortem_ts < \
                self._postmortem_min_interval_s:
            return None
        self._last_postmortem_ts = now
        from sparktorch_tpu.obs.blackbox import collect_postmortem

        history = getattr(self.collector, "history", None)
        try:
            path = collect_postmortem(
                self.postmortem_dir, reason,
                telemetry=self.telemetry,
                collector=self.collector,
                history=history,
                extra_events=self.history,
                window_s=self.postmortem_window_s,
                rank=rank,
            )
        except Exception as e:  # noqa: BLE001 - evidence is best-effort
            self.telemetry.counter("ctl.postmortem_failures_total")
            _LOG.warning(f"[sparktorch_tpu:ctl] postmortem write failed: "
                         f"{type(e).__name__}: {e}")
            return None
        self.postmortems.append(path)
        self.telemetry.counter("ctl.postmortems_total")
        return path

    # -- membership --------------------------------------------------------

    def add_rank(self, rank: int, start_fn=None,
                 ctl_url: Optional[str] = None) -> None:
        """Register a member BEFORE run(). ``start_fn`` None = a
        remote rank this controller can watch and kill (via
        ``ctl_url``) but not relaunch — its death shrinks the world."""
        if start_fn is None and not ctl_url:
            raise ValueError(f"rank {rank}: need a start_fn or a ctl_url")
        self._members[int(rank)] = _Member(int(rank), start_fn, ctl_url)

    def grow(self, rank: int, start_fn=None,
             ctl_url: Optional[str] = None) -> None:
        """Request a world GROW: the new rank joins at the next poll
        tick as a resize event (generation bump, pending work
        redistributed over the enlarged world). Thread-safe — callable
        from an operator thread or a ctl verb while run() spins."""
        if start_fn is None and not ctl_url:
            raise ValueError(f"rank {rank}: need a start_fn or a ctl_url")
        with self._lock:
            self._pending_grow.append(_Member(int(rank), start_fn, ctl_url))

    def stop(self) -> None:
        """Request shutdown; also the teardown for a controller that
        never reached ``run()`` (whose finally is the other detach
        path) — a retired controller must not stay subscribed as an
        alert consumer."""
        self._stop.set()
        self.detach_alerts()

    # -- views -------------------------------------------------------------

    def active_ranks(self) -> List[int]:
        return sorted(r for r, m in self._members.items()
                      if not m.removed)

    def world_size(self) -> int:
        return len(self.active_ranks())

    def pending_work(self) -> List[Any]:
        return [p for p in self.work if not self.completed_fn(p)]

    def _publish(self) -> None:
        """The elastic world document, as a telemetry section — the
        collector folds it into ``/gang`` when buses are shared."""
        doc = {
            "generation": self.generation,
            "world_size": self.world_size(),
            "min_world": self.min_world,
            "members": {
                str(m.rank): {
                    "state": ("removed" if m.removed else
                              "done" if m.done else
                              "backoff" if m.restart_at is not None else
                              "running"),
                    "restarts": m.restarts,
                    "remote": m.start_fn is None,
                    "exporter_gone": m.exporter_gone,
                }
                # list() snapshot: _on_alert publishes from the
                # collector's poll thread while a resize mutates the
                # member table on the run thread.
                for m in list(self._members.values())
            },
            "work": {"total": len(self.work),
                     "pending": len(self.pending_work())},
            "resizes": dict(self._resizes),
            "history": self.history[-_HISTORY_CAP:],
        }
        self.telemetry.set_section(ELASTIC_SECTION, doc)

    def _event(self, kind: str, **fields: Any) -> None:
        rec = {"kind": kind, "generation": self.generation,
               "world_size": self.world_size(), "ts": wall_ts(),
               **fields}
        self.history.append(rec)
        self.telemetry.event(f"ctl.{kind}", **{k: v for k, v in rec.items()
                                               if k != "kind"})
        self.telemetry.counter(f"ctl.{kind}_total")
        self._publish()

    # -- launching ---------------------------------------------------------

    def _assignment_for(self, rank: int) -> List[Any]:
        ranks = [r for r in self.active_ranks()
                 if self._members[r].start_fn is not None]
        pending = self.pending_work()
        if not ranks or rank not in ranks:
            return []
        return self.assign_fn(ranks, pending).get(rank, [])

    def _launch(self, m: _Member, attempt: int,
                assignment: Optional[List[Any]] = None) -> None:
        if m.start_fn is None:
            return  # remote: supervised, never (re)launched from here
        old = m.handle
        if old is not None:
            # A replaced handle is retired: let process handles remove
            # their payload/url files instead of leaking one tmp file
            # per relaunch for the controller's lifetime.
            getattr(old, "cleanup", lambda: None)()
        m.assignment = (list(assignment) if assignment is not None
                        else self._assignment_for(m.rank))
        m.handle = m.start_fn(m.rank, attempt, self.generation,
                              m.assignment)
        m.restart_at = None
        m.draining = False
        m.done = False

    # -- death / restart / shrink -----------------------------------------

    def _schedule_restart(self, m: _Member, reason: str) -> bool:
        """Spend a restart slot (True) or report budget exhaustion
        (False — the caller shrinks)."""
        if m.restarts >= self.policy.restart.max_restarts:
            return False
        delay = self.policy.restart.delay_s(m.restarts, self._rng)
        m.detected_at = time.perf_counter()  # lint-obs: ok (recovery clock origin, ledger-fed in _do_restart)
        m.restart_at = m.detected_at + delay
        _LOG.warning(
            f"[sparktorch_tpu:ctl] rank {m.rank} {reason}; restart "
            f"{m.restarts + 1}/{self.policy.restart.max_restarts} "
            f"in {delay:.3f}s"
        )
        self._event("restart_scheduled", rank=m.rank, reason=reason,
                    delay_s=delay)
        # The death is the postmortem trigger: the bundle's window
        # closes AFTER this transition landed, so the restart_scheduled
        # event (and the victim's last scraped ring) are inside it.
        self._write_postmortem(f"rank {m.rank} {reason}", rank=m.rank)
        return True

    def _do_restart(self, m: _Member) -> None:
        attempt = m.restarts + 1
        # A restart (same generation, same world) resumes the member's
        # OWN assignment minus what already completed. Recomputing the
        # round-robin here would re-deal the current pending set over
        # ranks whose survivors still hold their original lists —
        # overlapping them and duplicating (idempotent, but wasted)
        # partition work. Full redistribution belongs to _resize,
        # where everyone relaunches together.
        self._launch(m, attempt,
                     assignment=[p for p in m.assignment
                                 if not self.completed_fn(p)])
        m.restarts = attempt
        labels = {"worker": f"rank{m.rank}"}
        self.telemetry.counter("ft_restarts_total", labels=labels)
        latency = (time.perf_counter()  # lint-obs: ok (recovery clock pair, ledger-fed below)
                   - (m.detected_at or time.perf_counter()))  # lint-obs: ok (fallback read of the same clock)
        self.telemetry.observe("ft_recovery_latency_s", latency,
                               labels=labels)
        # The detection->relaunch gap (backoff included) is RUN
        # DOWNTIME: the goodput ledger's restart_downtime bucket
        # closes on exactly the window ft_recovery_latency_s measures,
        # so the two reconcile by construction.
        _goodput.add("restart_downtime", latency)
        self._event("restart", rank=m.rank, attempt=attempt)

    def _resize(self, kind: str, rank: Optional[int],
                joiners: Sequence[_Member] = ()) -> None:
        """One world-membership change: drain survivors, bump the
        generation (through the coordinator when attached — its
        members re-register fresh), recompute the assignment over the
        INCOMPLETE work, relaunch everyone. Completed partitions are
        never re-run (``completed_fn`` is the idempotency line), so a
        resize costs the survivors their in-flight partitions at
        worst, never the records already landed."""
        # The whole resize wall — drain, generation bump, relaunch —
        # is world downtime: nobody computes while the membership
        # changes. The ledger span closes when the survivors (and
        # joiners) are relaunched.
        with _goodput.span("resize_downtime", {"kind": kind}):
            self._resize_body(kind, rank, joiners)

    def _resize_body(self, kind: str, rank: Optional[int],
                     joiners: Sequence[_Member] = ()) -> None:
        # Survivors are the PRE-JOIN launchable members: joiners enter
        # the member table after this snapshot, or the relaunch loop
        # below would see each joiner twice (once as a "survivor",
        # once as a joiner) and double-launch it — the first handle
        # orphaned into an unsupervised worker racing the same
        # partitions.
        survivors = [self._members[r] for r in self.active_ranks()
                     if self._members[r].start_fn is not None
                     and not self._members[r].done]
        for m in joiners:
            self._members[m.rank] = m
        # Drain: cooperative stop, escalation handled by the handle's
        # own grace logic; join so two attempts never overlap on one
        # partition file (atomic renames make even that benign, but
        # the join keeps the schedule readable).
        for m in survivors:
            if m.handle is not None and m.handle.is_alive():
                m.draining = True
                m.handle.kill()
        for m in survivors:
            if m.handle is not None:
                m.handle.join(self.drain_grace_s + 2.0)
        if self.coordinator is not None:
            self.generation = self.coordinator.resize(
                max(1, self.world_size()))
        else:
            self.generation += 1
        self._resizes[kind] += 1
        self.telemetry.counter("ctl.resizes_total",
                               labels={"kind": kind})
        self._event(kind, rank=rank,
                    ranks=self.active_ranks())
        for m in survivors + [j for j in joiners if j.start_fn is not None]:
            if not m.removed:
                self._launch(m, m.restarts)

    def _shrink(self, m: _Member, reason: str) -> None:
        if self.world_size() - 1 < self.min_world:
            m.done = True
            raise WorkerFailed(
                f"{self.name}: rank {m.rank} exhausted its restart "
                f"budget ({reason}) and the world cannot shrink below "
                f"min_world={self.min_world}"
            )
        m.removed = True
        if m.ctl_url:
            # Best-effort remote kill: the rank may be a zombie whose
            # exporter still answers — it must not keep computing
            # against a generation that no longer includes it.
            from sparktorch_tpu.ctl.route import CtlRefused, ctl_request

            try:
                ctl_request(m.ctl_url, "kill", token=self.ctl_token,
                            timeout=2.0)
            except CtlRefused:
                pass
        _LOG.warning(
            f"[sparktorch_tpu:ctl] rank {m.rank} {reason}; SHRINKING "
            f"world {self.world_size() + 1} -> {self.world_size()}"
        )
        self._resize("shrink", m.rank)
        self._write_postmortem(f"world shrunk around rank {m.rank} "
                               f"({reason})", rank=m.rank)

    # -- collector-driven supervision --------------------------------------

    def _gang_view(self) -> Optional[Dict[str, Any]]:
        if self.collector is not None:
            try:
                return self.collector.gang_view()
            except Exception as e:  # a torn merge must not kill the loop
                _LOG.warning(f"[sparktorch_tpu:ctl] gang view failed: {e}")
                return None
        if self.gang_url:
            from sparktorch_tpu.obs.collector import ScrapeError, scrape_json

            try:
                view = scrape_json(self.gang_url.rstrip("/") + "/gang",
                                   timeout=2.0)
                return view if isinstance(view, dict) else None
            except ScrapeError as e:
                self.telemetry.counter("ctl.gang_scrape_errors_total")
                _LOG.warning(
                    f"[sparktorch_tpu:ctl] /gang scrape failed "
                    f"(handle supervision continues): {e}")
                return None
        return None

    def _apply_gang_view(self) -> None:
        """Whole-pod liveness from the collector: the two failure
        classes the /gang join makes distinguishable —

        - **exporter vanished**: the rank's scrape is failing but its
          heartbeat is fresh (or its local handle is alive). The rank
          is WORKING; only its observability died. Degrade: count it,
          latch one event per episode, keep handle supervision.
        - **rank died**: heartbeat age past the barrier deadline. With
          a live local handle that is a WEDGED process (preempt: the
          handle kill's grace/SIGKILL escalation applies); with no
          handle (remote rank) it is a death this controller cannot
          relaunch — shrink.
        """
        view = self._gang_view()
        if not view:
            return
        deadline = self.policy.barrier.deadline_s
        scrape_status = view.get("ranks") or {}
        hb_ranks = (view.get("heartbeats") or {}).get("ranks") or {}
        for m in self._members.values():
            if m.removed or m.done:
                continue
            st = scrape_status.get(str(m.rank))
            hb = hb_ranks.get(str(m.rank))
            hb_age = (hb or {}).get("last_seen_age_s")
            handle_alive = m.handle is not None and m.handle.is_alive()
            scrape_ok = bool(st.get("ok")) if st else None
            if scrape_ok is False:
                hb_fresh = (hb_age is not None and deadline
                            and hb_age <= deadline)
                if hb_fresh or handle_alive:
                    if not m.exporter_gone:
                        m.exporter_gone = True
                        self.telemetry.counter(
                            "ctl.exporter_vanished_total",
                            labels={"rank": str(m.rank)})
                        self._event("exporter_vanished", rank=m.rank)
                    continue  # degraded, not dead
            elif scrape_ok and m.exporter_gone:
                m.exporter_gone = False  # episode over
                self._event("exporter_recovered", rank=m.rank)
            if (deadline and hb_age is not None and hb_age > deadline
                    and m.restart_at is None and not m.draining):
                if handle_alive:
                    # Alive-but-wedged: preempt through the handle
                    # (grace -> SIGKILL); the death lands in the next
                    # poll's restart path.
                    self.telemetry.counter(
                        "ft_stall_preemptions_total",
                        labels={"worker": f"rank{m.rank}"})
                    self._event("stall_preempt", rank=m.rank,
                                hb_age_s=hb_age)
                    m.handle.kill()
                    self._write_postmortem(
                        f"rank {m.rank} stall-preempted "
                        f"(hb age {hb_age:.1f}s)", rank=m.rank)
                elif m.start_fn is None:
                    # Remote rank, silent past the deadline, nothing
                    # to relaunch: the world must shrink around it.
                    self._shrink(m, f"remote heartbeat silent "
                                    f"{hb_age:.1f}s > {deadline}s")

    # -- main loop ---------------------------------------------------------

    def detach_alerts(self) -> None:
        """Stop consuming alert firings (idempotent). A finished or
        retired controller must not keep turning alerts into scale
        signals and postmortem bundles — the AlertManager would
        otherwise hold it (and its buses) alive forever."""
        alerts, self.alerts = self.alerts, None
        if alerts is not None:
            alerts.unsubscribe(self._on_alert)

    def run(self, poll_interval_s: float = 0.05,
            deadline_s: Optional[float] = None,
            gang_check_interval_s: float = 0.5) -> Dict[str, Any]:
        """Launch every member and supervise until the WORK is done
        (every partition complete) and no member is mid-restart.
        Returns the run summary; raises :class:`WorkerFailed` only
        when the world can no longer shrink (below ``min_world``).
        Either way the controller retires as an alert consumer."""
        try:
            return self._run_supervise(poll_interval_s, deadline_s,
                                       gang_check_interval_s)
        finally:
            self.detach_alerts()

    def _run_supervise(self, poll_interval_s: float,
                       deadline_s: Optional[float],
                       gang_check_interval_s: float) -> Dict[str, Any]:
        t0 = time.perf_counter()  # lint-obs: ok (run-wall clock for the summary)
        if not self._members:
            raise ValueError(f"{self.name}: no members added")
        self._event("start", ranks=self.active_ranks())
        for m in self._members.values():
            if not m.removed:
                self._launch(m, 0)
        while not self._stop.is_set():
            with self._lock:
                joiners, self._pending_grow = self._pending_grow, []
            if joiners:
                for j in joiners:
                    _LOG.info(f"[sparktorch_tpu:ctl] rank {j.rank} "
                              f"joining; GROWING world")
                self._resize("grow", joiners[0].rank, joiners=joiners)
            pending_members = False
            for m in list(self._members.values()):
                if m.removed or m.done:
                    continue
                if m.restart_at is not None:
                    if time.perf_counter() >= m.restart_at:  # lint-obs: ok (backoff deadline check)
                        self._do_restart(m)
                    pending_members = True
                    continue
                if m.start_fn is None:
                    continue  # remote: watched via the gang view only
                if m.handle.is_alive():
                    pending_members = True
                    continue
                err = m.handle.error
                drained = m.draining or getattr(m.handle, "preempted",
                                                False)
                if err is None and not drained:
                    m.done = True
                    self._event("member_done", rank=m.rank)
                    continue
                reason = (f"failed: {type(err).__name__}: {err}"
                          if err is not None else "preempted")
                if not self._schedule_restart(m, reason):
                    self._shrink(m, f"restart budget exhausted ({reason})")
                    continue
                pending_members = True
            now = time.perf_counter()  # lint-obs: ok (poll-interval clock)
            if now - self._gang_check_ts >= gang_check_interval_s:
                self._gang_check_ts = now
                self._apply_gang_view()
            if not self.pending_work():
                # Work is complete: drain any member still running its
                # (now-empty or in-flight-duplicate) tail and finish.
                still = [m for m in self._members.values()
                         if not m.removed and not m.done
                         and m.start_fn is not None]
                live = [m for m in still
                        if m.handle is not None and m.handle.is_alive()]
                if not live and not any(m.restart_at is not None
                                        for m in still):
                    break
            elif not pending_members and not self._pending_grow:
                # Work remains but nobody is running or scheduled —
                # every launchable member finished an earlier (pre-
                # resize) assignment. Relaunch over the remainder.
                runnable = [m for m in self._members.values()
                            if not m.removed and m.start_fn is not None]
                if not runnable:
                    raise WorkerFailed(
                        f"{self.name}: work pending but no launchable "
                        f"members remain")
                for m in runnable:
                    m.done = False
                    self._launch(m, m.restarts)
                self._event("relaunch", ranks=[m.rank for m in runnable])
            if (deadline_s is not None
                    and time.perf_counter() - t0 > deadline_s):  # lint-obs: ok (deadline check)
                raise WorkerFailed(
                    f"{self.name}: deadline {deadline_s}s exceeded with "
                    f"work pending")
            time.sleep(poll_interval_s)
        for m in self._members.values():
            if m.handle is not None:
                getattr(m.handle, "cleanup", lambda: None)()
        summary = {
            "generation": self.generation,
            "world_size": self.world_size(),
            "restarts": {str(m.rank): m.restarts
                         for m in self._members.values() if m.restarts},
            "resizes": dict(self._resizes),
            "removed": sorted(m.rank for m in self._members.values()
                              if m.removed),
            "work_total": len(self.work),
            "work_pending": len(self.pending_work()),
            "events": len(self.history),
            "wall_s": time.perf_counter() - t0,  # lint-obs: ok (summary wall)
        }
        self._event("finish", **{k: v for k, v in summary.items()
                                 if k in ("restarts", "resizes",
                                          "wall_s")})
        return summary
