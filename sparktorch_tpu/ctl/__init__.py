"""sparktorch_tpu.ctl — the elastic gang control plane.

Driver-side process supervision for multi-host runs: real process
workers with non-cooperative preemption (:mod:`ctl.proc`), one
executable entry shape for every worker kind (:mod:`ctl.worker`), an
elastic controller that shrinks/grows the world live instead of
failing the run (:mod:`ctl.elastic`), and the authenticated control
route (``POST /ctl``) that lets the controller manage ranks it has no
local handle on (:mod:`ctl.route`).
"""

from sparktorch_tpu.ctl.elastic import (
    ELASTIC_SECTION,
    ElasticController,
    round_robin_assign,
)
from sparktorch_tpu.ctl.proc import (
    EXIT_FAILED,
    EXIT_OK,
    EXIT_PREEMPTED,
    ProcessWorker,
    spawn_worker,
    worker_ctl_url,
)
from sparktorch_tpu.ctl.route import (
    CTL_TOKEN_ENV,
    CtlRefused,
    CtlRegistry,
    ctl_request,
)

__all__ = [
    "ELASTIC_SECTION",
    "ElasticController",
    "round_robin_assign",
    "EXIT_FAILED",
    "EXIT_OK",
    "EXIT_PREEMPTED",
    "ProcessWorker",
    "spawn_worker",
    "worker_ctl_url",
    "CTL_TOKEN_ENV",
    "CtlRefused",
    "CtlRegistry",
    "ctl_request",
]
