"""The process-worker entry: ``python -m sparktorch_tpu.ctl.worker``.

One executable shape for every process-level worker the control plane
spawns — the ``run_shard_server``-shaped entry the ROADMAP filed for
fleet shards, plus inference replicas, hogwild workers, and arbitrary
dill-shipped callables (how the chaos benches ship their elastic work
loops). The parent writes a dill payload file; this entry:

1. installs a SIGTERM handler that sets the **cancel event** — the
   cooperative half of preemption (the supervisor's ``kill()`` sends
   SIGTERM first; SIGKILL only lands after the grace window);
2. builds a :class:`WorkerContext`: rank, cancel, a rank-attributed
   :class:`~sparktorch_tpu.obs.HeartbeatEmitter` when the payload
   names a heartbeat directory, a run-scoped telemetry bus, and —
   when ``ctl_port`` is set — a
   :class:`~sparktorch_tpu.native.gang.GangMetricsExporter` serving
   this process's ``/metrics``/``/telemetry`` plus ``POST /ctl``
   (kill/drain verbs), its bound URL published beside the payload;
3. dispatches the payload ``kind`` and exits 0 (done), 75 (drained:
   SIGTERM honored before the work finished), or 1 (crashed, with the
   traceback logged) — exactly the codes
   :class:`~sparktorch_tpu.ctl.proc.ProcessWorker.error` decodes.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

from sparktorch_tpu.ctl.proc import EXIT_FAILED, EXIT_OK, EXIT_PREEMPTED
from sparktorch_tpu.ctl.route import CtlRegistry
from sparktorch_tpu.obs.log import get_logger

_LOG = get_logger("sparktorch_tpu.ctl.worker")


class WorkerContext:
    """What every entry kind receives: identity, the SIGTERM-wired
    cancel event, heartbeat publishing, and the telemetry bus."""

    def __init__(self, name: str, rank: Optional[int], cancel,
                 heartbeat=None, telemetry=None, ctl: Optional[CtlRegistry] = None):
        self.name = name
        self.rank = rank
        self.cancel = cancel
        self.heartbeat = heartbeat
        self.telemetry = telemetry
        self.ctl = ctl

    def notify_step(self, step: int) -> None:
        """Publish training/work progress on the heartbeat (readers
        derive step skew; the chaos ``kill_process_at`` fault and the
        straggler policies key off it). No-op without a heartbeat."""
        if self.heartbeat is not None:
            self.heartbeat.notify_step(step)

    def should_stop(self) -> bool:
        return self.cancel.is_set()


def _hard_exit_soon(code: int, delay_s: float = 0.1) -> None:
    """Reply-then-die for the ctl ``kill`` verb: the HTTP handler must
    get its 200 onto the wire before the process vanishes, or the
    controller counts a successful kill as a transport error."""

    def die():
        time.sleep(delay_s)
        os._exit(code)

    threading.Thread(target=die, daemon=True).start()


def build_context(payload: Dict[str, Any]) -> WorkerContext:
    name = payload.get("name") or "worker"
    rank = payload.get("rank")
    cancel = threading.Event()

    def on_sigterm(signum, frame):
        cancel.set()

    signal.signal(signal.SIGTERM, on_sigterm)

    heartbeat = None
    telemetry = None
    hb_dir = payload.get("heartbeat_dir")
    from sparktorch_tpu.obs import Telemetry

    telemetry = Telemetry(run_id=os.environ.get(
        "SPARKTORCH_TPU_RUN_ID", f"ctl-{name}"))
    # Every process worker keeps a flight recorder: its recent spans
    # and events ride the /telemetry scrape as the ``blackbox``
    # section, so the collector's last-good snapshot of a rank that
    # then dies still holds the victim's final ring — the evidence a
    # postmortem bundle is assembled from.
    from sparktorch_tpu.obs.blackbox import attach_recorder

    recorder = attach_recorder(telemetry)
    if hb_dir and rank is not None:
        from sparktorch_tpu.obs import HeartbeatEmitter

        heartbeat = HeartbeatEmitter(hb_dir, rank, telemetry=telemetry)
        heartbeat.beat()  # liveness visible before the first step

    ctl: Optional[CtlRegistry] = None
    exporter = None
    if payload.get("ctl_port") is not None:
        from sparktorch_tpu.native.gang import GangMetricsExporter

        ctl = CtlRegistry()
        # kill: reply, then die HARD (exit 86 reads as "killed by
        # ctl" in the parent's error — any nonzero code restarts
        # under budget). drain: cooperative — same path as SIGTERM.
        ctl.register("kill", lambda code=86: _hard_exit_soon(int(code)))
        ctl.register("drain", lambda: (cancel.set(), True)[1])
        ctl.register("ping", lambda: {"name": name, "rank": rank,
                                      "pid": os.getpid()})
        exporter = GangMetricsExporter(
            heartbeat_dir=hb_dir, telemetry=telemetry,
            port=int(payload["ctl_port"]), ctl=ctl,
        ).start()
        url_path = payload["__path__"] + ".url"
        tmp = url_path + ".tmp"
        with open(tmp, "w") as f:  # lint-obs: ok (url handoff, not telemetry)
            f.write(exporter.url)
        os.replace(tmp, url_path)
    # Every process worker keeps a goodput ledger beside its flight
    # recorder: installed ambient, so the instrumentation in train/,
    # serve/ and utils/checkpoint attributes into it, and its
    # ``goodput`` section rides the same /telemetry scrape — the
    # collector's run-level /goodput merge (and a postmortem's
    # goodput-at-death block) is built from these per-rank ledgers.
    from sparktorch_tpu.obs import goodput as _goodput

    ledger = _goodput.GoodputLedger(telemetry=telemetry, rank=rank)
    ledger.start_auto_publish()
    ledger.publish()  # section visible from the FIRST scrape
    _goodput.install(ledger)
    # And the stack sampler beside the ledger: the ledger says which
    # bucket is stealing, the profiler says which function inside it.
    # Env-gated (SPARKTORCH_TPU_PROFILE=0 disables); publishes
    # throttled from its own thread, so a SIGKILLed worker's last-good
    # snapshot still carries its final ``profile`` section.
    from sparktorch_tpu.obs import profile as _profile

    profiler = None
    if _profile.enabled():
        profiler = _profile.StackProfiler(telemetry=telemetry, rank=rank)
        profiler.start()
        profiler.publish()  # section visible from the FIRST scrape
        _profile.install(profiler)
    ctx = WorkerContext(name, rank, cancel, heartbeat=heartbeat,
                        telemetry=telemetry, ctl=ctl)
    ctx._exporter = exporter  # kept alive for the process lifetime
    ctx._recorder = recorder
    ctx.ledger = ledger
    ctx.profiler = profiler
    return ctx


def _dispatch(payload: Dict[str, Any], ctx: WorkerContext) -> Any:
    kind = payload.get("kind", "callable")
    kwargs = dict(payload.get("kwargs") or {})
    if kind == "callable":
        fn: Callable[..., Any] = payload["fn"]
        return fn(ctx)
    if kind == "shard_server":
        from sparktorch_tpu.serve.fleet import run_shard_server

        return run_shard_server(ctx=ctx, **kwargs)
    if kind == "replica_server":
        from sparktorch_tpu.serve.infer import run_replica_server

        return run_replica_server(ctx=ctx, **kwargs)
    if kind == "hogwild_worker":
        from sparktorch_tpu.train.hogwild import run_hogwild_worker

        return run_hogwild_worker(ctx=ctx, **kwargs)
    raise ValueError(f"unknown worker kind {kind!r}")


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        _LOG.error("usage: python -m sparktorch_tpu.ctl.worker "
                   "<payload.dill>")
        return 2
    import dill

    with open(argv[0], "rb") as f:
        payload = dill.load(f)
    payload["__path__"] = argv[0]
    # The payload is consumed: remove it now so a worker the parent
    # never cleans up (chaos SIGKILL leaves the parent's handle, but a
    # long-lived controller relaunching for hours must not fill /tmp)
    # leaks at most the tiny .url handoff file, not a dill payload per
    # spawn. The .url path is derived from the NAME, so publishing
    # still works after the unlink.
    try:
        os.unlink(argv[0])
    except OSError:
        pass
    ctx = build_context(payload)
    try:
        _dispatch(payload, ctx)
    except BaseException as e:
        if ctx.cancel.is_set():
            # A drain that surfaced as an exception (a worker loop
            # raising its preemption error) is still a drain.
            _LOG.warning(f"[sparktorch_tpu:ctl] {ctx.name} drained "
                         f"({type(e).__name__})")
            return EXIT_PREEMPTED
        _LOG.error(f"[sparktorch_tpu:ctl] {ctx.name} failed: "
                   f"{type(e).__name__}: {e}")
        import traceback

        traceback.print_exc()
        return EXIT_FAILED
    finally:
        if ctx.heartbeat is not None:
            ctx.heartbeat.close()
        # Final ledger publish: the closing accounting lands on the
        # exporter's snapshot for whoever scrapes the corpse (a
        # SIGKILLed worker never reaches here — its last THROTTLED
        # publish is what the collector's last-good snapshot holds).
        ledger = getattr(ctx, "ledger", None)
        if ledger is not None:
            ledger.close()
        profiler = getattr(ctx, "profiler", None)
        if profiler is not None:
            profiler.stop()  # joins the sampler + final publish
    # A normal return is a fulfilled contract (entry fns drain by
    # returning early, with idempotent skip-on-restart semantics) —
    # exit 0 even when cancel fired late in the run.
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
