"""The gang control route: verbs a controller can POST at a process.

Every supervised process already serves a read-only observability
surface (:class:`~sparktorch_tpu.native.gang.GangMetricsExporter`,
``ParamServerHttp``); this module adds the WRITE half — a tiny verb
registry the exporter mounts as ``POST /ctl`` — so the elastic
controller can manage ranks it holds **no local process handle on**
(remote hosts, ranks adopted after a controller restart): ``kill`` a
wedged rank, ``drain`` one for a graceful world change, ``resize`` the
world through a collector-side registry.

Authentication is deliberately "enough, not more": a shared secret
token (``SPARKTORCH_TPU_CTL_TOKEN`` or an explicit ``token=``) carried
as ``X-Ctl-Token``. Within a pod the exporters bind loopback/pod
network anyway; the token exists so a stray scrape client or a
recycled-port neighbour cannot kill ranks by accident. With no token
configured the route is open (the single-host dev rig), and
:meth:`CtlRegistry.check_token` says so explicitly.

The registry is duck-typed on purpose (``check_token`` + ``handle``):
``native/gang.py`` and ``obs/collector.py`` mount it without importing
this package, keeping the layering acyclic (ctl/ imports native/ and
obs/, never the reverse).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Mapping, Optional

from sparktorch_tpu.obs.collector import post_json
from sparktorch_tpu.obs.log import get_logger

CTL_TOKEN_ENV = "SPARKTORCH_TPU_CTL_TOKEN"

_LOG = get_logger("sparktorch_tpu.ctl.route")


class CtlRefused(RuntimeError):
    """The control endpoint refused the verb (bad token, unknown verb,
    unknown rank) or was unreachable."""


class CtlRegistry:
    """Named verb handlers behind one token check.

    ``register(verb, fn)`` mounts ``fn(**args)``; ``handle`` dispatches
    one request (KeyError on unknown verbs — the HTTP layers translate
    that to 400). Thread-safe: HTTP handler threads dispatch while the
    owning process registers/unregisters verbs.
    """

    def __init__(self, token: Optional[str] = None):
        self.token = token if token is not None \
            else os.environ.get(CTL_TOKEN_ENV)
        self._verbs: Dict[str, Callable[..., Any]] = {}
        self._lock = threading.Lock()

    def register(self, verb: str, fn: Callable[..., Any]) -> None:
        with self._lock:
            self._verbs[str(verb)] = fn

    def verbs(self) -> list:
        with self._lock:
            return sorted(self._verbs)

    def check_token(self, token: Optional[str]) -> bool:
        if not self.token:
            return True  # unguarded: no secret configured
        return token == self.token

    def handle(self, verb: Any, args: Mapping[str, Any]) -> Any:
        with self._lock:
            fn = self._verbs[str(verb)]  # KeyError -> HTTP 400
        return fn(**dict(args))


def ctl_request(url: str, verb: str, token: Optional[str] = None,
                timeout: float = 5.0, **args: Any) -> Dict[str, Any]:
    """POST one verb at a ``/ctl`` endpoint (an exporter's, or the
    collector's fan-out). Returns the decoded reply document; raises
    :class:`CtlRefused` on refusal or unreachability — callers decide
    whether a refused kill is fatal (it usually is not: the rank the
    controller wanted dead may already be dead)."""
    from sparktorch_tpu.obs.collector import ScrapeError

    token = token if token is not None else os.environ.get(CTL_TOKEN_ENV)
    headers = {"X-Ctl-Token": token} if token else None
    try:
        reply = post_json(url.rstrip("/") + "/ctl",
                          {"verb": verb, "args": args},
                          timeout=timeout, headers=headers)
    except ScrapeError as e:
        raise CtlRefused(f"{verb} @ {url}: {e}") from e
    if not isinstance(reply, dict) or not reply.get("ok", False):
        raise CtlRefused(f"{verb} @ {url}: refused: {reply!r}")
    return reply
