"""Python API over the native gang coordinator (native/gang.cpp).

Gang scheduling + rendezvous + failure detection for multi-host
bring-up — the native replacement for the reference's Spark JVM
barrier stage (``distributed.py:39-43``) and gloo TCP rendezvous on a
hardcoded port (``distributed.py:101-105``). The typical flow:

    # driver / host 0
    coord = GangCoordinator(world_size=4)
    # every host (including 0)
    worker = GangWorker(coord_host, coord.port, rank, my_addr)
    worker.barrier(0)                 # gang entry
    peers = worker.world()            # rank-ordered addresses
    jax.distributed.initialize(coordinator_address=peers[0], ...)

Heartbeats run on a daemon thread; a dead host flips every barrier
into a GangFailure, so surviving hosts fail fast instead of hanging
in an XLA collective.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from typing import List, Optional

from sparktorch_tpu.native.build import load_library
from sparktorch_tpu.obs.heartbeat import HEARTBEAT_DIR_ENV, HeartbeatEmitter


class GangFailure(RuntimeError):
    pass


class GangMetricsExporter:
    """Tiny HTTP surface beside the gang coordinator (ROADMAP:
    "multi-host sync training has no HTTP surface yet").

    The param server already scrapes; this gives the SYNC/gang path
    its twin: ``GET /metrics`` serves the attached telemetry snapshot
    as Prometheus text with the heartbeat table folded in as per-rank
    gauges (liveness, step, last-seen age, step skew — derived at
    scrape time from the shared heartbeat directory, so a dead rank
    shows up as a growing age even though it stopped publishing), plus
    coordinator state (registered/failed/dead_rank) when a
    :class:`GangCoordinator` is attached. ``GET /telemetry`` is the
    same merged view as JSON; ``GET /heartbeats`` just the per-rank
    table. Runs on a daemon thread like :class:`ParamServerHttp`; all
    three pieces (telemetry, heartbeat dir, coordinator) are optional,
    so the exporter serves whatever the deployment actually has.
    """

    def __init__(self, heartbeat_dir: Optional[str] = None,
                 coordinator: Optional["GangCoordinator"] = None,
                 telemetry=None, host: str = "127.0.0.1", port: int = 0,
                 ctl=None):
        self.heartbeat_dir = heartbeat_dir or os.environ.get(HEARTBEAT_DIR_ENV)
        self.coordinator = coordinator
        self.telemetry = telemetry
        self.host = host
        self.port = port
        # Control surface (``POST /ctl``): a :class:`sparktorch_tpu.
        # ctl.CtlRegistry` (duck-typed — anything with ``check_token``
        # and ``handle``) lets an elastic controller manage this
        # process (kill/drain/resize verbs) over HTTP when it holds no
        # local handle on it. None = the route answers 404 (the
        # original read-only exporter).
        self.ctl = ctl
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def _merged_snapshot(self) -> dict:
        from sparktorch_tpu.obs import Telemetry, gang_report

        tele = self.telemetry
        snap = (tele.snapshot() if tele is not None
                else Telemetry(run_id="gang_exporter").snapshot())
        gauges = snap.setdefault("gauges", {})
        if self.heartbeat_dir:
            report = gang_report(self.heartbeat_dir)
            snap["gang_report"] = report
            for rank, rec in report.get("ranks", {}).items():
                gauges[f"gang.hb_alive{{rank={rank}}}"] = (
                    1.0 if rec["alive"] else 0.0
                )
                gauges[f"gang.hb_last_seen_age_s{{rank={rank}}}"] = (
                    rec["last_seen_age_s"]
                )
                if rec.get("step") is not None:
                    gauges[f"gang.hb_step{{rank={rank}}}"] = float(rec["step"])
            if "step_skew" in report:
                gauges["gang.hb_step_skew"] = float(report["step_skew"])
            gauges["gang.hb_ranks"] = float(report.get("n_ranks", 0))
        coord = self.coordinator
        if coord is not None:
            gauges["gang.coordinator_registered"] = float(coord.registered)
            gauges["gang.coordinator_failed"] = 1.0 if coord.failed else 0.0
            gauges["gang.coordinator_dead_rank"] = float(coord.dead_rank)
            gauges["gang.coordinator_world_size"] = float(coord.world_size)
            gauges["gang.coordinator_generation"] = float(coord.generation)
            if getattr(coord, "run_id", None):
                # The gang run_id rides the scrape like a build_info
                # string, so a collector can correlate this exporter
                # with the rank streams without parsing REG lines.
                snap.setdefault("info", {})["gang.run_id"] = coord.run_id
        return snap

    def start(self) -> "GangMetricsExporter":
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from sparktorch_tpu.obs import (
            PROMETHEUS_CONTENT_TYPE,
            render_prometheus,
        )

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes = b"",
                      content_type: Optional[str] = None):
                self.send_response(code)
                if content_type:
                    self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                route = self.path.split("?", 1)[0]
                if route == "/":
                    self._send(200, b"sparktorch-tpu gang exporter")
                elif route == "/metrics":
                    snap = exporter._merged_snapshot()
                    snap.pop("gang_report", None)  # gauges carry it
                    self._send(200, render_prometheus(snap).encode(),
                               content_type=PROMETHEUS_CONTENT_TYPE)
                elif route == "/telemetry":
                    self._send(200,
                               _json.dumps(
                                   exporter._merged_snapshot()).encode(),
                               content_type="application/json")
                elif route == "/heartbeats":
                    from sparktorch_tpu.obs import gang_report

                    report = (gang_report(exporter.heartbeat_dir)
                              if exporter.heartbeat_dir else {"n_ranks": 0,
                                                              "ranks": {},
                                                              "alive": []})
                    self._send(200, _json.dumps(report).encode(),
                               content_type="application/json")
                else:
                    self._send(404)

            def do_POST(self):
                route = self.path.split("?", 1)[0]
                if route != "/ctl" or exporter.ctl is None:
                    self._send(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = _json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("ctl body must be an object")
                except (ValueError, TypeError) as e:
                    self._send(400, str(e).encode())
                    return
                if not exporter.ctl.check_token(
                        self.headers.get("X-Ctl-Token")):
                    self._send(403, b"bad ctl token")
                    return
                verb = body.get("verb")
                args = body.get("args") or {}
                try:
                    result = exporter.ctl.handle(verb, args)
                except KeyError:
                    self._send(400, f"unknown verb {verb!r}".encode())
                    return
                except Exception as e:  # verb handlers are user code
                    self._send(500, f"{type(e).__name__}: {e}".encode())
                    return
                self._send(200, _json.dumps(
                    {"ok": True, "verb": verb, "result": result}).encode(),
                    content_type="application/json")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self.start() if self._httpd is None else self

    def __exit__(self, *exc):
        self.stop()


def _lib():
    lib = load_library("gang")
    lib.gang_server_start.restype = ctypes.c_void_p
    lib.gang_server_start.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.gang_server_start2.restype = ctypes.c_void_p
    lib.gang_server_start2.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.gang_server_start3.restype = ctypes.c_void_p
    lib.gang_server_start3.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.gang_server_run_id.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.gang_server_port.argtypes = [ctypes.c_void_p]
    lib.gang_server_resize.restype = ctypes.c_long
    lib.gang_server_resize.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.gang_server_world_size.argtypes = [ctypes.c_void_p]
    lib.gang_server_generation.restype = ctypes.c_long
    lib.gang_server_generation.argtypes = [ctypes.c_void_p]
    lib.gang_server_failed.argtypes = [ctypes.c_void_p]
    lib.gang_server_dead_rank.argtypes = [ctypes.c_void_p]
    lib.gang_server_registered.argtypes = [ctypes.c_void_p]
    lib.gang_server_stop.argtypes = [ctypes.c_void_p]
    lib.gang_client_connect.restype = ctypes.c_void_p
    lib.gang_client_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.gang_client_connect2.restype = ctypes.c_void_p
    lib.gang_client_connect2.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    lib.gang_client_connect3.restype = ctypes.c_void_p
    lib.gang_client_connect3.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_long, ctypes.POINTER(ctypes.c_int),
    ]
    lib.gang_client_connect4.restype = ctypes.c_void_p
    lib.gang_client_connect4.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_long, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.gang_client_run_id.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.gang_client_generation.restype = ctypes.c_long
    lib.gang_client_generation.argtypes = [ctypes.c_void_p]
    lib.gang_client_barrier.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.gang_client_heartbeat.argtypes = [ctypes.c_void_p]
    lib.gang_client_world.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.gang_client_close.argtypes = [ctypes.c_void_p]
    return lib


class GangCoordinator:
    """Driver-side coordinator. world_size hosts must register.

    ``rejoin_grace_ms`` (default 0 = disabled, the original behavior):
    after a member is declared dead, a FRESH re-registration arriving
    within this window opens a NEW GENERATION — the failure latch
    clears, membership and barrier counts reset, and every rank must
    register again — so a supervisor-restarted gang reforms on the
    same coordinator instead of being refused with DEAD forever.
    Outside the window, re-registration stays refused (a dead gang
    must not be silently resurrected under survivors that already saw
    DEAD).

    REG/HB lines are GENERATION-TAGGED (closing the rejoin-grace race
    filed by the ft PR): clients echo the generation they joined, and
    the coordinator refuses stale tags with DEAD — so a survivor of
    the failed generation whose heartbeat socket broke cannot open
    (or sneak into) the new generation while its old-generation peers
    still hold live connections; only genuinely fresh registrations
    (supervisor-restarted ranks) reform the gang. Untagged lines from
    old clients keep the pre-tag semantics, so mixed-version gangs
    interoperate.
    """

    def __init__(self, world_size: int, port: int = 0,
                 heartbeat_timeout_ms: int = 10_000,
                 rejoin_grace_ms: int = 0,
                 run_id: Optional[str] = None):
        # ``run_id`` (None = untagged, the pre-run-id wire format —
        # raw-wire peers keep seeing "OK <ws> <gen>"): a gang-unique
        # id announced in every OK reply; workers stamp it on their
        # spans/events/heartbeats so a fleet collector can join the
        # per-rank streams. bringup_multihost mints one by default.
        # The id travels as ONE token on the space-delimited line
        # protocol (and sscanf caps it at 127 bytes): an id containing
        # whitespace would be silently split — the client would learn
        # a truncated id, claim it on its heartbeat-channel REG, and
        # be refused ERR run, surfacing as a baffling bring-up
        # failure. Refuse the malformed id HERE instead.
        if run_id is not None and (
                not run_id or len(run_id) > 120
                or not run_id.isascii() or not run_id.isprintable()
                or any(c.isspace() for c in run_id)):
            raise ValueError(
                f"run_id {run_id!r} is not line-protocol-safe: need a "
                f"non-empty printable-ASCII token without whitespace, "
                f"<= 120 chars (obs.mint_run_id() produces one)"
            )
        self._lib = _lib()
        self.run_id = run_id
        self._handle = self._lib.gang_server_start3(
            port, world_size, heartbeat_timeout_ms, rejoin_grace_ms,
            (run_id or "").encode(),
        )
        if not self._handle:
            raise RuntimeError("gang coordinator failed to start")
        self.port = self._lib.gang_server_port(self._handle)
        self.world_size = world_size
        self.rejoin_grace_ms = rejoin_grace_ms
        # Last-observed native state, snapshotted by stop() BEFORE the
        # handle is freed: callers (the elastic bench's summary, a
        # supervisor's post-mortem) read .generation/.failed after the
        # run's finally-block stop, and passing the nulled handle into
        # the native calls is a use-after-free (observed segfault).
        self._final = {"failed": False, "dead_rank": -1,
                       "generation": 0, "registered": 0}

    @property
    def failed(self) -> bool:
        if not self._handle:
            return self._final["failed"]
        return bool(self._lib.gang_server_failed(self._handle))

    @property
    def dead_rank(self) -> int:
        if not self._handle:
            return self._final["dead_rank"]
        return int(self._lib.gang_server_dead_rank(self._handle))

    @property
    def generation(self) -> int:
        """Bumped once per rejoin-after-failure episode; generation 0
        is the original gang."""
        if not self._handle:
            return self._final["generation"]
        return int(self._lib.gang_server_generation(self._handle))

    @property
    def registered(self) -> int:
        if not self._handle:
            return self._final["registered"]
        return int(self._lib.gang_server_registered(self._handle))

    def resize(self, new_world_size: int) -> int:
        """Elastic world resize: a membership event with the same
        semantics as a rejoin-after-failure — the generation bumps,
        membership/barrier state clears, parked barrier waiters are
        released with an error, and every (surviving or new) rank must
        re-register fresh into the new generation. The elastic
        controller calls this when a rank exhausts its restart budget
        (shrink: the world continues without it) or a new host joins
        (grow). Returns the new generation."""
        if new_world_size < 1:
            raise ValueError(
                f"world_size must be >= 1, got {new_world_size}")
        if not self._handle:
            raise RuntimeError("cannot resize a stopped coordinator")
        gen = int(self._lib.gang_server_resize(self._handle,
                                               int(new_world_size)))
        if gen < 0:
            raise RuntimeError("gang coordinator refused the resize")
        self.world_size = int(new_world_size)
        return gen

    def stop(self):
        if self._handle:
            self._final = {
                "failed": bool(self._lib.gang_server_failed(self._handle)),
                "dead_rank": int(
                    self._lib.gang_server_dead_rank(self._handle)),
                "generation": int(
                    self._lib.gang_server_generation(self._handle)),
                "registered": int(
                    self._lib.gang_server_registered(self._handle)),
            }
            self._lib.gang_server_stop(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class GangWorker:
    """Per-host client: register, barrier, heartbeat, peer table."""

    def __init__(self, host: str, port: int, rank: int, address: str,
                 timeout_ms: int = 30_000, heartbeat_interval_s: float = 2.0,
                 heartbeat_dir: Optional[str] = None, telemetry=None):
        self._lib = _lib()
        self.rank = rank
        # Rank/host-attributed liveness publishing (obs.heartbeat):
        # the native protocol is a liveness BIT; the emitter adds WHO
        # and HOW FAR (rank, host, pid, training step, last-seen ts),
        # readable by anything sharing the directory. Enabled by the
        # kwarg or the SPARKTORCH_TPU_HEARTBEAT_DIR env var.
        heartbeat_dir = heartbeat_dir or os.environ.get(HEARTBEAT_DIR_ENV)
        self.heartbeat = (
            HeartbeatEmitter(heartbeat_dir, rank, telemetry=telemetry)
            if heartbeat_dir else None
        )
        # Kept for heartbeat-socket reconnection (re-REG overwrites
        # members[rank] server-side while the gang is healthy; once the
        # gang has failed the coordinator refuses with DEAD).
        self._endpoint = (host, port, address, timeout_ms)
        # Fresh registration (generation tag -1: "never joined"); the
        # OK reply tells us which generation we joined, and every
        # subsequent HB/reconnect-REG carries it — so the coordinator
        # can refuse us once the gang reforms without us. -1 after
        # connect means an old untagged coordinator (legacy lines).
        self._handle = self._lib.gang_client_connect(
            host.encode(), port, rank, address.encode(), timeout_ms
        )
        if not self._handle:
            raise GangFailure(f"rank {rank}: cannot register with {host}:{port}")
        self._generation = int(self._lib.gang_client_generation(self._handle))
        # Run-id correlation: a run-id-tagged coordinator announced
        # the gang's run_id in its OK reply. Adopt it everywhere this
        # rank publishes — telemetry events (spans included) and the
        # attributed heartbeat records — so a fleet collector can join
        # the per-rank streams into one gang timeline. None when the
        # coordinator predates the run-id protocol.
        buf = ctypes.create_string_buffer(256)
        n = self._lib.gang_client_run_id(self._handle, buf, len(buf))
        self.run_id: Optional[str] = (
            buf.value.decode() if n > 0 else None
        )
        if self.run_id:
            if self.heartbeat is not None:
                self.heartbeat.set_run_id(self.run_id)
            if telemetry is not None:
                telemetry.set_run_id(self.run_id)
        # Separate connection for heartbeats: the main connection can
        # be parked inside a blocking barrier read, and interleaving
        # HB traffic on the same socket would steal its GO line. A
        # worker without a working heartbeat channel has no failure
        # detection at all — refuse to construct rather than run blind.
        # Tagged with the generation the main channel just joined (and
        # the run id it learned): a reformed gang must not accept this
        # worker's second REG as a fresh member, and a recycled
        # endpoint serving a DIFFERENT run must refuse it.
        status = ctypes.c_int(-1)
        self._hb_handle = self._lib.gang_client_connect4(
            host.encode(), port, rank, address.encode(), timeout_ms,
            self._generation, (self.run_id or "").encode(),
            ctypes.byref(status),
        )
        if not self._hb_handle:
            self._lib.gang_client_close(self._handle)
            self._handle = None
            raise GangFailure(
                f"rank {rank}: heartbeat channel to {host}:{port} refused"
            )
        self._hb_lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_dead = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_interval_s,), daemon=True
        )
        self._hb_thread.start()

    # Consecutive socket-level heartbeat failures tolerated before the
    # gang is considered lost. A DEAD reply from the coordinator (rc=1)
    # is authoritative and fires immediately; rc=-1 is a local I/O
    # error (TCP hiccup, slow coordinator) and must not kill a healthy
    # run — especially now that check_gang() polls every chunk.
    _HB_MAX_IO_FAILURES = 3

    def _heartbeat_loop(self, interval: float):
        io_failures = 0
        while not self._hb_stop.wait(interval):
            if self.heartbeat is not None:
                # Attributed liveness rides the same cadence as the
                # native liveness bit: rank/host/pid/step/ts land in
                # the shared directory every tick. Never let a full
                # disk kill the native channel that actually keeps
                # this member alive.
                try:
                    self.heartbeat.beat()
                except OSError:
                    pass
            with self._hb_lock:
                if self._hb_handle is None:
                    return
                rc = self._lib.gang_client_heartbeat(self._hb_handle)
            if rc == 0:
                io_failures = 0
            elif rc > 0:  # coordinator replied DEAD: authoritative
                self._hb_dead.set()
                return
            else:
                io_failures += 1
                if io_failures >= self._HB_MAX_IO_FAILURES:
                    self._hb_dead.set()
                    return
                # A failed fd stays failed: reconnect before retrying.
                # Dial OUTSIDE the lock (close() must never wait on a
                # connect) and with a short timeout — this is a quick
                # probe, not first registration; a failed dial just
                # spends one of the remaining strikes. A DEAD reply on
                # the re-REG is authoritative (the coordinator now
                # refuses to resurrect a slot in a failed gang): stop
                # probing and declare the gang lost immediately. The
                # re-REG carries OUR generation, so if the gang failed
                # and reformed without us during the rejoin grace
                # window, the coordinator refuses this survivor with
                # DEAD instead of letting its fresh-looking REG open
                # (or join) a generation its peers aren't in — the
                # rejoin-grace race the generation tags exist to close.
                host, port, address, timeout_ms = self._endpoint
                status = ctypes.c_int(-1)
                fresh = self._lib.gang_client_connect4(
                    host.encode(), port, self.rank,
                    address.encode(), min(timeout_ms, 2000),
                    self._generation, (self.run_id or "").encode(),
                    ctypes.byref(status),
                ) or None
                if status.value == 1:
                    self._hb_dead.set()
                    return
                with self._hb_lock:
                    if self._hb_handle is None:  # close()d meanwhile
                        if fresh:
                            self._lib.gang_client_close(fresh)
                        return
                    if fresh:
                        self._lib.gang_client_close(self._hb_handle)
                        self._hb_handle = fresh

    def barrier(self, epoch: int) -> None:
        """Gang entry point — the analog of all barrier tasks reaching
        the stage (``distributed.py:39-43``). Raises on gang failure."""
        if self._hb_dead.is_set():
            raise GangFailure("gang member declared dead")
        rc = self._lib.gang_client_barrier(self._handle, epoch)
        if rc != 0:
            raise GangFailure(f"barrier {epoch} failed (rc={rc})")

    @property
    def failed(self) -> bool:
        """True once the coordinator has declared ANY member dead (the
        heartbeat reply flips to DEAD gang-wide, so survivors learn of
        a peer's death within one heartbeat interval)."""
        return self._hb_dead.is_set()

    @property
    def generation(self) -> int:
        """The gang generation this worker registered into (see
        :class:`GangCoordinator`); -1 when the coordinator predates
        the generation-tagged protocol."""
        return self._generation

    def check(self) -> None:
        """Raise :class:`GangFailure` if the gang has failed. Cheap
        (reads a local event set by the heartbeat thread) — call it
        from host-side training loops between compiled steps so a dead
        host aborts the survivors promptly instead of letting them
        wedge in the next XLA collective."""
        if self.failed:
            raise GangFailure(
                f"rank {self.rank}: gang failed (peer declared dead)"
            )

    def world(self) -> List[str]:
        buf = ctypes.create_string_buffer(1 << 16)
        n = self._lib.gang_client_world(self._handle, buf, len(buf))
        if n < 0:
            raise GangFailure("world query failed")
        return buf.value.decode().split(",") if buf.value else []

    def suspend_heartbeat(self):
        """Test hook: silence this member so the coordinator's failure
        detector fires."""
        self._hb_stop.set()

    @property
    def closed(self) -> bool:
        return self._handle is None

    def close(self):
        self._hb_stop.set()
        if self.heartbeat is not None:
            # Join the heartbeat thread BEFORE the final beat: a tick
            # already past its stop-check would otherwise publish
            # alive=True after (and over) the alive=False record.
            self._hb_thread.join(timeout=5.0)
            # Final alive=False beat: a CLEAN shutdown is readable in
            # the heartbeat table, distinct from a silent death whose
            # last record just ages with alive=True.
            self.heartbeat.close()
        with self._hb_lock:
            if self._hb_handle:
                self._lib.gang_client_close(self._hb_handle)
                self._hb_handle = None
        if self._handle:
            self._lib.gang_client_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
