"""Python API over the native CSV/row packer (native/rowpack.cpp)."""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from sparktorch_tpu.native.build import load_library


def _lib():
    lib = load_library("rowpack")
    lib.rowpack_count.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
    ]
    lib.rowpack_parse.restype = ctypes.c_long
    lib.rowpack_parse.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    return lib


def read_csv(
    path: str,
    label_col: Optional[int] = None,
    nthreads: int = 0,
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Parse a numeric CSV into (features float32 matrix, labels).

    The native ingestion path for MNIST-style files (the reference's
    examples load ``examples/mnist_train.csv`` through Spark's CSV
    reader and then convert row-by-row, torch_distributed.py:43-55).
    Header rows are auto-detected. ``label_col`` extracts one column
    as labels; the rest become the feature matrix.
    """
    lib = _lib()
    rows = ctypes.c_long()
    cols = ctypes.c_int()
    rc = lib.rowpack_count(path.encode(), ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise FileNotFoundError(path)
    n, c = rows.value, cols.value
    if n == 0:
        empty_c = c - (1 if label_col is not None else 0)
        return (np.zeros((0, max(empty_c, 0)), np.float32),
                np.zeros((0,), np.float32) if label_col is not None else None)

    lc = -1 if label_col is None else int(label_col)
    feat_cols = c - (1 if lc >= 0 else 0)
    out = np.empty((n, feat_cols), np.float32)
    labels = np.empty((n,), np.float32) if lc >= 0 else None
    parsed = lib.rowpack_parse(
        path.encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        c,
        lc,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) if labels is not None
        else None,
        nthreads,
    )
    if parsed < 0:
        raise IOError(f"rowpack failed on {path}")
    return out, labels
