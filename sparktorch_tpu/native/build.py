"""Build-on-demand loader for the native libraries."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_LOCK = threading.Lock()
_CACHE: dict = {}


def load_library(name: str) -> ctypes.CDLL:
    """Load ``lib<name>.so``, compiling it first if missing/stale."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        so_path = os.path.join(_BUILD_DIR, f"lib{name}.so")
        src_path = os.path.join(_NATIVE_DIR, f"{name}.cpp")
        if not os.path.exists(so_path) or (
            os.path.exists(src_path)
            and os.path.getmtime(src_path) > os.path.getmtime(so_path)
        ):
            subprocess.run(  # lint-obs: ok (build serialization is the lock's purpose: one compiler run per process)
                ["make", "-C", _NATIVE_DIR, f"build/lib{name}.so"],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(so_path)
        _CACHE[name] = lib
        return lib
