"""ctypes bindings for the native (C++) runtime components.

Libraries are built on demand from ``native/*.cpp`` with the repo's
Makefile and cached in ``native/build/``. See native/gang.cpp and
native/rowpack.cpp for what each replaces in the reference.
"""

from sparktorch_tpu.native.build import load_library

__all__ = ["load_library"]
