"""Small reference-parity networks (Flax linen).

Counterparts of the reference's test fixtures and example nets:

- ``Net`` (10->20->1 regressor)            tests/simple_net.py:5-16
- ``AutoEncoder`` (10->5->10)              tests/simple_net.py:19-36
- ``ClassificationNet`` (10->20->2 + log-softmax) tests/simple_net.py:39-51
- ``NetworkWithParameters`` (ctor-sized)   tests/simple_net.py:54-65
- MNIST MLP                                 examples/simple_dnn.py
- MNIST CNN                                 examples/cnn_network.py:6-24

These are *re-designed* for TPU rather than transliterated: widths are
kept as the reference documents them (parity), but everything runs in
a jittable functional forward, defaults to float32 params with
bfloat16-friendly compute, and avoids per-row dynamic shapes.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    """Generic MLP: hidden widths + activation + optional head act."""

    features: Sequence[int]
    activation: Callable = nn.relu
    final_activation: Callable | None = None

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        for i, width in enumerate(self.features):
            x = nn.Dense(width, name=f"dense_{i}")(x)
            if i < len(self.features) - 1:
                x = self.activation(x)
        if self.final_activation is not None:
            x = self.final_activation(x)
        return x


class Net(nn.Module):
    """10 -> 20 -> 1 regressor (tests/simple_net.py:5-16)."""

    in_features: int = 10
    hidden: int = 20

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(1)(x)


class AutoEncoder(nn.Module):
    """10 -> 5 -> 10 autoencoder (tests/simple_net.py:19-36)."""

    in_features: int = 10
    latent: int = 5

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        z = nn.relu(nn.Dense(self.latent)(x))
        return nn.Dense(self.in_features)(z)


class ClassificationNet(nn.Module):
    """10 -> 20 -> n_classes with log-softmax head
    (tests/simple_net.py:39-51). Pairs with the ``nll`` loss the way
    the reference pairs LogSoftmax with NLLLoss / CrossEntropy."""

    in_features: int = 10
    hidden: int = 20
    n_classes: int = 2

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.Dense(self.n_classes)(x)
        return nn.log_softmax(x, axis=-1)


class NetworkWithParameters(nn.Module):
    """Ctor-parameterized net (tests/simple_net.py:54-65) — exercises
    the lazy-serialization path where ctor kwargs ship with the class."""

    input_size: int = 10
    hidden_size: int = 20
    output_size: int = 1

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden_size)(x))
        return nn.Dense(self.output_size)(x)


class MnistMLP(nn.Module):
    """784 -> 256 -> 128 -> 10 (examples/simple_dnn.py workload)."""

    hidden: Sequence[int] = (256, 128)
    n_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width)(x))
        return nn.Dense(self.n_classes)(x)


class MnistCNN(nn.Module):
    """MNIST conv net (examples/cnn_network.py:6-24 capability).

    TPU notes: NHWC layout (XLA:TPU's native conv layout), channel
    counts padded to MXU-friendly sizes, single reshape at the stem so
    flat 784-feature rows (the reference's VectorAssembler output) feed
    straight in.
    """

    n_classes: int = 10
    width: int = 32
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:  # flat (batch, 784) rows
            x = x.reshape(x.shape[0], 28, 28, 1)
        elif x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.compute_dtype)
        x = nn.Conv(self.width, (3, 3), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.width * 2, (3, 3), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(128, dtype=self.compute_dtype)(x))
        x = nn.Dense(self.n_classes, dtype=jnp.float32)(x)
        return x
