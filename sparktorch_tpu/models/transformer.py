"""Transformer encoder / LM family (BERT-class workloads).

Covers the BASELINE stress configs the reference can only feed through
its generic DP loop (BERT-base SST-2 fine-tune, BASELINE.md config 4;
the reference itself contains no transformer or attention code —
SURVEY §5 "Long-context": *entirely absent*). Long context is
first-class here:

- ``attn_impl='dense'``: fused-by-XLA softmax attention.
- ``attn_impl='ring'``: sequence-parallel ring attention
  (:mod:`sparktorch_tpu.ops.attention`) — the sequence axis is
  sharded over the mesh's ``sp`` axis and K/V blocks rotate over ICI,
  so max sequence length scales linearly with the number of chips.
  Requires running under ``jax.set_mesh(mesh)`` (the sharded trainer
  does this), because the shard_map island resolves the ambient mesh.

Tensor parallelism: head and FFN dims are sharded over ``tp`` by the
sharding rules in :mod:`sparktorch_tpu.parallel.sharding_rules`; XLA
GSPMD inserts the tp collectives. Heads must divide the tp size.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from sparktorch_tpu.ops.attention import dense_attention, ring_attention
from sparktorch_tpu.parallel.mesh import AXIS_EP, BATCH_AXES



@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_len: int = 512
    n_classes: int = 2
    dtype: str = "bfloat16"
    attn_impl: str = "dense"  # 'dense' | 'ring'
    causal: bool = False
    remat: bool = False
    # Mixture-of-experts (0 = dense FFN everywhere). Expert weights
    # carry a leading experts dim that the sharding rules lay out over
    # the ``ep`` mesh axis; GSPMD then derives the dispatch/combine
    # all-to-alls from the einsum operand shardings.
    n_experts: int = 0
    moe_every: int = 2          # every k-th layer uses the MoE FFN
    capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2  # switch-style load-balance loss
    # Routing fan-out per token. 1 = switch-style (gate = raw top prob);
    # k>=2 = GShard-style: top-k experts with gates renormalized over
    # the chosen k, first choices claim capacity before second choices.
    moe_top_k: int = 1
    # Routing group size: tokens route within fixed-size groups, so
    # the dispatch/combine one-hots are O(n * group * cf) elements —
    # linear in total tokens — instead of O(n^2) with global routing.
    moe_group_size: int = 4096
    # How tokens reach their experts across the ``ep`` mesh axis in the
    # pipeline trainer's manual MoE path (train/pipeline.py):
    # 'a2a'       — GShard-style: each ep member routes only its own
    #               slice of the routing groups and token blocks travel
    #               to their experts' owners over an all_to_all (and
    #               back) — per-member routing/dispatch work and
    #               activation bytes scale 1/ep;
    # 'replicate' — every member routes the full batch and computes its
    #               expert slice, one psum combines (the round-4
    #               layout; correct but does not shrink with ep);
    # 'auto'      — 'a2a' when the group count divides by ep, else
    #               'replicate'. The GSPMD trainer is unaffected: there
    #               the layout comes from sharding constraints and XLA
    #               derives the all-to-alls.
    moe_ep_dispatch: str = "auto"
    # CausalLM: share the input embedding matrix with the LM head
    # (logits = h @ E^T) — halves the vocab-sized params.
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def moe_pattern(self):
        """Per-layer use_moe flags — THE layer schedule, shared by the
        flax ``Transformer`` stack and the pipeline trainer's stacked
        layout (they must agree or restacked params would silently
        swap kinds)."""
        return [
            self.n_experts > 0 and (i + 1) % max(1, self.moe_every) == 0
            for i in range(self.n_layers)
        ]

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


class MultiHeadAttention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, _ = x.shape
        dt = cfg.compute_dtype
        qkv = nn.DenseGeneral(
            (3, cfg.n_heads, cfg.head_dim), axis=-1, dtype=dt, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (b,s,h,hd)

        if cfg.attn_impl == "flash":
            from sparktorch_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, cfg.causal)
        elif cfg.attn_impl == "ring" and _sp_mesh_available():
            from sparktorch_tpu.train.step import shard_map_compat

            spec = P(BATCH_AXES, "sp", "tp", None)
            attn = shard_map_compat(
                lambda q, k, v: ring_attention(
                    q, k, v, axis_name="sp", causal=cfg.causal
                ),
                mesh=None,  # ambient mesh (jax.set_mesh)
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
            out = attn(q, k, v)
        else:
            # dense — also the ring fallback when no GSPMD mesh with
            # sp>1 is ambient (plain init/apply, inference transforms,
            # manual-axis trainers): ring IS dense attention computed
            # blockwise, so a ring-trained model applies anywhere.
            out = dense_attention(q, k, v, causal=cfg.causal)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=dt, name="proj"
        )(out)


def _sp_mesh_available() -> bool:
    """Whether a GSPMD (non-Manual) ambient mesh with sp > 1 is in
    scope — the only context where the ring-attention shard_map island
    can (and should) open. Everywhere else — plain init/apply with no
    mesh, or inside a shard_map trainer where axes are Manual — ring
    falls back to dense (same math, single block)."""
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is None or "sp" not in am.shape or am.shape["sp"] <= 1:
            return False
        types = dict(zip(am.axis_names, am.axis_types))
        return "Manual" not in str(types["sp"])
    except Exception:
        return False


def _gspmd_constraint(x, spec: P):
    """``with_sharding_constraint`` iff the ambient (set_mesh) mesh has
    every axis the spec names in GSPMD (non-Manual) mode — i.e. the
    GSPMD sharded trainer. Inside a shard_map trainer (DP or pipeline)
    those axes are Manual and the constraint would be meaningless-to-
    wrong, and under plain apply (inference, tests) there is no mesh at
    all; both cases fall through to identity."""
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is None or not am.shape:
            return x
        types = dict(zip(am.axis_names, am.axis_types))
        axes = [
            a
            for part in spec
            if part is not None
            for a in (part if isinstance(part, tuple) else (part,))
        ]
        for ax in axes:
            if ax not in types or "Manual" in str(types[ax]):
                return x
        # Each constrained dim must divide its axes' total extent —
        # constraining a 1-group tensor across 8 devices just forces
        # an involuntary full reshard (SPMD partitioner warning).
        for dim, part in zip(x.shape, spec):
            if part is None:
                continue
            total = 1
            for a in (part if isinstance(part, tuple) else (part,)):
                total *= am.shape[a]
            if total > 1 and dim % total != 0:
                return x
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception:  # no mesh context / legacy jax — layout hint only
        return x


class MoEFFN(nn.Module):
    """Top-k mixture-of-experts FFN (switch-style at k=1, GShard-style
    gate-weighted combine at k>=2).

    No reference counterpart (SURVEY §2.4: EP "absent"). TPU-first
    design: routing, dispatch, expert matmuls and combine are einsums
    over a (experts, capacity, d_model) layout — no per-expert Python,
    no dynamic shapes. Expert weights have a leading experts dim that
    the sharding rules place on the ``ep`` mesh axis; under GSPMD the
    dispatch einsum's operands (tokens sharded over dp, experts sharded
    over ep) force the all-to-all, and the combine reverses it. The
    switch load-balance loss is sown (pre-weighted by
    ``moe_aux_weight``) into the ``losses`` collection; every trainer
    adds sown losses to the objective.

    Tokens route within fixed-size groups (``moe_group_size``), so the
    dispatch/combine one-hots stay linear in total tokens.

    ``token_w`` (per-token weights, (b, s)) masks weight-0 rows — the
    empty-partition padding protocol — OUT of routing: masked tokens
    claim no capacity, contribute nothing to the aux loss, and get
    zero expert output (their residual path carries them). Trainers
    pass the batch's example weights down automatically (step._forward).

    Observability: the fraction of routed token-choices dropped at
    capacity is sown into the ``moe_metrics`` collection as raw
    (dropped, routed) counts; trainers psum them and expose
    ``moe_drop_fraction`` in the step metrics.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, x, token_w=None):
        import math

        cfg = self.config
        dt = cfg.compute_dtype
        b, s, d = x.shape
        e = cfg.n_experts
        k = max(1, min(cfg.moe_top_k, e))
        n = b * s
        # Largest group size <= moe_group_size dividing n (n and the
        # bound are trace-time ints, so this loop is free).
        g = min(n, max(1, cfg.moe_group_size))
        while n % g:
            g -= 1
        n_groups = n // g
        tokens = x.reshape(n_groups, g, d)
        # GSPMD layout (active only under the sharded trainer's mesh):
        # routing groups shard over EVERY data axis including ep —
        # each ep member routes only its share of the groups — and the
        # constraint on expert_in below (experts over ep) makes XLA
        # insert the GShard dispatch all-to-all; the constraint on the
        # combine output reverses it. See the pipeline trainer's
        # _moe_ffn_ep_a2a for the same layout written as explicit
        # collectives.
        _groups_spec = P(BATCH_AXES + (AXIS_EP,), None, None)
        _experts_spec = P(BATCH_AXES, AXIS_EP, None, None)
        tokens = _gspmd_constraint(tokens, _groups_spec)
        # Static per-group capacity: ceil(cf * g * k / e) — scales with
        # the routing fan-out so k=2 doesn't halve effective capacity.
        cap = max(1, math.ceil(cfg.capacity_factor * g * k / e))
        if token_w is not None:
            mask = (token_w.reshape(n_groups, g) > 0)      # (G, g) bool
        else:
            mask = None

        # Router in f32 (small matmul; numerics matter more than MXU).
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )                                            # (G, g, e)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_p, topk_idx = jax.lax.top_k(probs, k)   # (G, g, k)
        if k == 1:
            gates = topk_p                           # switch: raw prob
        else:
            gates = topk_p / jnp.maximum(
                jnp.sum(topk_p, axis=-1, keepdims=True), 1e-9
            )

        oh = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # (G, g, k, e)
        if mask is not None:
            oh = oh * mask[:, :, None, None]
            gates = gates * mask[:, :, None]
        # Capacity assignment with choice-level priority: ALL first
        # choices rank before any second choice (GShard). Flatten
        # (k, g) choice-major, cumsum arrival order, unflatten.
        oh_t = oh.transpose(0, 2, 1, 3).reshape(n_groups, k * g, e)
        pos = jnp.cumsum(oh_t, axis=1) * oh_t        # 1-based rank
        keep = (pos > 0) & (pos <= cap)
        slot = jnp.clip(pos - 1, 0, cap - 1)
        disp_flat = keep[..., None] & jax.nn.one_hot(slot, cap, dtype=bool)
        disp = disp_flat.reshape(n_groups, k, g, e, cap).transpose(
            0, 2, 1, 3, 4
        )                                            # (G, g, k, e, cap)

        # A token's k choices hit k DISTINCT experts, so summing over
        # the choice dim yields a 0/1 dispatch tensor.
        dispatch = jnp.any(disp, axis=2).astype(dt)  # (G, g, e, cap)
        expert_in = jnp.einsum("gnec,gnd->gecd", dispatch,
                               tokens.astype(dt))    # (G, e, cap, d)
        expert_in = _gspmd_constraint(expert_in, _experts_spec)  # <- a2a
        w_in = self.param("moe_w_in", nn.initializers.lecun_normal(),
                          (e, d, cfg.d_ff))
        b_in = self.param("moe_b_in", nn.initializers.zeros, (e, cfg.d_ff))
        w_out = self.param("moe_w_out", nn.initializers.lecun_normal(),
                           (e, cfg.d_ff, d))
        b_out = self.param("moe_b_out", nn.initializers.zeros, (e, d))
        h = jnp.einsum("gecd,edf->gecf", expert_in, w_in.astype(dt))
        h = nn.gelu(h + b_in[None, :, None].astype(dt))
        h = _gspmd_constraint(h, _experts_spec)
        expert_out = jnp.einsum("gecf,efd->gecd", h, w_out.astype(dt))
        expert_out = expert_out + b_out[None, :, None].astype(dt)
        expert_out = _gspmd_constraint(expert_out, _experts_spec)

        # Gate-weighted combine over the kept (token, choice) slots.
        combine = jnp.einsum("gnk,gnkec->gnec", gates.astype(dt),
                             disp.astype(dt))        # (G, g, e, cap)
        out = jnp.einsum("gnec,gecd->gnd", combine, expert_out)
        out = _gspmd_constraint(out, _groups_spec)   # <- combine a2a back

        # Switch load-balance loss over VALID tokens only: e * sum_e
        # frac_e * prob_e, where frac uses the primary (first) choice.
        oh0 = oh[:, :, 0, :].astype(jnp.float32)     # (G, g, e)
        if mask is not None:
            mf = mask.astype(jnp.float32)
            valid = jnp.maximum(jnp.sum(mf, axis=1), 1.0)         # (G,)
            frac = jnp.sum(oh0, axis=1) / valid[:, None]
            mean_prob = (
                jnp.sum(probs * mf[:, :, None], axis=1) / valid[:, None]
            )
        else:
            frac = jnp.mean(oh0, axis=1)                          # (G, e)
            mean_prob = jnp.mean(probs, axis=1)                   # (G, e)
        aux = cfg.moe_aux_weight * e * jnp.mean(
            jnp.sum(frac * mean_prob, axis=-1)
        )
        self.sow("losses", "moe_aux", aux)

        # Raw drop counts (masked tokens never counted as routed).
        routed = jnp.sum(oh).astype(jnp.float32)
        kept = jnp.sum(keep.astype(jnp.float32))
        self.sow("moe_metrics", "dropped", routed - kept)
        self.sow("moe_metrics", "routed", routed)
        return out.reshape(b, s, d)


class EncoderLayer(nn.Module):
    config: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, token_w=None):
        cfg = self.config
        dt = cfg.compute_dtype
        h = nn.LayerNorm(dtype=dt, name="ln_attn")(x)
        x = x + MultiHeadAttention(cfg, name="attn")(h)
        h = nn.LayerNorm(dtype=dt, name="ln_mlp")(x)
        if self.use_moe:
            h = MoEFFN(cfg, name="moe")(h, token_w)
        else:
            h = nn.Dense(cfg.d_ff, dtype=dt, name="mlp_in")(h)
            h = nn.gelu(h)
            h = nn.Dense(cfg.d_model, dtype=dt, name="mlp_out")(h)
        return x + h


class Transformer(nn.Module):
    """Token-id encoder backbone. Accepts int ids or float columns
    (the estimator's feature matrix is float32; ids are cast)."""

    config: TransformerConfig

    # Optional externally-owned embedding module (weight tying: the
    # CausalLM owns it and reuses it as the LM head).
    embed: Optional[nn.Module] = None

    @nn.compact
    def __call__(self, ids, example_w=None):
        cfg = self.config
        if jnp.issubdtype(ids.dtype, jnp.floating):
            ids = ids.astype(jnp.int32)
        b, s = ids.shape
        embed = self.embed if self.embed is not None else nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype,
            name="tok_embed",
        )
        tok = embed(ids)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (cfg.max_len, cfg.d_model),
        )
        x = tok + pos[None, :s].astype(cfg.compute_dtype)
        # Per-token weights for MoE routing: padding EXAMPLES (w=0,
        # the empty-partition protocol) broadcast over their tokens.
        token_w = (
            jnp.broadcast_to(example_w[:, None], (b, s))
            if example_w is not None and cfg.n_experts > 0 else None
        )
        layer = EncoderLayer
        if cfg.remat:
            layer = nn.remat(EncoderLayer)
        for i, use_moe in enumerate(cfg.moe_pattern()):
            x = layer(cfg, use_moe=use_moe, name=f"layer_{i}")(x, token_w)
        return nn.LayerNorm(dtype=cfg.compute_dtype, name="ln_final")(x)


class SequenceClassifier(nn.Module):
    """BERT-style classifier (SST-2 workload, BASELINE config 4)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, ids, example_w=None):
        x = Transformer(self.config, name="backbone")(ids, example_w)
        # Mean-pool (padding-id masking is the caller's concern; the
        # estimator's weighted loss handles padded *examples*).
        pooled = jnp.mean(x, axis=1)
        pooled = jnp.tanh(
            nn.Dense(self.config.d_model, dtype=self.config.compute_dtype,
                     name="pooler")(pooled)
        )
        return nn.Dense(self.config.n_classes, dtype=jnp.float32,
                        name="classifier")(pooled)


class CausalLM(nn.Module):
    """Decoder-style LM head over the same backbone (long-context
    training workload for ring attention)."""

    config: TransformerConfig

    def setup(self):
        cfg = dataclasses.replace(self.config, causal=True)
        if cfg.tie_embeddings:
            # One vocab-sized matrix: the embedding doubles as the LM
            # head (logits = h @ E^T via nn.Embed.attend).
            self.tok_embed = nn.Embed(
                cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype,
                name="tok_embed",
            )
            self.backbone = Transformer(cfg, embed=self.tok_embed)
        else:
            self.backbone = Transformer(cfg)
            self.lm_head = nn.Dense(cfg.vocab_size, dtype=jnp.float32)

    def __call__(self, ids, example_w=None):
        x = self.backbone(ids, example_w)
        if self.config.tie_embeddings:
            # f32 logits like the untied Dense head (attend would run
            # the vocab matmul in the embed's compute dtype; logit
            # precision matters for the CE loss and its gradients).
            emb = self.tok_embed.embedding
            return x.astype(jnp.float32) @ emb.astype(jnp.float32).T
        return self.lm_head(x)


def bert_base(n_classes: int = 2, **overrides) -> SequenceClassifier:
    cfg = TransformerConfig(n_classes=n_classes, **overrides)
    return SequenceClassifier(cfg)


def tiny_transformer(**overrides) -> TransformerConfig:
    """Small config for tests/dryruns."""
    defaults = dict(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                    d_ff=128, max_len=128)
    defaults.update(overrides)
    return TransformerConfig(**defaults)
