"""Transformer encoder / LM family (BERT-class workloads).

Covers the BASELINE stress configs the reference can only feed through
its generic DP loop (BERT-base SST-2 fine-tune, BASELINE.md config 4;
the reference itself contains no transformer or attention code —
SURVEY §5 "Long-context": *entirely absent*). Long context is
first-class here:

- ``attn_impl='dense'``: fused-by-XLA softmax attention.
- ``attn_impl='ring'``: sequence-parallel ring attention
  (:mod:`sparktorch_tpu.ops.attention`) — the sequence axis is
  sharded over the mesh's ``sp`` axis and K/V blocks rotate over ICI,
  so max sequence length scales linearly with the number of chips.
  In the pipeline trainer the rotation rides the schedule's own
  shard_map; under the GSPMD trainer the partitioner computes the
  global dense attention over the sp sharding (the island form is
  opt-in via ``SPARKTORCH_TPU_GSPMD_RING_ISLAND=1`` — it shifts
  blockwise-softmax rounding at bf16, see ``MultiHeadAttention``).

Tensor parallelism: head and FFN dims are sharded over ``tp`` by the
sharding rules in :mod:`sparktorch_tpu.parallel.sharding_rules`; XLA
GSPMD inserts the tp collectives. Heads must divide the tp size.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from sparktorch_tpu.ops.attention import dense_attention, ring_attention
from sparktorch_tpu.parallel.compat import ambient_gspmd_mesh
from sparktorch_tpu.parallel.mesh import AXIS_EP, BATCH_AXES



@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_len: int = 512
    n_classes: int = 2
    dtype: str = "bfloat16"
    attn_impl: str = "dense"  # 'dense' | 'ring'
    causal: bool = False
    remat: bool = False
    # Mixture-of-experts (0 = dense FFN everywhere). Expert weights
    # carry a leading experts dim that the sharding rules lay out over
    # the ``ep`` mesh axis; the dispatch/combine are explicit shard_map
    # all-to-alls (MoEFFN / _ep_relayout), never partitioner-derived.
    n_experts: int = 0
    moe_every: int = 2          # every k-th layer uses the MoE FFN
    capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2  # switch-style load-balance loss
    # Routing fan-out per token. 1 = switch-style (gate = raw top prob);
    # k>=2 = GShard-style: top-k experts with gates renormalized over
    # the chosen k, first choices claim capacity before second choices.
    moe_top_k: int = 1
    # Routing group size: tokens route within fixed-size groups, so
    # the dispatch/combine one-hots are O(n * group * cf) elements —
    # linear in total tokens — instead of O(n^2) with global routing.
    moe_group_size: int = 4096
    # How tokens reach their experts across the ``ep`` mesh axis —
    # governs BOTH manual-ep paths (the pipeline trainer's shard_map
    # MoE in train/pipeline.py, and the GSPMD trainer's MoEFFN, whose
    # dispatch/combine are explicit shard_map all_to_all islands):
    # 'a2a'       — GShard-style: each ep member routes only its own
    #               slice of the routing groups and token blocks travel
    #               to their experts' owners over an all_to_all (and
    #               back) — per-member routing/dispatch work and
    #               activation bytes scale 1/ep. Raises at trace time
    #               if the group count cannot shard evenly.
    # 'replicate' — no explicit dispatch collectives. In the pipeline
    #               trainer: every ep member routes the full batch and
    #               computes its expert slice, one psum combines (the
    #               round-4 layout; correct but does not shrink with
    #               ep). In the GSPMD trainer: the layout is left to
    #               sharding constraints and the partitioner — which on
    #               jax 0.4.x lowers to all-gather + all-reduce (full
    #               token replication); kept ONLY as the bench-moe
    #               control leg and an escape hatch.
    # 'auto'      — 'a2a' when the routing groups shard evenly, else
    #               'replicate'. Under the GSPMD trainer the group
    #               partition is mesh-anchored (see moe_group_partition)
    #               so 'auto' reaches the a2a path whenever the token
    #               count divides the device count.
    moe_ep_dispatch: str = "auto"
    # CausalLM: share the input embedding matrix with the LM head
    # (logits = h @ E^T) — halves the vocab-sized params.
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def moe_pattern(self):
        """Per-layer use_moe flags — THE layer schedule, shared by the
        flax ``Transformer`` stack and the pipeline trainer's stacked
        layout (they must agree or restacked params would silently
        swap kinds)."""
        return [
            self.n_experts > 0 and (i + 1) % max(1, self.moe_every) == 0
            for i in range(self.n_layers)
        ]

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


class MultiHeadAttention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, _ = x.shape
        dt = cfg.compute_dtype
        qkv = nn.DenseGeneral(
            (3, cfg.n_heads, cfg.head_dim), axis=-1, dtype=dt, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (b,s,h,hd)

        if cfg.attn_impl == "flash":
            from sparktorch_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, cfg.causal)
        elif cfg.attn_impl == "ring" and _ring_island_enabled() \
                and _sp_mesh_available(q.shape):
            from sparktorch_tpu.train.step import shard_map_compat

            spec = P(BATCH_AXES, "sp", "tp", None)
            attn = shard_map_compat(
                lambda q, k, v: ring_attention(
                    q, k, v, axis_name="sp", causal=cfg.causal
                ),
                mesh=ambient_gspmd_mesh(),
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
            out = attn(q, k, v)
        else:
            # dense — the ring default under the GSPMD trainer and the
            # fallback everywhere else (plain init/apply, inference
            # transforms, manual-axis trainers): ring IS dense
            # attention computed blockwise, so a ring-trained model
            # applies anywhere. Under a GSPMD mesh with sp>1 the
            # partitioner computes THIS global dense attention over the
            # sequence sharding itself — the correctness the sp/ep
            # parity matrix pins; the explicit ring island
            # (SPARKTORCH_TPU_GSPMD_RING_ISLAND=1) changes blockwise-
            # softmax rounding at bf16 and is opt-in on this jax line.
            # (The pipeline trainer's ring — where the rotation is
            # load-bearing — is unaffected: it rides the pp shard_map,
            # not this island.)
            out = dense_attention(q, k, v, causal=cfg.causal)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=dt, name="proj"
        )(out)


def _ring_island_enabled() -> bool:
    """Opt-in knob for the GSPMD ring-attention island. Off by
    default: GSPMD computes the global dense attention over the sp
    sharding itself, and the island's blockwise softmax would shift
    bf16 rounding vs the dense-reference parity matrix."""
    import os

    return os.environ.get(
        "SPARKTORCH_TPU_GSPMD_RING_ISLAND", "0"
    ) not in ("", "0", "false", "off")


def _sp_mesh_available(qkv_shape=None) -> bool:
    """Whether a GSPMD (non-Manual) ambient mesh with sp > 1 is in
    scope — the only context where the ring-attention shard_map island
    can (and should) open. Everywhere else — plain init/apply with no
    mesh, or inside a shard_map trainer where axes are Manual — ring
    falls back to dense (same math, single block). With ``qkv_shape``
    given, the island's (b, s, h, hd) in_spec must also divide
    (batch over dp+fsdp, sequence over sp, heads over tp)."""
    mesh = ambient_gspmd_mesh()
    if mesh is None or dict(mesh.shape).get("sp", 1) <= 1:
        return False
    if qkv_shape is not None:
        sizes = dict(mesh.shape)
        b, s, h = qkv_shape[0], qkv_shape[1], qkv_shape[2]
        n_batch = 1
        for ax in BATCH_AXES:
            n_batch *= sizes.get(ax, 1)
        if b % n_batch or s % sizes["sp"] or h % sizes.get("tp", 1):
            return False
    return True


def _gspmd_constraint(x, spec: P):
    """``with_sharding_constraint`` iff an ambient (set_mesh) mesh is
    in scope in GSPMD (non-Manual) mode — i.e. the GSPMD sharded
    trainer. Inside a shard_map trainer (DP or pipeline) the axes are
    Manual and the constraint would be meaningless-to-wrong, and under
    plain apply (inference, tests) there is no mesh at all; both cases
    fall through to identity (:func:`ambient_gspmd_mesh` returns
    None)."""
    mesh = ambient_gspmd_mesh()
    if mesh is None:
        return x
    sizes = dict(mesh.shape)
    for part in spec:
        if part is None:
            continue
        for a in (part if isinstance(part, tuple) else (part,)):
            if a not in sizes:
                return x
    # Each constrained dim must divide its axes' total extent —
    # constraining a 1-group tensor across 8 devices just forces
    # an involuntary full reshard (SPMD partitioner warning).
    for dim, part in zip(x.shape, spec):
        if part is None:
            continue
        total = 1
        for a in (part if isinstance(part, tuple) else (part,)):
            total *= sizes[a]
        if total > 1 and dim % total != 0:
            return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def moe_group_partition(cfg, n: int,
                        n_shards: Optional[int] = None) -> Tuple[int, int]:
    """``(group size, group count)`` for routing ``n`` tokens — THE one
    definition of the MoE group partition, shared by the flax
    :class:`MoEFFN` and the pipeline trainer's manual MoE paths.

    Base rule: the largest ``g <= cfg.moe_group_size`` dividing ``n``
    (trace-time ints, the loop is free). With ``n_shards`` (the GSPMD
    trainer passes its mesh's TOTAL device count), ``g`` must also
    keep ``n/g`` divisible by ``n_shards`` — at least one routing
    group per device, the GShard layout — so the groups dim shards
    evenly over dp x fsdp x ep and the dispatch all-to-all can engage.
    Anchoring on the whole device count (not dp*fsdp*ep) keeps the
    partition IDENTICAL across every mesh shape of the same rig, which
    is what makes ep (and tp/sp/fsdp) a pure layout choice in the
    parity tests. Falls back to the base rule when ``n`` has no such
    divisor (then the a2a path cannot engage either)."""
    cap = max(1, cfg.moe_group_size)
    if n_shards and n_shards > 1 and n % n_shards == 0:
        per_shard = n // n_shards
        g = min(per_shard, cap)
        while per_shard % g:
            g -= 1
        return g, n // g
    g = min(n, cap)
    while n % g:
        g -= 1
    return g, n // g


# ---------------------------------------------------------------------------
# Explicit MoE dispatch/combine all-to-alls (the shard_map island)
# ---------------------------------------------------------------------------


def _moe_relayout_island(x, to_experts: bool):
    """One tiled ``all_to_all`` over ``ep`` relaying a (G, e, cap, d)
    capacity-block tensor between the two MoE layouts (specs in
    :mod:`sparktorch_tpu.parallel.sharding_rules`):

    - GROUPS layout (``to_experts=True`` input): groups dim sharded
      over dp x fsdp x ep — each member holds its own groups' blocks
      for EVERY expert;
    - EXPERTS layout (output): experts dim sharded over ep — each
      member holds every group's blocks for ITS experts.

    Within an ep subgroup the exchange swaps expert slices for group
    blocks, which is exactly the relayout of the UNCHANGED global
    array: the island is a global identity, so it is numerics-proof by
    construction — and partitioner-proof, because the all-to-all is
    spelled out instead of derived (jax 0.4.x GSPMD derives all-gather
    + all-reduce, replicating every token ep-fold). ``to_experts=False``
    is the combine-side inverse."""
    from sparktorch_tpu.parallel.sharding_rules import (
        MOE_EXPERTS_BLOCKS_SPEC,
        MOE_GROUPS_BLOCKS_SPEC,
    )
    from sparktorch_tpu.train.step import shard_map_compat

    if to_experts:
        body = lambda t: jax.lax.all_to_all(t, AXIS_EP, 1, 0, tiled=True)
        in_s, out_s = MOE_GROUPS_BLOCKS_SPEC, MOE_EXPERTS_BLOCKS_SPEC
    else:
        body = lambda t: jax.lax.all_to_all(t, AXIS_EP, 0, 1, tiled=True)
        in_s, out_s = MOE_EXPERTS_BLOCKS_SPEC, MOE_GROUPS_BLOCKS_SPEC
    return shard_map_compat(
        body, mesh=ambient_gspmd_mesh(), in_specs=(in_s,), out_specs=out_s,
    )(x)


def _top_k_routing(probs, k: int):
    """``jax.lax.top_k`` equivalent for the router (first index wins
    ties, like top_k), as ``k`` argmax+mask rounds. top_k's sort-based
    partitioner lowering ALL-GATHERS the sharded probs tensor (the one
    token-scale gather the HLO regression pin would flag); argmax
    reduces only the (local) experts dim, so routing stays device-
    local under the groups sharding."""
    vals, idxs = [], []
    p = probs
    for _ in range(k):
        i = jnp.argmax(p, axis=-1)
        vals.append(jnp.max(p, axis=-1))
        idxs.append(i)
        # Finite mask sentinel: probs are softmax outputs in [0, 1],
        # so -1 loses every later argmax. -inf would poison the next
        # round's max/argmax gradients with (-inf * 0) NaNs in eager
        # mode (jitted runs were rescued only by XLA's simplifier).
        p = jnp.where(jax.nn.one_hot(i, p.shape[-1], dtype=bool),
                      -1.0, p)
    return jnp.stack(vals, axis=-1), jnp.stack(idxs, axis=-1)


@partial(jax.custom_vjp, nondiff_argnums=(5,))
def _expert_ffn(x, w_in, b_in, w_out, b_out, dt):
    """The dense per-expert FFN on (G, e, cap, d) capacity blocks —
    custom VJP so the WEIGHT gradients are layout-invariant.

    Autodiff would contract the weight grads over (groups x cap) in
    one low-precision dot whose per-device extent depends on the mesh
    (ep absorbs dp, so ep=2 holds 2x the groups per device that ep=1
    does) — reassociating the bf16 reduction and drifting expert grads
    ~1e-4 between worlds, which adamw amplifies well past the rtol
    1e-5 ep-parity gate within a few steps. The custom backward
    contracts each GROUP's partial separately (identical work on every
    world — cap never shards) and accumulates across groups in f32, so
    the only cross-world difference left is f32 psum ordering
    (~1e-7/step). Forward math is exactly the inline version it
    replaces."""
    return _expert_ffn_fwd(x, w_in, b_in, w_out, b_out, dt)[0]


def _expert_ffn_fwd(x, w_in, b_in, w_out, b_out, dt):
    from sparktorch_tpu.parallel.sharding_rules import (
        MOE_EXPERTS_BLOCKS_SPEC,
    )

    z = jnp.einsum("gecd,edf->gecf", x, w_in.astype(dt)) \
        + b_in[None, :, None].astype(dt)
    h = nn.gelu(z)
    h = _gspmd_constraint(h, MOE_EXPERTS_BLOCKS_SPEC)
    y = jnp.einsum("gecf,efd->gecd", h, w_out.astype(dt)) \
        + b_out[None, :, None].astype(dt)
    # Residuals hold z but NOT h: the post-gelu hidden is one
    # elementwise gelu away, and saving both would double the
    # dominant (G, e, cap, d_ff) activation footprint per MoE layer.
    return y, (x, z, w_in, b_in, w_out, b_out)


def _expert_ffn_bwd(dt, res, ct):
    x, z, w_in, b_in, w_out, b_out = res
    f32 = jnp.float32
    h = nn.gelu(z)  # recomputed from the saved pre-activation
    # Per-group partials contract over cap ONLY (world-consistent);
    # the f32 sum over the groups dim is the one cross-device
    # reduction (GSPMD psums it over the axes the groups shard over).
    d_w_out = jnp.sum(
        jnp.einsum("gecf,gecd->gefd", h, ct, preferred_element_type=f32),
        axis=0,
    )
    d_b_out = jnp.sum(jnp.sum(ct.astype(f32), axis=2), axis=0)
    d_h = jnp.einsum("gecd,efd->gecf", ct, w_out.astype(dt))
    _, gelu_vjp = jax.vjp(nn.gelu, z)
    d_z = gelu_vjp(d_h)[0]
    d_b_in = jnp.sum(jnp.sum(d_z.astype(f32), axis=2), axis=0)
    d_w_in = jnp.sum(
        jnp.einsum("gecd,gecf->gedf", x, d_z, preferred_element_type=f32),
        axis=0,
    )
    d_x = jnp.einsum("gecf,edf->gecd", d_z, w_in.astype(dt))
    return (d_x, d_w_in.astype(w_in.dtype), d_b_in.astype(b_in.dtype),
            d_w_out.astype(w_out.dtype), d_b_out.astype(b_out.dtype))


_expert_ffn.defvjp(_expert_ffn_fwd, _expert_ffn_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ep_relayout(x, to_experts: bool):
    """Custom-vjp wrapper of :func:`_moe_relayout_island`: the op is a
    permutation of the global array, so its true VJP is the inverse
    exchange. Spelling it out keeps autodiff off jax's all_to_all
    transpose path (miscompiles for split != concat on some versions —
    same guard as the pipeline trainer's ``_a2a_ep``) and off
    shard_map's replication-rewrite rules."""
    return _moe_relayout_island(x, to_experts)


def _ep_relayout_fwd(x, to_experts):
    return _ep_relayout(x, to_experts), None


def _ep_relayout_bwd(to_experts, _, ct):
    return (_moe_relayout_island(ct, not to_experts),)


_ep_relayout.defvjp(_ep_relayout_fwd, _ep_relayout_bwd)


class MoEFFN(nn.Module):
    """Top-k mixture-of-experts FFN (switch-style at k=1, GShard-style
    gate-weighted combine at k>=2).

    No reference counterpart (SURVEY §2.4: EP "absent"). TPU-first
    design: routing, dispatch, expert matmuls and combine are einsums
    over a (experts, capacity, d_model) layout — no per-expert Python,
    no dynamic shapes. Expert weights have a leading experts dim that
    the sharding rules place on the ``ep`` mesh axis.

    Under the GSPMD sharded trainer (an ambient ``set_mesh`` mesh with
    ep > 1) the dispatch and combine are EXPLICIT shard_map
    all-to-alls (:func:`_ep_relayout`): the group partition is
    mesh-anchored (one-plus routing groups per device,
    :func:`moe_group_partition`), each ep member routes only its own
    slice of the groups, a dispatch all_to_all ships its capacity
    blocks to the owning expert shards, the experts run dense against
    their local weights, and a combine all_to_all ships the outputs
    back for the gate-weighted sum — no token replication, version-
    independent, partitioner-proof. (Deriving the same movement from
    einsum operand shardings — ``moe_ep_dispatch='replicate'`` — is
    lowered by jax 0.4.x GSPMD to all-gather + all-reduce, O(world)
    comm bytes and ~0.7% loss drift; kept only as the bench-moe
    control leg.) The switch load-balance loss is sown (pre-weighted
    by ``moe_aux_weight``) into the ``losses`` collection; every
    trainer adds sown losses to the objective.

    Tokens route within fixed-size groups (``moe_group_size``), so the
    dispatch/combine one-hots stay linear in total tokens.

    ``token_w`` (per-token weights, (b, s)) masks weight-0 rows — the
    empty-partition padding protocol — OUT of routing: masked tokens
    claim no capacity, contribute nothing to the aux loss, and get
    zero expert output (their residual path carries them). Trainers
    pass the batch's example weights down automatically (step._forward).

    Observability: the fraction of routed token-choices dropped at
    capacity is sown into the ``moe_metrics`` collection as raw
    (dropped, routed) counts; trainers psum them and expose
    ``moe_drop_fraction`` in the step metrics.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, x, token_w=None):
        import math

        from sparktorch_tpu.parallel.sharding_rules import (
            MOE_EXPERTS_BLOCKS_SPEC as _experts_spec,
            MOE_GROUPS_BLOCKS_SPEC as _blocks_spec,
            MOE_GROUPS_TOKENS_SPEC as _groups_spec,
        )

        cfg = self.config
        dt = cfg.compute_dtype
        b, s, d = x.shape
        e = cfg.n_experts
        k = max(1, min(cfg.moe_top_k, e))
        n = b * s
        # The ambient GSPMD mesh (the sharded trainer) anchors the
        # group partition and decides whether the explicit-a2a path
        # engages; everywhere else (plain apply, shard_map trainers)
        # mesh is None and the base partition applies.
        mesh = ambient_gspmd_mesh()
        sizes = dict(mesh.shape) if mesh is not None else {}
        n_dev = 1
        for v in sizes.values():
            n_dev *= v
        g, n_groups = moe_group_partition(
            cfg, n, n_dev if mesh is not None else None
        )
        n_ep = sizes.get(AXIS_EP, 1)
        n_shards = n_ep
        for ax in BATCH_AXES:
            n_shards *= sizes.get(ax, 1)
        mode = cfg.moe_ep_dispatch
        if mode not in ("auto", "a2a", "replicate"):
            raise ValueError(f"unknown moe_ep_dispatch {mode!r}")
        # Explicit dispatch/combine all-to-alls (trace-time decision —
        # shapes are static): each ep member routes 1/ep of the groups
        # and only its experts' capacity blocks ever cross the wire.
        use_a2a = (
            mesh is not None and n_ep > 1 and mode in ("auto", "a2a")
            and e % n_ep == 0 and n_groups % n_shards == 0
        )
        if mode == "a2a" and mesh is not None and n_ep > 1 and not use_a2a:
            raise ValueError(
                f"moe_ep_dispatch='a2a' needs n_experts ({e}) divisible "
                f"by ep={n_ep} and the routing group count ({n_groups}) "
                f"divisible by dp*fsdp*ep={n_shards}; lower "
                "moe_group_size or use 'auto'"
            )
        tokens = x.reshape(n_groups, g, d)
        # GSPMD layout (active only under the sharded trainer's mesh):
        # routing groups shard over EVERY data axis including ep — each
        # ep member routes only its share of the groups, device-locally.
        tokens = _gspmd_constraint(tokens, _groups_spec)
        # Static per-group capacity: ceil(cf * g * k / e) — scales with
        # the routing fan-out so k=2 doesn't halve effective capacity.
        cap = max(1, math.ceil(cfg.capacity_factor * g * k / e))
        if token_w is not None:
            mask = (token_w.reshape(n_groups, g) > 0)      # (G, g) bool
        else:
            mask = None

        # Router in f32 (small matmul; numerics matter more than MXU).
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )                                            # (G, g, e)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_p, topk_idx = _top_k_routing(probs, k)  # (G, g, k)
        if k == 1:
            gates = topk_p                           # switch: raw prob
        else:
            gates = topk_p / jnp.maximum(
                jnp.sum(topk_p, axis=-1, keepdims=True), 1e-9
            )

        oh = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # (G, g, k, e)
        if mask is not None:
            oh = oh * mask[:, :, None, None]
            gates = gates * mask[:, :, None]
        # Capacity assignment with choice-level priority: ALL first
        # choices rank before any second choice (GShard). Flatten
        # (k, g) choice-major, cumsum arrival order, unflatten.
        oh_t = oh.transpose(0, 2, 1, 3).reshape(n_groups, k * g, e)
        pos = jnp.cumsum(oh_t, axis=1) * oh_t        # 1-based rank
        keep = (pos > 0) & (pos <= cap)
        slot = jnp.clip(pos - 1, 0, cap - 1)
        disp_flat = keep[..., None] & jax.nn.one_hot(slot, cap, dtype=bool)
        disp = disp_flat.reshape(n_groups, k, g, e, cap).transpose(
            0, 2, 1, 3, 4
        )                                            # (G, g, k, e, cap)

        # A token's k choices hit k DISTINCT experts, so summing over
        # the choice dim yields a 0/1 dispatch tensor.
        dispatch = jnp.any(disp, axis=2).astype(dt)  # (G, g, e, cap)
        expert_in = jnp.einsum("gnec,gnd->gecd", dispatch,
                               tokens.astype(dt))    # (G, e, cap, d)
        if use_a2a:
            # Dispatch all-to-all: the member's locally-built capacity
            # blocks travel to their experts' owners (groups layout ->
            # experts layout; a global identity, see _ep_relayout).
            expert_in = _gspmd_constraint(expert_in, _blocks_spec)
            expert_in = _ep_relayout(expert_in, True)
        expert_in = _gspmd_constraint(expert_in, _experts_spec)
        w_in = self.param("moe_w_in", nn.initializers.lecun_normal(),
                          (e, d, cfg.d_ff))
        b_in = self.param("moe_b_in", nn.initializers.zeros, (e, cfg.d_ff))
        w_out = self.param("moe_w_out", nn.initializers.lecun_normal(),
                           (e, cfg.d_ff, d))
        b_out = self.param("moe_b_out", nn.initializers.zeros, (e, d))
        expert_out = _expert_ffn(expert_in, w_in, b_in, w_out, b_out, dt)
        expert_out = _gspmd_constraint(expert_out, _experts_spec)
        if use_a2a:
            # Combine all-to-all: weighted-output blocks ship back to
            # their groups' owners; the gate-weighted sum below then
            # runs device-local on the member's own groups.
            expert_out = _ep_relayout(expert_out, False)
            expert_out = _gspmd_constraint(expert_out, _blocks_spec)

        # Gate-weighted combine over the kept (token, choice) slots.
        combine = jnp.einsum("gnk,gnkec->gnec", gates.astype(dt),
                             disp.astype(dt))        # (G, g, e, cap)
        out = jnp.einsum("gnec,gecd->gnd", combine, expert_out)
        out = _gspmd_constraint(out, _groups_spec)   # <- groups layout

        # Switch load-balance loss over VALID tokens only: e * sum_e
        # frac_e * prob_e, where frac uses the primary (first) choice.
        oh0 = oh[:, :, 0, :].astype(jnp.float32)     # (G, g, e)
        if mask is not None:
            mf = mask.astype(jnp.float32)
            valid = jnp.maximum(jnp.sum(mf, axis=1), 1.0)         # (G,)
            frac = jnp.sum(oh0, axis=1) / valid[:, None]
            mean_prob = (
                jnp.sum(probs * mf[:, :, None], axis=1) / valid[:, None]
            )
        else:
            frac = jnp.mean(oh0, axis=1)                          # (G, e)
            mean_prob = jnp.mean(probs, axis=1)                   # (G, e)
        aux = cfg.moe_aux_weight * e * jnp.mean(
            jnp.sum(frac * mean_prob, axis=-1)
        )
        self.sow("losses", "moe_aux", aux)

        # Raw drop counts (masked tokens never counted as routed).
        routed = jnp.sum(oh).astype(jnp.float32)
        kept = jnp.sum(keep.astype(jnp.float32))
        self.sow("moe_metrics", "dropped", routed - kept)
        self.sow("moe_metrics", "routed", routed)
        return out.reshape(b, s, d)


class EncoderLayer(nn.Module):
    config: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, token_w=None):
        cfg = self.config
        dt = cfg.compute_dtype
        h = nn.LayerNorm(dtype=dt, name="ln_attn")(x)
        x = x + MultiHeadAttention(cfg, name="attn")(h)
        h = nn.LayerNorm(dtype=dt, name="ln_mlp")(x)
        if self.use_moe:
            h = MoEFFN(cfg, name="moe")(h, token_w)
        else:
            h = nn.Dense(cfg.d_ff, dtype=dt, name="mlp_in")(h)
            h = nn.gelu(h)
            h = nn.Dense(cfg.d_model, dtype=dt, name="mlp_out")(h)
        return x + h


class Transformer(nn.Module):
    """Token-id encoder backbone. Accepts int ids or float columns
    (the estimator's feature matrix is float32; ids are cast)."""

    config: TransformerConfig

    # Optional externally-owned embedding module (weight tying: the
    # CausalLM owns it and reuses it as the LM head).
    embed: Optional[nn.Module] = None

    @nn.compact
    def __call__(self, ids, example_w=None):
        cfg = self.config
        if jnp.issubdtype(ids.dtype, jnp.floating):
            ids = ids.astype(jnp.int32)
        b, s = ids.shape
        embed = self.embed if self.embed is not None else nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype,
            name="tok_embed",
        )
        tok = embed(ids)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (cfg.max_len, cfg.d_model),
        )
        x = tok + pos[None, :s].astype(cfg.compute_dtype)
        # Per-token weights for MoE routing: padding EXAMPLES (w=0,
        # the empty-partition protocol) broadcast over their tokens.
        token_w = (
            jnp.broadcast_to(example_w[:, None], (b, s))
            if example_w is not None and cfg.n_experts > 0 else None
        )
        layer = EncoderLayer
        if cfg.remat:
            layer = nn.remat(EncoderLayer)
        for i, use_moe in enumerate(cfg.moe_pattern()):
            x = layer(cfg, use_moe=use_moe, name=f"layer_{i}")(x, token_w)
        return nn.LayerNorm(dtype=cfg.compute_dtype, name="ln_final")(x)


class SequenceClassifier(nn.Module):
    """BERT-style classifier (SST-2 workload, BASELINE config 4)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, ids, example_w=None):
        x = Transformer(self.config, name="backbone")(ids, example_w)
        # Mean-pool (padding-id masking is the caller's concern; the
        # estimator's weighted loss handles padded *examples*).
        pooled = jnp.mean(x, axis=1)
        pooled = jnp.tanh(
            nn.Dense(self.config.d_model, dtype=self.config.compute_dtype,
                     name="pooler")(pooled)
        )
        return nn.Dense(self.config.n_classes, dtype=jnp.float32,
                        name="classifier")(pooled)


class CausalLM(nn.Module):
    """Decoder-style LM head over the same backbone (long-context
    training workload for ring attention)."""

    config: TransformerConfig

    def setup(self):
        cfg = dataclasses.replace(self.config, causal=True)
        if cfg.tie_embeddings:
            # One vocab-sized matrix: the embedding doubles as the LM
            # head (logits = h @ E^T via nn.Embed.attend).
            self.tok_embed = nn.Embed(
                cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype,
                name="tok_embed",
            )
            self.backbone = Transformer(cfg, embed=self.tok_embed)
        else:
            self.backbone = Transformer(cfg)
            self.lm_head = nn.Dense(cfg.vocab_size, dtype=jnp.float32)

    def __call__(self, ids, example_w=None):
        x = self.backbone(ids, example_w)
        if self.config.tie_embeddings:
            # f32 logits like the untied Dense head (attend would run
            # the vocab matmul in the embed's compute dtype; logit
            # precision matters for the CE loss and its gradients).
            emb = self.tok_embed.embedding
            return x.astype(jnp.float32) @ emb.astype(jnp.float32).T
        return self.lm_head(x)


def bert_base(n_classes: int = 2, **overrides) -> SequenceClassifier:
    cfg = TransformerConfig(n_classes=n_classes, **overrides)
    return SequenceClassifier(cfg)


def tiny_transformer(**overrides) -> TransformerConfig:
    """Small config for tests/dryruns."""
    defaults = dict(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                    d_ff=128, max_len=128)
    defaults.update(overrides)
    return TransformerConfig(**defaults)
