"""ResNet family (BASELINE configs 3 & 5: ResNet-18 CIFAR-10 hogwild
training, ResNet-50 batch inference over Parquet).

TPU-native choices: NHWC layout (XLA:TPU's native conv layout),
bfloat16 compute with float32 params and batch stats, strided-conv
downsampling, and a stem that accepts flat feature rows (the
estimator's column matrix) by reshaping to (H, W, C) from a declared
``input_hw``. BatchNorm runs in ``batch_stats`` mutable collection —
the SPMD train step syncs the stats by cross-shard mean
(train/step.py), which the reference's per-executor BN silently never
does (each gloo worker kept its own running stats,
``distributed.py:112-115``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 10
    width: int = 64
    compute_dtype: jnp.dtype = jnp.bfloat16
    input_hw: Optional[Tuple[int, int, int]] = None  # (H, W, C) for flat rows
    small_images: bool = True  # CIFAR-style stem (3x3, no maxpool)

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:
            if self.input_hw is None:
                raise ValueError("flat input needs input_hw=(H, W, C)")
            h, w, c = self.input_hw
            x = x.reshape(x.shape[0], h, w, c)
        x = x.astype(self.compute_dtype)

        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       padding="SAME")
        # Train/eval switches on collection mutability, not a flag:
        # apply(..., mutable=['batch_stats']) => batch stats update
        # (training); plain apply => running averages (inference).
        # This keeps the generic train step and the compiled inference
        # path (train/step.py) model-agnostic.
        norm = partial(
            nn.BatchNorm,
            use_running_average=not self.is_mutable_collection("batch_stats"),
            momentum=0.9, epsilon=1e-5, dtype=self.compute_dtype,
        )

        if self.small_images:
            x = conv(self.width, (3, 3), name="conv_stem")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), name="conv_stem")(x)
        x = norm(name="norm_stem")(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.width * 2**i, conv=conv, norm=norm, strides=strides,
                    name=f"stage{i}_block{j}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def resnet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=ResNetBlock,
                  num_classes=num_classes, **kw)


def resnet34(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=ResNetBlock,
                  num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    kw.setdefault("small_images", False)
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock,
                  num_classes=num_classes, **kw)
