from sparktorch_tpu.models.simple import (
    MLP,
    Net,
    AutoEncoder,
    ClassificationNet,
    NetworkWithParameters,
    MnistMLP,
    MnistCNN,
)

__all__ = [
    "MLP",
    "Net",
    "AutoEncoder",
    "ClassificationNet",
    "NetworkWithParameters",
    "MnistMLP",
    "MnistCNN",
]
