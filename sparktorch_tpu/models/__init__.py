from sparktorch_tpu.models.simple import (
    MLP,
    Net,
    AutoEncoder,
    ClassificationNet,
    NetworkWithParameters,
    MnistMLP,
    MnistCNN,
)
from sparktorch_tpu.models.resnet import (
    ResNet,
    resnet18,
    resnet34,
    resnet50,
)
from sparktorch_tpu.models.transformer import (
    TransformerConfig,
    Transformer,
    SequenceClassifier,
    CausalLM,
    bert_base,
    tiny_transformer,
)

__all__ = [
    "MLP",
    "Net",
    "AutoEncoder",
    "ClassificationNet",
    "NetworkWithParameters",
    "MnistMLP",
    "MnistCNN",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "TransformerConfig",
    "Transformer",
    "SequenceClassifier",
    "CausalLM",
    "bert_base",
    "tiny_transformer",
]
