"""The compiled SPMD train step.

This module replaces the reference's entire per-step hot path
(``distributed.py:141-204``): zero_grad -> minibatch sample -> forward
-> loss (with long-label retry) -> backward -> per-parameter
``dist.all_reduce(SUM)`` + divide -> early-stop all_reduces ->
``optimizer.step()`` — a Python loop doing one gloo collective *per
parameter per step*.

TPU-native redesign: ONE jitted function. Inside a ``shard_map`` over
the mesh's batch axes, each shard samples its own minibatch from its
resident data shard, computes the local weighted-SUM gradient, and a
single fused ``psum`` of (grads, loss_num, weight_den) produces the
globally weighted-mean gradient — mathematically the reference's
``grad_sum / (world_size - 1)`` (``distributed.py:180-182``) but
weight-correct under ragged/empty shards and lowered by XLA onto ICI.
The early-stop signal needs no extra collective: the returned loss is
already the global mean, replicated on every host
(vs. ``distributed.py:186-197``'s two extra all_reduces per step).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from sparktorch_tpu.parallel.compat import axis_size as _axis_size
from sparktorch_tpu.parallel.mesh import BATCH_AXES, replicated
from sparktorch_tpu.utils.data import DataBatch, sample_minibatch

try:  # jax>=0.6 top-level export; fall back for older trees
    from jax import shard_map as _shard_map_raw
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_raw


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across the API rename
    (new keyword ``check_vma``; the legacy API spells it
    ``check_rep``). ``mesh=None`` means the ambient (set_mesh) mesh:
    new jax resolves that natively, but 0.4.x requires the concrete
    handle — resolve it here so island call sites (ring attention, the
    MoE dispatch relayout) stay version-portable."""
    if mesh is None:
        from sparktorch_tpu.parallel.compat import ambient_gspmd_mesh

        mesh = ambient_gspmd_mesh()
    try:
        return _shard_map_raw(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover - legacy jax
        return _shard_map_raw(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


class TrainState(NamedTuple):
    """Carried training state. ``model_state`` holds non-trainable
    collections (e.g. batch_stats); replicated across the mesh the way
    the reference replicates the full model (``distributed.py:115``)."""

    step: jax.Array
    params: Any
    model_state: Any
    opt_state: Any
    rng: jax.Array


class HealthVec(NamedTuple):
    """On-device model-health vector, computed inside the jitted step
    (obs/health.py's TrainHealthLedger fetches it asynchronously K
    steps late — nothing here may force a host sync).

    ``finite`` is 1.0 iff loss and the global grad-norm are both
    finite (the grad-norm is a sum of squares, so any NaN/Inf grad
    leaf poisons it — one bit covers the whole tree). ``leaf_norms``
    is the per-leaf grad-norm vector in tree-flatten order; the key
    table lives host-side (health.health_leaf_keys)."""

    finite: jax.Array        # f32 scalar, 1.0 = all finite
    update_ratio: jax.Array  # ||update|| / ||new params||
    leaf_norms: jax.Array    # f32[n_leaves]


class StepMetrics(NamedTuple):
    loss: jax.Array        # global weighted-mean train loss
    examples: jax.Array    # real (weight>0) examples this step, global
    grad_norm: jax.Array
    # Fraction of routed MoE token-choices dropped at expert capacity
    # (global); None (empty pytree leaf) for models without MoE.
    drop_fraction: Optional[jax.Array] = None
    health: Optional[HealthVec] = None


class EpochMetrics(NamedTuple):
    """Stacked per-step metrics from a fused chunk with early-stop /
    validation support. ``val_loss`` is NaN when no val batch was given;
    ``active`` is False for steps masked out after the stop fired (the
    host must ignore those rows)."""

    loss: jax.Array
    examples: jax.Array
    grad_norm: jax.Array
    val_loss: jax.Array
    active: jax.Array
    drop_fraction: Optional[jax.Array] = None
    health: Optional[HealthVec] = None


class EsConfig(NamedTuple):
    """Static early-stopping config compiled into the fused chunk.
    Field semantics match :class:`~sparktorch_tpu.utils.early_stopper.
    EarlyStopping` (itself mirroring ``early_stopper.py:8-56``)."""

    mode: str = "min"
    min_delta: float = 0.0
    patience: int = 10
    percentage: bool = False


class EsState(NamedTuple):
    """Device-resident early-stopper carry (the jax translation of the
    host ``EarlyStopping`` object's mutable fields, so the stop decision
    can be made INSIDE the fused ``lax.scan`` instead of only at chunk
    boundaries)."""

    best: jax.Array         # f32; valid once `initialized`
    num_bad: jax.Array      # i32
    stopped: jax.Array      # bool — latches
    initialized: jax.Array  # bool — False before the first signal


def init_es_state() -> EsState:
    return EsState(
        best=jnp.zeros((), jnp.float32),
        num_bad=jnp.zeros((), jnp.int32),
        stopped=jnp.zeros((), jnp.bool_),
        initialized=jnp.zeros((), jnp.bool_),
    )


def _es_update(cfg: EsConfig, es: EsState, signal: jax.Array) -> EsState:
    """One ``EarlyStopping.step`` in jax ops. Exact host semantics:
    first signal only seeds ``best``; NaN after that stops; otherwise
    patience counting with abs/pct delta in min/max mode."""
    signal = signal.astype(jnp.float32)
    first = ~es.initialized
    if cfg.percentage:
        # SIGNED best, matching the host stopper and the reference
        # (early_stopper.py:48-55 uses `best * min_delta / 100`): for
        # negative best in min mode the threshold moves toward zero.
        delta = es.best * (cfg.min_delta / 100.0)
    else:
        delta = jnp.float32(cfg.min_delta)
    if cfg.mode == "min":
        better = signal < es.best - delta
    else:
        better = signal > es.best + delta
    num_bad = jnp.where(better, 0, es.num_bad + 1)
    best = jnp.where(better, signal, es.best)
    stop_now = jnp.isnan(signal) | (num_bad >= cfg.patience)
    best = jnp.where(first, signal, best)
    num_bad = jnp.where(first, 0, num_bad)
    stop_now = jnp.where(first, jnp.zeros((), jnp.bool_), stop_now)
    return EsState(
        best=best,
        num_bad=num_bad,
        stopped=es.stopped | stop_now,
        initialized=jnp.ones((), jnp.bool_),
    )


def _split_variables(variables) -> Tuple[Any, Any]:
    variables = dict(variables)
    params = variables.pop("params", variables)
    # 'losses' and 'moe_metrics' are write-only collections (sown aux
    # objectives / drop counters); carrying them would make sow()
    # append every step and grow the pytree. Every trainer re-requests
    # them via `mutable` each training forward (_forward above;
    # sharded.py does the same).
    variables.pop("losses", None)
    variables.pop("moe_metrics", None)
    return params, variables


def _accepts_example_w(apply_fn) -> bool:
    """Whether the module behind ``apply_fn`` takes per-example weights
    (``example_w``) — the hook MoE models use to mask weight-0 padding
    rows out of routing. ``module.apply`` is a bound method, so the
    module's ``__call__`` signature is inspectable at trace time."""
    import inspect

    mod = getattr(apply_fn, "__self__", None)
    if mod is None:
        return False
    try:
        return "example_w" in inspect.signature(mod.__call__).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic callables
        return False


def create_train_state(
    spec,
    rng: jax.Array,
    sample_x: Optional[jax.Array] = None,
    tx: Optional[optax.GradientTransformation] = None,
) -> TrainState:
    """Initialize params + optimizer state from a ModelSpec."""
    tx = tx or spec.make_optimizer()
    variables = spec.init_params(rng, sample_x)
    params, model_state = _split_variables(variables)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=model_state,
        opt_state=tx.init(params),
        rng=rng,
    )


def _forward(apply_fn, params, model_state, x, train: bool, example_w=None):
    """Apply with mutable non-trainable collections when present.

    Training forwards also request the write-only ``losses`` and
    ``moe_metrics`` collections so sown auxiliary objectives (e.g. the
    MoE load-balance loss) and observability counters reach the
    caller; they are popped — never carried — because ``sow`` appends
    to carried-in collections. ``example_w`` (per-example weights) is
    forwarded to modules that accept it, letting MoE routing mask
    weight-0 padding rows. Returns ``(preds, new_model_state,
    sown_losses_or_None, sown_metrics_or_None)``.
    """
    variables = {"params": params, **model_state}
    kwargs = {}
    if example_w is not None and _accepts_example_w(apply_fn):
        kwargs["example_w"] = example_w
    if train:
        mutable = [*model_state.keys(), "losses", "moe_metrics"]
        preds, new_state = apply_fn(variables, x, mutable=mutable, **kwargs)
        new_state = dict(new_state)
        sown = new_state.pop("losses", None)
        sown_metrics = new_state.pop("moe_metrics", None)
        if not model_state:
            new_state = model_state
        return preds, new_state, sown, sown_metrics
    preds = apply_fn(variables, x, **kwargs)
    return preds, model_state, None, None


def _sown_total(sown, dtype) -> jax.Array:
    """Sum every sown aux-loss leaf into one scalar (0 when none)."""
    total = jnp.zeros((), dtype)
    if sown is not None:
        for leaf in jax.tree.leaves(sown):
            total = total + jnp.sum(leaf).astype(dtype)
    return total


def _moe_drop_counts(sown_metrics) -> Optional[Tuple[jax.Array, jax.Array]]:
    """Sum the sown (dropped, routed) counters across MoE layers.
    Returns None when the model sowed none (non-MoE model) — a static
    trace-time decision, so non-MoE programs carry no extra values."""
    if not sown_metrics:
        return None
    from jax.tree_util import tree_flatten_with_path

    dropped = jnp.zeros((), jnp.float32)
    routed = jnp.zeros((), jnp.float32)
    found = False
    for path, leaf in tree_flatten_with_path(sown_metrics)[0]:
        names = [str(getattr(p, "key", getattr(p, "name", p))) for p in path]
        if "dropped" in names:
            dropped = dropped + jnp.sum(leaf)
            found = True
        elif "routed" in names:
            routed = routed + jnp.sum(leaf)
            found = True
    return (dropped, routed) if found else None


def _shard_index(axis_names: Tuple[str, ...]) -> jax.Array:
    """Linearized index of this shard over the batch axes."""
    shard_id = jnp.zeros((), jnp.int32)
    for ax in axis_names:
        shard_id = shard_id * _axis_size(ax) + jax.lax.axis_index(ax)
    return shard_id


def _dp_body(apply_fn, loss_fn, tx, axis_names, per_shard_mb,
             state: TrainState, batch: DataBatch):
    """One DP train step, called inside shard_map. Shared by the
    single-step, fused-epoch, and fused-with-early-stop builders.

    Per-shard sampling key: replicated rng folded with the shard index —
    data selection differs per shard, carried rng stays replicated so
    the output state is provably identical on all shards.
    """
    rng, next_rng = jax.random.split(state.rng)
    sample_key = jax.random.fold_in(rng, _shard_index(axis_names))

    if per_shard_mb is not None and per_shard_mb < batch.x.shape[0]:
        mb = sample_minibatch(batch, sample_key, per_shard_mb)
    else:
        mb = batch

    def weighted_sums(params):
        preds, new_model_state, sown, sown_metrics = _forward(
            apply_fn, params, state.model_state, mb.x, train=True,
            example_w=mb.w,
        )
        per = loss_fn(preds, mb.y)
        den = jnp.sum(mb.w)
        # Sown aux objectives (per-shard means, pre-weighted at the
        # sow site) scale by den so the global psum(num)/psum(den)
        # is the task mean plus the example-weighted mean aux —
        # matching the sharded trainer's objective.
        num = jnp.sum(per * mb.w) + _sown_total(sown, per.dtype) * den
        return num, (den, new_model_state, _moe_drop_counts(sown_metrics))

    (num, (den, new_model_state, drop_counts)), grads_num = jax.value_and_grad(
        weighted_sums, has_aux=True
    )(state.params)

    # ONE fused collective for everything the step needs globally.
    num_g = jax.lax.psum(num, axis_names)
    den_g = jax.lax.psum(den, axis_names)
    grads_g = jax.lax.psum(grads_num, axis_names)
    safe_den = jnp.maximum(den_g, 1.0)
    grads = jax.tree.map(lambda g: g / safe_den, grads_g)
    loss = num_g / safe_den
    drop_fraction = None
    if drop_counts is not None:
        dropped_g = jax.lax.psum(drop_counts[0], axis_names)
        routed_g = jax.lax.psum(drop_counts[1], axis_names)
        drop_fraction = dropped_g / jnp.maximum(routed_g, 1.0)

    # Non-trainable collections (batch_stats) sync by global mean.
    if state.model_state:
        new_model_state = jax.tree.map(
            lambda a: jax.lax.pmean(a, axis_names)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            new_model_state,
        )
    updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    gnorm = optax.global_norm(grads)

    # Model-health vector (obs/health.py): tiny fused reductions, no
    # extra collectives — grads are already globally psum'd above.
    grad_leaves = jax.tree.leaves(grads)
    leaf_norms = (
        jnp.stack([jnp.sqrt(jnp.sum(jnp.square(g))).astype(jnp.float32)
                   for g in grad_leaves])
        if grad_leaves else jnp.zeros((0,), jnp.float32)
    )
    health = HealthVec(
        finite=(jnp.isfinite(loss) & jnp.isfinite(gnorm)).astype(jnp.float32),
        update_ratio=optax.global_norm(updates)
        / jnp.maximum(optax.global_norm(new_params), 1e-12),
        leaf_norms=leaf_norms,
    )

    new_state = TrainState(
        step=state.step + 1,
        params=new_params,
        model_state=new_model_state,
        opt_state=new_opt_state,
        rng=next_rng,
    )
    return new_state, StepMetrics(loss=loss, examples=den_g, grad_norm=gnorm,
                                  drop_fraction=drop_fraction, health=health)


def make_train_step(
    apply_fn: Callable,
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    mini_batch: Optional[int] = None,
    axis_names: Tuple[str, ...] = BATCH_AXES,
) -> Callable[[TrainState, DataBatch], Tuple[TrainState, StepMetrics]]:
    """Build the jitted SPMD train step over ``mesh``.

    Semantics match one iteration of ``distributed.py:141-204`` with
    the quirks fixed: weighting is exact under ragged shards, and the
    "long label retry" is gone because losses promote dtypes at trace
    time (see utils/losses.py).

    ``mini_batch`` is PER batch-shard, exactly the reference's
    per-partition semantics (``distributed.py:146-149``): each shard
    samples ``mini_batch`` rows without replacement from its resident
    data, so world-total examples per step = mini_batch * n_shards and
    ported configs keep their training dynamics.
    """
    per_shard_mb = None
    if mini_batch is not None and mini_batch > 0:
        per_shard_mb = mini_batch

    def shard_step(state: TrainState, batch: DataBatch):
        return _dp_body(apply_fn, loss_fn, tx, axis_names, per_shard_mb,
                        state, batch)

    data_spec = P(axis_names)
    batch_specs = DataBatch(x=data_spec, y=data_spec, w=data_spec)
    mapped = shard_map_compat(
        shard_step,
        mesh,
        in_specs=(P(), batch_specs),
        out_specs=(P(), P()),
    )
    return jax.jit(mapped, donate_argnums=(0,))


def make_train_epoch(
    apply_fn: Callable,
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    steps_per_call: int,
    mini_batch: Optional[int] = None,
    axis_names: Tuple[str, ...] = BATCH_AXES,
) -> Callable[[TrainState, DataBatch], Tuple[TrainState, StepMetrics]]:
    """``steps_per_call`` train steps fused into ONE compiled call via
    ``lax.scan`` — zero per-step Python/dispatch on the hot path. The
    reference pays a Python iteration + a per-parameter gloo collective
    per step (``distributed.py:141-204``); here a whole epoch chunk is
    a single XLA program. Returns stacked per-step metrics.
    ``mini_batch`` is per batch-shard (see ``make_train_step``).
    """
    per_shard_mb = None
    if mini_batch is not None and mini_batch > 0:
        per_shard_mb = mini_batch

    def shard_epoch(state: TrainState, batch: DataBatch):
        def one_step(state: TrainState, _):
            return _dp_body(apply_fn, loss_fn, tx, axis_names, per_shard_mb,
                            state, batch)

        return jax.lax.scan(one_step, state, None, length=steps_per_call)

    data_spec = P(axis_names)
    batch_specs = DataBatch(x=data_spec, y=data_spec, w=data_spec)
    mapped = shard_map_compat(
        shard_epoch,
        mesh,
        in_specs=(P(), batch_specs),
        out_specs=(P(), P()),
    )
    return jax.jit(mapped, donate_argnums=(0,))


def _mask_state(active: jax.Array, new: TrainState, old: TrainState) -> TrainState:
    """Keep ``old`` when the step is masked out (post-stop). The rng
    always advances — once stopped no further step consumes it, so the
    advance cannot diverge from the per-step path (and typed PRNG keys
    don't support ``where``)."""
    sel = lambda n, o: jnp.where(active, n, o)
    return TrainState(
        step=sel(new.step, old.step),
        params=jax.tree.map(sel, new.params, old.params),
        model_state=jax.tree.map(sel, new.model_state, old.model_state),
        opt_state=jax.tree.map(sel, new.opt_state, old.opt_state),
        rng=new.rng,
    )


def make_train_epoch_fused(
    apply_fn: Callable,
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    steps_per_call: int,
    es_config: Optional[EsConfig] = None,
    with_val: bool = False,
    mini_batch: Optional[int] = None,
    axis_names: Tuple[str, ...] = BATCH_AXES,
):
    """Fused chunk with EXACT per-step early-stop / validation
    semantics, decided on-device inside the ``lax.scan``.

    This closes the semantic gap the per-step path otherwise covers:
    the reference evaluates the stop vote and the val forward every
    iteration (``distributed.py:166-197``); a plain fused chunk could
    only check at chunk boundaries, overshooting up to
    ``steps_per_call - 1`` steps. Here the early-stop state
    (:class:`EsState`) rides the scan carry: the step at which the stop
    fires latches ``stopped``, and every later step in the chunk is
    masked to a no-op (same math executed, update discarded — bounded
    waste, only in the one tail chunk). ``val_loss`` is computed inside
    the scan after each step, exactly the per-iteration val forward.

    Returns a jitted fn. With ``with_val``::

        ((state, es), EpochMetrics) = fn((state, es), batch, val_batch)

    otherwise ``fn((state, es), batch)``. ``EpochMetrics.active`` tells
    the host how many steps actually trained.
    """
    per_shard_mb = None
    if mini_batch is not None and mini_batch > 0:
        per_shard_mb = mini_batch

    def _val_loss(state: TrainState, vb: DataBatch) -> jax.Array:
        preds, _, _, _ = _forward(
            apply_fn, state.params, state.model_state, vb.x, train=False,
            example_w=vb.w,
        )
        per = loss_fn(preds, vb.y)
        num = jax.lax.psum(jnp.sum(per * vb.w), axis_names)
        den = jax.lax.psum(jnp.sum(vb.w), axis_names)
        return num / jnp.maximum(den, 1.0)

    def shard_epoch(carry, batch: DataBatch, val_batch: Optional[DataBatch]):
        def one_step(carry, _):
            state, es = carry
            active = ~es.stopped
            stepped, metrics = _dp_body(
                apply_fn, loss_fn, tx, axis_names, per_shard_mb, state, batch
            )
            new_state = _mask_state(active, stepped, state)
            if with_val:
                val = _val_loss(new_state, val_batch)
                signal = val
            else:
                val = jnp.float32(jnp.nan)
                signal = metrics.loss
            if es_config is not None:
                updated = _es_update(es_config, es, signal)
                new_es = jax.tree.map(
                    lambda n, o: jnp.where(active, n, o), updated, es
                )
            else:
                new_es = es
            out = EpochMetrics(
                loss=metrics.loss,
                examples=metrics.examples,
                grad_norm=metrics.grad_norm,
                val_loss=val,
                active=active,
                drop_fraction=metrics.drop_fraction,
                health=metrics.health,
            )
            return (new_state, new_es), out

        return jax.lax.scan(one_step, carry, None, length=steps_per_call)

    data_spec = P(axis_names)
    batch_specs = DataBatch(x=data_spec, y=data_spec, w=data_spec)
    carry_specs = (P(), P())
    if with_val:
        mapped = shard_map_compat(
            shard_epoch,
            mesh,
            in_specs=(carry_specs, batch_specs, batch_specs),
            out_specs=((P(), P()), P()),
        )
    else:
        fn = lambda carry, batch: shard_epoch(carry, batch, None)
        mapped = shard_map_compat(
            fn,
            mesh,
            in_specs=(carry_specs, batch_specs),
            out_specs=((P(), P()), P()),
        )
    return jax.jit(mapped, donate_argnums=(0,))


def make_eval_step(
    apply_fn: Callable,
    loss_fn: Callable,
    mesh: Mesh,
    axis_names: Tuple[str, ...] = BATCH_AXES,
) -> Callable[[TrainState, DataBatch], jax.Array]:
    """Global weighted-mean validation loss — the per-iteration val
    forward of ``distributed.py:166-176``, compiled and collective."""

    def shard_eval(state: TrainState, batch: DataBatch):
        preds, _, _, _ = _forward(
            apply_fn, state.params, state.model_state, batch.x, train=False,
            example_w=batch.w,
        )
        per = loss_fn(preds, batch.y)
        num = jax.lax.psum(jnp.sum(per * batch.w), axis_names)
        den = jax.lax.psum(jnp.sum(batch.w), axis_names)
        return num / jnp.maximum(den, 1.0)

    data_spec = P(axis_names)
    batch_specs = DataBatch(x=data_spec, y=data_spec, w=data_spec)
    mapped = shard_map_compat(
        shard_eval,
        mesh,
        in_specs=(P(), batch_specs),
        out_specs=P(),
    )
    return jax.jit(mapped)
