"""Synchronous SPMD training orchestration.

Replaces ``sparktorch/distributed.py:209-277`` (``train_distributed``):
the reference forks a phantom rank-0 process, ships dill'd closures to
barrier-scheduled Spark executors, and loops `partition_shuffles`
rounds of `iters` steps with per-step gloo all_reduces.

Here the driver IS the orchestrator and the mesh IS the gang: data
lives as one globally-sharded array (each device holds its shard in
HBM), the compiled step from :mod:`sparktorch_tpu.train.step` runs the
whole world per call, and "partition shuffles" become an on-device
global permutation between rounds. No phantom ranks: empty shards are
weight-zero padding (see utils/data.py).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.obs import get_logger, get_telemetry
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.parallel.launch import check_gang, notify_gang_step
from sparktorch_tpu.parallel.mesh import BATCH_AXES, batch_sharding, build_mesh, replicated
from sparktorch_tpu.train.step import (
    EsConfig,
    TrainState,
    create_train_state,
    init_es_state,
    make_eval_step,
    make_train_epoch,
    make_train_epoch_fused,
    make_train_step,
)
from sparktorch_tpu.utils.data import DataBatch, handle_features, pad_to_multiple
from sparktorch_tpu.utils.early_stopper import EarlyStopping
from sparktorch_tpu.utils.serde import ModelSpec, deserialize_model


class TrainResult(NamedTuple):
    params: Any
    model_state: Any
    metrics: list  # list of per-step dicts
    spec: ModelSpec
    summary: Optional[dict] = None  # roll-up (examples/sec/chip, p50/p99)


def _as_batch(data, labels=None, validation_pct=0.0, seed=0):
    if isinstance(data, DataBatch):
        return data, None
    if isinstance(data, tuple) and len(data) == 2 and labels is None:
        return handle_features(data[0], data[1], validation_pct, seed)
    return handle_features(data, labels, validation_pct, seed)


def prepare_sharded_batch(batch: DataBatch, mesh: Mesh) -> DataBatch:
    """Pad to a multiple of the batch-axis size and place shards.

    The padding rows carry weight 0 — this is the empty-partition
    protocol (``distributed.py:46-63,131-133``) done with math instead
    of phantom collective participants.
    """
    n_shards = 1
    for ax in BATCH_AXES:
        n_shards *= mesh.shape[ax]
    padded = pad_to_multiple(batch, n_shards)
    sharding = batch_sharding(mesh)
    return DataBatch(*(jax.device_put(a, sharding) for a in padded))


def _shuffle_batch(batch: DataBatch, key: jax.Array, mesh: Mesh) -> DataBatch:
    """Global permutation between shuffle rounds — the analog of the
    reference's RDD re-shuffle (``distributed.py:267-273``), executed
    on-device (an all-to-all under the hood, riding ICI)."""
    perm = jax.random.permutation(key, batch.x.shape[0])
    sharding = batch_sharding(mesh)
    out = jax.jit(
        lambda b, p: DataBatch(b.x[p], b.y[p], b.w[p]),
        out_shardings=DataBatch(sharding, sharding, sharding),
    )(batch, perm)
    return out


def _open_checkpoint(checkpoint_dir, resume, state):
    """Shared checkpoint bring-up for the trainers: open the manager
    and restore the latest snapshot when resuming. Returns
    (manager_or_None, possibly-restored state)."""
    if not checkpoint_dir:
        return None, state
    from sparktorch_tpu.utils.checkpoint import CheckpointManager

    ckpt = CheckpointManager(checkpoint_dir)
    if resume and ckpt.latest_step() is not None:
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding),
            state,
        )
        state = ckpt.restore(abstract)
    return ckpt, state


def _save_if_due(ckpt, state, last_ckpt_step: int, every: int) -> int:
    """Save on the first boundary at or past the cadence — a fused
    chunk that strides over the exact multiple must not silently skip
    the save. Returns the (possibly advanced) last-saved step."""
    if ckpt is None or every <= 0:
        return last_ckpt_step
    # lint-obs: ok (one scalar at checkpoint cadence, not per step)
    step_now = int(jax.device_get(state.step))
    if step_now - last_ckpt_step >= every:
        ckpt.save(step_now, state)
        return step_now
    return last_ckpt_step


def _resolve_steps_per_call(steps_per_call, default: int, iters: int,
                            checkpoint_every: int, ckpt_active: bool) -> int:
    """One place for the fused-chunk sizing contract (shared by the DP
    and pipeline trainers): a DEFAULTED chunk never exceeds the
    checkpoint cadence (saves happen between compiled calls); an
    EXPLICIT steps_per_call wins — saves then land at chunk boundaries
    >= the cadence (test_checkpoint_cadence_under_fused_stepping pins
    this). The result always divides ``iters`` exactly (a fused call
    runs its full scan; overshooting would silently train extra
    steps)."""
    if steps_per_call is None:
        steps_per_call = default
        if ckpt_active and checkpoint_every and checkpoint_every > 0:
            steps_per_call = min(steps_per_call, checkpoint_every)
    steps_per_call = max(1, min(int(steps_per_call), iters))
    while iters % steps_per_call != 0:
        steps_per_call -= 1
    return steps_per_call


def _finalize_checkpoint(ckpt, state, completed: bool) -> None:
    """Flush and close. The FINAL snapshot fires only on clean
    completion — orbax saves are cross-process collectives, so
    attempting one after a peer died would wedge the survivor in
    exactly the hang check_gang() exists to prevent (periodic saves
    already on disk keep the run resumable)."""
    if ckpt is None:
        return
    if completed:
        # lint-obs: ok (end-of-run scalar, the loop already drained)
        final_step = int(jax.device_get(state.step))
        if ckpt.latest_step() != final_step:
            ckpt.save(final_step, state, force=True)
    ckpt.wait()
    ckpt.close()


def train_distributed(
    torch_obj: Union[str, ModelSpec],
    data: Any,
    labels: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    iters: int = 10,
    partition_shuffles: int = 1,
    verbose: int = 0,
    mini_batch: Optional[int] = None,
    validation_pct: float = 0.0,
    early_stop_patience: int = -1,
    seed: int = 0,
    device: Optional[str] = None,  # accepted for API parity; mesh decides
    metrics_hook: Optional[Callable[[dict], None]] = None,
    steps_per_call: Optional[int] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    profile_dir: Optional[str] = None,
    pre_sharded: bool = False,
    n_micro: int = 4,
    pipeline_schedule: str = "gpipe",
    virtual_stages: int = 1,
    telemetry=None,
) -> TrainResult:
    """Synchronous data-parallel training over the mesh.

    Parameter surface mirrors ``train_distributed``
    (``distributed.py:209-236``): iters, partition_shuffles, verbose,
    mini_batch, validation_pct, early_stop_patience. ``world_size`` and
    ``device`` disappear — the mesh defines the world. ``n_micro`` and
    ``pipeline_schedule`` ('gpipe' | '1f1b') apply only when the mesh
    has pp>1, as does ``virtual_stages`` (>1 = interleaved 1F1B:
    requires pipeline_schedule='1f1b', n_micro divisible by pp, and a
    dense/MoE pattern uniform across all pp*V chunks — tp, sp, MoE
    and ep all compose; shrinks the pipeline bubble ~V-fold at
    O(V*pp) activation memory).
    """
    del device
    spec = deserialize_model(torch_obj)
    mesh = mesh or build_mesh()

    from sparktorch_tpu.parallel.mesh import AXIS_PP

    if dict(mesh.shape).get(AXIS_PP, 1) > 1:
        # pp is a MESH choice on this same entry point: a mesh with
        # pp>1 routes to the GPipe trainer (pipeline.py), which trains
        # the spec's CausalLM under the pipelined schedule and returns
        # ordinary flax params.
        from sparktorch_tpu.train.pipeline import train_distributed_pipeline

        return train_distributed_pipeline(
            spec, data, labels=labels, mesh=mesh, iters=iters,
            n_micro=n_micro, verbose=verbose, seed=seed,
            metrics_hook=metrics_hook, checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every, resume=resume,
            partition_shuffles=partition_shuffles,
            early_stop_patience=early_stop_patience,
            validation_pct=validation_pct,
            # -1/0 are the torch-parity "disabled" sentinels (the
            # pp=1 paths check `mini_batch > 0`), not a request.
            mini_batch=(mini_batch
                        if mini_batch is not None and mini_batch > 0
                        else None),
            steps_per_call=steps_per_call,
            profile_dir=profile_dir,
            schedule=pipeline_schedule,
            virtual_stages=virtual_stages,
            pre_sharded=pre_sharded,
            telemetry=telemetry,
        )

    tele = telemetry or get_telemetry()
    # The continuous stack sampler lives wherever ledgers live: the
    # ambient ledger names the thieving bucket, the sampler names the
    # function inside it. Env-gated; idempotent per process.
    from sparktorch_tpu.obs import health as _health
    from sparktorch_tpu.obs import profile as _profile

    _profile.ensure(tele)
    # Model-health lane (obs/health.py): per-rank ledger fed each step
    # with device values fetched K steps late. reset() re-bases the
    # EWMAs so a restarted attempt on the same bus is not judged
    # against the previous attempt's loss baseline.
    _hl = _health.ensure(tele, rank=jax.process_index())
    if _hl is not None:
        _hl.reset()
    if pre_sharded:
        # ``data`` is already a globally-sharded DataBatch (multi-host
        # path, train_distributed_multihost) — do not re-place it.
        train_batch, val_batch = data, None
        if spec.input_shape is None:
            spec.input_shape = tuple(train_batch.x.shape[1:])
    else:
        # data_wait: host-side batch prep + host->device placement is
        # time the accelerators spend waiting on input.
        with tele.span("train/data_prep"), _goodput.span("data_wait"):
            train_batch, val_batch = _as_batch(data, labels, validation_pct,
                                               seed)
            if spec.input_shape is None:
                spec.input_shape = tuple(np.asarray(train_batch.x).shape[1:])

            train_batch = prepare_sharded_batch(train_batch, mesh)
            if val_batch is not None:
                val_batch = prepare_sharded_batch(val_batch, mesh)

    rng = jax.random.key(seed)
    tx = spec.make_optimizer()
    if pre_sharded:
        # Slicing a non-fully-addressable global array is not allowed;
        # init from an abstract sample of the right shape instead.
        sample_x = jnp.zeros((1,) + tuple(train_batch.x.shape[1:]),
                             train_batch.x.dtype)
    else:
        sample_x = train_batch.x[:1]
    # Initialize UNDER jit with replicated out_shardings: every process
    # runs the same compiled init, so this works on multi-process
    # (non-fully-addressable) meshes where a host-side device_put of
    # replicated state cannot (the reference replicates the model onto
    # every executor, distributed.py:112-115).
    # The jitted init is a compile-dominated call (one trace+compile,
    # negligible device work) — the ledger's compile bucket takes it.
    with tele.span("train/init"), _goodput.span(
            "compile", {"site": "train_init"}), mesh:
        state = jax.jit(
            lambda: create_train_state(spec, rng, sample_x=sample_x, tx=tx),
            out_shardings=replicated(mesh),
        )()

    ckpt, state = _open_checkpoint(checkpoint_dir, resume, state)
    if _hl is not None and _hl.leaf_keys is None:
        _hl.leaf_keys = _health.health_leaf_keys(state.params)

    loss_fn = spec.loss_fn()
    module = spec.make_module()

    stopper = (
        EarlyStopping(patience=early_stop_patience)
        if early_stop_patience is not None and early_stop_patience > 0
        else None
    )
    # Fast path: fuse many steps into one compiled call (lax.scan).
    # Early stopping / the val forward no longer force 1 step/call:
    # the stop decision and per-iter val forward ride INSIDE the fused
    # scan (make_train_epoch_fused) with exact per-step semantics —
    # post-stop steps are masked to no-ops, so the only fusion cost is
    # the masked tail of the chunk where the stop fires (hence the
    # smaller default chunk there).
    steps_per_call = _resolve_steps_per_call(
        steps_per_call,
        default=(
            min(iters, 8)
            if (stopper is not None or val_batch is not None)
            else min(iters, 32)
        ),
        iters=iters,
        checkpoint_every=checkpoint_every,
        ckpt_active=ckpt is not None,
    )

    fused_signals = steps_per_call > 1 and (
        stopper is not None or val_batch is not None
    )
    es_state = init_es_state() if fused_signals else None
    if fused_signals:
        train_step = make_train_epoch_fused(
            module.apply, loss_fn, tx, mesh, steps_per_call,
            es_config=(
                EsConfig(patience=early_stop_patience)
                if stopper is not None else None
            ),
            with_val=val_batch is not None,
            mini_batch=mini_batch,
        )
    elif steps_per_call > 1:
        train_step = make_train_epoch(
            module.apply, loss_fn, tx, mesh, steps_per_call, mini_batch=mini_batch
        )
    else:
        train_step = make_train_step(
            module.apply, loss_fn, tx, mesh, mini_batch=mini_batch
        )
    eval_step = (
        make_eval_step(module.apply, loss_fn, mesh)
        if val_batch is not None and not fused_signals
        else None
    )

    from sparktorch_tpu.utils.metrics import MetricsRecorder
    from sparktorch_tpu.utils.tracing import profile_run, step_annotation

    recorder = MetricsRecorder(n_chips=mesh.size, telemetry=tele)
    metrics = recorder.records
    log = get_logger("sparktorch_tpu.train")
    # lint-obs: ok (pre-loop scalar — nothing queued yet)
    last_ckpt_step = int(jax.device_get(state.step)) if ckpt is not None else 0
    shuffle_key = jax.random.key(seed + 1)
    profiler = profile_run(profile_dir, telemetry=tele)
    profiler.__enter__()
    completed = False
    try:
        for shuffle_round in range(max(1, partition_shuffles)):
            # Round 0 must ALSO shuffle when minibatch sampling is on:
            # sample_minibatch takes contiguous blocks, whose
            # uniformity argument requires random resident order — an
            # input sorted by label (common from Spark groupBy) would
            # otherwise feed near-single-class blocks all run.
            if shuffle_round > 0 or (mini_batch is not None and mini_batch > 0):
                shuffle_key, sub = jax.random.split(shuffle_key)
                with tele.span("train/shuffle"):
                    train_batch = _shuffle_batch(train_batch, sub, mesh)
            stop = False
            i = 0
            while i < iters:
                # Fail fast if a peer host died (multi-host runs only; the
                # gang's heartbeat marks survivors dead within one
                # interval). Checking here — before dispatching the next
                # compiled chunk — means we raise GangFailure instead of
                # wedging in the chunk's collectives. The same spot
                # publishes this rank's progress on its heartbeat so
                # the driver can read cross-rank step skew, and hosts
                # the chaos kill point (a seeded injection dies here,
                # between compiled dispatches — where a real preempt
                # lands; ft.supervisor.supervise_run then restarts the
                # attempt resuming from the latest checkpoint).
                check_gang()
                notify_gang_step(i)
                # `i` (the round-local iteration), not state.step: the
                # latter would cost a device sync per chunk on the hot
                # path; one-shot kill configs make the distinction
                # irrelevant across resumes.
                _chaos.fire("worker.step", worker=jax.process_index(),
                            step=i)
                # Seeded poison-batch injection (bench-health drill):
                # the site returns an action dict instead of raising,
                # and the poisoned copy REPLACES the resident batch so
                # the health ledger's replay anchor records exactly
                # what dispatches.
                _act = _chaos.fire("data.batch",
                                   worker=jax.process_index(), step=i)
                if _act and _act.get("poison"):
                    train_batch = _chaos.poison_batch(train_batch)
                if _hl is not None:
                    _hl.note_replay_anchor(state, train_batch)
                # Seeded straggler injection: sleep BEFORE the step
                # span so the skew referee sees a late fence arrival
                # on this rank, not a longer step.
                _chaos.straggle(jax.process_index(), i)
                # The step clock is a goodput LedgerSpan: it times the
                # dispatch+sync region whether or not a ledger is
                # active (step_time_s comes off its duration), and when
                # one is, the seconds land in the step bucket — or in
                # ``compile`` when the jit dispatch cache grew under
                # the call (first call / new shape).
                cache0 = (_goodput.jit_cache_size(train_step)
                          if _goodput.active() is not None else None)
                if steps_per_call > 1:
                    n = min(steps_per_call, iters - i)
                    with _goodput.step_span(step=i) as _led:
                        with tele.span("train/step_chunk") as _chunk_span, \
                                step_annotation(
                                    int(metrics[-1]["iter"]) + 1
                                    if metrics else 0,
                                    telemetry=tele):
                            if fused_signals:
                                args = (((state, es_state), train_batch,
                                         val_batch)
                                        if val_batch is not None
                                        else ((state, es_state), train_batch))
                                (state, es_state), stacked = train_step(*args)
                            else:
                                state, stacked = train_step(state, train_batch)
                            _chunk_span.sync(stacked.loss)
                        losses = np.asarray(stacked.loss)[:n]
                        examples = np.asarray(stacked.examples)[:n]
                        gnorms = np.asarray(stacked.grad_norm)[:n]
                        if fused_signals:
                            vals = np.asarray(stacked.val_loss)[:n]
                            actives = np.asarray(stacked.active)[:n]
                        else:
                            vals = [None] * n
                            actives = [True] * n
                        drops = (
                            np.asarray(stacked.drop_fraction)[:n]
                            if stacked.drop_fraction is not None
                            else [None] * n
                        )
                        n_active = int(np.sum(np.asarray(actives)))
                        _led.count = max(1, n_active)
                        if cache0 is not None and (
                                _goodput.jit_cache_size(train_step)
                                or cache0) > cache0:
                            _led.rebucket("compile")
                    dt = _led.duration_s / max(1, n_active)
                    if _hl is not None and n_active > 0:
                        _h = stacked.health
                        _hl.note_step(
                            count=n_active,
                            device=None if _h is None else {
                                "finite": _h.finite,
                                "update_ratio": _h.update_ratio,
                                "leaf_norms": _h.leaf_norms,
                            },
                            host={"loss": losses, "grad_norm": gnorms},
                        )
                    chunk = [
                        (float(l), float(e), float(g),
                         None if v is None or np.isnan(v) else float(v),
                         bool(a), None if dr is None else float(dr))
                        for l, e, g, v, a, dr in zip(losses, examples, gnorms,
                                                     vals, actives, drops)
                    ]
                else:
                    with _goodput.step_span(step=i) as _led:
                        with tele.span("train/step") as _step_span, \
                                step_annotation(i, telemetry=tele):
                            state, step_metrics = train_step(state,
                                                             train_batch)
                            _step_span.sync(step_metrics.loss)
                        if cache0 is not None and (
                                _goodput.jit_cache_size(train_step)
                                or cache0) > cache0:
                            _led.rebucket("compile")
                    if eval_step is not None:
                        # The per-iteration val forward is productive
                        # device work, just not a train step.
                        with _goodput.span("compute", {"site": "eval"}):
                            val_now = float(eval_step(state, val_batch))
                    else:
                        val_now = None
                    chunk = [(
                        float(step_metrics.loss),
                        float(step_metrics.examples),
                        float(step_metrics.grad_norm),
                        val_now,
                        True,
                        float(step_metrics.drop_fraction)
                        if step_metrics.drop_fraction is not None else None,
                    )]
                    dt = _led.duration_s
                    if _hl is not None:
                        _h = step_metrics.health
                        _hl.note_step(
                            device=None if _h is None else {
                                "finite": _h.finite,
                                "update_ratio": _h.update_ratio,
                                "leaf_norms": _h.leaf_norms,
                            },
                            host={"loss": chunk[0][0],
                                  "grad_norm": chunk[0][2]},
                        )

                for loss, examples_n, gnorm, val_loss, active, drop_f in chunk:
                    if not active:
                        # Step masked out inside the fused chunk: the
                        # stop had already fired — nothing trained.
                        break
                    record = {
                        "round": shuffle_round,
                        "iter": i,
                        "loss": loss,
                        "val_loss": val_loss,
                        "examples": examples_n,
                        "grad_norm": gnorm,
                        "step_time_s": dt,
                    }
                    if drop_f is not None:
                        record["moe_drop_fraction"] = drop_f
                    recorder.record(record)
                    if metrics_hook:
                        metrics_hook(record)
                    if verbose:
                        # Reference prints per-partition loss lines
                        # (distributed.py:201-204); here one global
                        # line through the obs logger (lint-obs bans
                        # raw prints in library code).
                        msg = f"[sparktorch_tpu] round {shuffle_round} iter {i} loss {loss:.6f}"
                        if val_loss is not None:
                            msg += f" val_loss {val_loss:.6f}"
                        log.info(msg)
                    # Early stop needs no collective: `loss` is already the
                    # global mean, identical on every host (vs the
                    # reference's two extra all_reduces,
                    # distributed.py:186-197). On the fused path the
                    # decision already happened on-device (EsState).
                    if stopper is not None and not fused_signals:
                        signal = val_loss if val_loss is not None else loss
                        if stopper.step(signal):
                            stop = True
                            break
                    i += 1
                # lint-obs: ok (one early-stop scalar per drained chunk)
                if fused_signals and bool(jax.device_get(es_state.stopped)):
                    stop = True
                if ckpt is not None:
                    with tele.span("train/checkpoint"):
                        last_ckpt_step = _save_if_due(
                            ckpt, state, last_ckpt_step, checkpoint_every
                        )
                if stop:
                    break
            if stop:
                break
        completed = True
    finally:
        # Cleanup must run on the failure paths too (GangFailure from
        # check_gang, a raising metrics_hook): close the profiler
        # trace and flush async checkpoint writes already in flight.
        profiler.__exit__(None, None, None)
        if _hl is not None:
            # Drain the delayed-fetch tail so the published section
            # (and any postmortem) reflects the final steps.
            _hl.flush()
        _finalize_checkpoint(ckpt, state, completed)

    # lint-obs: ok (end-of-run gather after the loop drained)
    params = jax.device_get(state.params)
    model_state = jax.device_get(state.model_state)  # lint-obs: ok (end-of-run)
    return TrainResult(params=params, model_state=model_state, metrics=metrics,
                       spec=spec, summary=recorder.summary())


def train_distributed_multihost(
    torch_obj: Union[str, ModelSpec],
    local_x: np.ndarray,
    local_y: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    **kwargs,
) -> TrainResult:
    """Multi-host entry: each process contributes ITS partition of the
    data; the global batch is assembled across processes.

    Call after ``jax.distributed.initialize`` (e.g. via
    ``parallel.launch.bringup_multihost``). The analog of the
    reference's executor-side ``handle_model`` receiving a partition
    iterator (``distributed.py:66-128``), minus the phantom ranks:
    hosts with fewer rows pad with weight-0 examples, so skewed and
    empty partitions are mathematically absorbed into the global
    weighted mean.
    """
    from jax.experimental import multihost_utils

    mesh = mesh or build_mesh()
    n_proc = jax.process_count()

    local_x = np.asarray(local_x)
    if not np.issubdtype(local_x.dtype, np.integer):
        # Float features stay the DP trainer's float32; integer inputs
        # (token ids for the pp/sequence models) keep their dtype so
        # the pp route can cast them back to int32 on device.
        local_x = local_x.astype(np.float32)
    if local_x.ndim == 1:
        local_x = local_x.reshape(0, 1) if local_x.size == 0 else local_x[:, None]
    local_y = np.asarray(local_y) if local_y is not None else None

    # Agree on a common per-host row count AND feature shape (hosts
    # must build identically-shaped local shards for the global array;
    # an EMPTY host has no way to know the feature shape locally — the
    # analog of the reference's empty-partition protocol,
    # distributed.py:131-133). Fixed-width vector so the allgather
    # lines up even when ranks differ across hosts.
    _MAX_RANK = 8
    if local_x.ndim - 1 > _MAX_RANK:
        raise ValueError(f"feature rank {local_x.ndim - 1} > {_MAX_RANK}")
    # Layout: [rows, x_rank, x_dims(8), y_rank, y_dims(8), x_dtype,
    # y_dtype] — y_rank is -1 when this host has no labels, so donors
    # can repair BOTH the feature and label shapes of an empty host;
    # the dtype codes let the repair match the donors' dtype too (an
    # int-token host must not be joined by a float32 empty shard).
    _DTYPES = [np.float32, np.float64, np.int32, np.int64, np.int8,
               np.uint8, np.int16, np.uint16, np.uint32, np.uint64,
               np.bool_]

    def _dtype_code(dt) -> int:
        for i, d in enumerate(_DTYPES):
            if np.dtype(dt) == np.dtype(d):
                return i
        # Silently coding an unknown dtype as float32 would let an
        # empty host repair itself with a dtype its donors don't have.
        raise ValueError(
            f"unsupported multihost shard dtype {np.dtype(dt)}; use one "
            f"of {[np.dtype(d).name for d in _DTYPES]}"
        )

    width = 2 + _MAX_RANK + 1 + _MAX_RANK + 2
    shape_vec = np.full((width,), 0, np.int64)
    shape_vec[0] = local_x.shape[0]
    feat = local_x.shape[1:]
    shape_vec[1] = len(feat)
    shape_vec[2 : 2 + len(feat)] = feat
    y_off = 2 + _MAX_RANK
    if local_y is None:
        shape_vec[y_off] = -1
    else:
        y_feat = local_y.shape[1:]
        if len(y_feat) > _MAX_RANK:
            raise ValueError(f"label rank {len(y_feat)} > {_MAX_RANK}")
        shape_vec[y_off] = len(y_feat)
        shape_vec[y_off + 1 : y_off + 1 + len(y_feat)] = y_feat
    dt_off = y_off + 1 + _MAX_RANK
    shape_vec[dt_off] = _dtype_code(local_x.dtype)
    shape_vec[dt_off + 1] = (
        _dtype_code(local_y.dtype) if local_y is not None else -1
    )
    gathered = multihost_utils.process_allgather(shape_vec)
    gathered = gathered.reshape(-1, width)
    counts = gathered[:, 0]
    if local_x.shape[0] == 0:
        donors = gathered[gathered[:, 0] > 0]
        if len(donors):
            nd = int(donors[0, 1])
            feat = tuple(int(v) for v in donors[0, 2 : 2 + nd])
            local_x = np.zeros((0,) + feat,
                               _DTYPES[int(donors[0, dt_off])])
            if local_y is not None:
                y_rank = int(donors[0, y_off])
                y_feat = (
                    tuple(int(v) for v in donors[0, y_off + 1 : y_off + 1 + y_rank])
                    if y_rank > 0 else ()
                )
                y_code = int(donors[0, dt_off + 1])
                local_y = np.zeros(
                    (0,) + y_feat,
                    _DTYPES[y_code] if y_code >= 0 else local_y.dtype,
                )
    # Unsupervised (y=x) aliasing AFTER the donor repair, so the empty
    # host's labels adopt the repaired feature shape too. The pp route
    # must never see the alias: its heads are an LM (targets are the
    # NEXT token — alias the raw matrix and it trains an identity
    # copier) or a classifier (needs real labels).
    if local_y is None and dict(mesh.shape).get("pp", 1) > 1:
        from sparktorch_tpu.models.transformer import CausalLM as _CLM

        probe = deserialize_model(torch_obj)
        if isinstance(probe.make_module(), _CLM) and local_x.ndim == 2:
            local_x, local_y = local_x[:, :-1], local_x[:, 1:]
        else:
            raise ValueError(
                "pp>1 multihost training requires labels (local_y): "
                "next-token targets for a CausalLM id matrix, or class "
                "labels for a classifier"
            )
    if local_y is None:
        local_y = local_x
    local_w = np.ones((local_x.shape[0],), np.float32)
    per_host = int(counts.max())
    # The global batch must divide the mesh's batch shards.
    n_shards = 1
    for ax in BATCH_AXES:
        n_shards *= mesh.shape[ax]
    shards_per_host = max(1, n_shards // n_proc)
    per_host = max(
        shards_per_host,
        -(-per_host // shards_per_host) * shards_per_host,
    )
    from sparktorch_tpu.parallel.mesh import AXIS_PP as _PP

    if dict(mesh.shape).get(_PP, 1) > 1:
        # The pp route needs global rows divisible by dp * n_micro
        # (each dp shard splits into n_micro microbatches). Round
        # per_host up so per_host * n_proc satisfies that.
        import math as _math

        dp_sz = mesh.shape[BATCH_AXES[0]]
        need = dp_sz * int(kwargs.get("n_micro", 4))
        unit = need // _math.gcd(n_proc, need)
        per_host = -(-per_host // unit) * unit

    def pad_to(arr, n):
        if arr.shape[0] == n:
            return arr
        widths = [(0, n - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, widths)

    sharding = batch_sharding(mesh)
    global_batch = DataBatch(
        jax.make_array_from_process_local_data(sharding, pad_to(local_x, per_host)),
        jax.make_array_from_process_local_data(sharding, pad_to(local_y, per_host)),
        jax.make_array_from_process_local_data(sharding, pad_to(local_w, per_host)),
    )
    return train_distributed(torch_obj, global_batch, mesh=mesh,
                             pre_sharded=True, **kwargs)


def train_distributed_streaming(
    torch_obj: Union[str, ModelSpec],
    data: Any,
    labels: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    chunk_rows: int = 65536,
    epochs: int = 1,
    steps_per_chunk: Optional[int] = None,
    mini_batch: Optional[int] = None,
    verbose: int = 0,
    seed: int = 0,
    metrics_hook: Optional[Callable[[dict], None]] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    telemetry=None,
) -> TrainResult:
    """Train on data LARGER than device HBM by streaming host chunks.

    The reference trains on whatever a Spark partition iterator yields
    (``distributed.py:66-128``) — dataset size is bounded by executor
    host memory, not accelerator memory. The resident-batch trainer
    (:func:`train_distributed`) device-puts the whole dataset, so its
    ceiling is HBM. This entry restores the reference's ceiling:

    - ``data`` is a host numpy array (or ``(x, y)`` tuple), kept in
      host RAM; it is walked in ``chunk_rows`` slices per epoch.
    - Each chunk is padded to the mesh's batch shards (weight-0 rows,
      the usual empty-partition protocol) and transferred while the
      PREVIOUS chunk is still training — double-buffered, so the copy
      rides under compute. Device memory stays O(2 chunks).
    - Per chunk, ``steps_per_chunk`` minibatch steps run as ONE fused
      compiled call (``lax.scan``); chunks share a single compiled
      program (uniform shape). Default: one pass over the chunk
      (``ceil(chunk_rows / mini_batch)`` steps, or 1 full-chunk step).
    - Each epoch re-walks the data in a fresh host permutation — the
      streaming analog of ``partition_shuffles``.
    """
    spec = deserialize_model(torch_obj)
    mesh = mesh or build_mesh()

    train_all, _ = _as_batch(data, labels, 0.0, seed)
    x = np.asarray(train_all.x, np.float32)
    y = np.asarray(train_all.y)
    w = np.asarray(train_all.w, np.float32)
    n = x.shape[0]
    if spec.input_shape is None:
        spec.input_shape = tuple(x.shape[1:])
    chunk_rows = min(chunk_rows, n)

    n_shards = 1
    for ax in BATCH_AXES:
        n_shards *= mesh.shape[ax]
    chunk_rows = -(-chunk_rows // n_shards) * n_shards  # pad up to shards
    if mini_batch is not None and mini_batch > 0:
        per_shard_rows = chunk_rows // n_shards
        default_steps = max(1, -(-per_shard_rows // max(1, mini_batch)))
    else:
        default_steps = 1
    steps = steps_per_chunk or default_steps

    tx = spec.make_optimizer()
    rng = jax.random.key(seed)
    sample_x = jnp.zeros((1,) + tuple(x.shape[1:]), jnp.float32)
    # Compile-dominated (same attribution as the DP trainer's init).
    with _goodput.span("compile", {"site": "train_init"}), mesh:
        state = jax.jit(
            lambda: create_train_state(spec, rng, sample_x=sample_x, tx=tx),
            out_shardings=replicated(mesh),
        )()

    module = spec.make_module()
    loss_fn = spec.loss_fn()
    if steps > 1:
        step_fn = make_train_epoch(module.apply, loss_fn, tx, mesh, steps,
                                   mini_batch=mini_batch)
    else:
        step_fn = make_train_step(module.apply, loss_fn, tx, mesh,
                                  mini_batch=mini_batch)

    sharding = batch_sharding(mesh)

    def put_chunk(lo: int, order: np.ndarray) -> DataBatch:
        idx = order[lo : lo + chunk_rows]
        cx, cy, cw = x[idx], y[idx], w[idx]
        pad = chunk_rows - cx.shape[0]
        if pad:
            cx = np.concatenate([cx, np.zeros((pad, *cx.shape[1:]), cx.dtype)])
            cy = np.concatenate([cy, np.zeros((pad, *cy.shape[1:]), cy.dtype)])
            cw = np.concatenate([cw, np.zeros((pad,), cw.dtype)])
        return DataBatch(
            jax.device_put(cx, sharding),
            jax.device_put(cy, sharding),
            jax.device_put(cw, sharding),
        )

    from sparktorch_tpu.utils.metrics import MetricsRecorder

    ckpt, state = _open_checkpoint(checkpoint_dir, resume, state)
    # lint-obs: ok (pre-loop scalar — nothing queued yet)
    last_ckpt_step = int(jax.device_get(state.step)) if ckpt is not None else 0

    tele = telemetry or get_telemetry()
    log = get_logger("sparktorch_tpu.train")
    # Stack sampler beside the ambient ledger (see train_distributed).
    from sparktorch_tpu.obs import health as _health
    from sparktorch_tpu.obs import profile as _profile

    _profile.ensure(tele)
    _hl = _health.ensure(tele, rank=jax.process_index())
    if _hl is not None:
        _hl.reset()
        if _hl.leaf_keys is None:
            _hl.leaf_keys = _health.health_leaf_keys(state.params)
    recorder = MetricsRecorder(n_chips=mesh.size, telemetry=tele,
                               prefix="train_streaming")
    # Fold the restored step into the shuffle seed: a resumed run must
    # draw FRESH permutations, not replay the epochs the interrupted
    # run already consumed.
    shuffle_rng = np.random.default_rng(seed + 1 + last_ckpt_step)
    it_counter = 0
    completed = False
    try:
        for epoch in range(max(1, epochs)):
            check_gang()
            order = shuffle_rng.permutation(n)
            starts = list(range(0, n, chunk_rows))
            # The epoch's first chunk has nothing to hide under: a
            # pure data wait.
            with _goodput.span("data_wait", {"site": "streaming_chunk"}):
                resident = put_chunk(starts[0], order)
            for ci, lo in enumerate(starts):
                # Per-chunk liveness, matching train_distributed: a
                # peer host dying mid-epoch must abort before the next
                # compiled dispatch, not at the epoch boundary.
                check_gang()
                notify_gang_step(it_counter)
                _act = _chaos.fire("data.batch",
                                   worker=jax.process_index(),
                                   step=it_counter)
                if _act and _act.get("poison"):
                    resident = _chaos.poison_batch(resident)
                if _hl is not None:
                    _hl.note_replay_anchor(state, resident)
                # Straggler injection before the step span: a late
                # fence arrival, visible to the skew referee.
                _chaos.straggle(jax.process_index(), it_counter)
                cache0 = (_goodput.jit_cache_size(step_fn)
                          if _goodput.active() is not None else None)
                with _goodput.step_span(step=it_counter) as _led, \
                        tele.span("train_streaming/chunk"):
                    state, metrics = step_fn(state, resident)
                    # Enqueue the NEXT chunk's host->device copy while
                    # the current chunk's (already dispatched) steps
                    # compute. The placement is a nested data_wait
                    # span: its seconds subtract from this chunk's
                    # step attribution (one second, one bucket) —
                    # though being deliberately overlapped under the
                    # in-flight compute, it is usually small.
                    if ci + 1 < len(starts):
                        with _goodput.span("data_wait",
                                           {"site": "streaming_chunk"}):
                            resident = put_chunk(starts[ci + 1], order)
                    losses = np.asarray(metrics.loss).reshape(-1)
                    _led.count = len(losses)
                    if cache0 is not None and (
                            _goodput.jit_cache_size(step_fn)
                            or cache0) > cache0:
                        _led.rebucket("compile")
                examples = np.asarray(metrics.examples).reshape(-1)
                dt = _led.duration_s / len(losses)
                if _hl is not None:
                    _h = metrics.health
                    _hl.note_step(
                        count=len(losses),
                        device=None if _h is None else {
                            "finite": _h.finite,
                            "update_ratio": _h.update_ratio,
                            "leaf_norms": _h.leaf_norms,
                        },
                        host={"loss": losses,
                              "grad_norm": np.asarray(
                                  metrics.grad_norm).reshape(
                                      losses.shape[0], -1)[:, 0]},
                    )
                for j in range(len(losses)):
                    record = {
                        "round": epoch, "iter": it_counter,
                        "loss": float(losses[j]),
                        "val_loss": None,
                        "examples": float(examples[j]),
                        "grad_norm": None,
                        "step_time_s": dt,
                    }
                    recorder.record(record)
                    if metrics_hook:
                        metrics_hook(record)
                    it_counter += 1
                # Chunk boundaries are the save points.
                last_ckpt_step = _save_if_due(
                    ckpt, state, last_ckpt_step, checkpoint_every
                )
                if verbose:
                    log.info(f"[sparktorch_tpu] epoch {epoch} chunk {ci} "
                             f"loss {losses[-1]:.6f}")
        completed = True
    finally:
        if _hl is not None:
            _hl.flush()
        _finalize_checkpoint(ckpt, state, completed)
    # lint-obs: ok (end-of-run gather after the loop drained)
    params = jax.device_get(state.params)
    model_state = jax.device_get(state.model_state)  # lint-obs: ok (end-of-run)
    return TrainResult(params=params, model_state=model_state,
                       metrics=recorder.records, spec=spec,
                       summary=recorder.summary())
