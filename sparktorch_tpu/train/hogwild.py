"""Asynchronous (hogwild) training against the parameter server.

Reference: ``sparktorch/hogwild.py`` — HTTP client helpers with one
retry (:31-62), a per-partition worker loop that pulls the full
state_dict, does forward/backward, pushes raw grads and polls early
stop (:65-142), and a driver ``train()`` that runs partition-shuffle
rounds and pulls final weights (:145-186).

TPU-native redesign:

- Workers are device-pinned: each worker owns a chip, holds its data
  shard in that chip's HBM, and runs one jitted gradient step per
  iteration. Pulls are version-tagged (no redundant transfers), and
  the push is the local weighted-mean gradient pytree.
- The reference's missing ``zero_grad`` (grads accumulate across
  iterations, ``hogwild.py:96-140`` — SURVEY flags it as a real
  behavioral quirk) is deliberately NOT reproduced: each push is the
  gradient of the current minibatch only.
- Transports: ``local`` (in-process, device-to-device) or ``http``
  (the reference's wire shape, stdlib client with one retry + timeout
  like ``hogwild.py:34-38``).
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from functools import partial
from typing import Any, List, Optional

import dill
import jax
import jax.numpy as jnp
import numpy as np

from sparktorch_tpu.serve.param_server import ParameterServer, ParamServerHttp
from sparktorch_tpu.train.sync import TrainResult, _as_batch
from sparktorch_tpu.utils.data import DataBatch
from sparktorch_tpu.utils.serde import ModelSpec, deserialize_model

_HTTP_TIMEOUT = 10.0  # hogwild.py:34-38 parity (10s timeout, 1 retry)


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class LocalTransport:
    """Direct in-process access to the server object."""

    def __init__(self, server: ParameterServer):
        self.server = server

    def pull(self, have_version: int):
        return self.server.get_parameters(have_version)

    def push(self, grads) -> None:
        self.server.push_gradients(grads)

    def post_loss(self, loss: float) -> bool:
        return self.server.post_loss(loss)

    def alive(self) -> bool:
        return True


class HttpTransport:
    """The reference's wire (hogwild.py:31-62): dill over HTTP with
    one retry and a 10s timeout per call.

    Unlike the reference — which ships full-precision state both ways
    every iteration (its 2x-model-per-iter pathology) — pushes are
    bf16-compressed by default: gradients tolerate the 8-bit mantissa
    (it is the TPU's native matmul dtype) and the wire bytes halve.
    The server casts back up to the param dtype before the optimizer
    update, so moments stay full precision."""

    def __init__(self, url: str, compress: bool = True):
        self.url = url.rstrip("/")
        self.compress = compress

    def _request(self, req):
        try:
            return urllib.request.urlopen(req, timeout=_HTTP_TIMEOUT)
        except (urllib.error.URLError, ConnectionError):
            return urllib.request.urlopen(req, timeout=_HTTP_TIMEOUT)  # retry once

    def pull(self, have_version: int):
        req = urllib.request.Request(
            self.url + "/parameters", headers={"X-Have-Version": str(have_version)}
        )
        with self._request(req) as resp:
            if resp.status == 204:
                return None
            return dill.loads(resp.read())

    def push(self, grads) -> None:
        if self.compress:
            host_grads = jax.tree.map(
                lambda a: np.asarray(
                    a.astype(jnp.bfloat16)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                    else a
                ),
                grads,
            )
        else:
            host_grads = jax.tree.map(lambda a: np.asarray(a), grads)
        req = urllib.request.Request(
            self.url + "/update", data=dill.dumps(host_grads), method="POST"
        )
        with self._request(req) as resp:
            if resp.status != 200:
                raise RuntimeError(f"/update failed: {resp.status}")

    def post_loss(self, loss: float) -> bool:
        req = urllib.request.Request(
            self.url + "/losses", data=dill.dumps(float(loss)), method="POST"
        )
        with self._request(req) as resp:
            return bool(dill.loads(resp.read())["stop"])

    def alive(self) -> bool:
        # GET / liveness probe (hogwild.py:60-62).
        req = urllib.request.Request(self.url + "/")
        with self._request(req) as resp:
            return resp.status == 200


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------


def make_grad_step(apply_fn, loss_fn):
    """Jitted local gradient step: weighted-mean grads + loss of one
    minibatch — the worker half of ``hogwild.handle_model``'s hot loop
    (hogwild.py:96-130), with zero_grad semantics done right."""

    @jax.jit
    def grad_step(params, model_state, batch: DataBatch):
        def weighted(params):
            variables = {"params": params, **(model_state or {})}
            preds = apply_fn(variables, batch.x)
            per = loss_fn(preds, batch.y)
            num = jnp.sum(per * batch.w)
            den = jnp.maximum(jnp.sum(batch.w), 1.0)
            return num / den

        loss, grads = jax.value_and_grad(weighted)(params)
        return grads, loss

    return grad_step


def _worker_loop(
    worker_id: int,
    device: jax.Device,
    transport,
    grad_step,
    model_state,
    shard: DataBatch,
    val_shard: Optional[DataBatch],
    iters: int,
    mini_batch: Optional[int],
    verbose: int,
    early_stop: bool,
    seed: int,
    records: List[dict],
    errors: List[BaseException],
    push_every: int = 1,
):
    try:
        rng = np.random.default_rng(seed + worker_id)
        shard = jax.device_put(shard, device)
        have_version = -1
        params = None
        n = int(shard.x.shape[0])
        # Local gradient accumulation: push the mean of `push_every`
        # minibatch gradients instead of every one — wire traffic (and
        # server applies) drop by that factor, the statistical content
        # is the same examples. Accumulation runs on-device (one fused
        # add per step); only the pushed mean leaves the chip.
        acc = None
        acc_n = 0
        for it in range(iters):
            snap = transport.pull(have_version)
            if snap is not None:
                have_version, params = snap
                params = jax.device_put(params, device)

            if mini_batch and 0 < mini_batch < n:
                idx = rng.integers(0, n, size=mini_batch)
                mb = DataBatch(shard.x[idx], shard.y[idx], shard.w[idx])
            else:
                mb = shard

            grads, loss = grad_step(params, model_state, mb)
            if push_every <= 1:
                transport.push(grads)
            else:
                acc = grads if acc is None else jax.tree.map(
                    jnp.add, acc, grads
                )
                acc_n += 1
                if acc_n >= push_every:
                    transport.push(
                        jax.tree.map(lambda g: g / acc_n, acc)
                    )
                    acc, acc_n = None, 0
            loss = float(loss)
            records.append(
                {"worker": worker_id, "iter": it, "loss": loss,
                 "version": have_version}
            )
            if verbose:
                print(f"[sparktorch_tpu:hogwild] worker {worker_id} iter {it} "
                      f"loss {loss:.6f} v{have_version}")
            if early_stop:
                signal = loss
                if val_shard is not None:
                    _, vloss = grad_step(params, model_state, val_shard)
                    signal = float(vloss)
                if transport.post_loss(signal):
                    break
        # Early-stop (or any non-boundary exit) must not drop examples
        # already trained on: flush the partial accumulator.
        if acc is not None and acc_n > 0:
            transport.push(jax.tree.map(lambda g: g / acc_n, acc))
    except BaseException as e:  # surfaced to the driver
        errors.append(e)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def train_async(
    torch_obj,
    data: Any,
    labels: Optional[np.ndarray] = None,
    mesh=None,  # accepted for API symmetry; workers pin devices directly
    iters: int = 10,
    partition_shuffles: int = 1,
    verbose: int = 0,
    mini_batch: Optional[int] = None,
    validation_pct: float = 0.0,
    early_stop_patience: int = -1,
    acquire_lock: bool = True,
    port: int = 0,
    partitions: int = -1,
    seed: int = 0,
    transport: str = "local",
    push_every: int = 1,
    compress: bool = True,
) -> TrainResult:
    """Asynchronous parameter-server training.

    The driver-side analog of ``hogwild.train`` (hogwild.py:145-186):
    start the server, run shuffle rounds of per-partition worker
    loops, pull final weights, stop the server (also on error,
    hogwild.py:184-186).
    """
    spec = deserialize_model(torch_obj)
    train_batch, val_batch = _as_batch(data, labels, validation_pct, seed)
    if spec.input_shape is None:
        spec.input_shape = tuple(np.asarray(train_batch.x).shape[1:])

    devices = jax.devices()
    n_workers = partitions if partitions and partitions > 0 else len(devices)

    server = ParameterServer(
        spec,
        window_len=n_workers,  # torch_distributed.py:315-322 parity
        early_stop_patience=early_stop_patience,
        acquire_lock=acquire_lock,
        seed=seed,
    )
    http: Optional[ParamServerHttp] = None
    try:
        if transport == "http":
            http = ParamServerHttp(server, port=port).start()
            worker_transports = [
                HttpTransport(http.url, compress=compress)
                for _ in range(n_workers)
            ]
            assert worker_transports[0].alive()  # liveness gate
            # (torch_distributed.py:326 parity)
        else:
            worker_transports = [LocalTransport(server) for _ in range(n_workers)]

        module = spec.make_module()
        grad_step = make_grad_step(module.apply, spec.loss_fn())
        model_state = server.model_state()

        records: List[dict] = []
        errors: List[BaseException] = []
        x = np.asarray(train_batch.x)
        y = np.asarray(train_batch.y)
        w = np.asarray(train_batch.w)
        shuffle_rng = np.random.default_rng(seed + 1)

        for round_idx in range(max(1, partition_shuffles)):
            if round_idx > 0:
                perm = shuffle_rng.permutation(x.shape[0])
                x, y, w = x[perm], y[perm], w[perm]  # hogwild.py:161-177
            xs = np.array_split(x, n_workers)
            ys = np.array_split(y, n_workers)
            ws = np.array_split(w, n_workers)
            threads = []
            for i in range(n_workers):
                shard = DataBatch(
                    jnp.asarray(xs[i]), jnp.asarray(ys[i]), jnp.asarray(ws[i])
                )
                t = threading.Thread(
                    target=_worker_loop,
                    args=(
                        i,
                        devices[i % len(devices)],
                        worker_transports[i],
                        grad_step,
                        model_state,
                        shard,
                        jax.device_put(val_batch, devices[i % len(devices)])
                        if val_batch is not None
                        else None,
                        iters,
                        mini_batch,
                        verbose,
                        early_stop_patience is not None and early_stop_patience > 0,
                        seed + round_idx * n_workers,
                        records,
                        errors,
                        push_every,
                    ),
                    daemon=True,
                )
                threads.append(t)
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise RuntimeError("hogwild worker failed") from errors[0]
            if server.should_stop:
                break

        params, model_state = server.final_state()
        params = jax.device_get(params)
        model_state = jax.device_get(model_state)
        return TrainResult(
            params=params, model_state=model_state, metrics=records, spec=spec
        )
    finally:
        # Stop server even on failure (hogwild.py:184-186 parity).
        if http is not None:
            http.stop()
        server.stop()
